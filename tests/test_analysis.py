"""Tests for the kcanalyze static-analysis framework (docs/ANALYSIS.md).

Each rule gets fixture snippets — bad (must fire, with the exact rule at the
exact file), good (must stay silent), and suppressed (baseline) — plus the
acceptance demonstration: the driver run against a temp tree seeded with one
host-sync, one static-arg mismatch, and one ABBA lock inversion exits
nonzero and names all three, so `make verify` provably fails when any of
these bug classes is introduced.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from karpenter_core_tpu.analysis.core import (
    Baseline,
    BaselineError,
    Finding,
    Project,
    apply_baseline,
    parse_mini_toml,
)
from karpenter_core_tpu.analysis.passes import (
    hygiene,
    instrumented,
    lock_order,
    retrace_budget,
    trace_safety,
    unbounded_block,
)

REPO = Path(__file__).resolve().parent.parent
MANIFEST_PATH = REPO / "karpenter_core_tpu" / "analysis" / "retrace_budget.json"


@pytest.fixture(scope="module")
def repo_project():
    """One Project (and one shared call graph) for every current-tree test —
    rebuilding it per test would re-parse 160+ files five times."""
    return Project(REPO)


@pytest.fixture(scope="module")
def repo_baseline():
    return Baseline.load(
        REPO / "karpenter_core_tpu" / "analysis" / "baseline.toml"
    )


def make_project(tmp_path: Path, files: dict, package: str = "badpkg") -> Project:
    """Write ``files`` (relpath -> source) under a temp package and load it."""
    for rel, src in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    pkg_init = tmp_path / package / "__init__.py"
    if not pkg_init.exists():
        pkg_init.parent.mkdir(parents=True, exist_ok=True)
        pkg_init.write_text("")
    return Project(tmp_path, package=package, extra_roots=())


def rules_of(findings) -> set:
    return {f.rule for f in findings}


# -- baseline / mini-toml -----------------------------------------------------


class TestBaseline:
    def test_parse_entries(self):
        entries = parse_mini_toml(
            '# comment\n'
            '[[suppress]]\n'
            'pass = "lock-order"\n'
            'rule = "blocking-under-lock"\n'
            'file = "pkg/mod.py"\n'
            'line = 12\n'
            'reason = "documented false positive"\n'
        )
        assert len(entries) == 1
        assert entries[0]["pass"] == "lock-order"
        assert entries[0]["line"] == 12

    def test_reason_required(self):
        with pytest.raises(BaselineError, match="reason"):
            Baseline(parse_mini_toml('[[suppress]]\nrule = "tabs"\n'))

    def test_inline_comment_after_quoted_value(self):
        entries = parse_mini_toml(
            '[[suppress]]\n'
            'rule = "host-sync"  # documented FP\n'
            'line = 3  # pinned\n'
            'reason = "detail with a # inside"\n'
        )
        assert entries[0]["rule"] == "host-sync"
        assert entries[0]["line"] == 3
        assert entries[0]["reason"] == "detail with a # inside"

    def test_garbage_after_quoted_value_rejected(self):
        with pytest.raises(BaselineError, match="trailing"):
            parse_mini_toml('[[suppress]]\nrule = "x" junk\n')

    def test_match_and_unused(self):
        baseline = Baseline(parse_mini_toml(
            '[[suppress]]\nrule = "tabs"\nfile = "a.py"\nreason = "r"\n'
            '[[suppress]]\nrule = "long-line"\nfile = "b.py"\nreason = "r"\n'
        ))
        hit = Finding("a.py", 3, "tabs", "use spaces", "hygiene")
        miss = Finding("a.py", 3, "trailing-ws", "x", "hygiene")
        kept, suppressed = apply_baseline([hit, miss], baseline)
        assert [f.rule for f in kept] == ["trailing-ws"]
        assert suppressed[0][1] == "r"
        assert [e["rule"] for e in baseline.unused()] == ["long-line"]

    def test_repo_baseline_parses_with_reasons(self):
        path = REPO / "karpenter_core_tpu" / "analysis" / "baseline.toml"
        baseline = Baseline.load(path)
        for entry in baseline.entries:
            assert str(entry.get("reason", "")).strip()


# -- hygiene ------------------------------------------------------------------


class TestHygiene:
    def test_ported_rules_fire(self, tmp_path):
        project = make_project(tmp_path, {
            "badpkg/mod.py": """\
                import os
                import sys

                def f(x=[]):
                    try:
                        return sys.argv
                    except:
                        return f"no field"
            """,
        })
        found = hygiene.run(project)
        assert {"unused-import", "bare-except", "mutable-default",
                "f-string-no-field"} <= rules_of(found)
        # the unused import is os, not sys (used in body)
        unused = [f for f in found if f.rule == "unused-import"]
        assert len(unused) == 1 and "os" in unused[0].detail

    def test_formatting_rules(self, tmp_path):
        src = "x = 1 \ny\t= 2\nz = '" + "a" * 130 + "'\n"
        path = tmp_path / "badpkg" / "fmt.py"
        path.parent.mkdir(parents=True)
        path.write_text(src)
        (tmp_path / "badpkg" / "__init__.py").write_text("")
        found = hygiene.run(Project(tmp_path, package="badpkg", extra_roots=()))
        assert {"trailing-ws", "tabs", "long-line"} <= rules_of(found)

    def test_assert_in_package(self, tmp_path):
        project = make_project(tmp_path, {
            "badpkg/mod.py": "def f(x):\n    assert x > 0\n    return x\n",
            # the test-harness subtree is exempt
            "badpkg/testing/helper.py": "def g(x):\n    assert x\n    return x\n",
        })
        found = [f for f in hygiene.run(project) if f.rule == "assert-in-package"]
        assert len(found) == 1
        assert found[0].path == "badpkg/mod.py"

    def test_wallclock_in_clocked_dirs_only(self, tmp_path):
        project = make_project(tmp_path, {
            "badpkg/state/cache.py": """\
                import time

                def now():
                    return time.time()
            """,
            # tracing-style modules outside the reconcile world may read wall
            "badpkg/tracing/span.py": """\
                import time

                def wall():
                    return time.time()
            """,
        })
        found = [f for f in hygiene.run(project) if f.rule == "wallclock"]
        assert len(found) == 1
        assert found[0].path == "badpkg/state/cache.py"

    def test_wallclock_covers_soak(self, tmp_path):
        """soak/ is clock-disciplined: probes and traces live on the
        FakeClock timeline, and a stray wall read would silently break
        verdict seed-replay (ISSUE 6 soak_hygiene satellite)."""
        project = make_project(tmp_path, {
            "badpkg/soak/probe.py": """\
                import time

                def sample():
                    return time.time()
            """,
        })
        found = [f for f in hygiene.run(project) if f.rule == "wallclock"]
        assert len(found) == 1
        assert found[0].path == "badpkg/soak/probe.py"

    def test_clean_module_silent(self, tmp_path):
        project = make_project(tmp_path, {
            "badpkg/ok.py": """\
                import sys

                def f(x=None):
                    if x is None:
                        x = []
                    return (sys.argv, x)
            """,
        })
        assert hygiene.run(project) == []

    def test_current_tree_clean(self, repo_project):
        """The repo itself stays hygiene-clean after the checked-in baseline
        (the make-verify contract) — the only raw findings allowed are the
        documented per-pod-loop suppressions."""
        from karpenter_core_tpu.analysis.core import Baseline, apply_baseline

        baseline = Baseline.load(
            Path(__file__).resolve().parents[1]
            / "karpenter_core_tpu" / "analysis" / "baseline.toml"
        )
        found = hygiene.run(repo_project)
        assert {f.rule for f in found} <= {"per-pod-loop"}, "\n".join(
            f.render() for f in found
        )
        kept, _suppressed = apply_baseline(found, baseline)
        assert kept == [], "\n".join(f.render() for f in kept)

    def test_per_pod_loop_flags_encode_hot_path_only(self, tmp_path):
        project = make_project(tmp_path, {
            "badpkg/models/columnar.py": """\
                def ingest(pods):
                    out = []
                    for pod in pods:
                        out.append(pod)
                    return out
            """,
            # pod loops OUTSIDE the encode hot path are not this rule's
            # business (the controllers legitimately iterate batches)
            "badpkg/controllers/thing.py": """\
                def count(pods):
                    return len([p for p in pods])
            """,
        })
        found = [f for f in hygiene.run(project) if f.rule == "per-pod-loop"]
        assert len(found) == 1
        assert found[0].path == "badpkg/models/columnar.py"
        assert found[0].symbol == "ingest"

    def test_per_pod_loop_comprehensions_and_attributes(self, tmp_path):
        """Comprehensions count, and so do pod-collection ATTRIBUTES
        (slot.pods.values()) — the loop shape doesn't matter, the O(pods)
        body does; the symbol carries the method qualname so baseline
        entries survive line churn."""
        project = make_project(tmp_path, {
            "badpkg/models/snapshot.py": """\
                class Encoder:
                    def walk(self, slot):
                        return [p.uid for p in slot.pods.values()]
            """,
        })
        found = [f for f in hygiene.run(project) if f.rule == "per-pod-loop"]
        assert len(found) == 1
        assert found[0].symbol == "Encoder.walk"

    def test_per_pod_loop_clean_class_loops_silent(self, tmp_path):
        """Loops over CLASSES (the O(distinct shapes) solve-path unit) never
        trip the rule — only pod collections do."""
        project = make_project(tmp_path, {
            "badpkg/models/snapshot.py": """\
                def encode(classes):
                    return [c.requirements for c in classes]
            """,
        })
        assert [f for f in hygiene.run(project) if f.rule == "per-pod-loop"] == []


# -- trace safety -------------------------------------------------------------

_TRACE_BAD = """\
    import jax
    import jax.numpy as jnp
    import numpy as np

    def helper(x):
        return x.item()

    @jax.jit
    def kernel(x):
        total = jnp.sum(x)
        if jnp.any(x > 0):
            pass
        print("tracing")
        y = np.asarray(total)
        return float(total) + helper(x) + y
"""

_TRACE_GOOD = """\
    import jax
    import jax.numpy as jnp
    import numpy as np

    def host_decode(out):
        # host-side decode: syncs are fine, this is not jit-reachable
        return float(np.asarray(out).sum())

    @jax.jit
    def kernel(x, v=3):
        vocab = jnp.asarray(np.arange(4))  # static host data: constant-folds
        return jnp.where(x > 0, x, 0.0) + vocab[v]
"""


class TestTraceSafety:
    def test_bad_kernel_fires_every_rule(self, tmp_path):
        project = make_project(tmp_path, {"badpkg/ops.py": _TRACE_BAD})
        found = trace_safety.run(project)
        assert {"host-sync", "trace-branch", "host-effect"} <= rules_of(found)
        # reachability: helper's .item() is found through the call edge
        helper_hits = [f for f in found if f.symbol == "helper"]
        assert helper_hits and helper_hits[0].rule == "host-sync"
        # exact anchoring: float(total) on the tainted sum
        casts = [f for f in found if "float()" in f.detail]
        assert casts and casts[0].path == "badpkg/ops.py"

    def test_good_kernel_silent(self, tmp_path):
        project = make_project(tmp_path, {"badpkg/ops.py": _TRACE_GOOD})
        assert trace_safety.run(project) == []

    def test_try_in_trace(self, tmp_path):
        project = make_project(tmp_path, {
            "badpkg/ops.py": """\
                import jax
                import jax.numpy as jnp

                @jax.jit
                def kernel(x):
                    try:
                        return jnp.sum(x)
                    except ValueError:
                        return x
            """,
        })
        assert "try-in-trace" in rules_of(trace_safety.run(project))

    def test_lambda_and_scan_step_reachable(self, tmp_path):
        project = make_project(tmp_path, {
            "badpkg/ops.py": """\
                import jax
                import jax.numpy as jnp

                def step(carry, x):
                    jnp.asarray(x).tolist()
                    return carry, x

                def core(xs):
                    return jax.lax.scan(step, 0, xs)

                solve = jax.jit(lambda xs: core(xs))
            """,
        })
        found = trace_safety.run(project)
        assert any(f.rule == "host-sync" and f.symbol == "step" for f in found)

    def test_current_tree_clean(self, repo_project):
        found = trace_safety.run(repo_project)
        assert found == [], "\n".join(f.render() for f in found)

    def test_shard_map_body_reachable(self, tmp_path):
        """A host sync inside a shard_map body is a static-gate failure even
        when the body never appears at a jax.jit site — sharded bodies seed
        the same reachability as jitted ones (ISSUE 10)."""
        project = make_project(tmp_path, {
            "badpkg/ops.py": """\
                import jax
                import jax.numpy as jnp
                from jax.experimental.shard_map import shard_map

                def body(x):
                    v = jnp.sum(x)
                    return float(v)  # host sync inside the sharded body

                def dispatch(mesh, specs, x):
                    return shard_map(
                        body, mesh=mesh, in_specs=specs, out_specs=specs
                    )(x)
            """,
        })
        found = trace_safety.run(project)
        assert "host-sync" in rules_of(found)
        assert any(f.symbol == "body" or "body" in f.detail for f in found)

    def test_shard_map_decorator_spelling_reachable(self, tmp_path):
        project = make_project(tmp_path, {
            "badpkg/ops.py": """\
                import functools

                import jax
                import jax.numpy as jnp
                from jax.experimental.shard_map import shard_map

                MESH = None

                @functools.partial(shard_map, mesh=MESH, in_specs=(), out_specs=())
                def body(x):
                    if jnp.sum(x) > 0:  # trace-branch inside sharded body
                        return x
                    return x + 1
            """,
        })
        assert "trace-branch" in rules_of(trace_safety.run(project))


# -- retrace budget (static) --------------------------------------------------

_CC_FIXTURE = """\
    import threading

    _lock = threading.Lock()
    _memo = {}

    def solve_callable(cls, n_slots, key_has_bounds, n_passes=1):
        key = (n_slots, tuple(key_has_bounds), n_passes, _leaf_sig(cls))
        return _memo.get(key)

    def _leaf_sig(tree):
        return ()
"""


class TestRetraceBudgetStatic:
    def test_missing_static_flagged(self, tmp_path):
        project = make_project(tmp_path, {
            "badpkg/utils/compilecache.py": _CC_FIXTURE,
            "badpkg/ops/solve.py": """\
                import functools

                import jax

                def solve_core(cls, n_slots, key_has_bounds, n_passes=1):
                    return cls

                _solve_jit = functools.partial(
                    jax.jit, static_argnames=("n_slots", "key_has_bounds")
                )(solve_core)
            """,
        })
        found = retrace_budget.run(project)
        missing = [f for f in found if f.rule == "static-args"]
        assert len(missing) == 1 and "'n_passes'" in missing[0].detail

    def test_consistent_site_silent(self, tmp_path):
        project = make_project(tmp_path, {
            "badpkg/utils/compilecache.py": _CC_FIXTURE,
            "badpkg/ops/solve.py": """\
                import functools

                import jax

                def solve_core(cls, n_slots, key_has_bounds, n_passes=1):
                    return cls

                _solve_jit = functools.partial(
                    jax.jit,
                    static_argnames=("n_slots", "key_has_bounds", "n_passes"),
                )(solve_core)
            """,
        })
        assert retrace_budget.run(project) == []

    def test_cache_key_drift_flagged(self, tmp_path):
        # n_passes is a solve_callable param but NOT in its key tuple:
        # declaring it static at a solve_core site must flag the drift
        project = make_project(tmp_path, {
            "badpkg/utils/compilecache.py": """\
                _memo = {}

                def solve_callable(cls, n_slots, key_has_bounds, n_passes=1):
                    key = (n_slots, tuple(key_has_bounds), _leaf_sig(cls))
                    return _memo.get(key)

                def _leaf_sig(tree):
                    return ()
            """,
            "badpkg/ops/solve.py": """\
                import functools

                import jax

                def solve_core(cls, n_slots, key_has_bounds, n_passes=1):
                    return cls

                _solve_jit = functools.partial(
                    jax.jit,
                    static_argnames=("n_slots", "key_has_bounds", "n_passes"),
                )(solve_core)
            """,
        })
        found = retrace_budget.run(project)
        drift = [f for f in found if f.rule == "cache-key-drift"]
        assert drift and "'n_passes'" in drift[0].detail

    def test_unknown_and_unhashable_static(self, tmp_path):
        project = make_project(tmp_path, {
            "badpkg/ops/solve.py": """\
                import jax

                def core(x, cfg=None):
                    return x

                wrapped = jax.jit(core, static_argnames=("cfg", "typo"))

                def caller(x):
                    return wrapped(x, cfg={"a": 1})
            """,
        })
        found = retrace_budget.run(project)
        assert "unknown-static" in rules_of(found)
        unhashable = [f for f in found if f.rule == "unhashable-static"]
        assert unhashable and "'cfg'" in unhashable[0].detail

    def test_uncached_jit_flagged_and_lru_exempt(self, tmp_path):
        project = make_project(tmp_path, {
            "badpkg/ops/solve.py": """\
                import functools

                import jax

                def hot(x):
                    return jax.jit(lambda v: v + 1)(x)

                @functools.lru_cache(maxsize=8)
                def builder(n):
                    return jax.jit(lambda v: v + n)
            """,
        })
        found = [f for f in retrace_budget.run(project)
                 if f.rule == "uncached-jit"]
        assert len(found) == 1 and found[0].symbol == "hot"

    def test_non_literal_static_flagged(self, tmp_path):
        project = make_project(tmp_path, {
            "badpkg/ops/solve.py": """\
                import jax

                NAMES = ("n",)

                def core(x, n=1):
                    return x

                wrapped = jax.jit(core, static_argnames=NAMES)
            """,
        })
        assert "non-literal-static" in rules_of(retrace_budget.run(project))

    def test_uncached_shard_map_flagged_and_lru_exempt(self, tmp_path):
        """shard_map constructed per call retraces exactly like per-call
        jax.jit; a memoized builder whose mesh derives from its parameters
        is the sanctioned shape (parallel.mesh pattern)."""
        project = make_project(tmp_path, {
            "badpkg/ops/solve.py": """\
                import functools

                import jax
                from jax.experimental.shard_map import shard_map

                def body(x):
                    return x

                def hot(mesh, specs, x):
                    return shard_map(
                        body, mesh=mesh, in_specs=specs, out_specs=specs
                    )(x)

                @functools.lru_cache(maxsize=8)
                def builder(mesh_axes, specs):
                    mesh = mesh_for(mesh_axes)
                    return jax.jit(shard_map(
                        body, mesh=mesh, in_specs=specs, out_specs=specs
                    ))

                def mesh_for(axes):
                    return axes
            """,
        })
        found = [f for f in retrace_budget.run(project)
                 if f.rule == "uncached-jit" and "shard_map" in f.detail]
        assert len(found) == 1 and found[0].symbol == "hot"

    def test_unkeyed_mesh_static_flagged(self, tmp_path):
        """A memoized builder whose shard_map captures a module-global mesh
        shares ONE cached executable across topologies — the sharded twin of
        cache-key-drift."""
        project = make_project(tmp_path, {
            "badpkg/ops/solve.py": """\
                import functools

                import jax
                from jax.experimental.shard_map import shard_map

                MESH = object()

                def body(x):
                    return x

                @functools.lru_cache(maxsize=8)
                def builder(specs):
                    return jax.jit(shard_map(
                        body, mesh=MESH, in_specs=specs, out_specs=specs
                    ))
            """,
        })
        found = [f for f in retrace_budget.run(project)
                 if f.rule == "unkeyed-mesh-static"]
        assert len(found) == 1 and found[0].symbol == "builder"

    def test_mesh_derived_from_params_silent(self, tmp_path):
        project = make_project(tmp_path, {
            "badpkg/ops/solve.py": """\
                import functools

                import jax
                from jax.experimental.shard_map import shard_map

                def body(x):
                    return x

                def mesh_for(axes):
                    return axes

                @functools.lru_cache(maxsize=8)
                def builder(mesh_axes, specs):
                    mesh = mesh_for(mesh_axes)
                    return jax.jit(shard_map(
                        body, mesh=mesh, in_specs=specs, out_specs=specs
                    ))
            """,
        })
        assert "unkeyed-mesh-static" not in rules_of(retrace_budget.run(project))

    def test_donated_read_flagged(self, tmp_path):
        """Reading a buffer after the dispatch that donated it — both the
        ``warm_carry=`` kwarg spelling and the ``*_donated`` helper
        convention (first positional argument)."""
        project = make_project(tmp_path, {
            "badpkg/solver/loop.py": """\
                def tick(solver, prep, carry, counts, plan):
                    out = solver.run_prepared(
                        prep, count=counts, warm_carry=carry, repair_plan=plan
                    )
                    return out, carry  # read after donation

                def free(repair_free_donated, carry, f):
                    freed = repair_free_donated(carry, f)
                    stale = carry.state  # read after donation
                    return freed, stale
            """,
        })
        found = [f for f in retrace_budget.run(project)
                 if f.rule == "donated-read"]
        assert len(found) == 2
        assert "'carry'" in found[0].detail and "'carry'" in found[1].detail

    def test_donated_read_rebind_and_branches_silent(self, tmp_path):
        """The intended idioms stay silent: rebinding the name to the
        dispatch's output clears the taint, and a donation inside one
        if-arm does not taint the sibling arm (it taints the code AFTER
        the branch)."""
        project = make_project(tmp_path, {
            "badpkg/solver/loop.py": """\
                def rebind(repair_free_donated, carry, f):
                    carry = repair_free_donated(carry, f)
                    return carry.state  # the OUTPUT: fine

                def branches(solver, prep, counts, carry, win_carry, plan):
                    if win_carry is not None:
                        keep = carry
                        out = solver.run_prepared(
                            prep, count=counts, warm_carry=win_carry,
                            repair_plan=plan,
                        )
                    else:
                        out = solver.run_prepared(
                            prep, count=counts, warm_carry=carry,
                            repair_plan=plan,
                        )
                    return out, keep  # keep bound BEFORE the donation
            """,
        })
        assert "donated-read" not in rules_of(retrace_budget.run(project))

    def test_donated_read_after_merged_branches_flagged(self, tmp_path):
        """Code AFTER an if/else inherits either arm's donations: a read of
        the else-arm's donated carry past the join is flagged."""
        project = make_project(tmp_path, {
            "badpkg/solver/loop.py": """\
                def tick(solver, prep, counts, carry, windowed, plan):
                    if windowed:
                        out = solver.run_prepared(prep, count=counts)
                    else:
                        out = solver.run_prepared(
                            prep, count=counts, warm_carry=carry,
                            repair_plan=plan,
                        )
                    return out, carry  # may read the donated buffer
            """,
        })
        found = [f for f in retrace_budget.run(project)
                 if f.rule == "donated-read"]
        assert len(found) == 1 and "'carry'" in found[0].detail

    def test_current_tree_only_baselined_findings(self, repo_project,
                                                  repo_baseline):
        kept, _ = apply_baseline(retrace_budget.run(repo_project), repo_baseline)
        assert kept == [], "\n".join(f.render() for f in kept)


# -- lock order ---------------------------------------------------------------

_ABBA = """\
    import threading

    lock_a = threading.Lock()
    lock_b = threading.Lock()

    def forward():
        with lock_a:
            with lock_b:
                return 1

    def backward():
        with lock_b:
            with lock_a:
                return 2
"""


class TestLockOrder:
    def test_abba_inversion(self, tmp_path):
        project = make_project(tmp_path, {"badpkg/locks.py": _ABBA})
        found = lock_order.run(project)
        inversions = [f for f in found if f.rule == "lock-order"]
        assert inversions, found
        assert "lock_a" in inversions[0].detail and "lock_b" in inversions[0].detail

    def test_abba_through_call_chain(self, tmp_path):
        # the synthetic deadlock graph: f holds A and calls g (which takes
        # B); h holds B and calls k (which takes A) — the inversion is only
        # visible interprocedurally
        project = make_project(tmp_path, {
            "badpkg/locks.py": """\
                import threading

                lock_a = threading.Lock()
                lock_b = threading.Lock()

                def takes_b():
                    with lock_b:
                        return 1

                def takes_a():
                    with lock_a:
                        return 2

                def f():
                    with lock_a:
                        return takes_b()

                def h():
                    with lock_b:
                        return takes_a()
            """,
        })
        found = lock_order.run(project)
        assert any(f.rule == "lock-order" for f in found), found

    def test_consistent_order_silent(self, tmp_path):
        project = make_project(tmp_path, {
            "badpkg/locks.py": """\
                import threading

                lock_a = threading.Lock()
                lock_b = threading.Lock()

                def one():
                    with lock_a:
                        with lock_b:
                            return 1

                def two():
                    with lock_a:
                        with lock_b:
                            return 2
            """,
        })
        assert [f for f in lock_order.run(project) if f.rule == "lock-order"] == []

    def test_blocking_under_lock(self, tmp_path):
        project = make_project(tmp_path, {
            "badpkg/mod.py": """\
                import subprocess
                import threading
                import time

                _lock = threading.Lock()

                def build():
                    with _lock:
                        subprocess.run(["make"])

                def nap_free():
                    time.sleep(0.1)  # no lock held: fine
                    with _lock:
                        return 1
            """,
        })
        found = [f for f in lock_order.run(project)
                 if f.rule == "blocking-under-lock"]
        assert len(found) == 1 and found[0].symbol == "build"

    def test_blocking_through_callee(self, tmp_path):
        project = make_project(tmp_path, {
            "badpkg/mod.py": """\
                import threading
                import time

                _lock = threading.Lock()

                def slow():
                    time.sleep(1.0)

                def holder():
                    with _lock:
                        slow()
            """,
        })
        found = [f for f in lock_order.run(project)
                 if f.rule == "blocking-under-lock"]
        assert found and found[0].symbol == "holder"

    def test_blocking_method_through_callee(self, tmp_path):
        # factoring a .result()/.wait() into a helper must not defeat the gate
        project = make_project(tmp_path, {
            "badpkg/mod.py": """\
                import threading

                _lock = threading.Lock()

                def helper(fut):
                    return fut.result()

                def holder(fut):
                    with _lock:
                        return helper(fut)
            """,
        })
        found = [f for f in lock_order.run(project)
                 if f.rule == "blocking-under-lock"]
        assert found and found[0].symbol == "holder", found

    def test_thread_join_under_lock_flagged(self, tmp_path):
        project = make_project(tmp_path, {
            "badpkg/mod.py": """\
                import threading

                _lock = threading.Lock()

                def stop(worker, parts):
                    with _lock:
                        label = ", ".join(parts)  # str.join: not a stall
                        worker.join(timeout=5)
                        return label
            """,
        })
        found = [f for f in lock_order.run(project)
                 if f.rule == "blocking-under-lock"]
        assert len(found) == 1 and ".join()" in found[0].detail, found

    def test_defining_sleeping_closure_not_blocking(self, tmp_path):
        # DEFINING a closure that sleeps is not sleeping: registering delayed
        # callbacks under a lock must stay clean
        project = make_project(tmp_path, {
            "badpkg/mod.py": """\
                import threading
                import time

                _lock = threading.Lock()

                def makes_closure():
                    def callback():
                        time.sleep(5.0)
                    return callback

                def holder():
                    with _lock:
                        return makes_closure()
            """,
        })
        found = [f for f in lock_order.run(project)
                 if f.rule == "blocking-under-lock"]
        assert found == [], found

    def test_self_deadlock_plain_lock_only(self, tmp_path):
        project = make_project(tmp_path, {
            "badpkg/mod.py": """\
                import threading

                class Plain:
                    def __init__(self):
                        self._mu = threading.Lock()

                    def outer(self):
                        with self._mu:
                            return self.inner()

                    def inner(self):
                        with self._mu:
                            return 1

                class Reentrant:
                    def __init__(self):
                        self._mu = threading.RLock()

                    def outer(self):
                        with self._mu:
                            return self.inner()

                    def inner(self):
                        with self._mu:
                            return 1
            """,
        })
        found = [f for f in lock_order.run(project) if f.rule == "self-deadlock"]
        assert len(found) == 1 and "Plain" in found[0].symbol

    def test_lock_no_with(self, tmp_path):
        project = make_project(tmp_path, {
            "badpkg/mod.py": """\
                import threading

                _lock = threading.Lock()

                def f():
                    _lock.acquire()
                    try:
                        return 1
                    finally:
                        _lock.release()
            """,
        })
        found = [f for f in lock_order.run(project) if f.rule == "lock-no-with"]
        assert len(found) == 2  # the acquire and the release

    def test_current_tree_clean(self, repo_project, repo_baseline):
        kept, _ = apply_baseline(lock_order.run(repo_project), repo_baseline)
        assert kept == [], "\n".join(f.render() for f in kept)


# -- instrumented -------------------------------------------------------------


class TestInstrumented:
    def test_uninstrumented_controller_fires(self, tmp_path):
        project = make_project(tmp_path, {
            "badpkg/controllers/thing.py": """\
                class ThingController:
                    name = "thing"

                    def reconcile(self, obj):
                        return None
            """,
        })
        found = instrumented.run(project)
        assert rules_of(found) == {"uninstrumented-reconcile"}

    def test_span_and_traced_accepted(self, tmp_path):
        project = make_project(tmp_path, {
            "badpkg/controllers/thing.py": """\
                from badpkg import tracing

                class WithSpan:
                    name = "a"

                    def reconcile(self, obj):
                        with tracing.span("a.reconcile"):
                            return None

                class WithDecorator:
                    name = "b"

                    @tracing.traced("b.reconcile")
                    def reconcile(self, obj):
                        return None
            """,
        })
        assert instrumented.run(project) == []


# -- the driver (acceptance demonstration) ------------------------------------

_SEEDED_HOST_SYNC = """\
    import jax
    import jax.numpy as jnp

    @jax.jit
    def kernel(x):
        return float(jnp.sum(x))
"""

_SEEDED_STATIC_MISMATCH = """\
    import functools

    import jax

    def solve_core(cls, n_slots, key_has_bounds, n_passes=1):
        return cls

    _solve_jit = functools.partial(
        jax.jit, static_argnames=("n_slots",)
    )(solve_core)
"""


def run_driver(root: Path, *extra: str):
    return subprocess.run(
        [sys.executable, str(REPO / "tools" / "kcanalyze.py"),
         "--root", str(root), "--package", "badpkg", *extra],
        capture_output=True, text=True, timeout=120,
    )


class TestDriver:
    def test_seeded_tree_fails_with_all_three(self, tmp_path):
        """One host-sync + one static-arg mismatch + one lock inversion in a
        temp tree: the driver (hence `make verify`) exits nonzero and names
        each — introducing any of the three bug classes breaks the build."""
        make_project(tmp_path, {
            "badpkg/ops/kernel.py": _SEEDED_HOST_SYNC,
            "badpkg/ops/solve.py": _SEEDED_STATIC_MISMATCH,
            "badpkg/utils/compilecache.py": _CC_FIXTURE,
            "badpkg/locks.py": _ABBA,
        })
        proc = run_driver(tmp_path)
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "trace-safety/host-sync" in proc.stdout
        assert "retrace-budget/static-args" in proc.stdout
        assert "lock-order/lock-order" in proc.stdout
        assert "FAIL" in proc.stdout

    def test_clean_tree_passes_with_timing(self, tmp_path):
        make_project(tmp_path, {
            "badpkg/ok.py": "def f(x):\n    return x\n",
        })
        proc = run_driver(tmp_path)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "OK" in proc.stdout
        # the satellite contract: a timing line for the suite
        assert "in " in proc.stdout and "s" in proc.stdout
        assert any("pass trace-safety" in ln for ln in proc.stdout.splitlines())

    def test_baseline_suppresses_documented_finding(self, tmp_path):
        make_project(tmp_path, {
            "badpkg/locks.py": _ABBA,
            "badpkg/analysis/baseline.toml": """\
                [[suppress]]
                pass = "lock-order"
                rule = "lock-order"
                file = "badpkg/locks.py"
                reason = "fixture: inversion is intentional in this test tree"
            """,
        })
        proc = run_driver(tmp_path)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "1 suppressed" in proc.stdout

    def test_baseline_without_reason_hard_fails(self, tmp_path):
        make_project(tmp_path, {
            "badpkg/ok.py": "def f(x):\n    return x\n",
            "badpkg/analysis/baseline.toml": (
                '[[suppress]]\nrule = "tabs"\n'
            ),
        })
        proc = run_driver(tmp_path)
        assert proc.returncode == 1
        assert "bad baseline" in proc.stderr

    def test_syntax_error_is_a_finding(self, tmp_path):
        make_project(tmp_path, {
            "badpkg/broken.py": "def f(:\n",
        })
        proc = run_driver(tmp_path)
        assert proc.returncode == 1
        assert "syntax-error" in proc.stdout

    @pytest.mark.slow
    def test_repo_tree_passes(self):
        """`python tools/kcanalyze.py` exits 0 on the final tree (the same
        invocation `make verify` gates on; slow tier because it re-parses
        the whole repo in a subprocess — the in-process current-tree tests
        above cover each pass inside the tier-1 budget)."""
        proc = subprocess.run(
            [sys.executable, str(REPO / "tools" / "kcanalyze.py")],
            capture_output=True, text=True, timeout=300, cwd=str(REPO),
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "OK" in proc.stdout


# -- retrace budget (runtime manifest) ----------------------------------------


class TestRetraceManifest:
    def test_manifest_shape(self):
        manifest = json.loads(MANIFEST_PATH.read_text())
        assert int(manifest["default_budget"]) > 0
        assert int(manifest["bench_cold_compiles"]) > 0
        assert isinstance(manifest.get("tests", {}), dict)
        for nodeid, budget in manifest.get("tests", {}).items():
            assert nodeid.startswith("tests/"), nodeid
            assert int(budget) > 0

    def test_fixture_fails_over_budget(self, monkeypatch):
        """Drive the conftest fixture by hand: a test that 'compiles' more
        than its budget must fail with the retrace message."""
        import conftest

        monkeypatch.setitem(conftest._MANIFEST, "tests", {})
        monkeypatch.setitem(conftest._MANIFEST, "default_budget", 2)
        monkeypatch.delenv("KC_RETRACE_BUDGET", raising=False)
        monkeypatch.delenv("KC_RETRACE_RECORD", raising=False)

        class _Node:
            nodeid = "tests/test_fake.py::test_over"

        class _Request:
            node = _Node()

        gen = conftest._retrace_budget.__wrapped__(_Request())
        next(gen)
        conftest._compile_count["n"] += 3  # over the budget of 2
        with pytest.raises(pytest.fail.Exception, match="retrace budget"):
            next(gen)

    def test_fixture_passes_within_budget(self, monkeypatch):
        import conftest

        monkeypatch.setitem(conftest._MANIFEST, "tests", {})
        monkeypatch.setitem(conftest._MANIFEST, "default_budget", 10)
        monkeypatch.delenv("KC_RETRACE_RECORD", raising=False)

        class _Node:
            nodeid = "tests/test_fake.py::test_ok"

        class _Request:
            node = _Node()

        gen = conftest._retrace_budget.__wrapped__(_Request())
        next(gen)
        conftest._compile_count["n"] += 1
        with pytest.raises(StopIteration):
            next(gen)

    @pytest.mark.slow
    def test_manifest_matches_reality_on_representative_tests(self, tmp_path):
        """Run two representative tier-1 tests in a fresh process with
        recording on: the observed compile counts must fit the manifest
        (and the budgeted run itself must pass)."""
        record = tmp_path / "counts.jsonl"
        targets = [
            "tests/test_masks.py::test_intersects_parity",
            "tests/test_masks.py::test_add_then_check_parity",
        ]
        env = dict(os.environ)
        env.update(JAX_PLATFORMS="cpu", KC_RETRACE_RECORD=str(record))
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
             *targets],
            capture_output=True, text=True, timeout=600, env=env,
            cwd=str(REPO),
        )
        assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr[-2000:]
        manifest = json.loads(MANIFEST_PATH.read_text())
        default = int(manifest["default_budget"])
        per_test = manifest.get("tests", {})
        rows = [json.loads(ln) for ln in record.read_text().splitlines()]
        assert rows, "recording produced no rows"
        for row in rows:
            budget = int(per_test.get(row["test"], default))
            assert row["compiles"] <= budget, (
                f"{row['test']}: {row['compiles']} compiles > budget {budget}"
            )


class TestChaosHygienePass:
    """chaos-hygiene: point registration uniqueness + the determinism gate."""

    def _run(self, tmp_path, files):
        from karpenter_core_tpu.analysis.passes import chaos_hygiene

        return chaos_hygiene.run(make_project(tmp_path, files))

    def test_duplicate_registration_fires_at_both_sites(self, tmp_path):
        found = self._run(tmp_path, {
            "badpkg/a.py": textwrap.dedent("""
                from karpenter_core_tpu import chaos
                P = chaos.point("cloud.create")
            """),
            "badpkg/b.py": textwrap.dedent("""
                from karpenter_core_tpu import chaos
                Q = chaos.point("cloud.create")
            """),
        })
        dups = [f for f in found if f.rule == "point-duplicate"]
        assert len(dups) == 2
        assert {f.path for f in dups} == {"badpkg/a.py", "badpkg/b.py"}

    def test_nonliteral_point_name_fires(self, tmp_path):
        found = self._run(tmp_path, {
            "badpkg/a.py": textwrap.dedent("""
                from karpenter_core_tpu import chaos
                NAME = "computed"
                P = chaos.point(NAME)
            """),
        })
        assert rules_of(found) == {"point-nonliteral"}

    def test_random_import_in_production_module_fires(self, tmp_path):
        found = self._run(tmp_path, {
            "badpkg/logic.py": "import random\nx = 1\n",
            "badpkg/sec.py": "import secrets\ny = 2\n",
        })
        rules = [(f.path, f.rule) for f in found]
        assert ("badpkg/logic.py", "nondeterminism") in rules
        assert ("badpkg/sec.py", "nondeterminism") in rules

    def test_chaos_subtree_is_exempt(self, tmp_path):
        found = self._run(tmp_path, {
            "badpkg/chaos/__init__.py": "",
            "badpkg/chaos/scenario.py": "import random\nx = 1\n",
        })
        assert found == []

    def test_unique_registrations_and_rng_are_clean(self, tmp_path):
        found = self._run(tmp_path, {
            "badpkg/a.py": textwrap.dedent("""
                from karpenter_core_tpu import chaos
                P = chaos.point("kubeapi.put")
                Q = chaos.point("cloud.create")
            """),
        })
        assert found == []

    def test_current_tree_clean(self, repo_project):
        from karpenter_core_tpu.analysis.passes import chaos_hygiene

        assert chaos_hygiene.run(repo_project) == []


class TestUnboundedBlock:
    """The unbounded-block pass (ISSUE 15): blocking device calls in the
    device-path subtrees must route through utils/watchdog — raw spellings
    are findings, monitored spellings are clean."""

    def _run(self, tmp_path, files):
        return unbounded_block.run(make_project(tmp_path, files))

    def test_raw_device_get_in_ops_is_flagged(self, tmp_path):
        found = self._run(tmp_path, {
            "badpkg/ops/kernel.py": textwrap.dedent("""
                import jax

                def fetch(outputs):
                    return jax.device_get(outputs)

                def sync(outputs):
                    jax.block_until_ready(outputs)

                def retire(handle):
                    return handle.result()
            """),
        })
        rules = sorted((f.path, f.symbol) for f in found)
        assert rules == [
            ("badpkg/ops/kernel.py", "fetch"),
            ("badpkg/ops/kernel.py", "retire"),
            ("badpkg/ops/kernel.py", "sync"),
        ]

    def test_monitored_spellings_are_clean(self, tmp_path):
        found = self._run(tmp_path, {
            "badpkg/ops/kernel.py": textwrap.dedent("""
                import jax
                from karpenter_core_tpu.utils import watchdog

                def fetch(outputs):
                    # the callable-argument shape: no raw Call node at all
                    return watchdog.run("site", jax.device_get, outputs)

                def fetch_lambda(outputs):
                    # lexically inside the monitored call expression
                    return watchdog.run("site", lambda: jax.device_get(outputs))

                def fetch_instance(outputs):
                    from karpenter_core_tpu.utils.watchdog import MonitoredDispatch
                    return MonitoredDispatch("site").run(
                        lambda: jax.device_get(outputs)
                    )
            """),
        })
        assert found == []

    def test_unrelated_run_receiver_is_not_a_monitored_scope(self, tmp_path):
        """A generic ``something_dispatch.run(...)`` must NOT exempt the
        blocking calls nested inside it — only watchdog/MonitoredDispatch
        receivers are monitored scopes."""
        found = self._run(tmp_path, {
            "badpkg/ops/kernel.py": textwrap.dedent("""
                import jax

                def sneak(batch_dispatch, outputs):
                    return batch_dispatch.run(jax.device_get(outputs))
            """),
        })
        assert [f.symbol for f in found] == ["sneak"]

    def test_unwatched_subtrees_and_watchdog_module_exempt(self, tmp_path):
        found = self._run(tmp_path, {
            "badpkg/controllers/loop.py": textwrap.dedent("""
                import jax

                def fetch(outputs):
                    return jax.device_get(outputs)
            """),
            "badpkg/utils/watchdog.py": textwrap.dedent("""
                def run(site, fn, *args):
                    return fn(*args)

                def wait(job):
                    return job.result()
            """),
        })
        assert found == []

    def test_current_tree_only_baselined_sites(self, repo_project):
        from karpenter_core_tpu.analysis.core import Baseline, apply_baseline

        baseline = Baseline.load(
            REPO / "karpenter_core_tpu" / "analysis" / "baseline.toml"
        )
        kept, _suppressed = apply_baseline(
            unbounded_block.run(repo_project), baseline
        )
        assert kept == [], [f.render() for f in kept]


# -- shared-state (lockset inference) -----------------------------------------


class TestSharedState:
    """shared-state: per-method lockset inference over lock-owning classes in
    the concurrency-bearing subtrees.  Fragments live under badpkg/service/
    because the pass scopes itself to the subtrees that actually run
    threaded (service/, fleet/, state/, solver/incremental.py,
    utils/compilecache.py)."""

    def _run(self, tmp_path, files):
        from karpenter_core_tpu.analysis.passes import shared_state

        return shared_state.run(make_project(tmp_path, files))

    def test_unguarded_field_fires_at_the_lock_free_site(self, tmp_path):
        found = self._run(tmp_path, {
            "badpkg/service/plane.py": """
                import threading

                class Plane:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self.count = 0

                    def bump(self):
                        with self._lock:
                            self.count += 1

                    def reset(self):
                        self.count = 0
            """,
        })
        assert rules_of(found) == {"unguarded-field"}
        (f,) = found
        assert f.symbol == "Plane.reset"
        assert "count" in f.detail

    def test_two_lock_field_is_mixed_guard(self, tmp_path):
        found = self._run(tmp_path, {
            "badpkg/service/plane.py": """
                import threading

                class Plane:
                    def __init__(self):
                        self._a = threading.Lock()
                        self._b = threading.Lock()
                        self.val = 0

                    def left(self):
                        with self._a:
                            self.val += 1

                    def right(self):
                        with self._b:
                            self.val += 1
            """,
        })
        assert rules_of(found) == {"mixed-guard"}
        assert "no single lock" in found[0].detail

    def test_init_only_field_is_silent(self, tmp_path):
        """Escape analysis: a field written only during __init__ (before the
        object is reachable by any other thread) and read lock-free after
        publication is the immutable-config idiom, not a race."""
        found = self._run(tmp_path, {
            "badpkg/service/plane.py": """
                import threading

                class Plane:
                    def __init__(self, cfg):
                        self._lock = threading.Lock()
                        self.cfg = dict(cfg)
                        self.hits = 0

                    def lookup(self, key):
                        return self.cfg[key]

                    def record(self):
                        with self._lock:
                            self.hits += 1
            """,
        })
        assert found == []

    def test_thread_target_makes_private_method_reachable(self, tmp_path):
        """A private method is exempt until something makes it a thread
        entry point: Thread(target=self._pump) seeds it, so its lock-free
        write fires; the never-referenced private twin stays silent."""
        found = self._run(tmp_path, {
            "badpkg/service/plane.py": """
                import threading

                class Plane:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self.jobs = 0

                    def start(self):
                        t = threading.Thread(target=self._pump)
                        t.start()

                    def submit(self):
                        with self._lock:
                            self.jobs += 1

                    def _pump(self):
                        self.jobs = 0

                    def _never_called(self):
                        self.jobs = -1
            """,
        })
        assert rules_of(found) == {"unguarded-field"}
        assert [f.symbol for f in found] == ["Plane._pump"]

    def test_lock_free_container_swap_is_unlocked_publication(self, tmp_path):
        found = self._run(tmp_path, {
            "badpkg/service/plane.py": """
                import threading

                class Plane:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self.items = []

                    def add(self, x):
                        with self._lock:
                            self.items.append(x)

                    def clear_all(self):
                        self.items = []
            """,
        })
        assert rules_of(found) == {"unlocked-publication"}
        assert found[0].symbol == "Plane.clear_all"

    def test_helper_inherits_caller_lockset(self, tmp_path):
        """Interprocedural half: a private helper called only from inside
        ``with self._lock:`` runs with that lockset, so its accesses are
        guarded even though the helper itself names no lock."""
        found = self._run(tmp_path, {
            "badpkg/service/plane.py": """
                import threading

                class Plane:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self.count = 0

                    def bump(self):
                        with self._lock:
                            self._bump_locked()

                    def drain(self):
                        with self._lock:
                            self._bump_locked()

                    def _bump_locked(self):
                        self.count += 1
            """,
        })
        assert found == []

    def test_out_of_scope_subtree_is_exempt(self, tmp_path):
        """The same unguarded pattern outside the concurrency-bearing
        subtrees (a controller that runs single-threaded) is not scanned."""
        found = self._run(tmp_path, {
            "badpkg/controllers/loop.py": """
                import threading

                class Loop:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self.count = 0

                    def bump(self):
                        with self._lock:
                            self.count += 1

                    def reset(self):
                        self.count = 0
            """,
        })
        assert found == []

    def test_current_tree_clean(self, repo_project):
        from karpenter_core_tpu.analysis.passes import shared_state

        kept = shared_state.run(repo_project)
        assert kept == [], [f.render() for f in kept]


# -- env-flags (KC_* registry) ------------------------------------------------


class TestEnvFlags:
    """env-flags: every KC_* environment read must appear in the central
    registry (utils/flags.py FLAGS) and the docs table (docs/FLAGS.md);
    registry rows nothing reads are dead."""

    def _run(self, tmp_path, files):
        from karpenter_core_tpu.analysis.passes import env_flags

        return env_flags.run(make_project(tmp_path, files))

    _REGISTRY = """
        FLAGS = {
            "KC_RATE": "per-tenant refill rate",
        }
    """

    def test_unregistered_read_fires_at_the_read_site(self, tmp_path):
        found = self._run(tmp_path, {
            "badpkg/svc.py": """
                import os
                TIMEOUT = os.getenv("KC_TIMEOUT_S", "5")
            """,
            "badpkg/utils/flags.py": self._REGISTRY,
        })
        rules = [(f.path, f.rule) for f in found]
        assert ("badpkg/svc.py", "unregistered-read") in rules
        assert any("KC_TIMEOUT_S" in f.detail for f in found)

    def test_dead_entry_and_undocumented_fire_at_the_registry_row(
            self, tmp_path):
        found = self._run(tmp_path, {
            "badpkg/utils/flags.py": self._REGISTRY,
        })
        rules = rules_of(found)
        assert "dead-entry" in rules          # nothing reads KC_RATE
        assert "undocumented-flag" in rules   # no docs/FLAGS.md row
        assert all(f.path == "badpkg/utils/flags.py" for f in found)

    def test_registered_documented_read_is_clean(self, tmp_path):
        project = make_project(tmp_path, {
            "badpkg/svc.py": """
                import os
                RATE = os.getenv("KC_RATE", "1")
            """,
            "badpkg/utils/flags.py": self._REGISTRY,
        })
        docs = tmp_path / "docs" / "FLAGS.md"
        docs.parent.mkdir(parents=True, exist_ok=True)
        docs.write_text("| `KC_RATE` | refill rate |\n")
        from karpenter_core_tpu.analysis.passes import env_flags

        assert env_flags.run(project) == []

    def test_helper_indirection_counts_as_a_read(self, tmp_path):
        """Reads through a local env helper (``_env("KC_X")`` where the
        helper's parameter flows into os.getenv) must resolve, in both
        directions: the flag is not dead, and an unregistered flag read
        through the helper still fires."""
        found = self._run(tmp_path, {
            "badpkg/cfg.py": """
                import os

                def _env(name, default=""):
                    return os.getenv(name, default)

                RATE = _env("KC_RATE")
                BURST = _env("KC_BURST")
            """,
            "badpkg/utils/flags.py": self._REGISTRY,
        })
        rules = [(f.rule, f.detail.split()[0]) for f in found]
        assert ("unregistered-read", "KC_BURST") in rules
        assert ("dead-entry", "registry") not in [
            (r, d) for r, d in rules if "KC_RATE" in d
        ]

    def test_environ_subscript_and_membership_are_reads(self, tmp_path):
        found = self._run(tmp_path, {
            "badpkg/svc.py": """
                import os
                A = os.environ["KC_A"]
                B = "KC_B" in os.environ
            """,
            "badpkg/utils/flags.py": self._REGISTRY,
        })
        flagged = {f.detail.split()[0] for f in found
                   if f.rule == "unregistered-read"}
        assert flagged == {"KC_A", "KC_B"}

    def test_current_tree_clean(self, repo_project):
        from karpenter_core_tpu.analysis.passes import env_flags

        kept = env_flags.run(repo_project)
        assert kept == [], [f.render() for f in kept]


# -- driver: --json and --strict ----------------------------------------------


class TestDriverJsonStrict:
    def test_json_report_on_clean_tree(self, tmp_path):
        make_project(tmp_path, {
            "badpkg/ok.py": "def f(x):\n    return x\n",
        })
        proc = run_driver(tmp_path, "--json")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        report = json.loads(proc.stdout)  # stdout must be pure JSON
        assert report["ok"] is True
        assert report["findings"] == []
        assert report["total_s"] > 0
        names = {p["name"] for p in report["passes"]}
        assert {"shared-state", "env-flags", "lock-order"} <= names
        for p in report["passes"]:
            assert p["seconds"] >= 0

    def test_json_report_carries_findings(self, tmp_path):
        make_project(tmp_path, {
            "badpkg/service/plane.py": textwrap.dedent("""
                import threading

                class Plane:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self.count = 0

                    def bump(self):
                        with self._lock:
                            self.count += 1

                    def reset(self):
                        self.count = 0
            """),
        })
        proc = run_driver(tmp_path, "--json")
        assert proc.returncode == 1
        report = json.loads(proc.stdout)
        assert report["ok"] is False
        assert any(
            f["pass"] == "shared-state" and f["rule"] == "unguarded-field"
            for f in report["findings"]
        )

    def test_strict_turns_unused_baseline_into_failure(self, tmp_path):
        files = {
            "badpkg/ok.py": "def f(x):\n    return x\n",
            "badpkg/analysis/baseline.toml": """\
                [[suppress]]
                pass = "hygiene"
                rule = "tabs"
                file = "badpkg/gone.py"
                reason = "stale: the offending file was deleted"
            """,
        }
        make_project(tmp_path, files)
        lax = run_driver(tmp_path)
        assert lax.returncode == 0, lax.stdout + lax.stderr
        assert "WARNING unused baseline entry" in lax.stderr
        strict = run_driver(tmp_path, "--strict")
        assert strict.returncode == 1, strict.stdout + strict.stderr
        assert "ERROR unused baseline entry" in strict.stderr
        assert "FAIL" in strict.stdout
