"""Vocabulary demand/supply split (models/vocab.py).

The kernel's mask compute is quadratic in the widest key, so supply-side
label families (catalog labels no requirement references — e.g. the fake
provider's per-instance ``integer`` label, fake.go's integer instance label
analog) must not define vocabulary keys; they may only widen the value lists
of keys the demand side (pods/classes, provisioner templates) references.
An unreferenced key can never deny compatibility: both denial paths (empty
intersection; the custom-key denied-if-undefined rule, requirements.go:115-131)
require the demand side to carry the key.
"""

from karpenter_core_tpu.models.vocab import Vocabulary
from karpenter_core_tpu.scheduling import Requirement, Requirements
from karpenter_core_tpu.apis.objects import OP_IN, OP_GT, OP_NOT_IN


def reqs(*rs: Requirement) -> Requirements:
    return Requirements(*rs)


class TestDemandSupplySplit:
    def test_supply_only_keys_are_excluded(self):
        demand = [reqs(Requirement("team", OP_IN, ["a"]))]
        supply = [
            reqs(Requirement("integer", OP_IN, [str(i)])) for i in range(100)
        ]
        v = Vocabulary.build(demand, supply_sets=supply)
        assert v.keys == ["team"]
        assert v.width == 2  # one value + other slot, not 101

    def test_supply_widens_demand_referenced_keys(self):
        # NotIn/Gt exactness needs node/catalog values representable once the
        # demand side references the key
        demand = [reqs(Requirement("integer", OP_GT, ["30"]))]
        supply = [reqs(Requirement("integer", OP_IN, [str(i)])) for i in (10, 40)]
        v = Vocabulary.build(demand, supply_sets=supply)
        assert v.keys == ["integer"]
        assert set(v.values["integer"]) >= {"10", "40"}

    def test_demand_values_always_present(self):
        demand = [reqs(Requirement("size", OP_NOT_IN, ["small"]))]
        v = Vocabulary.build(demand, supply_sets=[])
        assert v.values["size"] == ["small"]

    def test_templates_count_as_demand(self):
        # encode_snapshot passes template requirements as demand: a
        # provisioner restricting a custom key keeps that key exact
        from karpenter_core_tpu.cloudprovider import fake as fake_cp
        from karpenter_core_tpu.testing import make_pod, make_provisioner
        from karpenter_core_tpu.solver.tpu import TPUSolver

        provider = fake_cp.FakeCloudProvider(fake_cp.instance_types(30))
        solver = TPUSolver(provider, [make_provisioner()])
        snapshot = solver.encode([make_pod(requests={"cpu": "1"})])
        # the catalog's per-instance integer label must not define a key
        assert "integer" not in snapshot.vocab.keys
        assert snapshot.vocab.width <= 8
