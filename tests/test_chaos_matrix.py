"""The chaos acceptance harness: seeded fault scenarios + terminal invariants.

Every scenario drives the controller stack while the chaos plane injects
faults, then asserts the convergence contract once faults stop:

  - zero pending pods (everything schedulable scheduled)
  - zero machine leaks (every cloud machine maps to a live node object)
  - bounded reconcile rounds (no controller hot-loops)

and, because scenarios are seeded, that the SAME seed replays the SAME fault
schedule.  A small deterministic subset runs in tier-1; the randomized
matrix is ``slow``-marked (`make chaos` runs the tier-1 subset).
"""

import pytest

from karpenter_core_tpu import chaos
from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.controllers import provisioning as prov_mod
from karpenter_core_tpu.controllers.deprovisioning import (
    DEGRADED_PAUSES,
    Result,
)
from karpenter_core_tpu.testing import harness
from karpenter_core_tpu.testing.factories import make_pod, make_pods, make_provisioner
from karpenter_core_tpu.testing.harness import (
    expect_provisioned,
    machine_leaks,
    make_environment,
    pending_pods,
    step_scheduling_round,
)
from karpenter_core_tpu.utils import retry


# -- terminal invariants -------------------------------------------------------


def assert_no_machine_leaks(env):
    """Every machine alive at the provider must be a live node object —
    anything else is a stranded cloud instance nothing will ever delete."""
    leaked = machine_leaks(env)
    assert not leaked, f"leaked machines (no node object): {leaked}"


def drive_until_converged(env, max_rounds=20):
    """Provisioning loop + kube-scheduler/kubelet emulation until no pending
    pods remain; the round bound IS the no-hot-loop invariant.  Injected
    kubeapi faults landing on the emulation's own writes are retried next
    round, exactly as the real binder/kubelet would."""
    for round_no in range(1, max_rounds + 1):
        step_scheduling_round(env)
        if not pending_pods(env):
            return round_no
    raise AssertionError(
        f"no convergence in {max_rounds} rounds; "
        f"pending={[p.name for p in pending_pods(env)]}"
    )


def seeded_env():
    env = make_environment()
    env.kube.create(make_provisioner())
    return env


# -- scenario determinism ------------------------------------------------------


class TestScenarioDeterminism:
    def test_same_seed_same_schedule(self):
        mk = lambda: chaos.Scenario.from_dict({
            "name": "det", "seed": 421,
            "points": {"kubeapi.put": {"prob": 0.31, "code": 500}},
        })
        a, b = mk(), mk()
        assert (
            a.fault_schedule("kubeapi.put", 500)
            == b.fault_schedule("kubeapi.put", 500)
        )
        assert a.fault_schedule("kubeapi.put", 500)  # non-empty at p=.31

    def test_different_seeds_differ(self):
        a = chaos.Scenario("d", 1, {"p": chaos.PointSpec(prob=0.5)})
        b = chaos.Scenario("d", 2, {"p": chaos.PointSpec(prob=0.5)})
        assert a.fault_schedule("p", 200) != b.fault_schedule("p", 200)

    def test_explicit_schedule_and_first_n(self):
        s = chaos.Scenario("s", 0, {
            "a": chaos.PointSpec(schedule=[1, 3]),
            "b": chaos.PointSpec(first_n=2),
        })
        assert s.fault_schedule("a", 6) == [1, 3]
        assert s.fault_schedule("b", 6) == [0, 1]

    def test_stop_after_bounds_fired_faults(self):
        s = chaos.Scenario("s", 0, {"p": chaos.PointSpec(first_n=10, stop_after=3)})
        faults = [s.decide("p") for _ in range(10)]
        assert sum(1 for f in faults if f is not None) == 3

    def test_unsupported_kind_is_discarded_before_counting(self):
        """A scenario kind the call site cannot act on must not be reported
        as injected — kind="partial" on kubeapi.put injects nothing, so the
        metric, fired count, and span event all stay silent (the hit index
        still advances: schedule determinism is index-pure)."""
        from karpenter_core_tpu.chaos.plane import CHAOS_FAULTS_INJECTED

        env = seeded_env()
        s = chaos.Scenario("mis", 1, {
            "kubeapi.put": chaos.PointSpec(first_n=5, kind="partial"),
        })
        before = CHAOS_FAULTS_INJECTED.labels("kubeapi.put", "partial").value
        with chaos.armed(s, env.clock):
            env.kube.create(make_pod(name="px"))  # put succeeds, no fault
        assert s.hit_counts().get("kubeapi.put", 0) >= 1
        assert s.fired_counts() == {}
        assert CHAOS_FAULTS_INJECTED.labels("kubeapi.put", "partial").value == before

    def test_latency_without_armed_clock_not_counted(self):
        """arm() without a clock cannot apply latency faults; they must be
        dropped uncounted rather than reported as injected no-ops."""
        env = seeded_env()
        s = chaos.Scenario("lat", 1, {
            "kubeapi.put": chaos.PointSpec(first_n=5, kind="latency", delay_s=9.0),
        })
        with chaos.armed(s):  # no clock
            env.kube.create(make_pod(name="py"))
        assert s.fired_counts() == {}

    def test_first_class_knob_failure_not_attributed_to_chaos(self):
        """When a first-class fake-provider knob (capacity_errors) already
        fails the create, an armed cloud.create fault must not fire for it:
        the knob's error won, so counting the injection would misattribute
        the failure in the audit."""
        from karpenter_core_tpu.cloudprovider.types import (
            InsufficientCapacityError,
            TransientCloudError,
        )

        env = seeded_env()
        it = env.provider.get_instance_types(None)[0]
        env.provider.capacity_errors[it.name] = 1
        s = chaos.Scenario("knob", 1, {
            "cloud.create": chaos.PointSpec(first_n=10, kind="error"),
        })
        with chaos.armed(s, env.clock):
            with pytest.raises(InsufficientCapacityError):
                env.provider._check_create_faults(it)
            assert s.fired_counts() == {}  # the knob failed it, not chaos
            with pytest.raises(TransientCloudError):  # knob spent: chaos fires
                env.provider._check_create_faults(it)
            assert s.fired_counts() == {"cloud.create": 1}

    def test_toml_hash_inside_quoted_string_survives(self):
        s = chaos.Scenario.from_toml(
            '[scenario]\nname = "h"\nseed = 1\n'
            '[points."cloud.create"]\n'
            'first_n = 1  # trailing comment still stripped\n'
            'message = "quota #429 exceeded"\n'
        )
        assert s.points["cloud.create"].message == "quota #429 exceeded"
        assert s.points["cloud.create"].first_n == 1

    def test_toml_round_trip_matches_dict(self):
        toml = '''
        [scenario]
        name = "flake"
        seed = 77

        [points."kubeapi.put"]
        prob = 0.25
        kind = "error"
        code = 500
        stop_after = 4

        [points."cloud.create"]
        first_n = 2
        message = "insufficient capacity"
        '''
        s = chaos.Scenario.from_toml(toml)
        d = chaos.Scenario.from_dict({
            "name": "flake", "seed": 77,
            "points": {
                "kubeapi.put": {"prob": 0.25, "kind": "error", "code": 500,
                                "stop_after": 4},
                "cloud.create": {"first_n": 2,
                                 "message": "insufficient capacity"},
            },
        })
        assert s.name == d.name and s.seed == d.seed
        for point in ("kubeapi.put", "cloud.create"):
            assert s.fault_schedule(point, 100) == d.fault_schedule(point, 100)

    def test_unknown_fault_kind_rejected(self):
        with pytest.raises(ValueError):
            chaos.PointSpec(kind="explode")

    def test_duplicate_registration_raises(self):
        with pytest.raises(ValueError):
            chaos.point("kubeapi.put")  # already registered by the package


# -- plane mechanics -----------------------------------------------------------


class TestPlaneMechanics:
    def test_unarmed_points_are_noops(self):
        env = seeded_env()
        env.kube.create(make_pod(name="calm"))  # no scenario: no faults
        assert env.kube.get_pod("default", "calm") is not None

    def test_injected_faults_are_counted_and_visible_on_metrics(self):
        from karpenter_core_tpu.metrics import REGISTRY

        env = seeded_env()
        counter = chaos.CHAOS_FAULTS_INJECTED.labels("kubeapi.put", "error")
        before = counter.value
        scenario = chaos.Scenario("count", 1, {
            "kubeapi.put": chaos.PointSpec(first_n=1, code=500),
        })
        with chaos.armed(scenario, env.clock):
            with pytest.raises(chaos.InjectedFault):
                env.kube.create(make_pod(name="doomed"))
        assert counter.value == before + 1
        assert "karpenter_chaos_faults_injected_total" in REGISTRY.render()

    def test_conflict_code_maps_to_conflict_error(self):
        from karpenter_core_tpu.operator.kubeclient import ConflictError

        env = seeded_env()
        scenario = chaos.Scenario("conflict", 1, {
            "kubeapi.put": chaos.PointSpec(first_n=1, code=409),
        })
        with chaos.armed(scenario, env.clock):
            with pytest.raises(ConflictError):
                env.kube.create(make_pod(name="cas-victim"))

    def test_latency_fault_sleeps_through_the_armed_clock(self):
        env = seeded_env()
        scenario = chaos.Scenario("lag", 1, {
            "kubeapi.put": chaos.PointSpec(first_n=1, kind="latency", delay_s=7.5),
        })
        t0 = env.clock.now()
        with chaos.armed(scenario, env.clock):
            env.kube.create(make_pod(name="slow-but-fine"))
        assert env.clock.now() - t0 == pytest.approx(7.5)
        assert env.kube.get_pod("default", "slow-but-fine") is not None


# -- the convergence matrix (tier-1 deterministic subset) ----------------------


class TestConvergenceScenarios:
    def test_apiserver_flaking_during_provisioning(self):
        """kubeapi 500s hit the launch path's node create; the machine is
        compensated (no leak), the requeue retries, everything schedules."""
        env = seeded_env()
        for pod in make_pods(3, requests={"cpu": "100m"}):
            env.kube.create(pod)
        scenario = chaos.Scenario("apiserver-flake", 7, {
            "kubeapi.put": chaos.PointSpec(first_n=2, code=500, stop_after=2),
        })
        with chaos.armed(scenario, env.clock):
            rounds = drive_until_converged(env)
        assert rounds <= 6
        assert_no_machine_leaks(env)
        assert not pending_pods(env)
        # the compensation path actually fired: cloud creates outnumber nodes
        assert len(env.provider.create_calls) > len(env.kube.list_nodes())
        assert scenario.fired_counts().get("kubeapi.put") == 2

    def test_cloud_create_fails_n_times_then_succeeds(self):
        env = seeded_env()
        for pod in make_pods(2, requests={"cpu": "100m"}):
            env.kube.create(pod)
        scenario = chaos.Scenario("cloud-flake", 11, {
            "cloud.create": chaos.PointSpec(first_n=2, stop_after=2),
        })
        with chaos.armed(scenario, env.clock):
            rounds = drive_until_converged(env)
        assert rounds <= 6
        assert_no_machine_leaks(env)
        assert not pending_pods(env)
        assert scenario.fired_counts().get("cloud.create") == 2

    def test_insufficient_capacity_is_a_first_class_failure_mode(self):
        """The provider-layer negative test PR 2's launch-retry never had:
        per-instance-type ICE, then capacity returns and the retry lands."""
        env = seeded_env()
        # the cheapest compatible type for this pod — the one create() picks
        env.provider.capacity_errors["small-instance-type"] = 1
        pod = make_pod(requests={"cpu": "100m"})
        env.kube.create(pod)
        err = env.provisioning.reconcile(wait_for_batch=False)
        assert err is not None and "insufficient capacity" in err
        assert env.kube.list_nodes() == []
        # capacity returns (the single ICE is consumed): the requeue lands
        rounds = drive_until_converged(env)
        assert rounds <= 3
        assert_no_machine_leaks(env)

    def test_transient_cloud_error_then_recovery(self):
        env = seeded_env()
        env.provider.transient_create_failures = 1
        env.kube.create(make_pod(requests={"cpu": "100m"}))
        err = env.provisioning.reconcile(wait_for_batch=False)
        assert err is not None and "transient" in err
        rounds = drive_until_converged(env)
        assert rounds <= 3
        assert_no_machine_leaks(env)

    def test_stillborn_create_never_registers_and_is_flagged(self):
        """create succeeds but the node never registers: the kubelet
        emulation skips it, it never initializes, and the inflight checks
        surface it — the machine is visible, not silently lost."""
        from karpenter_core_tpu.controllers.inflightchecks import (
            InflightChecksController,
        )

        env = seeded_env()
        scenario = chaos.Scenario("stillborn", 3, {
            "cloud.create": chaos.PointSpec(first_n=1, kind="partial"),
        })
        pod = make_pod(requests={"cpu": "100m"})
        env.kube.create(pod)
        with chaos.armed(scenario, env.clock):
            env.provisioning.reconcile(wait_for_batch=False)
            env.make_all_nodes_ready()  # skips the stillborn machine
        node = env.kube.list_nodes()[0]
        assert node.spec.provider_id in env.provider.stillborn_ids
        assert node.metadata.labels.get(labels_api.LABEL_NODE_INITIALIZED) != "true"
        # an hour later the FailedInit inflight check calls it out
        checks = InflightChecksController(
            env.clock, env.kube, env.provider, env.recorder
        )
        env.clock.step(3601)
        env.recorder.reset()
        checks.reconcile_all()
        assert any(
            e.reason == "FailedInflightCheck" for e in env.recorder.events
        ), [e.reason for e in env.recorder.events]

    def test_solver_backend_dies_mid_batch_degraded_then_recovers(self):
        """The acceptance walk: solver.dispatch faults kill the backend mid
        batch -> breaker opens -> pods still schedule via the degraded host
        path (degraded=true surfaces) -> deprovisioning pauses -> half-open
        trial recovers the TPU path."""
        from karpenter_core_tpu.solver.scheduler import SchedulingResults

        env = seeded_env()
        env.provisioning.use_tpu_kernel = True
        env.provisioning.tpu_kernel_min_pods = 2
        degraded_before = prov_mod.DEGRADED_SOLVES.labels("provisioning").value
        fallback_before = prov_mod.TPU_KERNEL_FALLBACK.labels("degraded").value
        pause_before = DEGRADED_PAUSES.labels().value

        scenario = chaos.Scenario("solver-death", 13, {
            "solver.dispatch": chaos.PointSpec(first_n=8),
        })
        with chaos.armed(scenario, env.clock):
            # two faulted batches open the breaker; pods land on the host path
            for _ in range(prov_mod.TPU_KERNEL_MAX_FAILURES):
                pods = make_pods(2, requests={"cpu": "100m"})
                result = expect_provisioned(env, *pods)
                assert all(result[p.uid] is not None for p in pods)
            assert env.provisioning.solver_breaker.state == retry.OPEN
            assert env.provisioning.degraded() is True

            # degraded batch: solved on the host with degraded labels, the
            # dead backend untouched (hit counts stop growing)
            hits_before = scenario.hit_counts().get("solver.dispatch", 0)
            pods = make_pods(2, requests={"cpu": "100m"})
            result = expect_provisioned(env, *pods)
            assert all(result[p.uid] is not None for p in pods)
            assert scenario.hit_counts().get("solver.dispatch", 0) == hits_before
            assert (
                prov_mod.DEGRADED_SOLVES.labels("provisioning").value
                == degraded_before + 1
            )
            assert (
                prov_mod.TPU_KERNEL_FALLBACK.labels("degraded").value
                == fallback_before + 1
            )

            # deprovisioning is optional work: paused while degraded
            result, requeue = env.deprovisioning.reconcile()
            assert result == Result.NOTHING_TO_DO
            assert DEGRADED_PAUSES.labels().value == pause_before + 1

        # backend heals; the half-open trial closes the breaker
        env.clock.step(prov_mod.SOLVER_BREAKER_RESET_S + 1)
        assert env.provisioning.solver_breaker.state == retry.HALF_OPEN
        env.provisioning._schedule_tpu = lambda pods, state_nodes: SchedulingResults()
        expect_provisioned(env, *make_pods(2, requests={"cpu": "100m"}))
        assert env.provisioning.solver_breaker.state == retry.CLOSED
        assert env.provisioning.degraded() is False
        assert_no_machine_leaks(env)

    def test_flapping_solver_service_degraded_then_recovers(self, tmp_path,
                                                            monkeypatch):
        """The ``service.rpc`` chaos point flaps the gRPC channel (the one
        major I/O boundary the other six points don't cover): remote solves
        die at the transport, the controller's EXISTING solver breaker opens,
        degraded host solves keep the cluster converging, and the half-open
        trial restores the remote path once the flapping stops."""
        from karpenter_core_tpu.service.snapshot_channel import serve
        from karpenter_core_tpu.solver.scheduler import SchedulingResults

        monkeypatch.setenv("KC_LEASE_STATE", str(tmp_path / "leases.json"))
        env = seeded_env()
        env.provisioning.use_tpu_kernel = True
        env.provisioning.tpu_kernel_min_pods = 2
        server, port = serve(env.provider)
        env.provisioning.solver_endpoint = f"127.0.0.1:{port}"
        try:
            scenario = chaos.Scenario("service-flap", 17, {
                "service.rpc": chaos.PointSpec(first_n=8),
            })
            with chaos.armed(scenario, env.clock):
                # flapping channel: every remote attempt dies client-side,
                # the breaker counts each one, pods still land via fallback
                for _ in range(prov_mod.TPU_KERNEL_MAX_FAILURES):
                    pods = make_pods(2, requests={"cpu": "100m"})
                    result = expect_provisioned(env, *pods)
                    assert all(result[p.uid] is not None for p in pods)
                assert env.provisioning.solver_breaker.state == retry.OPEN
                assert env.provisioning.degraded() is True
                assert scenario.fired_counts().get("service.rpc", 0) >= 2
                # degraded batch never touches the flapping channel
                hits = scenario.hit_counts().get("service.rpc", 0)
                pods = make_pods(2, requests={"cpu": "100m"})
                result = expect_provisioned(env, *pods)
                assert all(result[p.uid] is not None for p in pods)
                assert scenario.hit_counts().get("service.rpc", 0) == hits
            # channel heals: half-open trial re-promotes the remote path
            env.clock.step(prov_mod.SOLVER_BREAKER_RESET_S + 1)
            assert env.provisioning.solver_breaker.state == retry.HALF_OPEN
            env.provisioning._schedule_tpu = (
                lambda pods, state_nodes: SchedulingResults()
            )
            expect_provisioned(env, *make_pods(2, requests={"cpu": "100m"}))
            assert env.provisioning.solver_breaker.state == retry.CLOSED
            assert env.provisioning.degraded() is False
            assert_no_machine_leaks(env)
        finally:
            server.stop(grace=0)

    def test_clock_skew_during_ttl_expiry(self):
        """Skewed clocks accelerate an emptiness TTL; the node deletes
        exactly once, and when the skew stops nothing re-fires."""
        env = make_environment()
        env.kube.create(make_provisioner(ttl_seconds_after_empty=300))
        pod = make_pod(requests={"cpu": "100m"})
        expect_provisioned(env, pod)
        env.make_all_nodes_ready()
        env.clock.step(21)  # past the nomination window
        env.kube.delete(env.kube.get_pod(pod.namespace, pod.name), force=True)
        env.node_lifecycle.reconcile_all()  # stamps the emptiness timestamp

        scenario = chaos.Scenario("skew", 17, {
            "clock.skew": chaos.PointSpec(kind="skew", delay_s=301.0),
        })
        skew_counter = chaos.CHAOS_FAULTS_INJECTED.labels("clock.skew", "skew")
        before = skew_counter.value
        with chaos.armed(scenario, env.clock):
            assert env.clock.now() == pytest.approx(1_000_021.0 + 301.0)
            result, _ = env.deprovisioning.reconcile()
            assert result == Result.SUCCESS
            assert env.kube.list_nodes() == []
        # skew is counted once, not once per clock read
        assert skew_counter.value == before + 1
        # faults stopped: the clock snaps back, nothing hot-loops
        assert env.clock.now() == pytest.approx(1_000_021.0, abs=20)
        result, _ = env.deprovisioning.reconcile()
        assert result == Result.NOTHING_TO_DO
        assert env.provider.created_machines() == []

    def test_watch_drops_and_410_mid_consolidation(self):
        """Apiserver backend: established watches drop AND history compacts
        (410) while an emptiness consolidation is due; the relist rebuilds
        state and the consolidation still lands — no leaked machine, no
        phantom node."""
        from karpenter_core_tpu.kubeapi.client import ApiServerClient
        from karpenter_core_tpu.testing.fakeapiserver import FakeApiServer

        server = FakeApiServer(bookmark_interval_s=0.2).start()
        try:
            env = make_environment(
                kube_factory=lambda clock: ApiServerClient(
                    server.url, clock, backoff_base_s=0.05, backoff_cap_s=0.5,
                    rng=retry.DeterministicRNG(23),
                )
            )
            env.kube.create(make_provisioner(consolidation_enabled=True))
            pod = make_pod(requests={"cpu": "100m"})
            expect_provisioned(env, pod)
            env.make_all_nodes_ready()
            env.clock.step(21)
            env.kube.delete(env.kube.get_pod(pod.namespace, pod.name), force=True)

            # mid-flight: drop every stream and compact history so resumes 410
            server.wait_for_watches(1)
            server.drop_watch_connections()
            server.compact()

            result, _ = env.deprovisioning.reconcile()
            assert result == Result.SUCCESS
            assert env.kube.list_nodes() == []
            assert env.provider.created_machines() == []
            env.kube.close()
        finally:
            server.stop()


# -- replay + the randomized matrix --------------------------------------------


def _run_flake_scenario(seed: int):
    env = seeded_env()
    for pod in make_pods(3, requests={"cpu": "100m"}):
        env.kube.create(pod)
    scenario = chaos.Scenario("replay", seed, {
        "kubeapi.put": chaos.PointSpec(prob=0.3, code=500, stop_after=3),
        "cloud.create": chaos.PointSpec(prob=0.3, stop_after=2),
    })
    with chaos.armed(scenario, env.clock):
        rounds = drive_until_converged(env, max_rounds=30)
    assert_no_machine_leaks(env)
    return {
        "rounds": rounds,
        "fired": scenario.fired_counts(),
        "hits": scenario.hit_counts(),
        "nodes": len(env.kube.list_nodes()),
        # pod/node names carry process-global counters; counts are the
        # run-invariant shape
        "bound": sum(1 for p in env.kube.list_pods() if p.spec.node_name),
    }


# -- the fleet routing leg (ISSUE-17, fleet/router.py, docs/FLEET.md) ----------


class TestFleetRouteMatrix:
    """The ``fleet.route`` leg of the matrix: injected forwarding faults PLUS
    a real replica eviction mid-stream — every routed tenant retries through
    to a structurally correct answer (zero cross-tenant wrong answers), and
    the evicted tenant resumes WARM on the adopting replica."""

    def _fleet(self, tmp_path):
        import os

        from karpenter_core_tpu.cloudprovider.fake import FakeCloudProvider
        from karpenter_core_tpu.fleet import FleetLocal, FleetMap
        from karpenter_core_tpu.fleet.router import serve_router
        from karpenter_core_tpu.service.snapshot_channel import (
            SnapshotSolverClient,
            serve,
        )
        from karpenter_core_tpu.service.tenant import TenantConfig

        directory = str(tmp_path / "fleet")
        config = TenantConfig(
            rate_per_s=1000.0, burst=1000, max_inflight=64,
            batch_window_s=0.0, max_batch=8,
        )
        servers, parts = {}, []
        for rid in ("r1", "r2"):
            fleet = FleetLocal(
                directory=directory, replica_id=rid,
                fleet_map=FleetMap.parse("r1=pending:0,r2=pending:0"),
                ckpt_every=1,
            )
            server, port = serve(
                FakeCloudProvider(), tenant_config=config, fleet=fleet,
                journal_dir=os.path.join(directory, "journals", rid),
            )
            servers[rid] = server
            parts.append(f"{rid}=127.0.0.1:{port}")
        router_fleet = FleetLocal(
            directory=directory,
            fleet_map=FleetMap.parse(",".join(parts)),
        )
        router_server, router_port = serve_router(
            router_fleet, tenant_config=config,
        )
        client = SnapshotSolverClient(f"127.0.0.1:{router_port}")
        return servers, router_server, client

    @staticmethod
    def _verify_accounting(resp, tenant_id, sent):
        """A wrong answer is any response that does not account for exactly
        this tenant's own classes (the cross-tenant contamination check)."""
        echo = resp["tenant"]
        assert echo["id"] == tenant_id, echo
        placed = [0] * len(sent)
        for node in resp.get("newNodes", []):
            for c, n in node.get("classCounts", []):
                assert 0 <= c < len(sent) and n >= 0, (c, n)
                placed[c] += n
        for counts in resp.get("existingAssignments", {}).values():
            for c, n in counts:
                assert 0 <= c < len(sent) and n >= 0, (c, n)
                placed[c] += n
        for bucket in ("failedClassCounts", "residualClassCounts"):
            for c, n in resp.get(bucket, []):
                assert 0 <= c < len(sent) and n >= 0, (c, n)
                placed[c] += n
        if echo.get("solveMode") == "full":
            assert placed == sent, (placed, sent)
        else:
            assert all(p <= s for p, s in zip(placed, sent)), (placed, sent)

    def test_routed_tenants_survive_eviction_and_route_faults(self, tmp_path):
        import grpc
        import msgpack

        servers, router_server, client = self._fleet(tmp_path)

        def solve(tid, count, version):
            """Retry through injected route faults, as real clients do."""
            for _ in range(10):
                try:
                    return client.solve_tenant_classes(
                        [(make_pod(requests={"cpu": "500m"}), count)],
                        [make_provisioner()],
                        tenant={"id": tid, "sessionVersion": version},
                    )
                except grpc.RpcError as e:
                    assert e.code() in (
                        grpc.StatusCode.UNAVAILABLE,
                        grpc.StatusCode.DEADLINE_EXCEEDED,
                    ), e
            raise AssertionError(f"{tid}: no convergence in 10 attempts")

        scenario = chaos.Scenario("fleet-evict", 29, {
            "fleet.route": chaos.PointSpec(prob=0.4, stop_after=3,
                                           kind="error"),
        })
        versions = {"acme": 0, "zeta": 0}
        recovered = {}
        try:
            with chaos.armed(scenario):
                for round_no in range(4):
                    for tid in versions:
                        count = 6 + 2 * round_no
                        resp = solve(tid, count, versions[tid])
                        self._verify_accounting(resp, tid, [count])
                        versions[tid] = resp["tenant"]["sessionVersion"]
                        if resp["tenant"].get("recovered"):
                            recovered[tid] = resp["tenant"]["recovered"]
                    if round_no == 1:
                        # mid-stream eviction: kill the replica holding acme
                        state = msgpack.unpackb(
                            client.channel.unary_unary(
                                "/karpenter.v1.SnapshotSolver/FleetState"
                            )(msgpack.packb({}))
                        )
                        holder = state["placements"]["acme"]
                        servers[holder].stop(grace=0)
                        servers[holder].kc_service.shutdown()
            # the injected faults actually fired, and the evicted tenant
            # came back WARM on the adopting replica — never a wrong answer
            assert scenario.fired_counts().get("fleet.route", 0) >= 1
            assert recovered.get("acme") == "warm", recovered
        finally:
            client.close()
            router_server.kc_router.close()
            router_server.stop(grace=0)
            for server in servers.values():
                server.stop(grace=0)
                try:
                    server.kc_service.shutdown()
                except Exception:  # noqa: BLE001 - already shut down
                    pass


class TestSeedReplay:
    def test_same_seed_reproduces_the_run(self):
        import os

        seed = int(os.environ.get("KC_CHAOS_SEED", "99"))  # `make chaos` pins it
        a = _run_flake_scenario(seed)
        b = _run_flake_scenario(seed)
        assert a == b

    @pytest.mark.slow
    def test_randomized_matrix(self):
        """The full randomized matrix: many seeds, probabilistic faults on
        several points at once, every run must converge leak-free."""
        for seed in range(20):
            result = _run_flake_scenario(seed)
            assert result["rounds"] <= 30
            assert result["nodes"] >= 1


# -- runtime lockset tracer (docs/CHAOS.md "Lockset tracing") ------------------


import threading

from karpenter_core_tpu.testing import lockcheck as lockcheck_mod
from karpenter_core_tpu.testing.lockcheck import LockCheck, LockCheckError


class _SeededPlane:
    """A deliberately racy fixture class: ``count`` is hammered lock-free by
    worker threads while ``guarded`` takes the lock — the tracer must flag
    exactly the former."""

    def __init__(self):
        self.lock = threading.Lock()
        self.count = 0
        self.guarded = 0


def _hammer(plane, racy_rounds=200):
    def racy():
        for _ in range(racy_rounds):
            plane.count = plane.count + 1

    def safe():
        for _ in range(racy_rounds):
            with plane.lock:
                plane.guarded = plane.guarded + 1

    threads = [threading.Thread(target=racy) for _ in range(2)]
    threads += [threading.Thread(target=safe) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


class TestLockcheck:
    """testing/lockcheck.py: the runtime half of the shared-state gate.  The
    static pass proves what it can see lexically; the tracer witnesses the
    callback/duck-typed paths it cannot, by recording (field, lockset)
    observations and failing on an empty intersection across a shared
    access pair."""

    def test_seeded_unguarded_write_is_caught(self):
        with LockCheck(watch=(_SeededPlane,)) as lc:
            _hammer(_SeededPlane())
        violations = lc.violations()
        assert any(v.fld == "count" for v in violations), violations
        bad = next(v for v in violations if v.fld == "count")
        assert bad.threads >= 2 and bad.writes > 0
        # the raw evidence: count was observed shared with an empty lockset
        obs = lc.observations()
        assert frozenset() in obs[("_SeededPlane", "count")]

    def test_guarded_twin_is_clean(self):
        with LockCheck(watch=(_SeededPlane,)) as lc:
            _hammer(_SeededPlane())
        assert not any(v.fld == "guarded" for v in lc.violations())

    def test_assert_clean_raises_naming_the_field(self):
        with LockCheck(watch=(_SeededPlane,)) as lc:
            _hammer(_SeededPlane())
        with pytest.raises(LockCheckError, match=r"_SeededPlane\.count"):
            lc.assert_clean()

    def test_single_thread_and_init_writes_never_report(self):
        """Thread-confined state and publish-once init writes are the
        runtime analogue of the static pass's init-only escape — silent."""
        with LockCheck(watch=(_SeededPlane,)) as lc:
            p = _SeededPlane()
            for _ in range(100):
                p.count = p.count + 1  # one thread only
        assert lc.violations() == []

    def test_factories_are_restored_on_exit(self):
        orig_lock, orig_rlock = threading.Lock, threading.RLock
        with LockCheck():
            assert threading.Lock is not orig_lock
        assert threading.Lock is orig_lock
        assert threading.RLock is orig_rlock

    def test_flake_scenario_under_tracer_opt_in(self):
        """The chaos matrix's KC_LOCKCHECK=1 opt-in: the seeded flake
        scenario replays under the tracer watching the tenant service
        classes, and must come back violation-free; without the env the
        same scenario runs untraced (the tier-1 default)."""
        if not lockcheck_mod.enabled():
            _run_flake_scenario(1729)
            return
        from karpenter_core_tpu.service.tenant import TenantEntry, TenantPlane

        with LockCheck(watch=(TenantPlane, TenantEntry)) as lc:
            _run_flake_scenario(1729)
        lc.assert_clean()
