"""Scenario tests for the greedy solver.

Coverage modeled on the reference suites
(/root/reference/pkg/controllers/provisioning/scheduling/{suite_test.go,
topology_test.go, instance_selection_test.go}) — resources, node affinity,
taints, host ports, topology spread, pod (anti-)affinity, relaxation, limits.
"""


from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_GT,
    OP_IN,
    OP_NOT_IN,
    LabelSelector,
    NodeSelectorRequirement,
    PodAffinityTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)
from karpenter_core_tpu.cloudprovider import fake as fake_cp
from karpenter_core_tpu.operator.kubeclient import KubeClient
from karpenter_core_tpu.solver.builder import build_scheduler
from karpenter_core_tpu.testing import make_pod, make_pods, make_provisioner

ZONE = labels_api.LABEL_TOPOLOGY_ZONE
HOSTNAME = labels_api.LABEL_HOSTNAME
CT = labels_api.LABEL_CAPACITY_TYPE


def solve(pods, provisioners=None, instance_types=None, state_nodes=None, daemonsets=None):
    kube = KubeClient()
    for p in provisioners or [make_provisioner()]:
        kube.create(p)
    provider = fake_cp.FakeCloudProvider(instance_types)
    scheduler = build_scheduler(
        kube,
        provider,
        cluster=None,
        pods=pods,
        state_nodes=state_nodes or [],
        daemonset_pods=daemonsets or [],
    )
    return scheduler.solve(pods)


def scheduled_count(results):
    return sum(len(n.pods) for n in results.new_nodes) + sum(
        len(n.pods) for n in results.existing_nodes
    )


class TestBasicScheduling:
    def test_single_pod_gets_a_node(self):
        results = solve([make_pod(requests={"cpu": 1})])
        assert len(results.new_nodes) == 1
        assert scheduled_count(results) == 1
        assert not results.failed_pods

    def test_pods_pack_onto_one_node(self):
        # 3 tiny pods fit one default (4-cpu, 5-pod) instance
        results = solve(make_pods(3, requests={"cpu": "500m"}))
        assert len(results.new_nodes) == 1
        assert scheduled_count(results) == 3

    def test_pod_count_limit_opens_new_nodes(self):
        # default instance types allow 5 pods per node
        results = solve(make_pods(6, requests={"cpu": "1m"}))
        assert scheduled_count(results) == 6
        assert len(results.new_nodes) == 2

    def test_huge_pod_fails(self):
        results = solve([make_pod(requests={"cpu": 1000})])
        assert results.failed_pods
        assert scheduled_count(results) == 0

    def test_gpu_pod_selects_gpu_instance(self):
        results = solve([make_pod(requests={fake_cp.RESOURCE_GPU_VENDOR_A: 1})])
        assert scheduled_count(results) == 1
        names = {it.name for it in results.new_nodes[0].instance_type_options}
        assert names == {"gpu-vendor-instance-type"}

    def test_different_resources_split_nodes(self):
        results = solve(
            [
                make_pod(requests={fake_cp.RESOURCE_GPU_VENDOR_A: 1}),
                make_pod(requests={fake_cp.RESOURCE_GPU_VENDOR_B: 1}),
            ]
        )
        assert scheduled_count(results) == 2
        assert len(results.new_nodes) == 2


class TestNodeAffinity:
    def test_node_selector(self):
        results = solve([make_pod(node_selector={ZONE: "test-zone-2"})])
        assert scheduled_count(results) == 1
        node = results.new_nodes[0]
        assert node.requirements.get(ZONE).values_list() == ["test-zone-2"]

    def test_node_selector_impossible_zone(self):
        results = solve([make_pod(node_selector={ZONE: "unknown-zone"})])
        assert scheduled_count(results) == 0

    def test_node_affinity_in(self):
        results = solve(
            [
                make_pod(
                    node_requirements=[
                        NodeSelectorRequirement(ZONE, OP_IN, ["test-zone-1", "test-zone-2"])
                    ]
                )
            ]
        )
        assert scheduled_count(results) == 1
        values = set(results.new_nodes[0].requirements.get(ZONE).values_list())
        assert values <= {"test-zone-1", "test-zone-2"}

    def test_node_affinity_not_in(self):
        results = solve(
            [make_pod(node_requirements=[NodeSelectorRequirement(ZONE, OP_NOT_IN, ["test-zone-1"])])]
        )
        assert scheduled_count(results) == 1
        assert not results.new_nodes[0].requirements.get(ZONE).has("test-zone-1")

    def test_custom_label_requires_provisioner_definition(self):
        # a pod requiring an undefined custom label cannot schedule
        results = solve([make_pod(node_requirements=[NodeSelectorRequirement("team", OP_IN, ["a"])])])
        assert scheduled_count(results) == 0
        # but schedules when the provisioner defines the label
        results = solve(
            [make_pod(node_requirements=[NodeSelectorRequirement("team", OP_IN, ["a"])])],
            provisioners=[
                make_provisioner(requirements=[NodeSelectorRequirement("team", OP_IN, ["a", "b"])])
            ],
        )
        assert scheduled_count(results) == 1

    def test_provisioner_requirements_constrain_pods(self):
        provisioner = make_provisioner(
            requirements=[NodeSelectorRequirement(ZONE, OP_IN, ["test-zone-1"])]
        )
        results = solve([make_pod(node_selector={ZONE: "test-zone-2"})], provisioners=[provisioner])
        assert scheduled_count(results) == 0

    def test_conflicting_pods_get_different_nodes(self):
        results = solve(
            [
                make_pod(node_selector={ZONE: "test-zone-1"}),
                make_pod(node_selector={ZONE: "test-zone-2"}),
            ]
        )
        assert scheduled_count(results) == 2
        assert len(results.new_nodes) == 2

    def test_gt_lt_operators(self):
        provisioner = make_provisioner(
            requirements=[
                NodeSelectorRequirement(fake_cp.INTEGER_INSTANCE_LABEL_KEY, OP_EXISTS)
            ]
        )
        results = solve(
            [
                make_pod(
                    node_requirements=[
                        NodeSelectorRequirement(fake_cp.INTEGER_INSTANCE_LABEL_KEY, OP_GT, ["8"])
                    ]
                )
            ],
            provisioners=[provisioner],
        )
        assert scheduled_count(results) == 1
        # only the 16-cpu arm instance has integer label > 8
        names = {it.name for it in results.new_nodes[0].instance_type_options}
        assert names == {"arm-instance-type"}


class TestTaints:
    def test_untolerated_taint_blocks(self):
        provisioner = make_provisioner(taints=[Taint("example.com/special", "true")])
        results = solve([make_pod()], provisioners=[provisioner])
        assert scheduled_count(results) == 0

    def test_tolerated_taint_schedules(self):
        provisioner = make_provisioner(taints=[Taint("example.com/special", "true")])
        pod = make_pod(
            tolerations=[Toleration(key="example.com/special", operator="Exists")]
        )
        results = solve([pod], provisioners=[provisioner])
        assert scheduled_count(results) == 1

    def test_exists_toleration_tolerates_all_of_key(self):
        provisioner = make_provisioner(taints=[Taint("k", "any-value")])
        pod = make_pod(tolerations=[Toleration(key="k", operator="Exists")])
        assert scheduled_count(solve([pod], provisioners=[provisioner])) == 1


class TestHostPorts:
    def test_conflicting_host_ports_split_nodes(self):
        results = solve(
            [make_pod(host_ports=[8080], requests={"cpu": "1m"}) for _ in range(2)]
        )
        assert scheduled_count(results) == 2
        assert len(results.new_nodes) == 2

    def test_distinct_host_ports_share_node(self):
        results = solve(
            [
                make_pod(host_ports=[8080], requests={"cpu": "1m"}),
                make_pod(host_ports=[8081], requests={"cpu": "1m"}),
            ]
        )
        assert scheduled_count(results) == 2
        assert len(results.new_nodes) == 1


class TestTopologySpread:
    def _spread_pods(self, n, key=ZONE, max_skew=1):
        return [
            make_pod(
                labels={"app": "web"},
                requests={"cpu": "10m"},
                topology_spread=[
                    TopologySpreadConstraint(
                        max_skew=max_skew,
                        topology_key=key,
                        label_selector=LabelSelector(match_labels={"app": "web"}),
                    )
                ],
            )
            for _ in range(n)
        ]

    def test_zonal_spread_balances(self):
        results = solve(self._spread_pods(9))
        assert scheduled_count(results) == 9
        counts = {}
        for node in results.new_nodes:
            zone = node.requirements.get(ZONE).values_list()[0]
            counts[zone] = counts.get(zone, 0) + len(node.pods)
        assert sorted(counts.values()) == [3, 3, 3]

    def test_hostname_spread_forces_nodes(self):
        results = solve(self._spread_pods(4, key=HOSTNAME))
        assert scheduled_count(results) == 4
        assert len(results.new_nodes) == 4

    def test_max_skew_2_allows_imbalance(self):
        results = solve(self._spread_pods(4, max_skew=2))
        assert scheduled_count(results) == 4
        counts = {}
        for node in results.new_nodes:
            zone = node.requirements.get(ZONE).values_list()[0]
            counts[zone] = counts.get(zone, 0) + len(node.pods)
        assert max(counts.values()) - min(counts.get(z, 0) for z in
                                          ["test-zone-1", "test-zone-2", "test-zone-3"]) <= 2

    def test_spread_constrained_by_zone_selector(self):
        # pods restricted to 2 zones spread across only those
        pods = [
            make_pod(
                labels={"app": "web"},
                requests={"cpu": "10m"},
                node_requirements=[
                    NodeSelectorRequirement(ZONE, OP_IN, ["test-zone-1", "test-zone-2"])
                ],
                topology_spread=[
                    TopologySpreadConstraint(
                        max_skew=1,
                        topology_key=ZONE,
                        label_selector=LabelSelector(match_labels={"app": "web"}),
                    )
                ],
            )
            for _ in range(4)
        ]
        results = solve(pods)
        assert scheduled_count(results) == 4
        zones = set()
        for node in results.new_nodes:
            zones.update(node.requirements.get(ZONE).values_list())
        assert zones <= {"test-zone-1", "test-zone-2"}


class TestPodAffinity:
    def _affinity_pod(self, **kwargs):
        return make_pod(
            labels={"app": "db"},
            pod_affinity=[
                PodAffinityTerm(
                    topology_key=HOSTNAME,
                    label_selector=LabelSelector(match_labels={"app": "db"}),
                )
            ],
            **kwargs,
        )

    def test_self_affinity_colocates(self):
        results = solve([self._affinity_pod(requests={"cpu": "10m"}) for _ in range(3)])
        assert scheduled_count(results) == 3
        assert len(results.new_nodes) == 1

    def test_anti_affinity_separates(self):
        pods = [
            make_pod(
                labels={"app": "db"},
                requests={"cpu": "10m"},
                pod_anti_affinity=[
                    PodAffinityTerm(
                        topology_key=HOSTNAME,
                        label_selector=LabelSelector(match_labels={"app": "db"}),
                    )
                ],
            )
            for _ in range(3)
        ]
        results = solve(pods)
        assert scheduled_count(results) == 3
        assert len(results.new_nodes) == 3

    def test_zonal_anti_affinity_caps_at_domain_count(self):
        pods = [
            make_pod(
                labels={"app": "db"},
                requests={"cpu": "10m"},
                pod_anti_affinity=[
                    PodAffinityTerm(
                        topology_key=ZONE,
                        label_selector=LabelSelector(match_labels={"app": "db"}),
                    )
                ],
            )
            for _ in range(4)
        ]
        results = solve(pods)
        # late committal is pessimistic: the first pod could land in any zone, so
        # it blocks all zones for the rest of the batch; zonal anti-affinity
        # resolves over multiple batches (reference topology_test.go:1896-1900)
        assert scheduled_count(results) == 1
        assert len(results.failed_pods) == 3

    def test_affinity_to_unconstrained_target_defers(self):
        # zone affinity to an unconstrained target can't resolve in one batch:
        # the target's zone never collapses to a single value, so it is never
        # counted (reference topology_test.go:1941-1963)
        target = make_pod(labels={"app": "web"}, requests={"cpu": "10m"})
        follower = make_pod(
            requests={"cpu": "10m"},
            pod_affinity=[
                PodAffinityTerm(
                    topology_key=ZONE,
                    label_selector=LabelSelector(match_labels={"app": "web"}),
                )
            ],
        )
        results = solve([target, follower])
        assert scheduled_count(results) == 1
        assert len(results.failed_pods) == 1

    def test_affinity_to_zone_constrained_target_colocates(self):
        # when the target is pinned to one zone, followers can join it in-batch
        target = make_pod(
            labels={"app": "web"},
            requests={"cpu": "10m"},
            node_selector={ZONE: "test-zone-2"},
        )
        followers = [
            make_pod(
                requests={"cpu": "10m"},
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=ZONE,
                        label_selector=LabelSelector(match_labels={"app": "web"}),
                    )
                ],
            )
            for _ in range(2)
        ]
        results = solve([target] + followers)
        assert scheduled_count(results) == 3
        for node in results.new_nodes:
            if node.pods:
                assert node.requirements.get(ZONE).values_list() == ["test-zone-2"]


class TestRelaxation:
    def test_preferred_node_affinity_relaxed(self):
        pod = make_pod(
            node_preferences=[NodeSelectorRequirement(ZONE, OP_IN, ["unknown-zone"])]
        )
        results = solve([pod])
        assert scheduled_count(results) == 1

    def test_schedule_anyway_spread_relaxed(self):
        # an unsatisfiable ScheduleAnyway spread is dropped
        pod = make_pod(
            labels={"app": "a"},
            topology_spread=[
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key="undefined-topology-key",
                    when_unsatisfiable="ScheduleAnyway",
                    label_selector=LabelSelector(match_labels={"app": "a"}),
                )
            ],
        )
        results = solve([pod])
        assert scheduled_count(results) == 1

    def test_required_affinity_not_relaxed(self):
        pod = make_pod(
            node_requirements=[NodeSelectorRequirement(ZONE, OP_IN, ["unknown-zone"])]
        )
        results = solve([pod])
        assert scheduled_count(results) == 0


class TestProvisionerSelection:
    def test_weight_order(self):
        heavy = make_provisioner(name="heavy", weight=100, labels={"tier": "heavy"})
        light = make_provisioner(name="light", weight=1, labels={"tier": "light"})
        results = solve([make_pod()], provisioners=[light, heavy])
        assert scheduled_count(results) == 1
        assert results.new_nodes[0].provisioner_name == "heavy"

    def test_fallback_to_compatible_provisioner(self):
        tainted = make_provisioner(name="tainted", weight=100, taints=[Taint("special", "true")])
        normal = make_provisioner(name="normal", weight=1)
        results = solve([make_pod()], provisioners=[tainted, normal])
        assert scheduled_count(results) == 1
        assert results.new_nodes[0].provisioner_name == "normal"

    def test_limits_enforced(self):
        provisioner = make_provisioner(limits={"cpu": 4})
        # each default node consumes up to 16 cpu pessimistically; after the
        # first node the provisioner is exhausted
        results = solve(
            make_pods(10, requests={"cpu": 3}),
            provisioners=[provisioner],
        )
        assert scheduled_count(results) < 10

    def test_limits_zero_blocks_all(self):
        provisioner = make_provisioner(limits={"cpu": 0})
        results = solve([make_pod(requests={"cpu": 1})], provisioners=[provisioner])
        assert scheduled_count(results) == 0


class TestInstanceSelection:
    def test_cheapest_instances_survive(self):
        # with the incremental catalog, a 1-cpu pod keeps small types viable
        its = fake_cp.instance_types(10)
        results = solve(make_pods(1, requests={"cpu": "500m"}), instance_types=its)
        assert scheduled_count(results) == 1
        names = {it.name for it in results.new_nodes[0].instance_type_options}
        assert "fake-it-0" in names  # smallest still viable

    def test_capacity_type_requirement(self):
        pod = make_pod(
            node_requirements=[NodeSelectorRequirement(CT, OP_IN, ["spot"])]
        )
        results = solve([pod])
        assert scheduled_count(results) == 1
        assert results.new_nodes[0].requirements.get(CT).values_list() == ["spot"]


class TestSelectorOperatorSemantics:
    """suite_test.go:400-470 — the undefined/defined-key × operator grid.
    An undefined key (no provisioner requirement, no well-known label) is
    schedulable only under NotIn/DoesNotExist; a defined key flips every
    outcome."""

    def _solves(self, requirement) -> bool:
        pod = make_pod(requests={"cpu": "100m"}, node_requirements=[requirement])
        results = solve([pod])
        return not results.failed_pods

    def test_in_with_undefined_key_fails(self):
        assert not self._solves(
            NodeSelectorRequirement("undefined.example/key", OP_IN, ["v"])
        )

    def test_not_in_with_undefined_key_schedules(self):
        assert self._solves(
            NodeSelectorRequirement("undefined.example/key", OP_NOT_IN, ["v"])
        )

    def test_exists_with_undefined_key_fails(self):
        assert not self._solves(
            NodeSelectorRequirement("undefined.example/key", OP_EXISTS)
        )

    def test_does_not_exist_with_undefined_key_schedules(self):
        assert self._solves(
            NodeSelectorRequirement("undefined.example/key", OP_DOES_NOT_EXIST)
        )

    def _solves_with_defined_key(self, requirement) -> bool:
        pod = make_pod(requests={"cpu": "100m"}, node_requirements=[requirement])
        provisioner = make_provisioner(requirements=[
            NodeSelectorRequirement("defined.example/key", OP_IN, ["v1", "v2"])
        ])
        results = solve([pod], provisioners=[provisioner])
        return not results.failed_pods

    def test_in_matching_value_schedules(self):
        assert self._solves_with_defined_key(
            NodeSelectorRequirement("defined.example/key", OP_IN, ["v1"])
        )

    def test_in_different_value_fails(self):
        assert not self._solves_with_defined_key(
            NodeSelectorRequirement("defined.example/key", OP_IN, ["other"])
        )

    def test_not_in_matching_value_narrows_but_schedules(self):
        # NotIn v1 leaves v2: still schedulable
        assert self._solves_with_defined_key(
            NodeSelectorRequirement("defined.example/key", OP_NOT_IN, ["v1"])
        )

    def test_not_in_all_values_fails(self):
        assert not self._solves_with_defined_key(
            NodeSelectorRequirement("defined.example/key", OP_NOT_IN, ["v1", "v2"])
        )

    def test_exists_with_defined_key_schedules(self):
        assert self._solves_with_defined_key(
            NodeSelectorRequirement("defined.example/key", OP_EXISTS)
        )

    def test_does_not_exist_with_defined_key_fails(self):
        assert not self._solves_with_defined_key(
            NodeSelectorRequirement("defined.example/key", OP_DOES_NOT_EXIST)
        )
