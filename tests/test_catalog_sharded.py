"""Catalog-sharded provisioning solve (parallel/mesh.solve_catalog_sharded).

VERDICT r4 #7 / BASELINE config 4: the headline solve's mesh story.  The pod
axis is degenerate after class dedup and the scan carry is sequential, so the
mesh shards the CATALOG (instance-type) axis: per class step the hot planes
are [N, I] with per-I independence, and GSPMD inserts the max/any collectives
from sharding annotations alone.  These tests pin exact equality between the
8-way-sharded solve and the single-device solve on the virtual CPU mesh.
"""

import numpy as np
import pytest

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import LabelSelector, TopologySpreadConstraint
from karpenter_core_tpu.cloudprovider import fake as fake_cp
from karpenter_core_tpu.ops import solve as solve_ops
from karpenter_core_tpu.parallel import mesh as mesh_ops
from karpenter_core_tpu.solver.tpu import TPUSolver
from karpenter_core_tpu.testing import make_pod, make_provisioner

pytestmark = pytest.mark.compile  # sharded executables compile per shape


def build_snapshot(n_its: int, n_pods: int):
    provider = fake_cp.FakeCloudProvider(fake_cp.instance_types(n_its))
    solver = TPUSolver(
        provider, [make_provisioner(name="a", weight=2), make_provisioner(name="b")]
    )
    pods = [make_pod(requests={"cpu": "500m"}) for _ in range(n_pods - n_pods // 4)]
    pods += [
        make_pod(
            labels={"app": "s"},
            requests={"cpu": "250m"},
            topology_spread=[
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=labels_api.LABEL_TOPOLOGY_ZONE,
                    label_selector=LabelSelector(match_labels={"app": "s"}),
                )
            ],
        )
        for _ in range(n_pods // 4)
    ]
    return solver, solver.encode(pods)


class TestCatalogShardedSolve:
    def test_matches_single_device_exactly(self):
        solver, snapshot = build_snapshot(n_its=60, n_pods=128)
        single = solve_ops.solve(snapshot)
        mesh = mesh_ops.default_mesh(8)
        sharded = mesh_ops.solve_catalog_sharded(snapshot, mesh=mesh)
        c0 = len(snapshot.classes)
        a = np.asarray(single.assign)
        b = np.asarray(sharded.assign)
        n = min(a.shape[1], b.shape[1])
        # the single path runs through the compile cache's bucket padding;
        # rows/slots beyond the real classes are zero on both sides
        assert np.array_equal(a[:c0, :n], b[:c0, :n])
        assert a[c0:].sum() == 0 and b[c0:].sum() == 0
        assert np.array_equal(
            np.asarray(single.failed)[:c0], np.asarray(sharded.failed)[:c0]
        )
        # the decoded zone/viable planes agree wherever pods landed
        pods_on = np.asarray(sharded.state.pod_count) > 0
        i0 = len(snapshot.it_names)
        assert np.array_equal(
            np.asarray(single.state.zone)[: len(pods_on)][pods_on],
            np.asarray(sharded.state.zone)[pods_on],
        )
        assert np.array_equal(
            np.asarray(single.state.viable)[: len(pods_on), :i0][pods_on],
            np.asarray(sharded.state.viable)[pods_on][:, :i0],
        )

    def test_catalog_not_divisible_by_devices(self):
        # 50 instance types over 8 devices: the inert-padding path
        solver, snapshot = build_snapshot(n_its=50, n_pods=64)
        single = solve_ops.solve(snapshot)
        sharded = mesh_ops.solve_catalog_sharded(
            snapshot, mesh=mesh_ops.default_mesh(8)
        )
        c0 = len(snapshot.classes)
        assert int(np.sum(np.asarray(sharded.assign))) == int(
            np.sum(np.asarray(single.assign))
        )
        assert np.array_equal(
            np.asarray(single.failed)[:c0], np.asarray(sharded.failed)[:c0]
        )
        # padded instance types must never be viable on an open node
        pods_on = np.asarray(sharded.state.pod_count) > 0
        i0 = len(snapshot.it_names)
        tail = np.asarray(sharded.state.viable)[pods_on][:, i0:]
        assert not tail.any(), "inert catalog padding leaked into viability"
