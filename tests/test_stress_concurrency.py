"""Concurrency stress: the -race-equivalent suite (VERDICT r1 aux gap).

The reference runs its suites under `go test -race`; CPython has no race
detector, so these tests hammer the shared structures from many threads and
assert the invariants that data races would break: cluster state consistency
under concurrent informer events, kubeclient store atomicity, recorder
dedupe/rate-limit counters, eviction-queue single-delivery, settings-store
last-write coherence, and leader-election single-winner under thread races.
"""

import threading
import time

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import Lease
from karpenter_core_tpu.operator.kubeclient import ConflictError, KubeClient
from karpenter_core_tpu.testing import make_node, make_pod, make_provisioner
from karpenter_core_tpu.testing.harness import make_environment

N_THREADS = 8
N_OPS = 60


def run_threads(worker, n=N_THREADS):
    errors = []

    def wrapped(i):
        try:
            worker(i)
        except Exception as e:  # noqa: BLE001 - surfaced to the assertion
            errors.append(e)

    threads = [threading.Thread(target=wrapped, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    return errors


class TestKubeClientConcurrency:
    def test_concurrent_creates_unique(self):
        kube = KubeClient()

        def worker(i):
            for j in range(N_OPS):
                kube.create(make_pod(name=f"pod-{i}-{j}"))

        run_threads(worker)
        assert len(kube.list_pods()) == N_THREADS * N_OPS

    def test_create_conflicts_exactly_once(self):
        kube = KubeClient()
        wins = []

        def worker(i):
            for j in range(N_OPS):
                try:
                    kube.create(make_node(name=f"node-{j}"))
                    wins.append(j)
                except ConflictError:
                    pass

        run_threads(worker)
        # every name created exactly once across all threads
        assert sorted(wins) == list(range(N_OPS))
        assert len(kube.list_nodes()) == N_OPS

    def test_cas_single_winner_per_version(self):
        import copy

        from karpenter_core_tpu.apis.objects import LeaseSpec, ObjectMeta

        kube = KubeClient()
        kube.create(
            Lease(
                metadata=ObjectMeta(name="l", namespace="ns"),
                spec=LeaseSpec(holder_identity="seed"),
            )
        )
        winners = []

        def worker(i):
            for _ in range(N_OPS):
                stored = kube.get(Lease, "l", "ns")
                version = stored.metadata.resource_version
                attempt = copy.deepcopy(stored)
                attempt.spec.holder_identity = f"t{i}"
                try:
                    kube.update_with_version(attempt, version)
                    winners.append(version)
                except ConflictError:
                    pass

        run_threads(worker)
        # optimistic concurrency: each observed version is won at most once
        assert len(winners) == len(set(winners))

    def test_watch_events_complete_under_churn(self):
        kube = KubeClient()
        seen = []
        lock = threading.Lock()

        def observer(event_type, obj):
            with lock:
                seen.append((event_type, obj.metadata.name))

        from karpenter_core_tpu.apis.objects import Node

        kube.watch(Node, observer, replay=False)

        def worker(i):
            for j in range(N_OPS):
                kube.create(make_node(name=f"churn-{i}-{j}"))

        run_threads(worker)
        added = [name for kind, name in seen if kind == "ADDED"]
        assert len(added) == N_THREADS * N_OPS
        assert len(set(added)) == len(added)


class TestClusterConcurrency:
    def test_informer_churn_keeps_state_consistent(self):
        env = make_environment()
        env.kube.create(make_provisioner())

        def worker(i):
            for j in range(N_OPS // 2):
                node = make_node(
                    name=f"n-{i}-{j}",
                    labels={
                        labels_api.PROVISIONER_NAME_LABEL_KEY: "default",
                        labels_api.LABEL_INSTANCE_TYPE_STABLE: "default-instance-type",
                        labels_api.LABEL_CAPACITY_TYPE: "spot",
                        labels_api.LABEL_TOPOLOGY_ZONE: "test-zone-1",
                    },
                    allocatable={"cpu": 4, "memory": "4Gi", "pods": 10},
                )
                env.kube.create(node)
                pod = make_pod(name=f"p-{i}-{j}", node_name=node.name, unschedulable=False)
                env.kube.create(pod)
                if j % 3 == 0:
                    env.kube.delete(env.kube.get_pod(pod.namespace, pod.name), force=True)

        run_threads(worker)
        snapshot = env.cluster.snapshot_nodes()
        node_names = {s.node.name for s in snapshot}
        assert len(node_names) == len(snapshot), "duplicate state nodes"
        kube_names = {n.name for n in env.kube.list_nodes()}
        assert node_names == kube_names
        # resource accounting stayed coherent: every surviving bound pod is
        # charged on exactly its node
        for state_node in snapshot:
            for key in state_node.pod_requests:
                pod = env.kube.get_pod(*key)
                assert pod is not None
                assert pod.spec.node_name == state_node.node.name

    def test_nominations_thread_safe(self):
        env = make_environment()
        env.kube.create(make_provisioner())
        names = [f"nom-{i}" for i in range(N_THREADS)]
        for name in names:
            env.kube.create(
                make_node(
                    name=name,
                    labels={labels_api.PROVISIONER_NAME_LABEL_KEY: "default",
                            labels_api.LABEL_INSTANCE_TYPE_STABLE: "default-instance-type",
                            labels_api.LABEL_CAPACITY_TYPE: "spot",
                            labels_api.LABEL_TOPOLOGY_ZONE: "test-zone-1"},
                    allocatable={"cpu": 4, "memory": "4Gi", "pods": 10},
                )
            )

        def worker(i):
            for _ in range(N_OPS):
                env.cluster.nominate_node_for_pod(names[i % len(names)])

        run_threads(worker)
        nominated = [s for s in env.cluster.snapshot_nodes() if s.nominated(env.clock)]
        assert len(nominated) == len(names)


class TestRecorderConcurrency:
    def test_dedupe_under_parallel_publish(self):
        from karpenter_core_tpu.events import Recorder, events as evt

        recorder = Recorder()
        pod = make_pod()
        node = make_node()

        def worker(i):
            for _ in range(N_OPS):
                recorder.publish(evt.nominate_pod(pod, node))

        run_threads(worker)
        nominated = [e for e in recorder.events if e.reason == "Nominated"]
        # dedupe cache: identical events collapse regardless of thread count
        assert len(nominated) < N_THREADS * N_OPS
        assert len(nominated) >= 1


class TestSettingsStoreConcurrency:
    def test_last_write_wins_consistently(self):
        from karpenter_core_tpu.apis.objects import ObjectMeta
        from karpenter_core_tpu.operator.settings import Settings
        from karpenter_core_tpu.operator.settingsstore import (
            SETTINGS_NAME,
            ConfigMap,
            SettingsStore,
        )

        kube = KubeClient()
        store = SettingsStore(kube, defaults=Settings()).start()

        def worker(i):
            for j in range(N_OPS // 2):
                cm = kube.get(ConfigMap, SETTINGS_NAME, "karpenter")
                cm.data = {
                    "batchMaxDuration": f"{10 + (i + j) % 5}s",
                    "batchIdleDuration": "1s",
                }
                kube.update(cm)

        run_threads(worker)
        # the store holds SOME valid parsed value, never a torn one
        assert store.batch_max_duration in {10.0, 11.0, 12.0, 13.0, 14.0}
        assert store.batch_idle_duration == 1.0


class TestLeaderElectionRaces:
    def test_thread_race_single_leader(self):
        from karpenter_core_tpu.operator.leaderelection import LeaderElector

        kube = KubeClient()
        electors = [
            LeaderElector(kube, identity=f"e{i}", retry_period=0.01)
            for i in range(N_THREADS)
        ]

        def worker(i):
            for _ in range(20):
                electors[i].tick()

        run_threads(worker)
        leaders = [e for e in electors if e.is_leader]
        assert len(leaders) == 1

    def test_failover_under_thread_churn(self):
        from karpenter_core_tpu.operator.leaderelection import LeaderElector
        from karpenter_core_tpu.utils.clock import FakeClock

        clock = FakeClock()
        kube = KubeClient(clock)
        electors = [
            LeaderElector(kube, clock=clock, identity=f"e{i}", lease_duration=5.0)
            for i in range(4)
        ]
        electors[0].tick()
        assert electors[0].is_leader
        # observation discipline (ADVICE r4 #1): standbys time staleness from
        # their OWN first sight of the lease — observe before the silence
        for elector in electors[1:]:
            elector.tick()
        clock.step(10.0)  # leader goes silent

        def worker(i):
            if i == 0:
                return  # the dead leader stays silent
            for _ in range(10):
                electors[i % 4].tick()
                time.sleep(0.001)

        run_threads(worker, n=4)
        leaders = [e for e in electors[1:] if e.is_leader]
        assert len(leaders) == 1


class TestEvictionQueueConcurrency:
    def test_parallel_enqueue_single_eviction_each(self):
        from karpenter_core_tpu.controllers.termination import EvictionQueue

        env = make_environment()
        pods = []
        for i in range(N_OPS):
            pod = make_pod(name=f"evict-{i}", node_name="n", unschedulable=False)
            env.kube.create(pod)
            pods.append(pod)
        queue = EvictionQueue(env.kube, env.recorder, synchronous=False)

        def worker(i):
            queue.add(pods)  # every thread tries to enqueue every pod

        run_threads(worker)
        # dedupe: each pod queued at most once across all threads
        assert len(queue._queue) <= len(pods)
        queue.drain_queue()
        for pod in pods:
            assert env.kube.get_pod(pod.namespace, pod.name) is None


class TestLeasePlaneRaces:
    """The solver-hosted lease plane under contention (snapshot_channel
    /LeaseApply): CAS must serialize concurrent writers over real gRPC the
    way the in-memory KubeClient does in-process — monotonic versions, at
    most one winner per expected-version token."""

    def test_concurrent_cas_single_winner_per_version(self, tmp_path, monkeypatch):
        from karpenter_core_tpu.cloudprovider.fake import FakeCloudProvider
        from karpenter_core_tpu.service.snapshot_channel import (
            SnapshotSolverClient,
            serve,
        )

        monkeypatch.setenv("KC_LEASE_STATE", str(tmp_path / "leases.json"))
        server, port = serve(FakeCloudProvider(), address="127.0.0.1:0", max_workers=8)
        try:
            seed = SnapshotSolverClient(f"127.0.0.1:{port}")
            assert seed.lease_apply({"name": "kc-hammer", "holderIdentity": "seed"})["ok"]

            n_threads, rounds = 6, 30
            wins = [0] * n_threads

            def hammer(i: int) -> None:
                client = SnapshotSolverClient(f"127.0.0.1:{port}")
                for _ in range(rounds):
                    stored = client.lease_get("kc-hammer")
                    response = client.lease_apply(
                        {"name": "kc-hammer", "holderIdentity": f"t{i}",
                         "renewTime": float(stored["resourceVersion"])},
                        expected_version=stored["resourceVersion"],
                    )
                    if response["ok"]:
                        wins[i] += 1
                        # the server must have advanced exactly one step
                        assert response["lease"]["resourceVersion"] == (
                            stored["resourceVersion"] + 1
                        )

            run_threads(hammer, n=n_threads)

            final = seed.lease_get("kc-hammer")
            # every successful CAS advanced the version exactly once: the final
            # version is the seed's 1 plus the total number of wins
            assert final["resourceVersion"] == 1 + sum(wins)
            # contention must not collapse throughput (CAS guarantees no
            # per-thread fairness, so per-thread minimums would be flaky)
            assert sum(wins) >= n_threads
        finally:
            server.stop(grace=0)


class TestOperatorSoak:
    """Sustained churn through the REAL operator on real threads: waves of
    pods arrive, get scheduled and bound, then vanish — the loop the
    reference's controllers run for days.  Asserts every wave converges with
    no warning events and that the operator's own threads wind down at stop
    (leak detection for the singleton/watch plumbing)."""

    def test_churn_waves_stay_healthy(self):
        import time as wall

        from karpenter_core_tpu.cloudprovider.fake import FakeCloudProvider
        from karpenter_core_tpu.operator.operator import Operator
        from karpenter_core_tpu.operator.settings import Settings
        from karpenter_core_tpu.testing.harness import nominations

        operator = Operator(
            cloud_provider=FakeCloudProvider(),
            settings=Settings(batch_idle_duration=0.05, batch_max_duration=0.2),
        ).with_controllers()
        operator.start()
        try:
            operator.kube_client.create(make_provisioner())
            for wave in range(5):
                pods = [
                    make_pod(name=f"soak-{wave}-{i}", requests={"cpu": "200m"})
                    for i in range(12)
                ]
                for pod in pods:
                    operator.kube_client.create(pod)

                # count only THIS wave's pods: a late event for a deleted
                # prior-wave pod must not fake convergence
                wave_uids = {p.uid for p in pods}
                deadline = wall.time() + 15
                nominated = {}
                while wall.time() < deadline and len(nominated) < len(pods):
                    nominated = {
                        uid: name
                        for uid, name in nominations(operator.recorder).items()
                        if uid in wave_uids
                    }
                    wall.sleep(0.05)
                assert len(nominated) == len(pods), (
                    f"wave {wave}: only {len(nominated)} of {len(pods)} nominated"
                )
                warnings = [e for e in operator.recorder.events if e.type == "Warning"]
                assert not warnings, f"wave {wave}: {warnings[:3]}"
                # bind like kube-scheduler, then clear the wave
                for pod in pods:
                    node_name = nominated.get(pod.uid)
                    if node_name and operator.kube_client.get_node(node_name):
                        pod.spec.node_name = node_name
                        operator.kube_client.apply(pod)
                for pod in pods:
                    operator.kube_client.delete(pod, force=True)
                operator.recorder.reset()
                assert operator.healthy() and operator.ready()
        finally:
            operator.stop()

        # the operator's own (daemon, controller-named) threads must wind
        # down after stop; allow the loops a moment to observe their events
        operator_thread_names = {
            "provisioning", "deprovisioning", "metrics_state", "inflightchecks",
            "node", "provisioning_trigger", "counter", "leader-election",
        }
        deadline = wall.time() + 5
        leaked = set()
        while wall.time() < deadline:
            leaked = {
                t.name for t in threading.enumerate()
                if t.is_alive() and t.name in operator_thread_names
            }
            if not leaked:
                break
            wall.sleep(0.1)
        assert not leaked, f"operator threads still alive after stop: {leaked}"
