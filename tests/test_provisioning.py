"""Provisioning controller suite.

Coverage modeled on /root/reference/pkg/controllers/provisioning/suite_test.go:
end-to-end pod → node launch through the controller, batcher, state reuse of
in-flight nodes, limits, daemonset overhead, volume topology.
"""

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import (
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    PersistentVolume,
    PersistentVolumeClaim,
    PersistentVolumeClaimSpec,
    PersistentVolumeClaimVolumeSource,
    PersistentVolumeSpec,
    StorageClass,
    ObjectMeta,
    Volume,
    OP_IN,
)
from karpenter_core_tpu.testing import make_pod, make_pods, make_provisioner, make_daemonset_pod
from karpenter_core_tpu.testing.harness import (
    expect_not_scheduled,
    expect_provisioned,
    expect_scheduled,
    make_environment,
)

ZONE = labels_api.LABEL_TOPOLOGY_ZONE


class TestProvisioning:
    def test_pod_gets_node_launched_and_bound(self):
        env = make_environment()
        env.kube.create(make_provisioner())
        pod = make_pod(requests={"cpu": 1})
        result = expect_provisioned(env, pod)
        node = expect_scheduled(env, result, pod)
        assert env.provider.create_calls, "machine launch expected"
        assert node.metadata.labels[labels_api.PROVISIONER_NAME_LABEL_KEY] == "default"
        assert labels_api.TERMINATION_FINALIZER in node.metadata.finalizers

    def test_no_provisioners_no_nodes(self):
        env = make_environment()
        pod = make_pod()
        result = expect_provisioned(env, pod)
        expect_not_scheduled(env, result, pod)
        assert not env.provider.create_calls

    def test_batch_shares_node(self):
        env = make_environment()
        env.kube.create(make_provisioner())
        pods = make_pods(3, requests={"cpu": "100m"})
        result = expect_provisioned(env, *pods)
        nodes = {result[p.uid].name for p in pods}
        assert len(nodes) == 1
        assert len(env.provider.create_calls) == 1

    def test_second_batch_reuses_inflight_node(self):
        env = make_environment()
        env.kube.create(make_provisioner())
        first = make_pod(requests={"cpu": "100m"})
        expect_provisioned(env, first)
        assert len(env.provider.create_calls) == 1
        # in-flight node (not initialized) has room: second pod nominates it
        second = make_pod(requests={"cpu": "100m"})
        result = expect_provisioned(env, second)
        node = expect_scheduled(env, result, second)
        assert len(env.provider.create_calls) == 1, "no second machine"
        assert node.name == env.kube.get_pod(first.namespace, first.name).spec.node_name

    def test_unschedulable_pod_records_failure(self):
        env = make_environment()
        env.kube.create(make_provisioner())
        pod = make_pod(requests={"cpu": 10_000})
        result = expect_provisioned(env, pod)
        expect_not_scheduled(env, result, pod)
        assert any(e.reason == "FailedScheduling" for e in env.recorder.events)

    def test_limits_block_launch(self):
        env = make_environment()
        provisioner = make_provisioner(limits={"cpu": 2})
        provisioner.status.resources = {"cpu": 4.0}  # already over
        env.kube.create(provisioner)
        pod = make_pod(requests={"cpu": 1})
        result = expect_provisioned(env, pod)
        # scheduling proposed a node but launch was rejected by limits
        assert not any(
            n.name for n in env.kube.list_nodes()
        ), f"nodes: {[n.name for n in env.kube.list_nodes()]}"

    def test_daemonset_overhead_reserved(self):
        env = make_environment()
        env.kube.create(make_provisioner())
        ds_pod = make_daemonset_pod(requests={"cpu": 1}, unschedulable=False)
        env.kube.create(ds_pod)
        pod = make_pod(requests={"cpu": "3500m"})
        result = expect_provisioned(env, pod)
        node = expect_scheduled(env, result, pod)
        # 4-cpu default type can't hold 3.5 + 1 daemon: must be a bigger shape
        # (the only bigger default is the 16-cpu arm instance)
        assert node.metadata.labels[labels_api.LABEL_INSTANCE_TYPE_STABLE] == "arm-instance-type"

    def test_volume_topology_zone_pinned(self):
        env = make_environment()
        env.kube.create(make_provisioner())
        env.kube.create(StorageClass(metadata=ObjectMeta(name="sc", namespace=""), provisioner="ebs"))
        env.kube.create(
            PersistentVolume(
                metadata=ObjectMeta(name="pv-1", namespace=""),
                spec=PersistentVolumeSpec(
                    node_affinity_required=NodeSelector(
                        node_selector_terms=[
                            NodeSelectorTerm(
                                match_expressions=[
                                    NodeSelectorRequirement(ZONE, OP_IN, ["test-zone-2"])
                                ]
                            )
                        ]
                    )
                ),
            )
        )
        env.kube.create(
            PersistentVolumeClaim(
                metadata=ObjectMeta(name="claim", namespace="default"),
                spec=PersistentVolumeClaimSpec(volume_name="pv-1", storage_class_name="sc"),
            )
        )
        pod = make_pod()
        pod.spec.volumes.append(
            Volume(name="data", persistent_volume_claim=PersistentVolumeClaimVolumeSource("claim"))
        )
        result = expect_provisioned(env, pod)
        node = expect_scheduled(env, result, pod)
        assert node.metadata.labels[ZONE] == "test-zone-2"

    def test_missing_pvc_ignored(self):
        env = make_environment()
        env.kube.create(make_provisioner())
        pod = make_pod()
        pod.spec.volumes.append(
            Volume(name="data", persistent_volume_claim=PersistentVolumeClaimVolumeSource("nope"))
        )
        result = expect_provisioned(env, pod)
        expect_not_scheduled(env, result, pod)

    def test_create_failure_surfaces(self):
        env = make_environment()
        env.kube.create(make_provisioner())
        env.provider.allowed_create_calls = 0
        pod = make_pod()
        err = None
        for p in [pod]:
            env.kube.create(p)
        err = env.provisioning.reconcile(wait_for_batch=False)
        assert err is not None and "AllowedCreateCalls" in err

    def test_deleting_node_pods_rescheduled(self):
        env = make_environment()
        env.kube.create(make_provisioner())
        pod = make_pod(requests={"cpu": "100m"})
        result = expect_provisioned(env, pod)
        node = expect_scheduled(env, result, pod)
        # mark node for deletion; its pod should be rescheduled to a new node
        env.cluster.mark_for_deletion(node.name)
        env.provider.reset()
        err = env.provisioning.reconcile(wait_for_batch=False)
        assert err is None
        assert env.provider.create_calls, "replacement machine expected"


class TestBatcher:
    def test_idle_window_closes_batch(self):
        from karpenter_core_tpu.controllers.provisioning import Batcher
        from karpenter_core_tpu.operator.settings import Settings
        from karpenter_core_tpu.utils.clock import FakeClock

        clock = FakeClock()
        batcher = Batcher(clock, Settings(batch_idle_duration=1.0, batch_max_duration=10.0))
        assert not batcher.wait()
        batcher.trigger()
        start = clock.now()
        assert batcher.wait()
        assert clock.now() - start >= 1.0

    def test_max_window_bounds_batch(self):
        from karpenter_core_tpu.controllers.provisioning import Batcher
        from karpenter_core_tpu.operator.settings import Settings
        from karpenter_core_tpu.utils.clock import FakeClock

        clock = FakeClock()
        batcher = Batcher(clock, Settings(batch_idle_duration=1.0, batch_max_duration=3.0))
        batcher.trigger()

        # keep re-triggering every poll: idle never elapses, max window does
        orig_sleep = clock.sleep

        def sleep_and_retrigger(seconds):
            orig_sleep(seconds)
            batcher.trigger()

        clock.sleep = sleep_and_retrigger
        start = clock.now()
        assert batcher.wait()
        assert 3.0 <= clock.now() - start < 5.0


class TestWarmupSingleStart:
    def test_concurrent_triggers_spawn_one_warmup(self, monkeypatch):
        """ADVICE r4 #3: _maybe_start_warmup's test-and-set is lock-guarded —
        concurrent trigger() calls from watch/batcher threads must spawn at
        most ONE warmup thread, and every spawned thread must be tracked for
        join_warmup (an untracked thread inside an XLA compile at interpreter
        teardown aborts the process)."""
        import threading

        from karpenter_core_tpu.testing.harness import make_environment
        from karpenter_core_tpu.testing import make_provisioner

        env = make_environment()
        env.kube.create(make_provisioner())
        ctrl = env.provisioning
        ctrl.use_tpu_kernel = True
        monkeypatch.setenv("KC_TPU_WARMUP", "1")
        started = []
        release = threading.Event()

        def fake_warmup(self, **kwargs):
            started.append(threading.current_thread())
            release.wait(5.0)
            return True

        from karpenter_core_tpu.solver.tpu import TPUSolver

        monkeypatch.setattr(TPUSolver, "warmup", fake_warmup)
        threads = [
            threading.Thread(target=ctrl._maybe_start_warmup) for _ in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(5.0)
        release.set()
        ctrl.join_warmup(timeout=5.0)
        assert len(started) == 1, "check-then-set raced: multiple warmups ran"
        assert not ctrl._warmup_thread.is_alive()
