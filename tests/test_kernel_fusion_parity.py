"""Kernel-vs-kernel parity for the phase-fused, statically-pruned, bit-packed
solve program (docs/KERNEL_PERF.md).

Three independent rewrites of the solve kernel must be bit-for-bit
output-preserving, each fuzzed against its reference form on randomized
snapshots:

  - ``fuse_zones``: the batched multi-zone committal block vs the sequential
    per-zone ``run_phase`` sweeps (zone spread quotas, required zonal anti)
  - ``packed_masks``: uint32-word mask algebra (AND + popcount) vs the
    bool-plane einsum path
  - ``features``: static phase pruning vs the all-phases trace — a pruned
    family must have been a provable no-op

The pure mask-op algebra is additionally fuzzed standalone (fast, no kernel
compile): every ops/masks.py operation must agree packed vs unpacked on
random requirement tensors, bounds included.

The kernel-level comparisons compile 2 full solve programs per case, so they
carry the ``slow`` marker (excluded from the budgeted tier-1 run, included in
``make test-all``); the production kernel configuration itself is exercised
against the HOST oracle throughout tier-1 (tests/test_parity_fuzz.py and the
topology matrices), so a semantic regression cannot hide behind the marker.
"""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import (
    LabelSelector,
    PodAffinityTerm,
    TopologySpreadConstraint,
)
from karpenter_core_tpu.cloudprovider import fake as fake_cp
from karpenter_core_tpu.ops import masks as mask_ops
from karpenter_core_tpu.ops import solve as solve_ops
from karpenter_core_tpu.solver.tpu import TPUSolver
from karpenter_core_tpu.testing import make_pod, make_provisioner

ZONE = labels_api.LABEL_TOPOLOGY_ZONE
HOSTNAME = labels_api.LABEL_HOSTNAME

SIZES = (
    {"cpu": "100m"},
    {"cpu": "500m"},
    {"cpu": 1, "memory": "1Gi"},
    {"cpu": "250m", "memory": "512Mi"},
)


# -- mask-op algebra: packed vs bool, standalone (fast) -----------------------


def _random_req(rng: random.Random, batch: int, k: int, v: int):
    """Random bool-layout ReqTensor batch + valid plane + vocab ints."""
    mask = np.zeros((batch, k, v + 1), dtype=bool)
    defined = np.zeros((batch, k), dtype=bool)
    negative = np.zeros((batch, k), dtype=bool)
    gt = np.full((batch, k), -np.inf, dtype=np.float32)
    lt = np.full((batch, k), np.inf, dtype=np.float32)
    for b in range(batch):
        for key in range(k):
            shape = rng.random()
            if shape < 0.3:  # undefined: all-ones mask
                mask[b, key, :] = True
            elif shape < 0.6:  # In set
                defined[b, key] = True
                for s in range(v):
                    mask[b, key, s] = rng.random() < 0.4
            else:  # complement (NotIn / Exists)
                defined[b, key] = True
                negative[b, key] = rng.random() < 0.5
                mask[b, key, :] = True
                for s in range(v):
                    if rng.random() < 0.3:
                        mask[b, key, s] = False
            if rng.random() < 0.2:
                gt[b, key] = rng.randint(-3, 3)
            if rng.random() < 0.2:
                lt[b, key] = rng.randint(4, 12)
    valid = np.zeros((k, v + 1), dtype=bool)
    valid[:, :v] = True
    vocab_ints = np.where(
        np.random.default_rng(rng.randint(0, 1 << 30)).random((k, v)) < 0.5,
        np.arange(v, dtype=np.float32)[None, :],
        np.inf,
    )
    t = mask_ops.ReqTensor(
        jnp.asarray(mask), jnp.asarray(defined), jnp.asarray(negative),
        jnp.asarray(gt), jnp.asarray(lt),
    )
    return t, jnp.asarray(valid), jnp.asarray(vocab_ints)


@pytest.mark.parametrize("seed", range(8))
def test_packed_mask_ops_match_bool_ops(seed):
    rng = random.Random(seed)
    k, v = rng.randint(1, 5), rng.randint(1, 40)
    a, valid, ints = _random_req(rng, rng.randint(1, 6), k, v)
    b, _, _ = _random_req(rng, 1, k, v)
    is_custom = jnp.asarray(
        np.random.default_rng(seed).random(k) < 0.5
    )
    khb = tuple(
        bool(np.isfinite(np.asarray(t.gt)).any() or np.isfinite(np.asarray(t.lt)).any())
        for t in (a,)
        for _ in range(1)
    ) * k  # per-key conservative: bounds possible on every key
    width = v + 1
    pa, pb = mask_ops.pack_req(a), mask_ops.pack_req(b)
    pvalid = mask_ops.pack_mask(valid)

    np.testing.assert_array_equal(
        np.asarray(mask_ops.nonempty_intersection(a, b, ints)),
        np.asarray(mask_ops.nonempty_intersection(pa, pb, ints, v=width)),
    )
    np.testing.assert_array_equal(
        np.asarray(mask_ops.intersects(a, b, ints)),
        np.asarray(mask_ops.intersects(pa, pb, ints, v=width)),
    )
    np.testing.assert_array_equal(
        np.asarray(mask_ops.compatible(a, b, is_custom, ints)),
        np.asarray(mask_ops.compatible(pa, pb, is_custom, ints, v=width)),
    )
    got = mask_ops.add(pa, pb, pvalid, ints, v=width, key_has_bounds=khb)
    want = mask_ops.add(a, b, valid, ints)
    np.testing.assert_array_equal(
        np.asarray(want.mask), mask_ops.unpack_mask(np.asarray(got.mask), width)
    )
    for field in ("defined", "negative", "gt", "lt"):
        np.testing.assert_array_equal(
            np.asarray(getattr(want, field)), np.asarray(getattr(got, field)),
            err_msg=field,
        )
    np.testing.assert_array_equal(
        np.asarray(mask_ops.count_allowed(a, valid)),
        np.asarray(mask_ops.count_allowed(pa, pvalid, v=width)),
    )
    np.testing.assert_array_equal(
        np.asarray(mask_ops.single_value(a)),
        np.asarray(mask_ops.single_value(pa, v=width)),
    )


def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(0)
    for m in (1, 7, 31, 32, 33, 64, 90):
        bits = rng.random((3, 2, m)) < 0.5
        words = np.asarray(mask_ops.pack_mask(jnp.asarray(bits)))
        assert words.shape[-1] == mask_ops.words_for(m)
        np.testing.assert_array_equal(
            mask_ops.unpack_mask(words, m), bits
        )
        # pad bits beyond m stay zero: OR over all rows never sets them
        if m % 32:
            top = words[..., -1] >> (m % 32)
            assert not np.any(top)


# -- kernel parity: fused / packed / pruned vs the reference trace ------------


def _random_batch(rng: random.Random, with_ports: bool = False):
    """A randomized kernel-supported pod batch exercising the committal phase
    families (zone spread, required zonal anti) plus host families."""
    pods = []
    n_classes = rng.randint(3, 6)
    for i in range(n_classes):
        labels = {"app": f"c{i}"}
        kwargs = dict(labels=labels, requests=rng.choice(SIZES))
        shape = rng.random()
        if shape < 0.30:
            kwargs["topology_spread"] = [
                TopologySpreadConstraint(
                    max_skew=rng.choice((1, 2)),
                    topology_key=rng.choice((ZONE, HOSTNAME)),
                    label_selector=LabelSelector(match_labels=dict(labels)),
                )
            ]
        elif shape < 0.50:
            kwargs["pod_anti_affinity"] = [
                PodAffinityTerm(
                    topology_key=rng.choice((ZONE, HOSTNAME)),
                    label_selector=LabelSelector(match_labels=dict(labels)),
                )
            ]
        elif shape < 0.65:
            kwargs["pod_affinity"] = [
                PodAffinityTerm(
                    topology_key=rng.choice((ZONE, HOSTNAME)),
                    label_selector=LabelSelector(match_labels=dict(labels)),
                )
            ]
        if with_ports and i == 0:
            kwargs["host_ports"] = [8080 + i]
        pods.extend(make_pod(**kwargs) for _ in range(rng.randint(2, 12)))
    return pods


def _solve_variant(cls, sa, n_slots, khb, n_passes, **kw):
    out = solve_ops._solve_jit(cls, sa, n_slots, khb, n_passes=n_passes, **kw)
    return jax.device_get((
        out.assign, out.assign_existing, out.failed, out.spread_suspect,
        out.state.used, out.state.zone, out.state.ct, out.state.viable,
        out.state.pod_count, out.state.tmpl_id, out.state.open_, out.state.n_next,
        out.ex_state.used, out.ex_state.zone, out.ex_state.pod_count,
    ))


_FIELDS = ("assign", "assign_existing", "failed", "spread_suspect", "used",
           "zone", "ct", "viable", "pod_count", "tmpl_id", "open_", "n_next",
           "ex_used", "ex_zone", "ex_pod_count")


def _assert_same(ref, got, label):
    for name, a, b in zip(_FIELDS, ref, got):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f"{label}: {name}"
        )


@pytest.mark.compile
@pytest.mark.slow
@pytest.mark.parametrize("seed", range(4))
def test_fused_and_packed_kernel_matches_reference(seed):
    """Production configuration (features + fused zones + packed masks) must
    produce SolveOutputs identical to the unpruned, sequential, bool-mask
    reference trace on randomized snapshots."""
    rng = random.Random(1000 + seed)
    provider = fake_cp.FakeCloudProvider(fake_cp.instance_types(16))
    solver = TPUSolver(provider, [make_provisioner()])
    pods = _random_batch(rng, with_ports=(seed == 1))
    snap = solver.encode(pods)
    cls, sa, khb = solve_ops.prepare_host(snap)
    n_slots = solve_ops.estimate_slots(snap)
    ft = solve_ops.snapshot_features(snap)

    ref = _solve_variant(cls, sa, n_slots, khb, snap.scan_passes,
                         features=None, fuse_zones=False, packed_masks=False)
    prod = _solve_variant(cls, sa, n_slots, khb, snap.scan_passes,
                          features=ft, fuse_zones=True, packed_masks=True)
    _assert_same(ref, prod, f"seed {seed} production-vs-reference")


@pytest.mark.compile
@pytest.mark.slow
def test_fused_zone_block_matches_sequential_alone():
    """Isolate the fuse_zones axis: same features, same mask layout."""
    rng = random.Random(7)
    provider = fake_cp.FakeCloudProvider(fake_cp.instance_types(16))
    solver = TPUSolver(provider, [make_provisioner()])
    # force both committal families: zone spread class + required zonal anti
    pods = [
        make_pod(
            labels={"app": "zs"}, requests={"cpu": "250m"},
            topology_spread=[TopologySpreadConstraint(
                max_skew=1, topology_key=ZONE,
                label_selector=LabelSelector(match_labels={"app": "zs"}))],
        )
        for _ in range(9)
    ] + [
        make_pod(
            labels={"app": "za"}, requests={"cpu": "250m"},
            pod_anti_affinity=[PodAffinityTerm(
                topology_key=ZONE,
                label_selector=LabelSelector(match_labels={"app": "za"}))],
        )
        for _ in range(4)
    ] + _random_batch(rng)
    snap = solver.encode(pods)
    assert snap.features.zone_spread and snap.features.required_zone_anti
    cls, sa, khb = solve_ops.prepare_host(snap)
    n_slots = solve_ops.estimate_slots(snap)
    ft = solve_ops.snapshot_features(snap)
    seq = _solve_variant(cls, sa, n_slots, khb, snap.scan_passes,
                         features=ft, fuse_zones=False, packed_masks=True)
    fused = _solve_variant(cls, sa, n_slots, khb, snap.scan_passes,
                           features=ft, fuse_zones=True, packed_masks=True)
    _assert_same(seq, fused, "fused-vs-sequential")


@pytest.mark.compile
@pytest.mark.slow
def test_parity_with_existing_nodes():
    """The committal block's existing-node path and the pruned volume/port
    families against the reference, with real state nodes in play."""
    from karpenter_core_tpu.testing.harness import make_environment
    from karpenter_core_tpu.testing import make_node

    env = make_environment(instance_types=fake_cp.instance_types(16))
    env.kube.create(make_provisioner(name="default"))
    it = env.provider.get_instance_types(None)[4]
    offering = next(o for o in it.offerings if o.available)
    for i in range(3):
        node = make_node(
            name=f"ex-{i}",
            labels={
                labels_api.PROVISIONER_NAME_LABEL_KEY: "default",
                labels_api.LABEL_INSTANCE_TYPE_STABLE: it.name,
                labels_api.LABEL_TOPOLOGY_ZONE: offering.zone,
                labels_api.LABEL_CAPACITY_TYPE: offering.capacity_type,
                labels_api.LABEL_NODE_INITIALIZED: "true",
            },
            allocatable=it.allocatable(),
            capacity=dict(it.capacity),
            provider_id=f"fake://ex-{i}",
        )
        env.kube.create(node)
    state_nodes = env.cluster.snapshot_nodes()
    solver = TPUSolver(env.provider, env.kube.list_provisioners())
    rng = random.Random(21)
    pods = _random_batch(rng)
    snap = solver.encode(pods, state_nodes=state_nodes)
    ex_state, ex_static = solver.encode_existing(snap, state_nodes)
    cls, sa, khb = solve_ops.prepare_host(snap)
    n_slots = solve_ops.estimate_slots(snap)
    ft = solve_ops.features_with_existing(snap, ex_static)

    def run(**kw):
        out = solve_ops._solve_jit(
            cls, sa, n_slots, khb, ex_state, ex_static,
            n_passes=snap.scan_passes, **kw,
        )
        return jax.device_get((
            out.assign, out.assign_existing, out.failed, out.spread_suspect,
            out.state.used, out.state.zone, out.state.pod_count, out.state.n_next,
            out.ex_state.used, out.ex_state.zone, out.ex_state.pod_count,
            out.ex_state.vol_used,
        ))

    ref = run(features=None, fuse_zones=False, packed_masks=False)
    prod = run(features=ft, fuse_zones=True, packed_masks=True)
    for i, (a, b) in enumerate(zip(ref, prod)):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f"field {i}"
        )
