"""Namespace scoping of kernel topology groups.

The reference scopes every topology group to a namespace set: spreads count
only the owner's namespace (topology.go:280-282), affinity/anti terms count
term.namespaces or the owner's namespace (buildNamespaceList,
topology.go:287-320), and the namespace set is part of group identity
(topologygroup.go:137-153).  namespaceSelector needs a live namespace
listing, so those pods route to the host path.
"""

import pytest

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import (
    LabelSelector,
    PodAffinityTerm,
    TopologySpreadConstraint,
)
from karpenter_core_tpu.models.snapshot import KernelUnsupported, classify_pods
from karpenter_core_tpu.testing import make_pod, make_pods

from tests.test_tpu_solver import ZONE, compare

HOSTNAME = labels_api.LABEL_HOSTNAME


def spread(app, key=ZONE, max_skew=1):
    return TopologySpreadConstraint(
        max_skew=max_skew,
        topology_key=key,
        label_selector=LabelSelector(match_labels={"app": app}),
    )


def anti(app, key=HOSTNAME, namespaces=None, namespace_selector=None):
    return PodAffinityTerm(
        topology_key=key,
        label_selector=LabelSelector(match_labels={"app": app}),
        namespaces=list(namespaces or []),
        namespace_selector=namespace_selector,
    )


class TestNamespaceScoping:
    def test_identical_shapes_split_by_namespace(self):
        pods = make_pods(3, requests={"cpu": "1"}) + [
            make_pod(requests={"cpu": "1"}, namespace="other") for _ in range(2)
        ]
        classes = classify_pods(pods)
        assert sorted(c.count for c in classes) == [2, 3]

    def test_spread_counts_only_own_namespace(self):
        # 6 spread pods in ns A + 3 same-label pods in ns B pinned... the B
        # pods don't own or join A's spread group, so A still balances 2/2/2
        def pods():
            return make_pods(
                6, requests={"cpu": "10m"}, labels={"app": "w"},
                topology_spread=[spread("w")],
            ) + [
                make_pod(
                    requests={"cpu": "10m"}, labels={"app": "w"}, namespace="other"
                )
                for _ in range(3)
            ]

        host, tpu = compare(pods)
        assert not tpu.failed_pods

    def test_anti_affinity_scoped_to_own_namespace(self):
        # anti pods in ns A don't repel same-label pods in ns B: all schedule,
        # and the B pods can share a node
        def pods():
            return [
                make_pod(requests={"cpu": "100m"}, labels={"app": "db"},
                         pod_anti_affinity=[anti("db")])
                for _ in range(2)
            ] + [
                make_pod(requests={"cpu": "100m"}, labels={"app": "db"},
                         namespace="other")
                for _ in range(4)
            ]

        host, tpu = compare(pods)
        assert not tpu.failed_pods

    def test_explicit_term_namespaces_cross_namespace(self):
        # anti term explicitly naming the other namespace DOES repel its pods:
        # the kernel must match the host's blocking behavior
        def pods():
            return [
                make_pod(requests={"cpu": "100m"}, labels={"app": "db"},
                         namespace="other")
                for _ in range(2)
            ] + [
                make_pod(requests={"cpu": "100m"}, labels={"app": "db"},
                         pod_anti_affinity=[anti("db", namespaces=["other", "default"])])
            ]

        host, tpu = compare(pods)

    def test_namespace_selector_routes_to_host(self):
        with pytest.raises(KernelUnsupported):
            classify_pods(
                [
                    make_pod(
                        requests={"cpu": "1"},
                        pod_anti_affinity=[
                            anti("x", namespace_selector=LabelSelector(
                                match_labels={"team": "a"}))
                        ],
                    )
                ]
            )

    def test_cross_namespace_affinity_parity(self):
        # follower's affinity (own-namespace scope) can't target pods in the
        # other namespace; both paths must agree on the outcome
        def pods():
            return [
                make_pod(requests={"cpu": "1"}, labels={"app": "target"},
                         namespace="other")
                for _ in range(2)
            ] + [
                make_pod(
                    requests={"cpu": "1"},
                    pod_affinity=[
                        PodAffinityTerm(
                            topology_key=ZONE,
                            label_selector=LabelSelector(
                                match_labels={"app": "target"}),
                        )
                    ],
                    labels={"app": "target"},  # self-match bootstraps in-ns
                )
                for _ in range(2)
            ]

        host, tpu = compare(pods)
        assert not tpu.failed_pods


class TestNamespaceScopeInClassSignature:
    """Pods whose affinity terms differ only in namespace scope must land in
    distinct classes — group identity includes the namespace set
    (topologygroup.go:137-153), so collapsing them would solve the second pod
    under the first pod's scope."""

    def test_explicit_namespace_sets_split_classes(self):
        a = make_pod(
            name="a", namespace="a", labels={"app": "web"},
            pod_anti_affinity=[anti("web", namespaces=["x"])],
        )
        b = make_pod(
            name="b", namespace="a", labels={"app": "web"},
            pod_anti_affinity=[anti("web", namespaces=["y"])],
        )
        classes = classify_pods([a, b])
        roots = [c for c in classes if not c.is_ladder_variant]
        assert len(roots) == 2
        scopes = {
            next(iter(c.selectors.values())).namespaces for c in roots
        }
        assert scopes == {frozenset({"x"}), frozenset({"y"})}

    def test_namespace_selector_pod_does_not_collapse_into_static_class(self):
        static = make_pod(
            name="s", namespace="a", labels={"app": "web"},
            pod_anti_affinity=[anti("web", namespaces=["x"])],
        )
        dynamic = make_pod(
            name="d", namespace="a", labels={"app": "web"},
            pod_anti_affinity=[
                anti("web", namespace_selector=LabelSelector(match_labels={"team": "t"}))
            ],
        )
        # the dynamic pod must raise (host path), not ride the static class
        with pytest.raises(KernelUnsupported):
            classify_pods([static, dynamic])
