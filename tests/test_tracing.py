"""Tracing subsystem: span nesting/ordering, ring-buffer eviction, exporter
round-trips, the /debug/traces endpoint, decision audits, histogram bucket
exposition with exemplars, backend-probe telemetry, and the six stage spans a
kernel solve produces (docs/OBSERVABILITY.md is the contract under test)."""

import json
import subprocess
import urllib.request

import pytest

from karpenter_core_tpu import tracing
from karpenter_core_tpu.metrics.registry import Histogram, Registry


@pytest.fixture()
def traced():
    """Tracing on, store clean; restores the disabled default afterwards."""
    capacity = tracing.TRACE_STORE.capacity
    tracing.TRACE_STORE.clear()
    tracing.enable()
    yield
    tracing.disable()
    tracing.TRACE_STORE.clear()
    tracing.TRACE_STORE.set_capacity(capacity)


class TestSpans:
    def test_nesting_ids_and_ordering(self, traced):
        with tracing.span("root", batch=1) as root:
            with tracing.span("stage-a") as a:
                a.event("checkpoint", n=3)
            with tracing.span("stage-b"):
                pass
        assert len(tracing.TRACE_STORE) == 1
        trace = tracing.TRACE_STORE.last(1)[0]
        assert trace.trace_id == root.trace_id
        by_name = {s["name"]: s for s in trace.spans}
        assert set(by_name) == {"root", "stage-a", "stage-b"}
        # children share the trace id and point at the root span
        for child in ("stage-a", "stage-b"):
            assert by_name[child]["traceId"] == root.trace_id
            assert by_name[child]["parentId"] == by_name["root"]["spanId"]
        assert by_name["root"]["parentId"] is None
        # start-time ordering reconstructs the pipeline sequence
        ordered = sorted(trace.spans, key=lambda s: s["startWall"])
        assert [s["name"] for s in ordered] == ["root", "stage-a", "stage-b"]
        # durations nest: the root covers its children
        assert by_name["root"]["durationS"] >= by_name["stage-a"]["durationS"]
        assert by_name["stage-a"]["events"][0] == {
            "name": "checkpoint",
            "wall": by_name["stage-a"]["events"][0]["wall"],
            "attrs": {"n": 3},
        }
        assert by_name["root"]["attrs"] == {"batch": 1}

    def test_disabled_tracing_records_nothing(self):
        assert not tracing.enabled()
        with tracing.span("invisible") as sp:
            sp.event("nope")
            sp.set(x=1)
        assert len(tracing.TRACE_STORE) == 0

    def test_exception_annotates_and_propagates(self, traced):
        with pytest.raises(ValueError):
            with tracing.span("boom"):
                raise ValueError("kaput")
        trace = tracing.TRACE_STORE.last(1)[0]
        assert "ValueError: kaput" in trace.spans[0]["attrs"]["error"]

    def test_event_cap_bounds_span_memory(self, traced):
        with tracing.span("flood") as sp:
            for i in range(2 * tracing.MAX_EVENTS_PER_SPAN):
                sp.event("e", i=i)
        trace = tracing.TRACE_STORE.last(1)[0]
        assert len(trace.spans[0]["events"]) == tracing.MAX_EVENTS_PER_SPAN

    def test_traced_decorator_opens_a_span(self, traced):
        @tracing.traced("decorated.op")
        def work():
            return 41 + 1

        assert work() == 42
        assert tracing.TRACE_STORE.last(1)[0].spans[0]["name"] == "decorated.op"


class TestRingBuffer:
    def test_eviction_keeps_the_newest(self, traced):
        tracing.TRACE_STORE.set_capacity(3)
        for i in range(7):
            with tracing.span(f"t{i}"):
                pass
        assert len(tracing.TRACE_STORE) == 3
        assert [t.name for t in tracing.TRACE_STORE.last()] == ["t4", "t5", "t6"]

    def test_find_and_last_n(self, traced):
        ids = []
        for i in range(4):
            with tracing.span(f"t{i}") as sp:
                ids.append(sp.trace_id)
        assert tracing.TRACE_STORE.find(ids[1]).name == "t1"
        assert tracing.TRACE_STORE.find("nonexistent") is None
        assert [t.name for t in tracing.TRACE_STORE.last(2)] == ["t2", "t3"]


class TestExporters:
    def _make_trace(self):
        with tracing.span("root"):
            with tracing.span("child") as c:
                c.event("milestone", detail="x")
        return tracing.TRACE_STORE.last(1)[0]

    def test_jsonl_round_trip(self, traced):
        trace = self._make_trace()
        text = tracing.to_jsonl(trace)
        # every line is standalone JSON
        for line in text.strip().splitlines():
            json.loads(line)
        (back,) = tracing.from_jsonl(text)
        assert back.trace_id == trace.trace_id
        assert back.spans == trace.spans
        assert back.duration_s == trace.duration_s
        # concatenated exports round-trip as multiple traces
        assert len(tracing.from_jsonl(text + text)) == 2

    def test_chrome_export_shape(self, traced):
        trace = self._make_trace()
        doc = json.loads(json.dumps(tracing.to_chrome([trace])))
        events = doc["traceEvents"]
        complete = [e for e in events if e["ph"] == "X"]
        instants = [e for e in events if e["ph"] == "i"]
        assert {e["name"] for e in complete} == {"root", "child"}
        for event in complete:
            assert event["dur"] >= 0 and event["ts"] > 0
            assert event["pid"] == 1 and event["tid"] == 1
        assert instants[0]["name"] == "milestone"
        # span ids ride along for cross-referencing with /debug/traces
        assert all(e["args"]["traceId"] == trace.trace_id for e in complete)


class TestDebugTracesEndpoint:
    @pytest.fixture()
    def server(self):
        from karpenter_core_tpu.operator.httpserver import OperatorHTTP

        http = OperatorHTTP(metrics_port=0, health_port=0).start()
        yield http
        http.stop()

    def _get(self, port, path):
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as resp:
            return resp.status, resp.headers.get("Content-Type", ""), resp.read().decode()

    def test_serves_last_traces_as_json(self, traced, server):
        for i in range(3):
            with tracing.span(f"solve-{i}"):
                with tracing.span("encode"):
                    pass
        status, ctype, body = self._get(server.metrics_port, "/debug/traces")
        assert status == 200 and ctype.startswith("application/json")
        doc = json.loads(body)
        assert doc["enabled"] is True
        assert [t["name"] for t in doc["traces"]] == ["solve-0", "solve-1", "solve-2"]
        status, _, body = self._get(server.metrics_port, "/debug/traces?n=1")
        assert [t["name"] for t in json.loads(body)["traces"]] == ["solve-2"]

    def test_endpoint_locked_down_when_tracing_off(self, server):
        assert not tracing.enabled()
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            self._get(server.metrics_port, "/debug/traces")
        assert excinfo.value.code == 403

    def test_exemplars_query_is_a_debug_view(self, traced, server):
        from karpenter_core_tpu.metrics.registry import SOLVE_STAGE_DURATION

        SOLVE_STAGE_DURATION.labels("om-stage").observe(
            0.01, exemplar={"trace_id": "deadbeef"}
        )
        status, ctype, body = self._get(server.metrics_port, "/metrics?exemplars=1")
        assert status == 200
        assert '# {trace_id="deadbeef"}' in body
        # the default exposition (the scrape surface) never carries exemplars
        _, ctype_plain, body_plain = self._get(server.metrics_port, "/metrics")
        assert ctype_plain.startswith("text/plain")
        assert "deadbeef" not in body_plain

    def test_chrome_format_and_bad_n(self, traced, server):
        with tracing.span("solve"):
            pass
        status, _, body = self._get(
            server.metrics_port, "/debug/traces?format=chrome"
        )
        assert status == 200
        assert any(e["name"] == "solve" for e in json.loads(body)["traceEvents"])
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            self._get(server.metrics_port, "/debug/traces?n=bogus")
        assert excinfo.value.code == 400

    def test_surfaces_unschedulable_audit(self, traced, server):
        from karpenter_core_tpu.cloudprovider import fake as fake_cp
        from karpenter_core_tpu.operator.kubeclient import KubeClient
        from karpenter_core_tpu.solver.builder import build_scheduler
        from karpenter_core_tpu.testing import make_pod, make_provisioner

        kube = KubeClient()
        kube.create(make_provisioner())
        scheduler = build_scheduler(
            kube, fake_cp.FakeCloudProvider(), cluster=None, pods=[],
            state_nodes=[], daemonset_pods=[],
        )
        results = scheduler.solve([make_pod(requests={"cpu": 10_000})])
        assert results.failed_pods
        _, _, body = self._get(server.metrics_port, "/debug/traces")
        audits = json.loads(body)["audits"]
        assert len(audits) == 1
        assert audits[0]["engine"] == "host"
        assert "resources" in audits[0]["predicates"]
        assert audits[0]["rejections"][0]["candidate"] == "template/default"


class TestTracePropagation:
    """Cross-boundary propagation (docs/OBSERVABILITY.md): wire_context /
    span_remote carry one trace id across a process boundary, and
    TraceStore.tree merges the per-side segments into one tree."""

    def test_wire_context_requires_tracing_and_a_span(self, traced):
        assert tracing.wire_context() is None  # enabled, but no active span
        tracing.disable()
        with tracing.span("off"):
            assert tracing.wire_context() is None
        tracing.enable()
        with tracing.span("on") as sp:
            ctx = tracing.wire_context()
        assert ctx == {"traceId": sp.trace_id, "spanId": sp.span_id}

    def test_span_remote_adopts_the_remote_trace(self, traced):
        # "client side": a local root span whose context goes on the wire
        with tracing.span("client.solve") as client:
            ctx = tracing.wire_context()
        # "server side": a store-root segment under the client's trace id
        with tracing.span_remote("solve.tenant", ctx, tenant="acme") as srv:
            assert srv.trace_id == client.trace_id
            with tracing.span("solve.incremental"):
                pass
        segments = tracing.TRACE_STORE.last()
        assert [t.name for t in segments] == ["client.solve", "solve.tenant"]
        assert segments[0].trace_id == segments[1].trace_id
        server_root = segments[1].spans[-1]
        assert server_root["parentId"] == client.span_id
        assert server_root["attrs"]["tenant"] == "acme"

    def test_span_remote_without_context_is_a_local_root(self, traced):
        with tracing.span_remote("solve.tenant", None, tenant="t") as sp:
            pass
        assert sp.trace_id  # minted locally, still lands in the store
        assert tracing.TRACE_STORE.last(1)[0].spans[0]["parentId"] is None

    def test_span_remote_disabled_is_noop(self):
        assert not tracing.enabled()
        with tracing.span_remote("x", {"traceId": "a", "spanId": "b"}) as sp:
            sp.event("ignored")
        assert len(tracing.TRACE_STORE) == 0

    def test_tree_merges_segments_in_wall_order(self, traced):
        with tracing.span("client.solve") as client:
            ctx = tracing.wire_context()
        with tracing.span_remote("solve.tenant", ctx):
            with tracing.span("solve.coalesced"):
                pass
        tree = tracing.TRACE_STORE.tree(client.trace_id)
        assert tree.trace_id == client.trace_id
        names = [s["name"] for s in tree.spans]
        assert set(names) == {"client.solve", "solve.tenant",
                              "solve.coalesced"}
        assert names[0] == "client.solve"  # earliest segment leads
        starts = [s["startWall"] for s in tree.spans]
        assert starts == sorted(starts)
        assert tracing.TRACE_STORE.tree("missing") is None

    def test_debug_traces_trace_id_query(self, traced):
        from karpenter_core_tpu.operator.httpserver import OperatorHTTP

        with tracing.span("client.solve") as client:
            ctx = tracing.wire_context()
        with tracing.span_remote("solve.tenant", ctx):
            pass
        http = OperatorHTTP(metrics_port=0, health_port=0).start()
        try:
            url = (f"http://127.0.0.1:{http.metrics_port}/debug/traces"
                   f"?trace_id={client.trace_id}")
            with urllib.request.urlopen(url, timeout=5) as resp:
                doc = json.loads(resp.read().decode())
            assert doc["trace"]["traceId"] == client.trace_id
            assert {s["name"] for s in doc["trace"]["spans"]} == {
                "client.solve", "solve.tenant"
            }
            with pytest.raises(urllib.error.HTTPError) as excinfo:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{http.metrics_port}/debug/traces"
                    "?trace_id=feedface", timeout=5,
                )
            assert excinfo.value.code == 404
        finally:
            http.stop()


class TestDecisionAudit:
    def test_predicate_classification(self):
        cases = {
            "did not tolerate gpu=true:NoSchedule": "taints",
            "IP=0.0.0.0 Port=80 Proto=TCP": "host-ports",
            "would exceed node volume limits": "volumes",
            "exceeds node resources": "resources",
            "no instance type satisfied resources {...}": "resources",
            "unsatisfiable topology constraint for pod anti-affinity, key=zone": "affinity",
            "unsatisfiable topology constraint for topology spread, key=zone": "topology",
            "incompatible requirements, key zone": "requirements",
            "": "unknown",
            "something else entirely": "other",
        }
        for err, expected in cases.items():
            assert tracing.classify_rejection(err) == expected, err

    def test_taint_rejection_audited_per_candidate(self, traced):
        from karpenter_core_tpu.apis.objects import Taint
        from karpenter_core_tpu.cloudprovider import fake as fake_cp
        from karpenter_core_tpu.operator.kubeclient import KubeClient
        from karpenter_core_tpu.solver.builder import build_scheduler
        from karpenter_core_tpu.testing import make_pod, make_provisioner

        kube = KubeClient()
        kube.create(
            make_provisioner(taints=[Taint("team", "a", "NoSchedule")])
        )
        scheduler = build_scheduler(
            kube, fake_cp.FakeCloudProvider(), cluster=None, pods=[],
            state_nodes=[], daemonset_pods=[],
        )
        with tracing.span("solve"):
            results = scheduler.solve([make_pod(requests={"cpu": 1})])
        assert results.failed_pods
        (audit,) = tracing.TRACE_STORE.last(1)[0].audits()
        assert audit["predicates"] == ["taints"]

    def test_no_audit_state_accumulates_when_disabled(self):
        from karpenter_core_tpu.cloudprovider import fake as fake_cp
        from karpenter_core_tpu.operator.kubeclient import KubeClient
        from karpenter_core_tpu.solver.builder import build_scheduler
        from karpenter_core_tpu.testing import make_pod, make_provisioner

        kube = KubeClient()
        kube.create(make_provisioner())
        scheduler = build_scheduler(
            kube, fake_cp.FakeCloudProvider(), cluster=None, pods=[],
            state_nodes=[], daemonset_pods=[],
        )
        scheduler.solve([make_pod(requests={"cpu": 10_000})])
        assert scheduler._audit == {}


class TestHistogramExposition:
    def test_cumulative_buckets_with_inf(self):
        registry = Registry()
        hist = registry.histogram("h_test_seconds", "t", buckets=[0.5, 1, 10])
        for value in (0.25, 0.25, 0.75, 4, 48):  # binary-exact: sum renders cleanly
            hist.observe(value)
        rendered = registry.render()
        assert 'h_test_seconds_bucket{le="0.5"} 2.0' in rendered
        assert 'h_test_seconds_bucket{le="1"} 3.0' in rendered
        assert 'h_test_seconds_bucket{le="10"} 4.0' in rendered
        assert 'h_test_seconds_bucket{le="+Inf"} 5.0' in rendered
        assert "h_test_seconds_count 5.0" in rendered
        assert "h_test_seconds_sum 53.25" in rendered

    def test_boundary_value_counts_into_its_le_bucket(self):
        hist = Histogram("h_edge", "t", buckets=[1.0, 2.0])
        hist.observe(1.0)  # le="1" means value <= 1
        samples = {
            (name, labels.get("le")): value for name, labels, value in hist.samples()
        }
        assert samples[("h_edge_bucket", "1")] == 1.0
        assert samples[("h_edge_bucket", "2")] == 1.0
        assert samples[("h_edge_bucket", "+Inf")] == 1.0

    def test_labeled_histogram_buckets_per_child(self):
        registry = Registry()
        hist = registry.histogram("h_lbl", "t", ("stage",), buckets=[1])
        hist.labels("encode").observe(0.5)
        hist.labels("solve").observe(2.0)
        rendered = registry.render()
        assert 'h_lbl_bucket{le="1",stage="encode"} 1.0' in rendered
        assert 'h_lbl_bucket{le="1",stage="solve"} 0.0' in rendered
        assert 'h_lbl_bucket{le="+Inf",stage="solve"} 1.0' in rendered

    def test_exemplars_render_on_request_only(self):
        registry = Registry()
        hist = registry.histogram("h_ex", "t", buckets=[1])
        hist.observe(0.5, exemplar={"trace_id": "abc123"})
        plain = registry.render()
        assert "abc123" not in plain
        with_ex = registry.render(exemplars=True)
        assert '# {trace_id="abc123"} 0.5' in with_ex

    def test_span_close_feeds_stage_histogram_with_trace_exemplar(self, traced):
        from karpenter_core_tpu.metrics.registry import SOLVE_STAGE_DURATION

        with tracing.span("exemplar-stage") as sp:
            trace_id = sp.trace_id
        child = SOLVE_STAGE_DURATION.labels("exemplar-stage")
        assert child.count >= 1
        exemplars = {ex[0]["trace_id"] for ex in child.exemplars.values()}
        assert trace_id in exemplars


class TestBackendProbe:
    @pytest.fixture(autouse=True)
    def _no_liveness(self, monkeypatch):
        """The pre-probe relay liveness check is exercised explicitly below;
        everywhere else it must not intercept the monkeypatched subprocess
        (an ambient PALLAS_AXON_POOL_IPS pointing at a dead relay would
        otherwise change these tests' outcomes)."""
        monkeypatch.setenv("KC_PROBE_LIVENESS_TIMEOUT_S", "0")

    def test_timeout_is_recorded_not_raised(self, monkeypatch, traced):
        from karpenter_core_tpu.solver import backendprobe

        def hang(*args, **kwargs):
            raise subprocess.TimeoutExpired(cmd="probe", timeout=kwargs["timeout"])

        monkeypatch.setattr(backendprobe.subprocess, "run", hang)
        backendprobe.reset_fail_cache()
        before = backendprobe.PROBE_TOTAL.labels("timeout").value
        with tracing.span("bringup"):
            result = backendprobe.probe_once(60.0, attempt=1)
        assert result.outcome == "timeout" and result.platform is None
        assert "hung past 60s" in result.error
        assert backendprobe.PROBE_TOTAL.labels("timeout").value == before + 1
        (span_rec,) = tracing.TRACE_STORE.last(1)[0].spans
        (event,) = span_rec["events"]
        assert event["name"] == "backend.probe"
        assert event["attrs"]["outcome"] == "timeout"

    def test_acquire_backend_falls_back_after_failures(self, monkeypatch):
        from karpenter_core_tpu.solver import backendprobe

        def broken(*args, **kwargs):
            raise subprocess.TimeoutExpired(cmd="probe", timeout=kwargs["timeout"])

        monkeypatch.setattr(backendprobe.subprocess, "run", broken)
        backendprobe.reset_fail_cache()
        state = backendprobe.acquire_backend(
            max_attempts=2, probe_timeout_s=1.0, sleep=lambda s: None
        )
        assert state.fell_back and state.platform == "cpu"
        assert state.attempts == 2
        # one REAL probe per failure window: the first timeout is cached and
        # the ladder short-circuits instead of re-paying the hang
        assert [p["outcome"] for p in state.probes] == ["timeout", "cached"]
        assert any("short-circuited" in f for f in state.probe_failures)

    def test_failure_cache_expires_and_success_clears_it(self, monkeypatch):
        from karpenter_core_tpu.solver import backendprobe

        def broken(*args, **kwargs):
            raise subprocess.TimeoutExpired(cmd="probe", timeout=kwargs["timeout"])

        monkeypatch.setattr(backendprobe.subprocess, "run", broken)
        backendprobe.reset_fail_cache()
        first = backendprobe.probe_once(1.0)
        assert first.outcome == "timeout" and not first.cached
        second = backendprobe.probe_once(1.0)
        assert second.cached and second.platform is None
        assert "cached failure" in second.error

        # TTL 0 disables the cache entirely
        monkeypatch.setenv("KC_PROBE_FAIL_TTL_S", "0")
        third = backendprobe.probe_once(1.0)
        assert third.outcome == "timeout" and not third.cached
        monkeypatch.delenv("KC_PROBE_FAIL_TTL_S")

        class FakeProc:
            returncode = 0
            stdout = "PLATFORM=tpu\n"
            stderr = ""

        monkeypatch.setattr(
            backendprobe.subprocess, "run", lambda *a, **k: FakeProc()
        )
        # expiry: pretend the window passed, the next probe runs for real
        backendprobe._fail_cache = (
            backendprobe.time.monotonic() - 3600.0, first,
        )
        ok = backendprobe.probe_once(1.0)
        assert ok.outcome == "ok" and ok.platform == "tpu"
        # ...and success cleared the cache
        assert backendprobe._cached_failure() is None
        backendprobe.reset_fail_cache()

    def test_success_short_circuits(self, monkeypatch):
        from karpenter_core_tpu.solver import backendprobe

        class FakeProc:
            returncode = 0
            stdout = "PLATFORM=tpu\n"
            stderr = ""

        monkeypatch.setattr(
            backendprobe.subprocess, "run", lambda *a, **k: FakeProc()
        )
        backendprobe.reset_fail_cache()
        state = backendprobe.acquire_backend(max_attempts=5, sleep=lambda s: None)
        assert state.platform == "tpu" and not state.fell_back
        assert state.attempts == 1 and len(state.probes) == 1

    def test_probe_timeout_env_var(self, monkeypatch):
        """KC_PROBE_TIMEOUT_S drives the per-attempt subprocess timeout when
        the caller doesn't pin one (ISSUE 3 satellite)."""
        from karpenter_core_tpu.solver import backendprobe

        seen = {}

        def record(*args, **kwargs):
            seen["timeout"] = kwargs["timeout"]
            raise subprocess.TimeoutExpired(cmd="probe", timeout=kwargs["timeout"])

        monkeypatch.setattr(backendprobe.subprocess, "run", record)
        backendprobe.reset_fail_cache()
        monkeypatch.setenv("KC_PROBE_TIMEOUT_S", "7.5")
        result = backendprobe.probe_once()
        assert seen["timeout"] == 7.5
        assert "hung past 8s" in result.error or "hung past 7" in result.error
        # an explicit timeout still wins over the env
        backendprobe.reset_fail_cache()
        backendprobe.probe_once(3.0)
        assert seen["timeout"] == 3.0
        # garbage env falls back to the default
        monkeypatch.setenv("KC_PROBE_TIMEOUT_S", "not-a-number")
        assert backendprobe.probe_timeout_s() == backendprobe.DEFAULT_PROBE_TIMEOUT_S
        backendprobe.reset_fail_cache()

    def test_acquire_backend_honors_env_timeout(self, monkeypatch):
        from karpenter_core_tpu.solver import backendprobe

        seen = []

        def record(*args, **kwargs):
            seen.append(kwargs["timeout"])
            raise subprocess.TimeoutExpired(cmd="probe", timeout=kwargs["timeout"])

        monkeypatch.setattr(backendprobe.subprocess, "run", record)
        backendprobe.reset_fail_cache()
        monkeypatch.setenv("KC_PROBE_TIMEOUT_S", "2")
        state = backendprobe.acquire_backend(max_attempts=3, sleep=lambda s: None)
        assert state.fell_back
        # one real probe (at the env timeout), then the cached short-circuit
        assert seen == [2.0]
        assert [p["outcome"] for p in state.probes] == ["timeout", "cached"]
        backendprobe.reset_fail_cache()

    def test_probe_failure_carries_child_stderr_tail(self, monkeypatch):
        """A crashing probe child's traceback must land in the structured
        failure record — BENCH_r02..r05 had nothing to debug a failed
        bring-up with except the wall clock (ISSUE 6 satellite)."""
        from karpenter_core_tpu.solver import backendprobe

        trace_text = (
            "Traceback (most recent call last):\n"
            '  File "<string>", line 1, in <module>\n'
            "RuntimeError: axon relay handshake refused\n"
        )

        class CrashProc:
            returncode = 1
            stdout = ""
            stderr = trace_text

        monkeypatch.setattr(
            backendprobe.subprocess, "run", lambda *a, **k: CrashProc()
        )
        backendprobe.reset_fail_cache()
        result = backendprobe.probe_once(1.0)
        assert result.outcome == "error"
        assert "axon relay handshake refused" in result.error  # last line
        assert "Traceback" in result.stderr_tail  # the full evidence
        # ...and it rides acquire_backend's per-attempt record (bench JSON)
        backendprobe.reset_fail_cache()
        state = backendprobe.acquire_backend(max_attempts=1, sleep=lambda s: None)
        assert "Traceback" in state.probes[0]["stderr_tail"]
        backendprobe.reset_fail_cache()

    def test_timeout_carries_partial_stderr(self, monkeypatch):
        from karpenter_core_tpu.solver import backendprobe

        def hang(*args, **kwargs):
            raise subprocess.TimeoutExpired(
                cmd="probe", timeout=kwargs["timeout"],
                stderr="relay: connecting to 10.0.0.9...",
            )

        monkeypatch.setattr(backendprobe.subprocess, "run", hang)
        backendprobe.reset_fail_cache()
        result = backendprobe.probe_once(1.0)
        assert result.outcome == "timeout"
        assert "connecting to 10.0.0.9" in result.stderr_tail
        backendprobe.reset_fail_cache()

    def test_liveness_check_fails_fast_on_dead_relay(self, monkeypatch):
        """A provably-unreachable relay fails the probe in seconds (no
        subprocess spawned) instead of hanging the full probe timeout."""
        from karpenter_core_tpu.solver import backendprobe

        def must_not_spawn(*args, **kwargs):
            raise AssertionError("liveness failure must skip the spawn")

        monkeypatch.setattr(backendprobe.subprocess, "run", must_not_spawn)
        # 127.0.0.1:9 (discard) is reliably refused without a listener
        monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1:9")
        monkeypatch.setenv("KC_PROBE_LIVENESS_TIMEOUT_S", "0.5")
        backendprobe.reset_fail_cache()
        result = backendprobe.probe_once(60.0)
        assert result.outcome == "error" and result.platform is None
        assert result.error.startswith("liveness:")
        assert result.duration_s < 30.0
        # the fast failure is cached like any other: the ladder short-circuits
        assert backendprobe.probe_once(60.0).cached
        backendprobe.reset_fail_cache()

    def test_liveness_check_is_conservative(self, monkeypatch):
        """No relay env ⇒ no check; unparseable entries ⇒ proceed; a live
        endpoint ⇒ proceed.  Only definitive unreachability fails fast."""
        import socket as socket_mod

        from karpenter_core_tpu.solver import backendprobe

        monkeypatch.setenv("KC_PROBE_LIVENESS_TIMEOUT_S", "0.5")
        monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)
        assert backendprobe.liveness_check() is None
        monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "relay:not-a-port")
        assert backendprobe.liveness_check() is None
        # a genuinely listening endpoint passes
        listener = socket_mod.socket()
        listener.bind(("127.0.0.1", 0))
        listener.listen(1)
        try:
            port = listener.getsockname()[1]
            monkeypatch.setenv("PALLAS_AXON_POOL_IPS", f"127.0.0.1:{port}")
            assert backendprobe.liveness_check() is None
        finally:
            listener.close()
        # disabled via env
        monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1:9")
        monkeypatch.setenv("KC_PROBE_LIVENESS_TIMEOUT_S", "0")
        assert backendprobe.liveness_check() is None

    def test_liveness_endpoint_parsing_handles_ipv6(self, monkeypatch):
        """IPv6 relay entries must not be split at the wrong colon: bracketed
        [v6]:port connects to the address inside the brackets, a bare v6
        address (ambiguous trailing group) is treated as a port-less host —
        numeric, so it resolves and the real probe runs — never as a bogus
        host/port pair that would falsely fail a live relay."""
        from karpenter_core_tpu.solver import backendprobe

        assert backendprobe._parse_endpoint("[fdaa::2]:8471") == ("fdaa::2", 8471)
        assert backendprobe._parse_endpoint("[fdaa::2]") == ("fdaa::2", None)
        assert backendprobe._parse_endpoint("fe80::1:8471") == ("fe80::1:8471", None)
        assert backendprobe._parse_endpoint("10.0.0.9:8471") == ("10.0.0.9", 8471)
        assert backendprobe._parse_endpoint("relay-host") == ("relay-host", None)
        assert backendprobe._parse_endpoint("[fdaa::2") is None  # unterminated
        assert backendprobe._parse_endpoint("[fdaa::2]x") is None
        assert backendprobe._parse_endpoint("relay:not-a-port") is None
        # end to end: a bare numeric v6 entry resolves ⇒ check passes through
        monkeypatch.setenv("KC_PROBE_LIVENESS_TIMEOUT_S", "0.5")
        monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "::1:8471")
        assert backendprobe.liveness_check() is None


@pytest.mark.compile
class TestSolvePipelineSpans:
    def test_small_solve_produces_the_six_stage_spans(self, traced):
        from karpenter_core_tpu.cloudprovider import fake as fake_cp
        from karpenter_core_tpu.models.columnar import PodIngest
        from karpenter_core_tpu.ops import solve as solve_ops
        from karpenter_core_tpu.solver.tpu import TPUSolver
        from karpenter_core_tpu.testing import make_pod, make_provisioner

        solver = TPUSolver(fake_cp.FakeCloudProvider(), [make_provisioner()])
        pods = [make_pod(requests={"cpu": "500m"}) for _ in range(8)]
        with tracing.span("test.solve"):
            ingest = PodIngest()
            ingest.add_all(pods)
            snapshot = solver.encode(ingest)
            out = solve_ops.solve(snapshot)
            results = solver.decode(snapshot, out)
            assert results.new_nodes
            results.new_nodes[0].instance_type_names  # noqa: B018 - materialize
        trace = tracing.TRACE_STORE.last(1)[0]
        names = {s["name"] for s in trace.spans}
        assert {"ingest", "encode", "dispatch", "solve", "decode",
                "decode.fetch", "materialize"} <= names
        stages = trace.stage_durations()
        assert all(stages[n] >= 0 for n in ("ingest", "encode", "solve", "decode"))
        # the fetch child never exceeds its decode parent (the split is real)
        assert stages["decode.fetch"] <= stages["decode"] + 1e-6
        # every span belongs to the one trace rooted at test.solve
        assert {s["traceId"] for s in trace.spans} == {trace.trace_id}
