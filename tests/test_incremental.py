"""Incremental warm-start solver (ISSUE 7): snapshot-store invariants,
fallback-policy decisions, and delta-vs-full parity.

Three layers:

  - ``models.store`` is pure host bookkeeping: version monotonicity,
    diff ∘ apply == identity over randomized membership maps, per-plane
    digest stability ACROSS PROCESSES (PYTHONHASHSEED independence), and
    input-digest sensitivity.
  - ``FallbackPolicy`` decisions are pinned per reason string — the
    ``solve.mode`` amortization contract docs/INCREMENTAL.md documents.
  - ``IncrementalSolveSession`` parity: over randomized steady-churn event
    sequences the delta lineage's final per-node assignment multiset must be
    IDENTICAL to a from-scratch full solve of the same population, at small N
    in tier-1 (kernel-scale churn is the bench's churn_line and the slow
    marker below).  KC_SOLVER_INCREMENTAL=0 keeps the old path as the
    degenerate case.
"""

import copy
import json
import random
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from karpenter_core_tpu.apis.objects import new_uid
from karpenter_core_tpu.cloudprovider import fake as fake_cp
from karpenter_core_tpu.metrics import REGISTRY
from karpenter_core_tpu.models import store as store_mod
from karpenter_core_tpu.models.columnar import PodIngest
from karpenter_core_tpu.models.store import (
    SnapshotDelta,
    SnapshotStore,
    diff_members,
    diff_snapshots,
)
from karpenter_core_tpu.solver.incremental import (
    MODE_DELTA,
    MODE_FULL,
    FallbackPolicy,
    IncrementalSolveSession,
    incremental_enabled,
    node_signature_of,
)
from karpenter_core_tpu.solver.tpu import TPUSolver
from karpenter_core_tpu.testing import make_pod, make_pods, make_provisioner


def _solver(n_provisioners: int = 1) -> TPUSolver:
    provisioners = [
        make_provisioner(name=f"prov-{i}") for i in range(n_provisioners)
    ]
    return TPUSolver(fake_cp.FakeCloudProvider(), provisioners)


def _population(n: int = 40):
    """A small mixed population: two generic shapes + a zone-spread shape."""
    pods = make_pods(n // 2, requests={"cpu": "500m"})
    pods += make_pods(n // 4, requests={"cpu": 1})
    pods += make_pods(
        n - len(pods),
        requests={"cpu": "250m"},
        labels={"app": "spread"},
    )
    return pods


# -- snapshot store ------------------------------------------------------------


class TestSnapshotStore:
    def test_version_monotonic(self):
        solver = _solver()
        store = SnapshotStore()
        versions = []
        for _ in range(3):
            ingest = PodIngest()
            ingest.add_all(_population(12))
            snap = solver.encode(ingest)
            versions.append(store.commit(snap).version)
        assert versions == [1, 2, 3]
        assert store.current.version == 3

    def test_ingest_version_counts_effective_mutations(self):
        ingest = PodIngest()
        assert ingest.version == 0
        pods = make_pods(3, requests={"cpu": 1})
        ingest.add_all(pods)
        assert ingest.version == 3
        assert ingest.remove(pods[0].uid) is True
        assert ingest.version == 4
        assert ingest.remove(pods[0].uid) is False  # no-op: not tracked
        assert ingest.version == 4
        assert ingest.get(pods[1].uid) is pods[1]
        assert ingest.get("nope") is None

    def test_diff_apply_identity_fuzz(self):
        rng = random.Random(1729)
        for trial in range(50):
            keys = [(("k", i),) for i in range(rng.randint(1, 6))]
            prev = {
                k: tuple(f"u{trial}-{i}-{j}" for j in range(rng.randint(0, 5)))
                for i, k in enumerate(keys)
                if rng.random() < 0.8
            }
            cur = {}
            for i, k in enumerate(keys):
                if rng.random() < 0.8:
                    survivors = tuple(
                        u for u in prev.get(k, ()) if rng.random() < 0.7
                    )
                    added = tuple(
                        f"n{trial}-{i}-{j}" for j in range(rng.randint(0, 3))
                    )
                    if survivors + added:
                        cur[k] = survivors + added
            delta = diff_members(prev, cur, from_version=7)
            assert delta.apply(prev) == cur, (trial, prev, cur)
            assert delta.to_version == 8
            assert delta.pods_before == sum(len(u) for u in prev.values())
            assert delta.pods_after == sum(len(u) for u in cur.values())

    def test_diff_snapshots_structure(self):
        solver = _solver()
        store = SnapshotStore()
        pods = make_pods(8, requests={"cpu": "500m"})
        other = make_pods(4, requests={"cpu": 2})
        ingest = PodIngest()
        ingest.add_all(pods + other)
        v1 = store.commit(solver.encode(ingest))

        ingest.remove(pods[0].uid)
        replacement = copy.deepcopy(pods[1])
        replacement.metadata.name = "repl"
        replacement.metadata.uid = new_uid()
        ingest.add(replacement)
        v2 = store.commit(solver.encode(ingest))

        delta = diff_snapshots(v1, v2)
        assert delta.from_version == 1 and delta.to_version == 2
        assert delta.added_count == 1 and delta.evicted_count == 1
        assert not delta.new_classes and not delta.removed_classes
        assert not delta.changed_planes  # same catalog, same axes
        assert 0 < delta.delta_fraction < 0.25
        # extents + touched partition the class axis
        touched = set(delta.touched_classes)
        spanned = set()
        for start, end in delta.unchanged_extents:
            spanned.update(range(start, end))
        assert not (touched & spanned)
        assert touched | spanned == set(range(len(v2.rows)))
        assert delta.touched_mask_words > 0
        # the store's convenience diff: current version ⇒ None, an older
        # version diffs FROM current (the reverse walk swaps add/evict)
        assert store.diff(v2) is None
        back = store.diff(v1)
        assert back.added_count == 1 and back.evicted_count == 1
        # replaying the membership delta reproduces v2's summary
        assert diff_members(v1.summary(), v2.summary()).apply(v1.summary()) \
            == v2.summary()

    def test_digest_stability_across_processes(self, tmp_path):
        """Same inputs ⇒ same per-plane digests in a different process with a
        different PYTHONHASHSEED — digests are content, not id()/hash()."""
        script = textwrap.dedent(
            """
            import json, sys
            from karpenter_core_tpu.cloudprovider import fake as fake_cp
            from karpenter_core_tpu.models import store as store_mod
            from karpenter_core_tpu.models.columnar import PodIngest
            from karpenter_core_tpu.solver.tpu import TPUSolver
            from karpenter_core_tpu.testing import make_pods, make_provisioner

            solver = TPUSolver(
                fake_cp.FakeCloudProvider(), [make_provisioner(name="p")]
            )
            ingest = PodIngest()
            pods = make_pods(6, requests={"cpu": "500m"})
            for i, p in enumerate(pods):
                p.metadata.name = f"pin-{i}"
                p.metadata.uid = f"uid-{i}"
            ingest.add_all(pods)
            print(json.dumps(store_mod.snapshot_digests(solver.encode(ingest))))
            """
        )
        outs = []
        for seed in ("0", "31337"):
            proc = subprocess.run(
                [sys.executable, "-c", script],
                capture_output=True, text=True, timeout=120,
                env={
                    **__import__("os").environ,
                    "PYTHONHASHSEED": seed,
                    "JAX_PLATFORMS": "cpu",
                },
                cwd=str(__import__("pathlib").Path(__file__).parent.parent),
            )
            assert proc.returncode == 0, proc.stderr[-2000:]
            outs.append(json.loads(proc.stdout.strip().splitlines()[-1]))
        assert outs[0] == outs[1]
        assert set(outs[0]) == {
            "catalog", "templates", "vocab", "classes", "groups", "axes",
            "policy",
        }

    def test_supply_digest_sensitivity(self):
        from karpenter_core_tpu.testing import harness

        env = harness.make_environment()
        env.kube.create(make_provisioner(name="default"))
        node_pods = make_pods(1, requests={"cpu": 1})
        # no nodes, no bound pods: stable empty digest
        assert store_mod.supply_digest([], []) == store_mod.supply_digest([], [])
        d0 = store_mod.supply_digest([], [])
        bound = make_pod(requests={"cpu": 1}, node_name="n-1", phase="Running")
        assert store_mod.supply_digest([], [bound]) != d0
        assert node_pods  # silence unused

    def test_catalog_digest_sensitivity(self):
        provs = [make_provisioner(name="p")]
        provider = fake_cp.FakeCloudProvider()
        by_name = {"p": provider.get_instance_types(provs[0])}
        d0 = store_mod.catalog_digest(provs, by_name)
        assert d0 == store_mod.catalog_digest(provs, by_name)
        provs2 = [make_provisioner(name="p")]
        provs2[0].metadata.resource_version = "999"
        assert store_mod.catalog_digest(provs2, by_name) != d0


# -- fallback policy -----------------------------------------------------------


def _mk_delta(**kw) -> SnapshotDelta:
    base = dict(from_version=1, to_version=2, pods_before=100, pods_after=100)
    base.update(kw)
    return SnapshotDelta(**base)


class TestFallbackPolicy:
    def test_reasons(self):
        pol = FallbackPolicy(
            enabled=True, max_delta_fraction=0.25, audit_interval=4
        )
        assert pol.decide(None, 0, 0) == (MODE_FULL, "first")
        d = _mk_delta(changed_planes=("supply",))
        assert pol.decide(d, 0, 0)[0] == MODE_FULL
        assert pol.decide(d, 0, 0)[1].startswith("supply-changed")
        d = _mk_delta(new_classes=(("unseen",),))
        assert pol.decide(d, 0, 0) == (MODE_FULL, "class-shape")
        # a key the previous tensors know repairs fine
        assert pol.decide(d, 0, 0, known_classes={("unseen",): 3})[0] \
            == MODE_DELTA
        # removed classes alone never force a full solve
        d = _mk_delta(removed_classes=(("gone",),))
        assert pol.decide(d, 0, 0)[0] == MODE_DELTA
        d = _mk_delta(added={("k",): tuple(f"u{i}" for i in range(30))})
        assert pol.decide(d, 0, 0)[1].startswith("delta-fraction")
        d = _mk_delta(added={("k",): ("u1",)})
        assert pol.decide(d, 4, 0) == (MODE_FULL, "audit")
        assert pol.decide(d, 3, 0) == (MODE_DELTA, "delta")

    def test_disabled_and_materialized(self, monkeypatch):
        assert FallbackPolicy(enabled=False).decide(None, 0, 0) \
            == (MODE_FULL, "disabled")
        pol = FallbackPolicy(enabled=True, materialized=True)
        d = _mk_delta(added={("k",): ("u1",)})
        assert pol.decide(d, 0, 1) == (MODE_FULL, "materialized-slots")
        assert pol.decide(d, 0, 0)[0] == MODE_DELTA
        monkeypatch.setenv("KC_SOLVER_INCREMENTAL", "0")
        assert not incremental_enabled()
        assert not FallbackPolicy.from_env().enabled
        monkeypatch.setenv("KC_SOLVER_INCREMENTAL", "1")
        assert incremental_enabled()

    def test_from_env_knobs(self, monkeypatch):
        monkeypatch.setenv("KC_DELTA_MAX_FRACTION", "0.5")
        monkeypatch.setenv("KC_DELTA_AUDIT_INTERVAL", "7")
        pol = FallbackPolicy.from_env(materialized=True)
        assert pol.max_delta_fraction == 0.5
        assert pol.audit_interval == 7
        assert pol.materialized is True


# -- session parity (kernel, small N) ------------------------------------------


def _mode_count(mode: str) -> float:
    from karpenter_core_tpu.solver.incremental import SOLVE_MODE

    for _name, labels, value in SOLVE_MODE.samples():
        if labels.get("mode") == mode:
            return value
    return 0.0


def _full_signature(solver, ingest):
    from karpenter_core_tpu.ops import solve as solve_ops
    import jax

    snapshot = solver.encode(ingest)
    out = solve_ops.solve(snapshot)
    a, ae = jax.device_get((out.assign, out.assign_existing))
    # stable class identities, not row indices (a fully-churned class
    # re-enters a fresh encode at a different row)
    keys = [store_mod.class_key(c) for c in snapshot.classes]
    return node_signature_of(np.asarray(a), keys) + node_signature_of(
        np.asarray(ae), keys
    )


def _churn(ingest, rng, fraction=0.1):
    """Replace ``fraction`` of the population with same-shaped fresh pods —
    the steady-state event the delta path amortizes."""
    members = ingest.class_members()
    uids = [(sig, u) for sig, us in members.items() for u in us]
    k = max(int(len(uids) * fraction), 1)
    victims = rng.sample(uids, k)
    for i, (_sig, uid) in enumerate(victims):
        rep = copy.deepcopy(ingest.get(uid))
        ingest.remove(uid)
        rep.metadata.name = f"churn-{rng.randint(0, 1 << 30)}-{i}"
        rep.metadata.uid = new_uid()
        rep.spec.node_name = ""
        ingest.add(rep)


class TestSessionParity:
    def test_steady_churn_matches_full_solve(self):
        """Randomized replace-churn sequences: every repair tick's cumulative
        assignments equal a from-scratch solve's (canonical per-node class
        loads) — the ISSUE 7 parity pin, at tier-1 scale."""
        rng = random.Random(7)
        solver = _solver()
        ingest = PodIngest()
        ingest.add_all(_population(40))
        session = IncrementalSolveSession(
            solver,
            FallbackPolicy(enabled=True, audit_interval=0,
                           max_delta_fraction=0.9),
        )
        session.solve(ingest)
        assert session.last_mode == MODE_FULL and session.last_reason == "first"
        for tick in range(4):
            _churn(ingest, rng, fraction=0.1)
            session.solve(ingest)
            assert session.last_mode == MODE_DELTA, session.last_reason
            assert session.node_signature() == _full_signature(solver, ingest), (
                f"tick {tick} diverged"
            )
        agg = session.aggregates()
        assert agg["scheduled"] == len(ingest)
        assert agg["failed"] == 0

    def test_windowed_repair_matches_full_solve(self, monkeypatch):
        """Same parity with the bounded repair window forced on at tier-1
        scale (KC_DELTA_WINDOW shrinks the bucket below n_slots)."""
        monkeypatch.setenv("KC_DELTA_WINDOW", "16")
        rng = random.Random(11)
        solver = _solver()
        ingest = PodIngest()
        ingest.add_all(_population(48))
        session = IncrementalSolveSession(
            solver,
            FallbackPolicy(enabled=True, audit_interval=0,
                           max_delta_fraction=0.9),
        )
        session.solve(ingest)
        for _ in range(3):
            _churn(ingest, rng, fraction=0.08)
            session.solve(ingest)
            assert session.last_mode == MODE_DELTA, session.last_reason
            assert session.node_signature() == _full_signature(solver, ingest)

    def test_fully_churned_class_keeps_parity_across_row_reorder(self):
        """Evicting EVERY member of a class deletes its ingest slot; same-shape
        replacements re-mint it at the END of insertion order, so a fresh
        encode's class axis reorders among equal-request classes.  The parity
        signature labels loads by class identity, not row index — identical
        placements must not read as divergence."""
        solver = _solver()
        ingest = PodIngest()
        small = make_pods(4, requests={"cpu": "250m"})
        big = make_pods(36, requests={"cpu": "500m"})
        ingest.add_all(small + big)
        session = IncrementalSolveSession(
            solver,
            FallbackPolicy(enabled=True, audit_interval=0,
                           max_delta_fraction=0.9),
        )
        session.solve(ingest)
        for p in small:
            ingest.remove(p.uid)
        for i in range(4):
            rep = copy.deepcopy(small[0])
            rep.metadata.name = f"remint-{i}"
            rep.metadata.uid = new_uid()
            rep.spec.node_name = ""
            ingest.add(rep)
        session.solve(ingest)
        assert session.last_mode == MODE_DELTA, session.last_reason
        assert session.node_signature() == _full_signature(solver, ingest)

    def test_net_additions_keep_aggregate_parity(self):
        """Pure additions of known shapes repair without an encode; the
        aggregate outcome (everything scheduled) matches a full solve even
        where slot-level tie-breaking may not."""
        solver = _solver()
        ingest = PodIngest()
        base = make_pods(20, requests={"cpu": "500m"})
        ingest.add_all(base)
        session = IncrementalSolveSession(
            solver,
            FallbackPolicy(enabled=True, audit_interval=0,
                           max_delta_fraction=0.9),
        )
        session.solve(ingest)
        extra = make_pods(3, requests={"cpu": "500m"})
        ingest.add_all(extra)
        results = session.solve(ingest)
        assert session.last_mode == MODE_DELTA
        assert session.aggregates()["scheduled"] == 23
        assert session.aggregates()["failed"] == 0
        placed = sum(len(d.pods) for d in results.new_nodes) + sum(
            len(ps) for ps in results.existing_assignments.values()
        )
        assert placed == 3  # the delta tick returns THIS tick's placements

    def test_unseen_class_escalates_to_full(self):
        solver = _solver()
        ingest = PodIngest()
        ingest.add_all(make_pods(16, requests={"cpu": "500m"}))
        session = IncrementalSolveSession(
            solver, FallbackPolicy(enabled=True, audit_interval=0)
        )
        session.solve(ingest)
        ingest.add_all(make_pods(2, requests={"cpu": 3}))  # new shape
        session.solve(ingest)
        assert session.last_mode == MODE_FULL
        assert session.last_reason == "class-shape"
        assert session.node_signature() == _full_signature(solver, ingest)

    def test_audit_interval_and_drift_reset(self):
        rng = random.Random(3)
        solver = _solver()
        ingest = PodIngest()
        ingest.add_all(_population(32))
        session = IncrementalSolveSession(
            solver,
            FallbackPolicy(enabled=True, audit_interval=2,
                           max_delta_fraction=0.9),
        )
        session.solve(ingest)
        modes = []
        for _ in range(5):
            _churn(ingest, rng, fraction=0.08)
            session.solve(ingest)
            modes.append((session.last_mode, session.last_reason))
        assert modes[0][0] == MODE_DELTA and modes[1][0] == MODE_DELTA
        assert modes[2] == (MODE_FULL, "audit")
        # the audit measured drift against the repair lineage
        assert session.last_audit_drift_nodes is None or isinstance(
            session.last_audit_drift_nodes, int
        )
        assert modes[3][0] == MODE_DELTA  # lineage re-anchored

    def test_catalog_change_forces_full(self):
        solver = _solver()
        ingest = PodIngest()
        ingest.add_all(make_pods(12, requests={"cpu": "500m"}))
        session = IncrementalSolveSession(
            solver, FallbackPolicy(enabled=True, audit_interval=0)
        )
        session.solve(ingest)
        solver.provisioners[0].metadata.resource_version = "bumped"
        _churn(ingest, random.Random(5), fraction=0.1)
        session.solve(ingest)
        assert session.last_mode == MODE_FULL
        assert session.last_reason.startswith("supply-changed")

    def test_mode_counter_and_span_attribute(self):
        from karpenter_core_tpu import tracing

        solver = _solver()
        ingest = PodIngest()
        ingest.add_all(make_pods(12, requests={"cpu": "500m"}))
        session = IncrementalSolveSession(
            solver,
            FallbackPolicy(enabled=True, audit_interval=0,
                           max_delta_fraction=0.9),
        )
        full0, delta0 = _mode_count("full"), _mode_count("delta")
        tracing.enable()
        try:
            session.solve(ingest)
            _churn(ingest, random.Random(9), fraction=0.1)
            session.solve(ingest)
        finally:
            tracing.disable()
        assert _mode_count("full") == full0 + 1
        assert _mode_count("delta") == delta0 + 1
        spans = [
            s
            for t in tracing.TRACE_STORE.last(None)
            for s in t.spans
            if s["name"] == "solve.incremental"
        ]
        assert spans, "solve.incremental span missing"
        modes = {s["attrs"].get("solve.mode") for s in spans}
        assert {"full", "delta"} <= modes

    def test_rendered_metric_reaches_exposition(self):
        text = REGISTRY.render()
        assert "karpenter_solve_mode_total" in text


# -- the soak smoke (kernel-path scenario wiring, host-sized) ------------------


class TestChurnSteadySmoke:
    def test_catalog_entry_targets_kernel_path(self):
        from karpenter_core_tpu.soak import scenarios

        scenario = scenarios.build("churn-steady", seed=3)
        assert scenario.use_tpu_kernel is True
        assert scenario.seed == 3
        probes = {r.probe for r in scenario.slo_spec().rules}
        assert "solve_latency_s" in probes
        trace = scenario.build_trace()
        # a 10k-fleet steady state: arrivals × lifetime ≈ standing population
        creates = sum(1 for e in trace.events if e.action == "create")
        assert creates > 5000

    def test_tiny_kernel_scenario_converges_and_counts_modes(self):
        """A scaled-down churn scenario through the runner with the kernel
        routing ON: proves the soak runner threads use_tpu_kernel into the
        provisioning controller and the run converges.  Batches stay under
        tpu_kernel_min_pods so solves take the host path — no XLA compiles in
        tier-1 (the full 10k kernel-path run is the slow matrix's job)."""
        from dataclasses import replace

        from karpenter_core_tpu.soak import run_scenario, scenarios

        scenario = replace(
            scenarios.build("churn-steady", seed=5),
            params={
                "duration_s": 120.0, "period_s": 120.0,
                "base_rate_per_s": 0.5, "peak_rate_per_s": 0.5,
                "mean_lifetime_s": 120.0,
            },
            tick_s=30.0,
            settle_ticks=10,
        )
        host0 = _mode_count("host")
        report = run_scenario(scenario)
        assert report["verdict"]["converged"] is True
        deterministic = [
            r for r in report["verdict"]["slo"]
        ]
        assert all(r["passed"] for r in deterministic), json.dumps(
            report["verdict"], indent=2
        )
        # the kernel-routed controller still counted its (host-path) solves
        assert _mode_count("host") > host0


# -- kernel-scale churn (slow tier) --------------------------------------------


@pytest.mark.slow
class TestKernelScaleChurn:
    def test_bench_churn_line_meets_acceptance(self):
        """The ISSUE 7 acceptance at kernel scale: warm repair ≥ 2x the full
        re-solve with identical assignments, through bench.churn_line."""
        import bench

        solver, pods = bench.build_inputs(20000, 40, n_provisioners=5)
        ingest = PodIngest()
        ingest.add_all(pods)
        solver.warmup()
        line = bench.churn_line(solver, ingest, churn_fraction=0.02, ticks=5)
        assert line["identical_assignments"] is True
        assert line["speedup"] >= 2.0, line
        assert line["modes"].get("delta", 0) >= 4
