"""Topology scenario matrix, ported from the reference's largest scheduling
suite (/root/reference/pkg/controllers/provisioning/scheduling/topology_test.go,
72 cases).  Every kernel-supported shape runs through the compare() parity
harness (host oracle AND TPU kernel on identical inputs); shapes the kernel
routes to the host path assert host behavior and the routing itself.
"""

import pytest

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import (
    OP_EXISTS,
    OP_IN,
    OP_NOT_IN,
    LabelSelector,
    LabelSelectorRequirement,
    NodeSelectorRequirement,
    PodAffinityTerm,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)
from karpenter_core_tpu.cloudprovider import fake as fake_cp
from karpenter_core_tpu.models.snapshot import KernelUnsupported, classify_pods
from karpenter_core_tpu.testing import make_pod, make_pods, make_provisioner
from tests.test_tpu_solver import compare, host_solve, tpu_solve

# compare() parity runs the kernel per case -- the slow tier (`make test-all`)
pytestmark = pytest.mark.compile

ZONE = labels_api.LABEL_TOPOLOGY_ZONE
HOSTNAME = labels_api.LABEL_HOSTNAME
CT = labels_api.LABEL_CAPACITY_TYPE
ARCH = labels_api.LABEL_ARCH_STABLE

def spread(key=ZONE, skew=1, labels=None, when="DoNotSchedule", expressions=None):
    selector = LabelSelector(
        match_labels=dict(labels or {"app": "web"}),
        match_expressions=list(expressions or []),
    )
    return TopologySpreadConstraint(
        max_skew=skew, topology_key=key, when_unsatisfiable=when, label_selector=selector
    )

def zone_counts(result):
    counts = {}
    for node in result.new_nodes:
        assert len(node.zones) == 1, "spread nodes must commit to one zone"
        counts[node.zones[0]] = counts.get(node.zones[0], 0) + len(node.pods)
    return counts

class TestZonalSpread:
    """topology_test.go:66-378 — the zonal skew matrix."""

    def test_balance_pods_across_zones_match_labels(self):
        host, tpu = compare(
            lambda: make_pods(
                6, labels={"app": "web"}, requests={"cpu": "10m"},
                topology_spread=[spread()],
            )
        )
        assert sorted(zone_counts(tpu).values()) == [2, 2, 2]

    def test_balance_pods_across_zones_match_expressions(self):
        host, tpu = compare(
            lambda: make_pods(
                6, labels={"app": "web"}, requests={"cpu": "10m"},
                topology_spread=[
                    spread(labels={}, expressions=[
                        LabelSelectorRequirement(key="app", operator=OP_IN, values=["web"])
                    ])
                ],
            )
        )
        assert sorted(zone_counts(tpu).values()) == [2, 2, 2]

    def test_respects_provisioner_zonal_constraints(self):
        # topology_test.go:106 — provisioner spanning all three zones: 4 pods
        # land [1, 1, 2]
        prov = make_provisioner(
            requirements=[
                NodeSelectorRequirement(
                    ZONE, OP_IN, ["test-zone-1", "test-zone-2", "test-zone-3"]
                )
            ]
        )
        host, tpu = compare(
            lambda: make_pods(
                4, labels={"app": "web"}, requests={"cpu": "10m"},
                topology_spread=[spread()],
            ),
            provisioners=[prov],
        )
        assert sorted(zone_counts(tpu).values()) == [1, 1, 2]

    def test_unreachable_domain_caps_reachable_zones(self):
        # topology_test.go:124-162 semantics: the skew min is measured over
        # ALL the pod's domains — a zone no provisioner can serve stays at
        # its count forever, capping every reachable zone at min + skew
        prov = make_provisioner(
            requirements=[
                NodeSelectorRequirement(ZONE, OP_IN, ["test-zone-1", "test-zone-2"])
            ]
        )
        host, tpu = compare(
            lambda: make_pods(
                4, labels={"app": "web"}, requests={"cpu": "10m"},
                topology_spread=[spread()],
            ),
            provisioners=[prov],
        )
        # zone-3 is in the pod's domain universe (the catalog spans it) but
        # unreachable: only one pod per reachable zone fits under skew 1
        assert len(tpu.failed_pods) == 2
        assert sorted(zone_counts(tpu).values()) == [1, 1]

    def test_unknown_topology_key_fails_pod(self):
        # topology_test.go:38 "should ignore unknown topology keys" — the
        # reference leaves the pod pending; both our paths fail it
        pods = [
            make_pod(
                labels={"app": "web"},
                topology_spread=[spread(key="unknown.io/key")],
            )
        ]
        host = host_solve(pods, [make_provisioner()])
        assert len(host.failed_pods) == 1
        with pytest.raises(KernelUnsupported):
            classify_pods(pods)

    def test_max_skew_respected_at_every_count(self):
        for n in (3, 5, 7, 9, 11):
            host, tpu = compare(
                lambda n=n: make_pods(
                    n, labels={"app": "web"}, requests={"cpu": "10m"},
                    topology_spread=[spread()],
                )
            )
            counts = zone_counts(tpu)
            assert max(counts.values()) - min(counts.values() or [0]) <= 1

    def test_larger_max_skew_allows_imbalance(self):
        host, tpu = compare(
            lambda: make_pods(
                9, labels={"app": "web"}, requests={"cpu": "10m"},
                topology_spread=[spread(skew=4)],
            )
        )
        counts = zone_counts(tpu)
        assert max(counts.values()) - min(counts.values()) <= 4

    def test_schedule_anyway_spreads_do_not_block(self):
        # ScheduleAnyway is a preference: all pods land even when skew breaks
        prov = make_provisioner(
            requirements=[NodeSelectorRequirement(ZONE, OP_IN, ["test-zone-1"])]
        )
        host, tpu = compare(
            lambda: make_pods(
                5, labels={"app": "web"}, requests={"cpu": "10m"},
                topology_spread=[spread(when="ScheduleAnyway")],
            ),
            provisioners=[prov],
        )
        assert not tpu.failed_pods

    def test_no_label_selector_matches_all(self):
        # topology_test.go:341 — a selector-less spread counts every pod
        def pods():
            return [
                make_pod(
                    labels={"app": f"a{i}"}, requests={"cpu": "10m"},
                    topology_spread=[
                        TopologySpreadConstraint(max_skew=1, topology_key=ZONE)
                    ],
                )
                for i in range(6)
            ]

        host = host_solve(pods(), [make_provisioner()])
        assert not host.failed_pods
        # selector-less spreads don't self-select -> kernel admissible-mask
        # path handles them since round 2 (counts never move)
        tpu = tpu_solve(pods(), [make_provisioner()])
        assert not tpu.failed_pods

    def test_interdependent_selectors(self):
        # topology_test.go:353 — two deployments sharing one spread selector
        def pods():
            return make_pods(
                3, labels={"app": "web", "tier": "a"}, requests={"cpu": "10m"},
                topology_spread=[spread()],
            ) + make_pods(
                3, labels={"app": "web", "tier": "b"}, requests={"cpu": "10m"},
                topology_spread=[spread()],
            )

        host, tpu = compare(pods)
        assert sorted(zone_counts(tpu).values()) == [2, 2, 2]

class TestHostnameSpread:
    """topology_test.go:380-490."""

    def test_balance_pods_across_nodes(self):
        host, tpu = compare(
            lambda: make_pods(
                4, labels={"app": "web"}, requests={"cpu": "10m"},
                topology_spread=[spread(key=HOSTNAME)],
            )
        )
        assert all(len(n.pods) == 1 for n in tpu.new_nodes)

    def test_balance_up_to_max_skew(self):
        host, tpu = compare(
            lambda: make_pods(
                8, labels={"app": "web"}, requests={"cpu": "10m"},
                topology_spread=[spread(key=HOSTNAME, skew=4)],
            )
        )
        assert all(len(n.pods) <= 4 for n in tpu.new_nodes)

    def test_multiple_deployments_hostname_spread(self):
        # topology_test.go:412 — two deployments, each hostname-spread
        def pods():
            out = []
            for app in ("a", "b"):
                out += make_pods(
                    3, labels={"app": app}, requests={"cpu": "10m"},
                    topology_spread=[spread(key=HOSTNAME, labels={"app": app})],
                )
            return out

        host, tpu = compare(pods)
        for node in tpu.new_nodes:
            per_app = {}
            for pod in node.pods:
                app = pod.metadata.labels["app"]
                per_app[app] = per_app.get(app, 0) + 1
            assert all(v <= 1 for v in per_app.values())

class TestCapacityTypeAndArchSpread:
    """topology_test.go:492-783 — spreads over capacity-type and arch keys
    are region-class custom topologies the kernel doesn't model: they must
    route to the host path, and the host must honor them."""

    def test_capacity_type_spread_routes_to_host(self):
        pods = [
            make_pod(
                labels={"app": "web"},
                topology_spread=[spread(key=CT)],
            )
        ]
        with pytest.raises(KernelUnsupported):
            classify_pods(pods)

    def test_capacity_type_spread_host_balances(self):
        def pods():
            return make_pods(
                4, labels={"app": "web"}, requests={"cpu": "10m"},
                topology_spread=[spread(key=CT)],
            )

        host = host_solve(pods(), [make_provisioner()])
        assert not host.failed_pods
        ct_counts = {}
        for node in host.new_nodes:
            reqs = node.requirements
            if reqs.has(CT):
                committed = tuple(sorted(reqs.get(CT).values_list()))
                ct_counts[committed] = ct_counts.get(committed, 0) + len(node.pods)
        if len(ct_counts) > 1:
            assert max(ct_counts.values()) - min(ct_counts.values()) <= 1

    def test_arch_spread_host_balances(self):
        def pods():
            return make_pods(
                4, labels={"app": "web"}, requests={"cpu": "10m"},
                topology_spread=[spread(key=ARCH)],
            )

        host = host_solve(
            pods(), [make_provisioner()], fake_cp.instance_types_assorted()[:200]
        )
        assert not host.failed_pods

class TestCombinedConstraints:
    """topology_test.go:785-1029 — zone and hostname spreads together."""

    def test_zone_and_hostname_spread_together(self):
        host, tpu = compare(
            lambda: make_pods(
                6, labels={"app": "web"}, requests={"cpu": "10m"},
                topology_spread=[spread(key=ZONE), spread(key=HOSTNAME)],
            )
        )
        assert sorted(zone_counts(tpu).values()) == [2, 2, 2]
        assert all(len(n.pods) == 1 for n in tpu.new_nodes)

    def test_spread_across_provisioner_requirements(self):
        # topology_test.go:825 adapted — single-zone provisioners covering
        # every zone balance the spread across them
        provs = [
            make_provisioner(
                name=f"zone-{i}",
                requirements=[NodeSelectorRequirement(ZONE, OP_IN, [f"test-zone-{i}"])],
            )
            for i in (1, 2, 3)
        ]
        host, tpu = compare(
            lambda: make_pods(
                4, labels={"app": "web"}, requests={"cpu": "10m"},
                topology_spread=[spread()],
            ),
            provisioners=provs,
        )
        assert sorted(zone_counts(tpu).values()) == [1, 1, 2]

    def test_partial_provisioner_coverage_caps_at_unreachable_min(self):
        # two single-zone provisioners over a three-zone catalog: zone-3
        # stays at 0 forever, capping each covered zone at skew
        provs = [
            make_provisioner(
                name=f"zone-{i}",
                requirements=[NodeSelectorRequirement(ZONE, OP_IN, [f"test-zone-{i}"])],
            )
            for i in (1, 2)
        ]
        host, tpu = compare(
            lambda: make_pods(
                4, labels={"app": "web"}, requests={"cpu": "10m"},
                topology_spread=[spread()],
            ),
            provisioners=provs,
        )
        assert sorted(zone_counts(tpu).values()) == [1, 1]
        assert len(tpu.failed_pods) == 2

class TestSpreadLimitedByNodeConstraints:
    """topology_test.go:1031-1194 — the pod's own node constraints shrink the
    spread's domain universe."""

    def test_limit_spread_by_node_selector(self):
        host, tpu = compare(
            lambda: make_pods(
                4, labels={"app": "web"}, requests={"cpu": "10m"},
                node_selector={ZONE: "test-zone-1"},
                topology_spread=[spread()],
            )
        )
        assert set(zone_counts(tpu)) == {"test-zone-1"}
        assert not tpu.failed_pods

    def test_limit_spread_by_node_requirements(self):
        host, tpu = compare(
            lambda: make_pods(
                6, labels={"app": "web"}, requests={"cpu": "10m"},
                node_requirements=[
                    NodeSelectorRequirement(ZONE, OP_IN, ["test-zone-1", "test-zone-2"])
                ],
                topology_spread=[spread()],
            )
        )
        counts = zone_counts(tpu)
        assert set(counts) <= {"test-zone-1", "test-zone-2"}
        assert sorted(counts.values()) == [3, 3]

    def test_limit_spread_by_not_in(self):
        host, tpu = compare(
            lambda: make_pods(
                4, labels={"app": "web"}, requests={"cpu": "10m"},
                node_requirements=[
                    NodeSelectorRequirement(ZONE, OP_NOT_IN, ["test-zone-3"])
                ],
                topology_spread=[spread()],
            )
        )
        assert "test-zone-3" not in zone_counts(tpu)

class TestPodAffinity:
    """topology_test.go:1196-1510."""

    def test_empty_affinity_schedules(self):
        compare(lambda: make_pods(2, requests={"cpu": "10m"}))

    def test_self_affinity_hostname(self):
        # topology_test.go:1282 — all pods share one node
        def pods():
            return make_pods(
                3, labels={"app": "db"}, requests={"cpu": "10m"},
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=HOSTNAME,
                        label_selector=LabelSelector(match_labels={"app": "db"}),
                    )
                ],
            )

        host, tpu = compare(pods)
        assert len(tpu.new_nodes) == 1
        assert len(tpu.new_nodes[0].pods) == 3

    def test_self_affinity_zone(self):
        def pods():
            return make_pods(
                4, labels={"app": "db"}, requests={"cpu": "10m"},
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=ZONE,
                        label_selector=LabelSelector(match_labels={"app": "db"}),
                    )
                ],
            )

        host, tpu = compare(pods)
        zones = {z for n in tpu.new_nodes for z in n.zones}
        assert len(zones) == 1

    def test_self_affinity_zone_with_constraint(self):
        # topology_test.go:1414 — self zone affinity + zone selector
        def pods():
            return make_pods(
                3, labels={"app": "db"}, requests={"cpu": "10m"},
                node_selector={ZONE: "test-zone-2"},
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=ZONE,
                        label_selector=LabelSelector(match_labels={"app": "db"}),
                    )
                ],
            )

        host, tpu = compare(pods)
        assert {z for n in tpu.new_nodes for z in n.zones} == {"test-zone-2"}

    def test_affinity_to_nonexistent_pod_fails(self):
        # topology_test.go:1924
        def pods():
            return make_pods(
                2, labels={"app": "fol"}, requests={"cpu": "10m"},
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=ZONE,
                        label_selector=LabelSelector(match_labels={"app": "ghost"}),
                    )
                ],
            )

        host, tpu = compare(pods)
        assert len(tpu.failed_pods) == 2

    def test_affinity_constrained_target(self):
        # topology_test.go:1974 — followers land in the target's zone
        def pods():
            targets = make_pods(
                2, labels={"app": "tgt"}, requests={"cpu": "10m"},
                node_selector={ZONE: "test-zone-3"},
            )
            followers = make_pods(
                3, labels={"app": "fol"}, requests={"cpu": "10m"},
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=ZONE,
                        label_selector=LabelSelector(match_labels={"app": "tgt"}),
                    )
                ],
            )
            return targets + followers

        host, tpu = compare(pods)
        for node in tpu.new_nodes:
            if any(p.metadata.labels.get("app") == "fol" for p in node.pods):
                assert node.zones == ["test-zone-3"]

    def test_multiple_dependent_affinities(self):
        # topology_test.go:2003 — a -> b -> c chain colocates (within the
        # kernel's pass budget)
        def pods():
            a = make_pods(1, labels={"app": "a"}, requests={"cpu": "10m"},
                          node_selector={ZONE: "test-zone-1"})
            b = make_pods(
                1, labels={"app": "b"}, requests={"cpu": "10m"},
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=ZONE,
                        label_selector=LabelSelector(match_labels={"app": "a"}),
                    )
                ],
            )
            c = make_pods(
                1, labels={"app": "c"}, requests={"cpu": "10m"},
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=ZONE,
                        label_selector=LabelSelector(match_labels={"app": "b"}),
                    )
                ],
            )
            return a + b + c

        host, tpu = compare(pods)
        assert not tpu.failed_pods
        assert {z for n in tpu.new_nodes for z in n.zones} == {"test-zone-1"}

    def test_unsatisfiable_dependency_fails(self):
        # topology_test.go:2037 — follower requires a zone its target can't be in
        def pods():
            targets = make_pods(
                1, labels={"app": "tgt"}, requests={"cpu": "10m"},
                node_selector={ZONE: "test-zone-1"},
            )
            followers = make_pods(
                2, labels={"app": "fol"}, requests={"cpu": "10m"},
                node_selector={ZONE: "test-zone-2"},
                pod_affinity=[
                    PodAffinityTerm(
                        topology_key=ZONE,
                        label_selector=LabelSelector(match_labels={"app": "tgt"}),
                    )
                ],
            )
            return targets + followers

        host, tpu = compare(pods)
        assert len(tpu.failed_pods) == 2

    def test_preferred_affinity_violation_allowed(self):
        # topology_test.go:1445 — preferred affinity to a ghost pod must not
        # block scheduling (relaxation drops it on the host; the kernel never
        # models preferences)
        def pods():
            return make_pods(
                2, labels={"app": "x"}, requests={"cpu": "10m"},
                pod_affinity_preferred=[
                    WeightedPodAffinityTerm(
                        weight=1,
                        pod_affinity_term=PodAffinityTerm(
                            topology_key=ZONE,
                            label_selector=LabelSelector(match_labels={"app": "ghost"}),
                        ),
                    )
                ],
            )

        host, tpu = compare(pods)
        assert not tpu.failed_pods

    def test_preferred_anti_affinity_violation_allowed(self):
        # topology_test.go:1478
        def pods():
            return make_pods(
                4, labels={"app": "x"}, requests={"cpu": "10m"},
                pod_anti_affinity_preferred=[
                    WeightedPodAffinityTerm(
                        weight=1,
                        pod_affinity_term=PodAffinityTerm(
                            topology_key=ZONE,
                            label_selector=LabelSelector(match_labels={"app": "x"}),
                        ),
                    )
                ],
            )

        host, tpu = compare(pods)
        assert not tpu.failed_pods

class TestPodAntiAffinity:
    """topology_test.go:1511-1923."""

    def test_simple_hostname_anti_affinity_separates(self):
        def pods():
            return make_pods(
                3, labels={"app": "db"}, requests={"cpu": "10m"},
                pod_anti_affinity=[
                    PodAffinityTerm(
                        topology_key=HOSTNAME,
                        label_selector=LabelSelector(match_labels={"app": "db"}),
                    )
                ],
            )

        host, tpu = compare(pods)
        assert all(len(n.pods) == 1 for n in tpu.new_nodes)

    def test_zone_anti_affinity_not_violated(self):
        # required zonal anti is in-kernel since round 5 with zone-committal
        # phases: the kernel places one member per admissible zone in batch
        # one (nodes pinned to distinct zones — never a violation), while
        # the host's record-time snapshots reach that fixpoint one pod per
        # batch (topology_test.go:1879-1923).  The parity fuzzer pins the
        # >=-with-validity contract; here the placements themselves matter.
        def pods():
            return make_pods(
                2, labels={"app": "db"}, requests={"cpu": "10m"},
                pod_anti_affinity=[
                    PodAffinityTerm(
                        topology_key=ZONE,
                        label_selector=LabelSelector(match_labels={"app": "db"}),
                    )
                ],
            )

        host = host_solve(pods(), [make_provisioner()])
        assert sum(len(n.pods) for n in host.new_nodes) == 1
        assert len(host.failed_pods) == 1
        tpu = tpu_solve(pods(), [make_provisioner()])
        placed = [n for n in tpu.new_nodes if n.pods]
        assert sum(len(n.pods) for n in placed) == 2
        zones = [tuple(n.zones) for n in placed]
        assert all(len(z) == 1 for z in zones) and len(set(zones)) == 2, zones

    def test_inverse_anti_affinity_blocks_target(self):
        # topology_test.go:1677 — an anti-affinity OWNER repels the pods its
        # selector matches even though those pods carry no anti term
        def pods():
            owner = make_pods(
                1, labels={"app": "lonely"}, requests={"cpu": "10m"},
                pod_anti_affinity=[
                    PodAffinityTerm(
                        topology_key=HOSTNAME,
                        label_selector=LabelSelector(match_labels={"app": "noisy"}),
                    )
                ],
            )
            noisy = make_pods(2, labels={"app": "noisy"}, requests={"cpu": "10m"})
            return owner + noisy

        host, tpu = compare(pods)
        for node in tpu.new_nodes:
            apps = {p.metadata.labels["app"] for p in node.pods}
            assert apps != {"lonely", "noisy"}

    def test_anti_affinity_arch_key_routes_to_host(self):
        # arch-key anti-affinity is a custom-key topology: host path only
        pods = [
            make_pod(
                labels={"app": "db"},
                pod_anti_affinity=[
                    PodAffinityTerm(
                        topology_key=ARCH,
                        label_selector=LabelSelector(match_labels={"app": "db"}),
                    )
                ],
            )
        ]
        with pytest.raises(KernelUnsupported):
            classify_pods(pods)

    def test_exists_selector_anti_affinity(self):
        # anti-affinity with an Exists expression selector
        def pods():
            return make_pods(
                3, labels={"team": "a"}, requests={"cpu": "10m"},
                pod_anti_affinity=[
                    PodAffinityTerm(
                        topology_key=HOSTNAME,
                        label_selector=LabelSelector(
                            match_expressions=[
                                LabelSelectorRequirement(key="team", operator=OP_EXISTS)
                            ]
                        ),
                    )
                ],
            )

        host, tpu = compare(pods)
        assert all(len(n.pods) == 1 for n in tpu.new_nodes)

class TestTolerationsAndTaints:
    """topology_test.go:2210-2256 tail cases."""

    def test_startup_taint_does_not_block(self):
        from karpenter_core_tpu.apis.objects import Taint

        prov = make_provisioner(startup_taints=[Taint("init.sh/agent", "true")])
        host, tpu = compare(
            lambda: make_pods(2, requests={"cpu": "10m"}), provisioners=[prov]
        )
        assert not tpu.failed_pods

    def test_tolerated_taints_schedule(self):
        from karpenter_core_tpu.apis.objects import Taint

        prov = make_provisioner(taints=[Taint("dedicated", "db")])
        host, tpu = compare(
            lambda: make_pods(
                2, requests={"cpu": "10m"},
                tolerations=[Toleration(key="dedicated", operator="Exists")],
            ),
            provisioners=[prov],
        )
        assert not tpu.failed_pods

class TestExistingPodCounting:
    """topology_test.go:124-162, 308-340 — countDomains seeding: pre-existing
    bound pods participate in spread counts; pods without matching labels or
    on domain-less nodes do not."""

    def _env(self):
        from karpenter_core_tpu.testing import make_node
        from karpenter_core_tpu.testing.harness import make_environment

        env = make_environment()
        env.kube.create(make_provisioner())
        return env, make_node

    def _node(self, env, make_node, name, zone):
        node = make_node(
            name=name,
            labels={
                labels_api.PROVISIONER_NAME_LABEL_KEY: "default",
                labels_api.LABEL_INSTANCE_TYPE_STABLE: "default-instance-type",
                labels_api.LABEL_CAPACITY_TYPE: "spot",
                labels_api.LABEL_NODE_INITIALIZED: "true",
                labels_api.LABEL_TOPOLOGY_ZONE: zone,
            },
            allocatable={"cpu": 8, "memory": "8Gi", "pods": 20},
        )
        env.kube.create(node)
        return node

    def _solve(self, env, pods):
        from karpenter_core_tpu.solver.tpu import TPUSolver

        solver = TPUSolver(env.provider, env.kube.list_provisioners())
        return solver.solve(
            pods,
            state_nodes=env.cluster.snapshot_nodes(),
            bound_pods=env.kube.list_pods(),
        )

    def test_existing_matching_pods_seed_counts(self):
        env, make_node = self._env()
        n1 = self._node(env, make_node, "z1", "test-zone-1")
        self._node(env, make_node, "z2", "test-zone-2")
        self._node(env, make_node, "z3", "test-zone-3")
        # two matching pods already in zone-1: new spread pods avoid it until
        # the other zones catch up
        for _ in range(2):
            env.kube.create(
                make_pod(labels={"app": "web"}, requests={"cpu": "100m"},
                         node_name=n1.name, unschedulable=False)
            )
        new = make_pods(
            4, labels={"app": "web"}, requests={"cpu": "100m"},
            topology_spread=[spread()],
        )
        res = self._solve(env, new)
        assert not res.failed_pods
        placed_z1 = len(res.existing_assignments.get("z1", []))
        # zone-1 starts at 2; balancing 4 more lands [0, 2, 2]
        assert placed_z1 == 0

    def test_non_matching_existing_pods_do_not_count(self):
        env, make_node = self._env()
        n1 = self._node(env, make_node, "z1", "test-zone-1")
        self._node(env, make_node, "z2", "test-zone-2")
        self._node(env, make_node, "z3", "test-zone-3")
        for _ in range(3):  # different labels: invisible to the spread
            env.kube.create(
                make_pod(labels={"app": "other"}, requests={"cpu": "100m"},
                         node_name=n1.name, unschedulable=False)
            )
        new = make_pods(
            3, labels={"app": "web"}, requests={"cpu": "100m"},
            topology_spread=[spread()],
        )
        res = self._solve(env, new)
        assert not res.failed_pods
        per_zone = {}
        for name, placed in res.existing_assignments.items():
            per_zone[name] = len(placed)
        for node in res.new_nodes:
            per_zone[node.zones[0]] = per_zone.get(node.zones[0], 0) + len(node.pods)
        # counts start level: one pod per zone
        assert sorted(per_zone.values()) == [1, 1, 1]
