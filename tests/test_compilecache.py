"""Persistent compile/export caches (utils.compilecache) — the cold-start
eliminator.  The disk entries must round-trip (a second, cache-backed load
produces identical solve outputs) and invalidate on kernel-source change."""

import os

import numpy as np
import pytest

from karpenter_core_tpu.cloudprovider import fake as fake_cp
from karpenter_core_tpu.models.columnar import PodIngest
from karpenter_core_tpu.ops import solve as solve_ops
from karpenter_core_tpu.solver.tpu import TPUSolver
from karpenter_core_tpu.testing import make_pods, make_provisioner
from karpenter_core_tpu.utils import compilecache

# exercises the export/XLA caches by compiling -- the slow tier (`make test-all`)
pytestmark = pytest.mark.compile

@pytest.fixture()
def cache_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("KC_TPU_COMPILE_CACHE", str(tmp_path))
    # reset module state (memo + slot hysteresis) so the fixture dir is
    # picked up and no stale slot count outlives its executable
    compilecache.reset_memo()
    yield tmp_path
    compilecache.reset_memo()

def _inputs():
    provider = fake_cp.FakeCloudProvider(fake_cp.instance_types(10))
    solver = TPUSolver(provider, [make_provisioner()])
    ingest = PodIngest()
    ingest.add_all(make_pods(12, requests={"cpu": "500m"}))
    snap = solver.encode(ingest)
    host_cls, host_statics, khb = solve_ops.prepare_host(snap)
    return snap, host_cls, host_statics, khb

class TestExportCache:
    def test_roundtrip_matches_plain_jit(self, cache_dir):
        snap, cls, statics, khb = _inputs()
        n_slots = solve_ops.estimate_slots(snap)

        fn = compilecache.solve_callable(cls, statics, n_slots, khb)
        assert fn is not None
        entries = [f for f in os.listdir(cache_dir) if f.endswith(".stablehlo")]
        assert len(entries) == 1

        import jax

        dev_cls, dev_statics = jax.device_put((cls, statics))
        out_cached = fn(dev_cls, dev_statics)
        out_plain = solve_ops._solve_jit(dev_cls, dev_statics, n_slots, khb)
        assert np.array_equal(np.asarray(out_cached.assign), np.asarray(out_plain.assign))
        assert np.array_equal(np.asarray(out_cached.failed), np.asarray(out_plain.failed))

    def test_disk_entry_reused_after_memo_clear(self, cache_dir):
        snap, cls, statics, khb = _inputs()
        n_slots = solve_ops.estimate_slots(snap)
        compilecache.solve_callable(cls, statics, n_slots, khb)
        before = {f: os.path.getmtime(os.path.join(cache_dir, f))
                  for f in os.listdir(cache_dir) if f.endswith(".stablehlo")}
        compilecache.reset_memo()  # simulate a process restart
        fn = compilecache.solve_callable(cls, statics, n_slots, khb)
        assert fn is not None
        after = {f: os.path.getmtime(os.path.join(cache_dir, f))
                 for f in os.listdir(cache_dir) if f.endswith(".stablehlo")}
        assert before == after  # loaded, not re-exported

    def test_memo_hit_returns_same_object(self, cache_dir):
        snap, cls, statics, khb = _inputs()
        n_slots = solve_ops.estimate_slots(snap)
        a = compilecache.solve_callable(cls, statics, n_slots, khb)
        b = compilecache.solve_callable(cls, statics, n_slots, khb)
        assert a is b

    def test_distinct_configs_get_distinct_entries(self, cache_dir):
        snap, cls, statics, khb = _inputs()
        n_slots = solve_ops.estimate_slots(snap)
        compilecache.solve_callable(cls, statics, n_slots, khb)
        compilecache.solve_callable(cls, statics, n_slots * 2, khb)
        entries = [f for f in os.listdir(cache_dir) if f.endswith(".stablehlo")]
        assert len(entries) == 2

    def test_corrupt_entry_recovers(self, cache_dir):
        snap, cls, statics, khb = _inputs()
        n_slots = solve_ops.estimate_slots(snap)
        compilecache.solve_callable(cls, statics, n_slots, khb)
        (entry,) = [f for f in os.listdir(cache_dir) if f.endswith(".stablehlo")]
        with open(os.path.join(cache_dir, entry), "wb") as f:
            f.write(b"garbage")
        compilecache._memo.clear()
        fn = compilecache.solve_callable(cls, statics, n_slots, khb)
        assert fn is not None  # re-exported over the corrupt entry

    def test_solver_path_uses_cache(self, cache_dir):
        provider = fake_cp.FakeCloudProvider(fake_cp.instance_types(10))
        solver = TPUSolver(provider, [make_provisioner()])
        pods = make_pods(8, requests={"cpu": "900m"})
        res = solver.solve(pods)
        assert sum(len(n.pods) for n in res.new_nodes) == 8
        entries = [f for f in os.listdir(cache_dir) if f.endswith(".stablehlo")]
        assert entries, "TPUSolver.solve must populate the export cache"

class TestFeatureVariants:
    """SnapshotFeatures static pruning must map onto a BOUNDED set of trace
    variants (cache keys): snap_features widens a requested flag set to an
    already-built superset, and past MAX_FEATURE_VARIANTS distinct sets
    everything widens to all-on — so feature-keyed compilation can never
    silently explode (ISSUE 3 satellite)."""

    def setup_method(self):
        compilecache.reset_memo()

    def teardown_method(self):
        compilecache.reset_memo()

    def test_variant_space_is_bounded(self):
        import random

        from karpenter_core_tpu.ops.solve import ALL_FEATURES, SnapshotFeatures

        rng = random.Random(0)
        snapped_sets = set()
        for _ in range(500):
            bits = [rng.random() < 0.5 for _ in range(len(ALL_FEATURES))]
            f = SnapshotFeatures(*bits)
            snapped = compilecache.snap_features(f)
            # widening only: every flag the request needs stays on
            assert snapped.covers(f.canonical()), (f, snapped)
            snapped_sets.add(snapped)
        assert len(snapped_sets) <= compilecache.MAX_FEATURE_VARIANTS + 1

    def test_subset_request_reuses_superset_variant(self):
        from karpenter_core_tpu.ops.solve import ALL_FEATURES, SnapshotFeatures

        superset = ALL_FEATURES
        assert compilecache.snap_features(superset) == superset
        subset = SnapshotFeatures(*(False,) * len(superset))._replace(
            zone_spread=True
        )
        # the subset request lands on the already-seen superset — one
        # executable serves both (the extra phases are runtime no-ops)
        assert compilecache.snap_features(subset) == superset

    def test_canonicalization_collapses_implied_flags(self):
        from karpenter_core_tpu.ops.solve import SnapshotFeatures

        f = SnapshotFeatures(*(False,) * 11)._replace(required_zone_anti=True)
        c = f.canonical()
        assert c.zone_anti and c.inv_zone_anti
        # equivalent requests share one cache key
        g = f._replace(zone_anti=True, inv_zone_anti=True)
        assert compilecache.snap_features(f) == compilecache.snap_features(g)

    def test_none_means_all_on(self):
        from karpenter_core_tpu.ops.solve import ALL_FEATURES

        assert compilecache.snap_features(None) == ALL_FEATURES

    def test_encoded_snapshot_features_match_workload(self):
        from karpenter_core_tpu.apis import labels as labels_api
        from karpenter_core_tpu.apis.objects import (
            LabelSelector,
            TopologySpreadConstraint,
        )
        from karpenter_core_tpu.testing import make_pod

        provider = fake_cp.FakeCloudProvider(fake_cp.instance_types(10))
        solver = TPUSolver(provider, [make_provisioner()])
        pods = make_pods(4, requests={"cpu": "500m"}) + [
            make_pod(
                requests={"cpu": "250m"},
                labels={"app": "s"},
                topology_spread=[
                    TopologySpreadConstraint(
                        max_skew=1,
                        topology_key=labels_api.LABEL_TOPOLOGY_ZONE,
                        label_selector=LabelSelector(match_labels={"app": "s"}),
                    )
                ],
            )
        ]
        snap = solver.encode(pods)
        ft = snap.features
        assert ft.zone_spread
        assert not ft.host_spread
        assert not ft.zone_affinity and not ft.host_affinity
        assert not ft.zone_anti and not ft.required_zone_anti
        assert not ft.host_ports and not ft.volume_limits


class TestShapeBuckets:
    """ops/solve.pad_planes: nearby problem sizes share one executable and
    padding is semantically invisible (ROADMAP compile-reuse item)."""

    @staticmethod
    def _solve_sig(results):
        return (
            sorted((d.provisioner_name, len(d.pods)) for d in results.new_nodes),
            sorted((name, len(pods)) for name, pods in results.existing_assignments.items()),
            len(results.failed_pods),
        )

    def test_bucket_grid(self):
        assert solve_ops.bucket(1) == 8
        assert solve_ops.bucket(8) == 8
        assert solve_ops.bucket(9) == 12
        assert solve_ops.bucket(13) == 16
        assert solve_ops.bucket(17) == 24
        assert solve_ops.bucket(25) == 32
        assert solve_ops.bucket(100) == 128
        assert solve_ops.bucket(3, floor=2) == 3
        assert solve_ops.bucket(5, floor=4) == 6

    def test_padding_parity(self, cache_dir, monkeypatch):
        from karpenter_core_tpu.apis.objects import LabelSelector, TopologySpreadConstraint
        from karpenter_core_tpu.testing import make_pod

        provider = fake_cp.FakeCloudProvider(fake_cp.instance_types(10))
        solver = TPUSolver(provider, [make_provisioner()])
        pods = (
            make_pods(9, requests={"cpu": "1"})
            + make_pods(4, requests={"cpu": "2", "memory": "1Gi"})
            + [
                make_pod(
                    requests={"cpu": "500m"},
                    labels={"app": "spread"},
                    topology_spread=[
                        TopologySpreadConstraint(
                            max_skew=1,
                            topology_key="topology.kubernetes.io/zone",
                            label_selector=LabelSelector(match_labels={"app": "spread"}),
                        )
                    ],
                )
                for _ in range(6)
            ]
        )
        sigs = {}
        for buckets in ("0", "1"):
            monkeypatch.setenv("KC_TPU_SHAPE_BUCKETS", buckets)
            sigs[buckets] = self._solve_sig(solver.solve(pods))
        assert sigs["0"] == sigs["1"]

    def test_nearby_sizes_share_executable(self, cache_dir, monkeypatch):
        monkeypatch.setenv("KC_TPU_SHAPE_BUCKETS", "1")
        provider = fake_cp.FakeCloudProvider(fake_cp.instance_types(10))
        solver = TPUSolver(provider, [make_provisioner()])
        solver.solve(
            make_pods(11, requests={"cpu": "1"}) + make_pods(5, requests={"cpu": "2"})
        )
        first = len(compilecache._memo)
        # different pod counts, one more class — same C/K/V buckets
        solver.solve(
            make_pods(14, requests={"cpu": "1"})
            + make_pods(3, requests={"cpu": "2"})
            + make_pods(2, requests={"memory": "512Mi"})
        )
        assert len(compilecache._memo) == first

    def test_padded_groups_and_existing_nodes(self, cache_dir, monkeypatch):
        """Topology groups + existing nodes keep exact results under padding."""
        from karpenter_core_tpu.apis import labels as labels_api
        from karpenter_core_tpu.apis.objects import LabelSelector, PodAffinityTerm
        from karpenter_core_tpu.testing import make_node, make_pod
        from karpenter_core_tpu.testing.harness import make_environment

        env = make_environment()
        node = make_node(
            labels={
                labels_api.PROVISIONER_NAME_LABEL_KEY: "default",
                labels_api.LABEL_INSTANCE_TYPE_STABLE: "default-instance-type",
                labels_api.LABEL_CAPACITY_TYPE: "spot",
                labels_api.LABEL_NODE_INITIALIZED: "true",
                labels_api.LABEL_TOPOLOGY_ZONE: "test-zone-1",
            },
            allocatable={"cpu": 16, "memory": "64Gi", "pods": 110},
        )
        env.kube.create(node)
        solver = TPUSolver(env.provider, [make_provisioner()])
        pods = make_pods(6, requests={"cpu": "1"}) + [
            make_pod(
                requests={"cpu": "500m"},
                labels={"app": "anti"},
                pod_anti_affinity=[
                    PodAffinityTerm(
                        topology_key="kubernetes.io/hostname",
                        label_selector=LabelSelector(match_labels={"app": "anti"}),
                    )
                ],
            )
            for _ in range(3)
        ]
        sigs = {}
        for buckets in ("0", "1"):
            monkeypatch.setenv("KC_TPU_SHAPE_BUCKETS", buckets)
            sigs[buckets] = self._solve_sig(
                solver.solve(pods, state_nodes=env.cluster.snapshot_nodes())
            )
        assert sigs["0"] == sigs["1"]
        assert sigs["1"][1], "some pods should land on the existing node"


class TestBucketQuantize:
    """KC_BUCKET_QUANTIZE (PR 18, docs/SERVICE.md "Solve fusion"): the
    opt-in power-of-two ladder must STRICTLY REDUCE distinct executable
    keys over a mixed-size tenant population, and stay byte-identical to
    the default grid when unset."""

    def test_quantized_grid_factors_through_default(self, monkeypatch):
        """Every default rung maps to exactly one quantized rung (the
        power-of-two grid is a subset grid), so over ANY size mix the
        distinct-bucket count under quantization can only shrink."""
        rung_map = {}
        for n in range(1, 4097):
            monkeypatch.setenv("KC_BUCKET_QUANTIZE", "0")
            b = solve_ops.bucket(n)
            monkeypatch.setenv("KC_BUCKET_QUANTIZE", "1")
            q = solve_ops.bucket(n)
            assert q >= n and q >= b, n
            assert q & (q - 1) == 0, (n, q)  # always a power of two
            assert rung_map.setdefault(b, q) == q, (n, b, q)

    def test_mixed_tenant_sizes_strictly_fewer_executable_keys(
        self, cache_dir, monkeypatch
    ):
        """The fusion_line sweep's shape, pinned: class counts straddling
        (1.5x-rung, next-pow2) pairs like (10, 14) -> default buckets
        {12, 16} merge into {16} under the quantized ladder, so the
        mixed population lands in STRICTLY FEWER coalescer rendezvous
        keys (= distinct batched executables)."""
        from karpenter_core_tpu.service.tenant import bucket_key
        from karpenter_core_tpu.testing import make_pod

        monkeypatch.setenv("KC_TPU_SHAPE_BUCKETS", "1")
        provider = fake_cp.FakeCloudProvider(fake_cp.instance_types(10))
        mixed = [5, 7, 10, 14, 20, 28]

        def keys_under(flag: str):
            monkeypatch.setenv("KC_BUCKET_QUANTIZE", flag)
            found = set()
            for n_classes in mixed:
                # fresh solver per prep: the prep cache anchors on the
                # quantize flag, stale preps must not leak across legs
                solver = TPUSolver(provider, [make_provisioner()])
                ingest = PodIngest()
                ingest.add_all([
                    make_pod(requests={"cpu": f"{100 + 25 * j}m"})
                    for j in range(n_classes)
                    for _ in range(12)
                ])
                found.add(bucket_key(solver.prepare_encoded(
                    solver.encode(ingest))))
            return found

        default_keys = keys_under("0")
        quantized_keys = keys_under("1")
        assert len(quantized_keys) < len(default_keys), (
            sorted(default_keys), sorted(quantized_keys),
        )

    def test_unset_is_byte_identical_to_disabled(self, monkeypatch):
        monkeypatch.delenv("KC_BUCKET_QUANTIZE", raising=False)
        assert solve_ops.bucket_quantize_enabled() is False
        unset_grid = [solve_ops.bucket(n) for n in range(1, 513)]
        monkeypatch.setenv("KC_BUCKET_QUANTIZE", "0")
        assert solve_ops.bucket_quantize_enabled() is False
        assert [solve_ops.bucket(n) for n in range(1, 513)] == unset_grid
        monkeypatch.setenv("KC_BUCKET_QUANTIZE", "1")
        assert solve_ops.bucket_quantize_enabled() is True
