"""Persistent compile/export caches (utils.compilecache) — the cold-start
eliminator.  The disk entries must round-trip (a second, cache-backed load
produces identical solve outputs) and invalidate on kernel-source change."""

import os

import numpy as np
import pytest

from karpenter_core_tpu.cloudprovider import fake as fake_cp
from karpenter_core_tpu.models.columnar import PodIngest
from karpenter_core_tpu.ops import solve as solve_ops
from karpenter_core_tpu.solver.tpu import TPUSolver
from karpenter_core_tpu.testing import make_pods, make_provisioner
from karpenter_core_tpu.utils import compilecache


@pytest.fixture()
def cache_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("KC_TPU_COMPILE_CACHE", str(tmp_path))
    # reset module state so the fixture dir is picked up
    compilecache._memo.clear()
    yield tmp_path
    compilecache._memo.clear()


def _inputs():
    provider = fake_cp.FakeCloudProvider(fake_cp.instance_types(10))
    solver = TPUSolver(provider, [make_provisioner()])
    ingest = PodIngest()
    ingest.add_all(make_pods(12, requests={"cpu": "500m"}))
    snap = solver.encode(ingest)
    host_cls, host_statics, khb = solve_ops.prepare_host(snap)
    return snap, host_cls, host_statics, khb


class TestExportCache:
    def test_roundtrip_matches_plain_jit(self, cache_dir):
        snap, cls, statics, khb = _inputs()
        n_slots = solve_ops.estimate_slots(snap)

        fn = compilecache.solve_callable(cls, statics, n_slots, khb)
        assert fn is not None
        entries = [f for f in os.listdir(cache_dir) if f.endswith(".stablehlo")]
        assert len(entries) == 1

        import jax

        dev_cls, dev_statics = jax.device_put((cls, statics))
        out_cached = fn(dev_cls, dev_statics)
        out_plain = solve_ops._solve_jit(dev_cls, dev_statics, n_slots, khb)
        assert np.array_equal(np.asarray(out_cached.assign), np.asarray(out_plain.assign))
        assert np.array_equal(np.asarray(out_cached.failed), np.asarray(out_plain.failed))

    def test_disk_entry_reused_after_memo_clear(self, cache_dir):
        snap, cls, statics, khb = _inputs()
        n_slots = solve_ops.estimate_slots(snap)
        compilecache.solve_callable(cls, statics, n_slots, khb)
        before = {f: os.path.getmtime(os.path.join(cache_dir, f))
                  for f in os.listdir(cache_dir) if f.endswith(".stablehlo")}
        compilecache._memo.clear()  # simulate a process restart
        fn = compilecache.solve_callable(cls, statics, n_slots, khb)
        assert fn is not None
        after = {f: os.path.getmtime(os.path.join(cache_dir, f))
                 for f in os.listdir(cache_dir) if f.endswith(".stablehlo")}
        assert before == after  # loaded, not re-exported

    def test_memo_hit_returns_same_object(self, cache_dir):
        snap, cls, statics, khb = _inputs()
        n_slots = solve_ops.estimate_slots(snap)
        a = compilecache.solve_callable(cls, statics, n_slots, khb)
        b = compilecache.solve_callable(cls, statics, n_slots, khb)
        assert a is b

    def test_distinct_configs_get_distinct_entries(self, cache_dir):
        snap, cls, statics, khb = _inputs()
        n_slots = solve_ops.estimate_slots(snap)
        compilecache.solve_callable(cls, statics, n_slots, khb)
        compilecache.solve_callable(cls, statics, n_slots * 2, khb)
        entries = [f for f in os.listdir(cache_dir) if f.endswith(".stablehlo")]
        assert len(entries) == 2

    def test_corrupt_entry_recovers(self, cache_dir):
        snap, cls, statics, khb = _inputs()
        n_slots = solve_ops.estimate_slots(snap)
        compilecache.solve_callable(cls, statics, n_slots, khb)
        (entry,) = [f for f in os.listdir(cache_dir) if f.endswith(".stablehlo")]
        with open(os.path.join(cache_dir, entry), "wb") as f:
            f.write(b"garbage")
        compilecache._memo.clear()
        fn = compilecache.solve_callable(cls, statics, n_slots, khb)
        assert fn is not None  # re-exported over the corrupt entry

    def test_solver_path_uses_cache(self, cache_dir):
        provider = fake_cp.FakeCloudProvider(fake_cp.instance_types(10))
        solver = TPUSolver(provider, [make_provisioner()])
        pods = make_pods(8, requests={"cpu": "900m"})
        res = solver.solve(pods)
        assert sum(len(n.pods) for n in res.new_nodes) == 8
        entries = [f for f in os.listdir(cache_dir) if f.endswith(".stablehlo")]
        assert entries, "TPUSolver.solve must populate the export cache"
