"""Helm chart validation (deploy/charts/) via the helm_lite renderer —
the `helm template | kubectl apply --dry-run` equivalent for an image with
no helm binary.  Parity bar: /root/reference/charts/karpenter-core/templates/
(ServiceMonitor, logging ConfigMap, PDB, SA, RBAC, Deployment, Service) and
charts/karpenter-core-crd/."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from helm_lite import render_chart  # noqa: E402

REPO = os.path.join(os.path.dirname(__file__), "..")
CHART = os.path.join(REPO, "deploy", "charts", "karpenter-core-tpu")
CRD_CHART = os.path.join(REPO, "deploy", "charts", "karpenter-core-tpu-crd")


def flat(docs_by_template):
    return [d for docs in docs_by_template.values() for d in docs]


class TestMainChart:
    def test_renders_with_default_values(self):
        docs = flat(render_chart(CHART))
        kinds = sorted(d["kind"] for d in docs)
        assert kinds == [
            "ClusterRole", "ClusterRoleBinding", "ConfigMap", "ConfigMap",
            "Deployment", "Deployment", "PodDisruptionBudget",
            "PodDisruptionBudget", "Role", "RoleBinding", "Service",
            "Service", "ServiceAccount",
        ]
        for doc in docs:
            assert doc["metadata"]["name"], doc

    def test_servicemonitor_gated_and_well_formed(self):
        # off by default (values.serviceMonitor.enabled: false), real object
        # when enabled — the reference gates it the same way
        # (servicemonitor.yaml:1)
        assert render_chart(CHART)["servicemonitor.yaml"] == []
        docs = render_chart(
            CHART,
            value_overrides={"serviceMonitor": {"enabled": True,
                                                "additionalLabels": {"team": "infra"}}},
        )["servicemonitor.yaml"]
        assert len(docs) == 1
        sm = docs[0]
        assert sm["kind"] == "ServiceMonitor"
        assert sm["apiVersion"] == "monitoring.coreos.com/v1"
        assert sm["metadata"]["labels"]["team"] == "infra"
        endpoint = sm["spec"]["endpoints"][0]
        assert endpoint == {"port": "http-metrics", "path": "/metrics"}
        # the scrape selector must match the metrics Service's labels
        service = render_chart(CHART)["service.yaml"][0]
        sel = sm["spec"]["selector"]["matchLabels"]
        assert all(service["metadata"]["labels"].get(k) == v for k, v in sel.items())

    def test_controller_wiring(self):
        deploy = render_chart(CHART)["deployment.yaml"][0]
        assert deploy["spec"]["replicas"] == 2
        container = deploy["spec"]["template"]["spec"]["containers"][0]
        env = {e["name"]: e.get("value") for e in container["env"]}
        solver_addr = "karpenter-core-tpu-solver.karpenter.svc.cluster.local:8980"
        assert env["KC_SOLVER_ADDRESS"] == solver_addr
        assert env["KC_LEASE_ENDPOINT"] == solver_addr
        assert env["LEADER_ELECT"] == "true"
        ports = {p["name"]: p["containerPort"] for p in container["ports"]}
        assert ports == {"http-metrics": 8080, "http": 8081}
        # kube backend defaults to the hermetic in-memory store; apiserver
        # mode adds the endpoint env (docs/KUBEAPI.md)
        assert env["KC_KUBE_BACKEND"] == "memory"
        assert "KC_KUBE_APISERVER" not in env
        api = render_chart(
            CHART,
            value_overrides={"controller": {"kubeBackend": "apiserver",
                                            "kubeApiserver": "http://127.0.0.1:8001"}},
        )["deployment.yaml"][0]
        env_api = {
            e["name"]: e.get("value")
            for e in api["spec"]["template"]["spec"]["containers"][0]["env"]
        }
        assert env_api["KC_KUBE_BACKEND"] == "apiserver"
        assert env_api["KC_KUBE_APISERVER"] == "http://127.0.0.1:8001"

    def test_solver_pins_jax_platform_for_the_xla_cache(self):
        # compilecache.enable() keeps the persistent XLA cache off for an
        # unpinned/cpu platform; the deployed TPU solver must name its
        # platform or silently lose the cache volume's benefit (ADVICE r5)
        solver = render_chart(CHART)["solver.yaml"]
        deploy = next(d for d in solver if d["kind"] == "Deployment")
        env = {
            e["name"]: e.get("value")
            for e in deploy["spec"]["template"]["spec"]["containers"][0]["env"]
        }
        assert env["JAX_PLATFORMS"] == "tpu"

    def test_solver_hostpath_default_and_pvc_option(self):
        solver = render_chart(CHART)["solver.yaml"]
        deploy = next(d for d in solver if d["kind"] == "Deployment")
        volume = deploy["spec"]["template"]["spec"]["volumes"][0]
        assert "hostPath" in volume
        assert not any(d["kind"] == "PersistentVolumeClaim" for d in solver)
        # persistence.enabled switches the lease/compile volume to a PVC
        # (ADVICE r4 #2: survives solver reschedules across nodes)
        solver_pvc = render_chart(
            CHART, value_overrides={"solver": {"persistence": {"enabled": True}}}
        )["solver.yaml"]
        deploy = next(d for d in solver_pvc if d["kind"] == "Deployment")
        volume = deploy["spec"]["template"]["spec"]["volumes"][0]
        assert volume["persistentVolumeClaim"]["claimName"] == (
            "karpenter-core-tpu-solver-cache"
        )
        pvc = next(d for d in solver_pvc if d["kind"] == "PersistentVolumeClaim")
        assert pvc["spec"]["resources"]["requests"]["storage"] == "10Gi"

    def test_solver_requests_the_tpu_resource(self):
        # without the extended-resource request the pod gets no chip and no
        # auto-toleration for the TPU taint — the whole point of the solver
        solver = render_chart(CHART)["solver.yaml"]
        deploy = next(d for d in solver if d["kind"] == "Deployment")
        resources = deploy["spec"]["template"]["spec"]["containers"][0]["resources"]
        assert resources["requests"]["google.com/tpu"] == "1"
        assert resources["limits"]["google.com/tpu"] == "1"
        assert resources["requests"]["cpu"] == "2"

    def test_solver_has_disruption_budget(self):
        # a solver outage halts lease renewal (ADVICE r4 #2): the singleton
        # must BLOCK voluntary evictions — maxUnavailable 1 on replicas 1
        # would permit every eviction and protect nothing
        solver = render_chart(CHART)["solver.yaml"]
        pdb = next(d for d in solver if d["kind"] == "PodDisruptionBudget")
        assert pdb["spec"] == {
            "minAvailable": 1,
            "selector": {
                "matchLabels": {
                    "app.kubernetes.io/name": "karpenter-core-tpu-solver"
                }
            },
        }

    def test_additional_labels_render_everywhere(self):
        docs = render_chart(CHART, value_overrides={"additionalLabels": {"team": "x"}})
        for tmpl in ("deployment.yaml", "service.yaml", "configmap.yaml"):
            for doc in docs[tmpl]:
                assert doc["metadata"]["labels"]["team"] == "x", (tmpl, doc)

    def test_logging_configmap(self):
        docs = render_chart(CHART)["configmap-logging.yaml"]
        assert docs[0]["metadata"]["name"] == "config-logging"
        assert docs[0]["data"]["loglevel.controller"] == "info"

    def test_name_overrides(self):
        docs = render_chart(CHART, value_overrides={"fullnameOverride": "karpenter"})
        assert docs["deployment.yaml"][0]["metadata"]["name"] == "karpenter"
        env = {
            e["name"]: e.get("value")
            for e in docs["deployment.yaml"][0]["spec"]["template"]["spec"][
                "containers"
            ][0]["env"]
        }
        assert env["KC_SOLVER_ADDRESS"].startswith("karpenter-solver.")


class TestCRDChart:
    def test_crds_render_and_match_api_model(self):
        docs = flat(render_chart(CRD_CHART))
        by_name = {d["metadata"]["name"]: d for d in docs}
        assert set(by_name) == {"provisioners.karpenter.sh", "machines.karpenter.sh"}
        prov = by_name["provisioners.karpenter.sh"]
        assert prov["spec"]["scope"] == "Cluster"
        version = prov["spec"]["versions"][0]
        assert version["name"] == "v1alpha5"
        spec_props = version["schema"]["openAPIV3Schema"]["properties"]["spec"][
            "properties"
        ]
        # every ProvisionerSpec field (apis/v1alpha5.py:66-82) is in the schema
        assert set(spec_props) >= {
            "annotations", "labels", "taints", "startupTaints", "requirements",
            "kubeletConfiguration", "provider", "providerRef",
            "ttlSecondsAfterEmpty", "ttlSecondsUntilExpired", "consolidation",
            "weight", "limits",
        }
        machine = by_name["machines.karpenter.sh"]
        machine_props = machine["spec"]["versions"][0]["schema"][
            "openAPIV3Schema"
        ]["properties"]["spec"]["properties"]
        assert set(machine_props) >= {
            "taints", "startupTaints", "requirements", "kubelet", "resources",
            "machineTemplateRef",
        }
