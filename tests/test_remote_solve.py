"""Controller → remote solver routing (the deployed topology, end to end).

In-cluster, controller replicas run on CPU nodes and ship device solves over
the snapshot channel to the one shared TPU solver
(deploy/manifests/deployment.yaml, KC_SOLVER_ADDRESS).  These tests run that
exact path in-process: a real gRPC solver service, a ProvisioningController
with solver_endpoint pointed at it, real pods through reconcile.
"""

import pytest

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import LabelSelector, TopologySpreadConstraint
from karpenter_core_tpu.service.snapshot_channel import serve
from karpenter_core_tpu.testing import make_pod, make_pods, make_provisioner
from karpenter_core_tpu.testing.harness import expect_provisioned, make_environment

pytestmark = pytest.mark.compile  # the service compiles the solve kernel


@pytest.fixture()
def remote_env(tmp_path, monkeypatch):
    monkeypatch.setenv("KC_LEASE_STATE", str(tmp_path / "leases.json"))
    env = make_environment()
    server, port = serve(env.provider, address="127.0.0.1:0")
    env.provisioning.use_tpu_kernel = True
    env.provisioning.tpu_kernel_min_pods = 4
    env.provisioning.solver_endpoint = f"127.0.0.1:{port}"
    env.kube.create(make_provisioner())
    yield env
    server.stop(grace=0)


class TestRemoteSolveRouting:
    def test_batch_solves_through_the_service(self, remote_env):
        env = remote_env
        pods = make_pods(12, requests={"cpu": "100m"})
        result = expect_provisioned(env, *pods)
        assert all(result[p.uid] is not None for p in pods)
        assert env.provider.create_calls, "remote solve must still launch machines"
        # the in-process kernel never ran: the remote client was built
        assert env.provisioning._solver_client is not None

    def test_spread_batch_matches_host_semantics(self, remote_env):
        env = remote_env
        topo = TopologySpreadConstraint(
            max_skew=1,
            topology_key=labels_api.LABEL_TOPOLOGY_ZONE,
            label_selector=LabelSelector(match_labels={"app": "web"}),
        )
        pods = make_pods(6, labels={"app": "web"}, requests={"cpu": "100m"},
                         topology_spread=[topo])
        result = expect_provisioned(env, *pods)
        assert all(result[p.uid] is not None for p in pods)
        zones = {}
        for p in pods:
            z = result[p.uid].metadata.labels.get(labels_api.LABEL_TOPOLOGY_ZONE)
            zones[z] = zones.get(z, 0) + 1
        assert sorted(zones.values()) == [2, 2, 2]

    def test_existing_nodes_nominate_over_the_wire(self, remote_env):
        env = remote_env
        first = make_pods(6, requests={"cpu": "100m"})
        result = expect_provisioned(env, *first)
        assert all(result[p.uid] is not None for p in first)
        env.make_all_nodes_ready()

        first_nodes = {result[p.uid].name for p in first}

        second = make_pods(6, requests={"cpu": "100m"})
        result2 = expect_provisioned(env, *second)
        assert all(result2[p.uid] is not None for p in second)
        # capacity existed on the already-launched nodes: at least part of the
        # second batch must have been nominated onto them via the wire's
        # existingAssignments
        second_nodes = {result2[p.uid].name for p in second}
        assert second_nodes & first_nodes, (
            f"no existing-node reuse over the wire: {second_nodes} vs {first_nodes}"
        )

    def test_unsupported_batch_falls_back_to_host(self, remote_env):
        env = remote_env
        from karpenter_core_tpu.apis.objects import ContainerPort

        # specific-IP host port: kernel-unsupported per classify, and the
        # whole batch shares the shape so the split cannot isolate it
        pods = []
        for _ in range(6):
            pod = make_pod(requests={"cpu": "100m"})
            pod.spec.containers[0].ports.append(
                ContainerPort(host_port=80, host_ip="10.0.0.1")
            )
            pods.append(pod)
        result = expect_provisioned(env, *pods)
        # host path still schedules them (one per node: the port collides)
        assert all(result[p.uid] is not None for p in pods)

    def test_consolidation_sweep_over_the_wire(self, tmp_path, monkeypatch):
        """The deployed topology's consolidation path: the device subset
        sweep runs on the solver service (/Consolidate), the controller
        reconstructs and executes the command (suite parity with the
        in-process sweep in test_service.py::TestTPUConsolidationInController)."""
        from tests.test_tpu_consolidation import build_cluster
        from karpenter_core_tpu.controllers.deprovisioning import Result
        from karpenter_core_tpu.service.snapshot_channel import serve

        monkeypatch.setenv("KC_LEASE_STATE", str(tmp_path / "leases.json"))
        env = build_cluster(n_nodes=2, pods_per_node=1, pod_cpu="500m", oversize=True)
        server, port = serve(env.provider, address="127.0.0.1:0")
        try:
            mnc = env.deprovisioning.multi_node_consolidation
            mnc.use_tpu_kernel = True
            mnc.solver_endpoint = f"127.0.0.1:{port}"
            result, _ = env.deprovisioning.reconcile()
            assert result == Result.SUCCESS
            assert mnc._solver_client is not None, "sweep must have gone remote"
            # consolidated: fewer nodes than before
            assert len(env.kube.list_nodes()) == 1
        finally:
            server.stop(grace=0)

    def test_wire_replacements_keep_capacity_type_pinning(self, tmp_path, monkeypatch):
        """The sweep's price rules narrow a both-viable replacement to
        SPOT-ONLY (consolidation.go:227-267 parity: on-demand candidates, an
        unrestricted template); the wire round-trip must preserve that
        narrowing, or the launch could buy on-demand above the replaced cost.
        The scenario is constructed so the pin actually fires: hand-built
        ON-DEMAND nodes under a provisioner that allows both capacity types —
        the local command's CT set is strictly {spot}, not the template's."""
        from karpenter_core_tpu.apis import labels as labels_api
        from karpenter_core_tpu.cloudprovider import fake as fake_cp
        from karpenter_core_tpu.controllers.deprovisioning import (
            Action,
            candidate_nodes,
        )
        from karpenter_core_tpu.service.snapshot_channel import serve
        from karpenter_core_tpu.testing import make_node
        from karpenter_core_tpu.testing.harness import make_environment

        monkeypatch.setenv("KC_LEASE_STATE", str(tmp_path / "leases.json"))
        env = make_environment(instance_types=fake_cp.instance_types(5))
        env.kube.create(make_provisioner(consolidation_enabled=True))  # spot AND on-demand

        # two oversized ON-DEMAND nodes, each holding one small pod
        catalog = env.provider.get_instance_types(None)
        big = catalog[-1]
        offering = next(
            o for o in big.offerings
            if o.capacity_type == labels_api.CAPACITY_TYPE_ON_DEMAND and o.available
        )
        for i in range(2):
            node = make_node(
                name=f"od-{i}",
                labels={
                    labels_api.PROVISIONER_NAME_LABEL_KEY: "default",
                    labels_api.LABEL_INSTANCE_TYPE_STABLE: big.name,
                    labels_api.LABEL_TOPOLOGY_ZONE: offering.zone,
                    labels_api.LABEL_CAPACITY_TYPE: labels_api.CAPACITY_TYPE_ON_DEMAND,
                },
                allocatable=big.allocatable(),
                capacity=dict(big.capacity),
                provider_id=f"fake://od-{i}",
            )
            env.kube.create(node)
            pod = make_pod(requests={"cpu": "300m"})
            env.kube.create(pod)
            env.bind(pod, node.name)
        env.make_all_nodes_ready()
        env.clock.step(21)

        server, port = serve(env.provider, address="127.0.0.1:0")
        try:
            mnc = env.deprovisioning.multi_node_consolidation
            mnc.use_tpu_kernel = True
            candidates = sorted(
                candidate_nodes(
                    env.cluster, env.kube, env.clock, env.provider,
                    mnc.should_deprovision,
                ),
                key=lambda c: c.disruption_cost,
            )
            assert len(candidates) == 2

            from karpenter_core_tpu.solver.consolidation import TPUConsolidationSearch

            local_cmd = TPUConsolidationSearch(
                env.provider, env.kube.list_provisioners()
            ).compute_command(
                candidates,
                pending_pods=[],
                state_nodes=env.cluster.snapshot_nodes(),
                bound_pods=env.kube.list_pods(),
            )
            assert local_cmd.action == Action.REPLACE

            def cts(cmd):
                requirements = cmd.replacement_nodes[0].requirements
                if requirements.has(labels_api.LABEL_CAPACITY_TYPE):
                    return set(requirements.get(labels_api.LABEL_CAPACITY_TYPE).values_list())
                return set()

            # the guard's precondition: the pin FIRED locally — the set is
            # strictly narrower than the template's {spot, on-demand}
            assert cts(local_cmd) == {labels_api.CAPACITY_TYPE_SPOT}

            mnc.solver_endpoint = f"127.0.0.1:{port}"
            remote_cmd = mnc._tpu_search(candidates)
            assert remote_cmd is not None and remote_cmd.action == Action.REPLACE
            assert cts(remote_cmd) == {labels_api.CAPACITY_TYPE_SPOT}
            assert {n.name for n in remote_cmd.nodes_to_remove} == {
                n.name for n in local_cmd.nodes_to_remove
            }
        finally:
            server.stop(grace=0)

    def test_transport_fault_trips_the_circuit_breaker(self, tmp_path, monkeypatch):
        env = make_environment()
        env.provisioning.use_tpu_kernel = True
        env.provisioning.tpu_kernel_min_pods = 2
        env.provisioning.solver_endpoint = "127.0.0.1:1"  # nothing listens
        env.kube.create(make_provisioner())
        from karpenter_core_tpu.controllers import provisioning as prov_mod

        for _ in range(prov_mod.TPU_KERNEL_MAX_FAILURES):
            pods = make_pods(3, requests={"cpu": "100m"})
            result = expect_provisioned(env, *pods)
            assert all(result[p.uid] is not None for p in pods)  # host fallback
        from karpenter_core_tpu.utils import retry

        assert env.provisioning.solver_breaker.state == retry.OPEN
        assert env.provisioning.degraded() is True
