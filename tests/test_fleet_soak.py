"""Fleet failover soak acceptance (soak/tenants.py, docs/FLEET.md).

The ISSUE-17 acceptance scenario: ≥3 REAL replica processes
(``python -m karpenter_core_tpu.fleet.replica_main``) behind one in-process
consistent-hash router, SIGKILL the most-loaded replica mid-churn — every
tenant it held must resume WARM on another replica (checkpoint adoption,
echo ``recovered="warm"``) inside the p99 SLO, with 0 cross-tenant wrong
answers and 0 machine leaks fleet-wide.  Wired into ``make soak``; the
tier-1 smoke runs the same multi-process topology scaled down.
"""

import contextlib
import os

import pytest

from karpenter_core_tpu.soak.tenants import FleetSoakScenario, run_fleet_failover
from karpenter_core_tpu.testing import lockcheck as lockcheck_mod


def _seed() -> int:
    return int(os.environ.get("KC_SOAK_SEED", "1729"))


def _maybe_lockcheck():
    """KC_LOCKCHECK=1 runs the soak's in-process side (the router the
    replica subprocesses sit behind) under the runtime lockset tracer
    (docs/CHAOS.md "Lockset tracing"); otherwise a no-op context."""
    if not lockcheck_mod.enabled():
        return contextlib.nullcontext(None)
    from karpenter_core_tpu.fleet.checkpoint import CheckpointPlane
    from karpenter_core_tpu.fleet.router import FleetRouter

    return lockcheck_mod.LockCheck(watch=(FleetRouter, CheckpointPlane))


def _assert_fleet_verdict(report: dict) -> None:
    verdict = report["verdict"]
    rules = {r["probe"]: r for r in verdict["slo"]}
    assert rules["wrong_answers"]["observed"] == 0, \
        report["diagnostics"]["errors"]
    assert rules["machine_leaks"]["observed"] == 0
    assert rules["incomplete_rounds"]["observed"] == 0
    # the SIGKILL really evicted tenants and they came back warm elsewhere
    assert verdict["killed_replica"] is not None
    assert rules["evicted_tenants"]["passed"], rules["evicted_tenants"]
    assert rules["warm_resume_fraction"]["passed"], (
        rules["warm_resume_fraction"], report["diagnostics"]["outcomes"],
        report["diagnostics"]["errors"],
    )
    assert rules["e2e_latency_p99_s"]["passed"], rules["e2e_latency_p99_s"]
    assert verdict["passed"] is True, verdict


class TestFleetFailoverSmoke:
    """Tier-1 smoke: the full multi-process topology (3 replicas + router +
    SIGKILL) at the smallest churn that still proves warm failover."""

    def test_fleet_failover_smoke(self, tmp_path):
        with _maybe_lockcheck() as lc:
            report = run_fleet_failover(
                FleetSoakScenario(
                    replicas=3, tenants=4, rounds=2, kill_after_round=0,
                    pods_per_tenant=6,
                ),
                seed=_seed(),
                fleet_dir=str(tmp_path / "fleet"),
            )
        _assert_fleet_verdict(report)
        if lc is not None:
            lc.assert_clean()
        # tools/soak.py renders this report with the same verdict-line code
        # path as every other scenario — pin the fields it reads
        verdict = report["verdict"]
        assert {"scenario", "seed", "passed", "slo", "ticks",
                "converged"} <= set(verdict)
        for rule in verdict["slo"]:
            assert {"probe", "agg", "limit", "observed", "passed"} <= set(rule)
        assert report["diagnostics"]["wall_s"] > 0


@pytest.mark.slow
class TestFleetFailoverScale:
    def test_eight_tenants_four_rounds(self, tmp_path):
        """The full ISSUE-17 acceptance scale: 8 tenants churning across 3
        replicas for 4 rounds, kill after round 1 — ≥95% of the victim's
        tenants resume warm."""
        report = run_fleet_failover(
            FleetSoakScenario(replicas=3, tenants=8, rounds=4,
                              kill_after_round=1),
            seed=_seed(),
            fleet_dir=str(tmp_path / "fleet"),
        )
        _assert_fleet_verdict(report)
