"""Multi-tenant solver service: admission control, tenant fault isolation,
batch coalescing, and crash-recoverable sessions (service/tenant.py,
docs/SERVICE.md).

The wire tests run small solves so the kernel executable compiles once per
pytest process and stays memoized across tests (the shapes share one
bucket)."""

import threading

import grpc
import msgpack
import pytest

from karpenter_core_tpu import chaos
from karpenter_core_tpu.cloudprovider.fake import FakeCloudProvider
from karpenter_core_tpu.service.snapshot_channel import (
    SERVICE,
    SnapshotSolverClient,
    serve,
    service_capacity,
)
from karpenter_core_tpu.service.tenant import (
    BatchCoalescer,
    TenantConfig,
    TenantPlane,
    parse_retry_after,
)
from karpenter_core_tpu.testing import make_pod, make_provisioner
from karpenter_core_tpu.utils import retry
from karpenter_core_tpu.utils.clock import FakeClock


def _loose_config(**kw) -> TenantConfig:
    """A config that never sheds or batches unless the test asks for it."""
    base = dict(
        rate_per_s=1000.0, burst=1000, max_inflight=64,
        batch_window_s=0.0, max_batch=8,
        breaker_threshold=3, breaker_reset_s=30.0,
    )
    base.update(kw)
    return TenantConfig(**base)


def _pod_classes(n: int = 4, cpu: str = "500m"):
    return [(make_pod(requests={"cpu": cpu}), n)]


def _solve(client, tenant_id: str, count: int = 4, version: int = 0,
           cpu: str = "500m", supply_digest=None):
    tenant = {"id": tenant_id, "sessionVersion": version}
    if supply_digest is not None:
        tenant["supplyDigest"] = supply_digest
    return client.solve_tenant_classes(
        _pod_classes(count, cpu), [make_provisioner()], tenant=tenant
    )


@pytest.fixture()
def channel(request):
    """(server, client, clock) with a per-test TenantConfig via
    ``@pytest.mark.tenant_config(...)``."""
    marker = request.node.get_closest_marker("tenant_config")
    config = _loose_config(**(marker.kwargs if marker else {}))
    clock = FakeClock()
    server, port = serve(FakeCloudProvider(), tenant_config=config, clock=clock)
    client = SnapshotSolverClient(f"127.0.0.1:{port}")
    yield server, client, clock
    client.close()
    server.stop(0)


def _raw_solve_classes(port_or_client, payload: dict) -> bytes:
    client = port_or_client
    raw = client.channel.unary_unary(f"/{SERVICE}/SolveClasses")
    return raw(msgpack.packb(payload))


class TestAdmissionControl:
    @pytest.mark.tenant_config(rate_per_s=0.1, burst=1)
    def test_rate_shed_is_resource_exhausted_with_retry_after(self, channel):
        _server, client, _clock = channel
        ok = _solve(client, "acme")
        assert ok["tenant"]["solveMode"] == "full"
        with pytest.raises(grpc.RpcError) as excinfo:
            _solve(client, "acme")
        assert excinfo.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
        assert "tenant-shed" in excinfo.value.details()
        hint = parse_retry_after(excinfo.value.details())
        assert hint is not None and hint > 0

    @pytest.mark.tenant_config(rate_per_s=0.1, burst=1)
    def test_one_tenants_burst_does_not_shed_another(self, channel):
        _server, client, _clock = channel
        _solve(client, "noisy")
        with pytest.raises(grpc.RpcError):
            _solve(client, "noisy")
        # the quiet tenant has its own bucket
        assert _solve(client, "quiet")["tenant"]["solveMode"] == "full"

    def test_queue_bound_sheds_with_hint(self):
        plane = TenantPlane(clock=FakeClock(), config=_loose_config(max_inflight=1))
        assert plane.admit("a").admitted
        decision = plane.admit("b")
        assert not decision.admitted and decision.reason == "queue"
        assert decision.retry_after_s > 0
        plane.release("a")
        assert plane.admit("b").admitted

    def test_shed_hints_escalate_while_hammering(self):
        clock = FakeClock()
        # fast-refill bucket: its own hint is tiny, so the per-tenant shed
        # Backoff is what the hammering client sees escalate
        plane = TenantPlane(
            clock=clock, config=_loose_config(rate_per_s=1000.0, burst=1)
        )
        assert plane.admit("a").admitted
        plane.release("a")
        hints = [plane.admit("a").retry_after_s for _ in range(3)]
        assert hints[0] < hints[1] < hints[2]  # Backoff-escalated

    def test_queue_shed_does_not_burn_rate_tokens(self):
        """Global queue pressure caused by OTHER tenants must not consume
        this tenant's tokens: a queue-shed storm followed by a drain leaves
        the tenant admissible, never escalated into a rate shed."""
        plane = TenantPlane(
            clock=FakeClock(),
            config=_loose_config(max_inflight=1, rate_per_s=0.001, burst=1),
        )
        assert plane.admit("hog").admitted
        for _ in range(5):
            decision = plane.admit("victim")
            assert not decision.admitted and decision.reason == "queue"
        plane.release("hog")
        # the victim's single burst token is still there
        assert plane.admit("victim").admitted

    def test_retry_budget_next_token_hint(self):
        clock = FakeClock()
        bucket = retry.RetryBudget(clock, budget=2, window_s=20.0, name="t")
        assert bucket.next_token_s() == 0.0
        assert bucket.allow() and bucket.allow() and not bucket.allow()
        hint = bucket.next_token_s()
        assert 0 < hint <= 10.0
        clock.step(hint)
        assert bucket.allow()


class TestWeightedFairShare:
    """ISSUE-13 satellite: per-tenant weights scale the admission bucket's
    rate AND burst; sheds keep exact retry-after hints."""

    def test_env_weights_scale_burst_and_rate(self, monkeypatch):
        monkeypatch.setenv("KC_TENANT_WEIGHTS", "heavy=4.0, light=0.5")
        config = TenantConfig.from_env()
        assert config.resolve_weight("heavy") == 4.0
        assert config.resolve_weight("light") == 0.5
        assert config.resolve_weight("unlisted") == 1.0
        plane = TenantPlane(clock=FakeClock(), config=_loose_config(
            rate_per_s=1.0, burst=4, weights={"heavy": 4.0, "light": 0.5},
        ))
        heavy = plane.checkout("heavy", weight=config.resolve_weight("heavy"))
        light = plane.checkout("light", weight=config.resolve_weight("light"))
        assert heavy.bucket.budget == 16.0  # burst * 4
        assert light.bucket.budget == 2.0   # burst * 0.5
        assert heavy.bucket.refill_per_s == pytest.approx(4.0)
        assert light.bucket.refill_per_s == pytest.approx(0.5)

    def test_weighted_admission_over_the_wire(self):
        """A weighted tenant sustains proportionally more requests before
        shedding, and the shed hint is the weighted bucket's exact refill
        time."""
        clock = FakeClock()
        config = _loose_config(
            rate_per_s=0.1, burst=2, weights={"heavy": 3.0},
        )
        server, port = serve(FakeCloudProvider(), tenant_config=config,
                             clock=clock)
        client = SnapshotSolverClient(f"127.0.0.1:{port}")
        try:
            for _ in range(6):  # burst 2 * weight 3
                assert _solve(client, "heavy")["tenant"]["id"] == "heavy"
            with pytest.raises(grpc.RpcError) as excinfo:
                _solve(client, "heavy")
            assert excinfo.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
            # the unweighted tenant sheds after its plain burst of 2
            _solve(client, "plain")
            _solve(client, "plain")
            with pytest.raises(grpc.RpcError) as excinfo:
                _solve(client, "plain")
            hint = parse_retry_after(excinfo.value.details())
            # exact refill hint: 1 token at rate 0.1/s (possibly escalated
            # by the shed backoff, never below the bucket's own time)
            assert hint is not None and hint >= 0.05
        finally:
            client.close()
            server.stop(0)

    def test_wire_weight_claims_are_honored_but_env_wins(self):
        plane = TenantPlane(clock=FakeClock(), config=_loose_config(
            rate_per_s=1.0, burst=4, weights={"pinned": 2.0},
        ))
        # the wire claim shapes an unpinned tenant's bucket
        decision = plane.admit("claimer", weight=5.0)
        assert decision.admitted
        assert decision.entry.bucket.budget == 20.0
        plane.release("claimer")
        # but an operator env pin beats the wire's self-promotion
        decision = plane.admit("pinned", weight=50.0)
        assert decision.admitted
        assert decision.entry.bucket.budget == 8.0
        plane.release("pinned")

    def test_weight_change_reshapes_bucket_proportionally(self):
        clock = FakeClock()
        plane = TenantPlane(clock=clock, config=_loose_config(
            rate_per_s=1.0, burst=4,
        ))
        entry = plane.checkout("a", weight=1.0)
        for _ in range(2):
            assert entry.bucket.allow()
        assert entry.bucket.remaining() == pytest.approx(2.0)
        # weight 1 -> 2: budget 4 -> 8, half-full stays half-full
        entry = plane.checkout("a", weight=2.0)
        assert entry.weight == 2.0
        assert entry.bucket.budget == 8.0
        assert entry.bucket.remaining() == pytest.approx(4.0)

    def test_weight_clamps(self):
        config = _loose_config(weights={"evil": 1e9})
        assert config.resolve_weight("evil") == 100.0
        assert config.resolve_weight("x", wire_weight=-5) == 0.01
        assert config.resolve_weight("x", wire_weight="bogus") == 1.0


class TestDrainingAdmission:
    def test_draining_sheds_without_minting_sessions(self):
        plane = TenantPlane(clock=FakeClock(), config=_loose_config())
        plane.start_draining(retry_after_s=7.0)
        decision = plane.admit("newcomer")
        assert not decision.admitted and decision.reason == "draining"
        assert decision.retry_after_s == 7.0
        assert plane.sessions() == []  # no session minted while draining


class TestTenantIsolation:
    @pytest.mark.tenant_config(breaker_threshold=2)
    def test_malformed_requests_isolate_the_tenant(self, channel):
        server, client, clock = channel
        for _ in range(2):
            with pytest.raises(grpc.RpcError) as excinfo:
                _raw_solve_classes(client, {
                    "tenant": {"id": "bad"},
                    "podClasses": [{"oops": 1}],
                })
            assert excinfo.value.code() == grpc.StatusCode.INVALID_ARGUMENT
            assert "tenant-ejected reason=malformed" in excinfo.value.details()
        # breaker open: even a VALID request is refused with a hint
        with pytest.raises(grpc.RpcError) as excinfo:
            _solve(client, "bad")
        assert excinfo.value.code() == grpc.StatusCode.UNAVAILABLE
        assert "tenant-isolated" in excinfo.value.details()
        assert parse_retry_after(excinfo.value.details()) > 0
        # the other N-1 tenants never notice
        assert _solve(client, "good")["tenant"]["solveMode"] == "full"
        # half-open after the reset window: one trial readmits the tenant
        clock.step(31.0)
        assert _solve(client, "bad")["tenant"]["solveMode"] == "full"
        plane = server.kc_service.tenants
        entry = plane.checkout("bad")
        assert entry.breaker.state == retry.CLOSED

    @pytest.mark.tenant_config(max_request_bytes=2048)
    def test_oversized_snapshot_is_ejected_and_counted(self, channel):
        _server, client, _clock = channel
        big = make_pod(requests={"cpu": "500m"},
                       labels={f"pad-{i}": "x" * 40 for i in range(40)})
        with pytest.raises(grpc.RpcError) as excinfo:
            client.solve_tenant_classes(
                [(big, 4)], [make_provisioner()], tenant={"id": "fat"}
            )
        assert excinfo.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        assert "reason=oversized" in excinfo.value.details()

    def test_missing_tenant_id_rejected(self, channel):
        _server, client, _clock = channel
        with pytest.raises(grpc.RpcError) as excinfo:
            _raw_solve_classes(client, {"tenant": {"sessionVersion": 1},
                                        "podClasses": []})
        assert excinfo.value.code() == grpc.StatusCode.INVALID_ARGUMENT


class TestSessionLifecycle:
    def test_ttl_eviction_on_fake_clock(self):
        clock = FakeClock()
        plane = TenantPlane(clock=clock, config=_loose_config(session_ttl_s=60.0))
        plane.checkout("a")
        clock.step(61.0)
        plane.checkout("b")
        assert plane.sessions() == ["b"]

    def test_lru_eviction_caps_resident_sessions(self):
        plane = TenantPlane(clock=FakeClock(), config=_loose_config(max_sessions=2))
        for tid in ("a", "b", "c"):
            plane.checkout(tid)
        assert plane.sessions() == ["b", "c"]
        # touching keeps a session warm
        plane.checkout("b")
        plane.checkout("d")
        assert plane.sessions() == ["b", "d"]

    @pytest.mark.tenant_config(max_sessions=1)
    def test_evicted_session_reanchors_as_session_lost(self, channel):
        _server, client, _clock = channel
        r1 = _solve(client, "a")
        assert r1["tenant"]["reason"] == "first"
        _solve(client, "b")  # evicts a (capacity 1)
        r2 = _solve(client, "a", version=r1["tenant"]["sessionVersion"])
        assert r2["tenant"]["solveMode"] == "full"
        assert r2["tenant"]["reason"] == "session-lost"


class TestSessionRecovery:
    def test_full_then_delta_then_stale_version_reanchors(self, channel):
        _server, client, _clock = channel
        r1 = _solve(client, "acme", count=8)
        assert (r1["tenant"]["solveMode"], r1["tenant"]["reason"]) == ("full", "first")
        v1 = r1["tenant"]["sessionVersion"]
        assert v1 > 0
        # +2 pods of 10 stays under the delta-fraction escalation bound
        r2 = _solve(client, "acme", count=10, version=v1)
        assert r2["tenant"]["solveMode"] == "delta"
        # only the delta's pods come back on a delta solve
        placed = sum(n for node in r2["newNodes"] for _c, n in node["classCounts"])
        placed += sum(n for _c, n in r2["failedClassCounts"])
        placed += sum(
            n for counts in r2["existingAssignments"].values() for _c, n in counts
        )
        assert placed == 2
        r3 = _solve(client, "acme", count=10, version=v1 + 999)
        assert (r3["tenant"]["solveMode"], r3["tenant"]["reason"]) == (
            "full", "session-lost"
        )

    def test_client_restart_reanchors(self, channel):
        _server, client, _clock = channel
        r1 = _solve(client, "acme")
        assert r1["tenant"]["sessionVersion"] > 0
        r2 = _solve(client, "acme", version=0)
        assert (r2["tenant"]["solveMode"], r2["tenant"]["reason"]) == (
            "full", "client-reanchor"
        )

    def test_supply_digest_mismatch_reanchors(self, channel):
        _server, client, _clock = channel
        r1 = _solve(client, "acme", supply_digest="sha:aaa")
        v1 = r1["tenant"]["sessionVersion"]
        r2 = _solve(client, "acme", version=v1, supply_digest="sha:bbb")
        assert (r2["tenant"]["solveMode"], r2["tenant"]["reason"]) == (
            "full", "supply-digest"
        )

    def test_server_restart_mid_stream_session_lost(self):
        """Kill the server, bring a new one up: in-memory lineages die with
        the process and every session re-anchors — reason session-lost, a
        FULL solve, never a stale delta."""
        provider = FakeCloudProvider()
        config = _loose_config()
        server, port = serve(provider, tenant_config=config)
        client = SnapshotSolverClient(f"127.0.0.1:{port}")
        try:
            r1 = _solve(client, "acme", count=5)
            v1 = r1["tenant"]["sessionVersion"]
            assert v1 > 0
        finally:
            client.close()
            server.stop(grace=0)
        server, port = serve(provider, tenant_config=config)
        client = SnapshotSolverClient(f"127.0.0.1:{port}")
        try:
            r2 = _solve(client, "acme", count=5, version=v1)
            assert (r2["tenant"]["solveMode"], r2["tenant"]["reason"]) == (
                "full", "session-lost"
            )
            # the full re-anchor accounts every pod, exactly once
            placed = sum(
                n for node in r2["newNodes"] for _c, n in node["classCounts"]
            )
            placed += sum(
                n for counts in r2["existingAssignments"].values()
                for _c, n in counts
            )
            assert placed == 5
        finally:
            client.close()
            server.stop(grace=0)


def _strip(resp: dict) -> dict:
    return {k: v for k, v in resp.items() if k != "tenant"}


class TestCoalescing:
    @pytest.mark.tenant_config(batch_window_s=1.0)
    def test_coalesced_batch_bit_identical_to_solo(self, channel):
        """Two concurrent compatible-bucket tenants coalesce into ONE
        batched (vmapped) solve whose per-tenant answers are bit-identical
        to their solo solves."""
        _server, client, _clock = channel
        solo = {
            "a": _solve(client, "solo-a", cpu="500m"),
            "b": _solve(client, "solo-b", cpu="250m"),
        }
        for attempt in range(3):
            results = {}
            errors = []

            def call(tid, cpu, attempt=attempt):
                try:
                    results[tid] = client.solve_tenant_classes(
                        _pod_classes(4, cpu), [make_provisioner()],
                        tenant={"id": f"{tid}-{attempt}"},
                    )
                except Exception as e:  # noqa: BLE001 - surfaced below
                    errors.append(e)

            threads = [
                threading.Thread(target=call, args=("a", "500m")),
                threading.Thread(target=call, args=("b", "250m")),
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errors, errors
            if all(r["tenant"]["batched"] == 2 for r in results.values()):
                break
        else:
            pytest.fail("coalescer never batched the concurrent tenants")
        assert _strip(results["a"]) == _strip(solo["a"])
        assert _strip(results["b"]) == _strip(solo["b"])

    @pytest.mark.tenant_config(batch_window_s=1.0)
    def test_ejected_tenant_contained_rest_bit_identical(self, channel):
        """The fault-containment acceptance pin: one tenant's snapshot
        fails validation (never reaches the batch) and is answered with a
        structured error, while its co-batched tenants' assignments stay
        bit-identical to their solo solves."""
        _server, client, _clock = channel
        solo = {
            "a": _solve(client, "solo2-a", cpu="500m"),
            "c": _solve(client, "solo2-c", cpu="250m"),
        }
        results = {}
        errors = {}

        def good(tid, cpu):
            results[tid] = client.solve_tenant_classes(
                _pod_classes(4, cpu), [make_provisioner()],
                tenant={"id": tid},
            )

        def bad():
            try:
                # no "pod" key at all: fails validation at decode, before
                # the batch ever sees it
                _raw_solve_classes(client, {
                    "tenant": {"id": "poison"},
                    "podClasses": [{"count": 3}],
                })
            except grpc.RpcError as e:
                errors["poison"] = e

        threads = [
            threading.Thread(target=good, args=("batch-a", "500m")),
            threading.Thread(target=good, args=("batch-c", "250m")),
            threading.Thread(target=bad),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert errors["poison"].code() == grpc.StatusCode.INVALID_ARGUMENT
        assert "tenant-ejected" in errors["poison"].details()
        assert _strip(results["batch-a"]) == _strip(solo["a"])
        assert _strip(results["batch-c"]) == _strip(solo["c"])

    @pytest.mark.tenant_config(batch_window_s=1.0)
    def test_batch_program_fault_falls_back_to_solo(self, channel, monkeypatch):
        """A fault in the batched PROGRAM itself (not attributable to one
        tenant) re-runs every member solo — answers still land, still
        correct."""
        def boom(preps):
            raise RuntimeError("batched executable died")

        monkeypatch.setattr(BatchCoalescer, "_run_batched", staticmethod(boom))
        _server, client, _clock = channel
        solo = _solve(client, "solo3-a", cpu="500m")
        results = {}

        def call(tid, cpu):
            results[tid] = client.solve_tenant_classes(
                _pod_classes(4, cpu), [make_provisioner()],
                tenant={"id": tid},
            )

        threads = [
            threading.Thread(target=call, args=("fb-a", "500m")),
            threading.Thread(target=call, args=("fb-b", "250m")),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results["fb-a"]["tenant"]["batched"] == 1
        assert _strip(results["fb-a"]) == _strip(solo)

    def test_coalescer_never_exceeds_max_batch(self, monkeypatch):
        """Late same-bucket arrivals racing a full group must start the NEXT
        group, never swell a dispatched batch past max_batch (an oversized
        batch would miss the (bucket, N) executable memo and compile on the
        request path)."""
        import numpy as np

        class _FakePrep:
            cls = (np.zeros(2, dtype=np.int32),)
            statics_arrays = (np.ones(2, dtype=np.int32),)
            ex_state = None
            ex_static = None
            n_slots = 4
            key_has_bounds = (False,)
            n_passes = 1
            features = None

        sizes = []

        def fake_batched(preps):
            sizes.append(len(preps))
            return [("out", i) for i in range(len(preps))]

        monkeypatch.setattr(BatchCoalescer, "_run_batched",
                            staticmethod(fake_batched))
        coalescer = BatchCoalescer(window_s=0.3, max_batch=2)
        results = []
        threads = [
            threading.Thread(
                target=lambda: results.append(
                    coalescer.run(_FakePrep(), lambda: ("solo", 0))
                )
            )
            for _ in range(5)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 5
        assert all(outputs is not None for outputs, _n in results)
        assert all(size <= 2 for size in sizes), sizes
        assert all(n <= 2 for _outputs, n in results)

    def test_tenant_mesh_axis_batched_solve_bit_identical(self, monkeypatch):
        """The sharded twin (KC_SOLVER_MESH=1): the coalesced batch splits
        over a mesh ``tenant`` axis (parallel.mesh.TENANT_PARTITION_RULES),
        each device vmapping its local tenants — outputs bit-identical to
        the plain vmap path and to solo solves."""
        import jax
        import numpy as np

        from karpenter_core_tpu.models.columnar import PodIngest
        from karpenter_core_tpu.parallel import mesh as mesh_mod
        from karpenter_core_tpu.solver.tpu import TPUSolver

        provider = FakeCloudProvider()
        solver = TPUSolver(provider, [make_provisioner()])
        preps = []
        for cpu in ("500m", "250m", "500m", "250m"):
            ingest = PodIngest()
            ingest.add_all([make_pod(requests={"cpu": cpu}) for _ in range(6)])
            preps.append(solver.prepare_encoded(solver.encode(ingest)))
        solo = [solver.run_prepared(p) for p in preps]

        plain = BatchCoalescer._run_batched(preps)
        monkeypatch.setenv("KC_SOLVER_MESH", "1")
        monkeypatch.setenv("KC_SOLVER_MESH_DEVICES", "2")
        assert mesh_mod.tenant_mesh_axes(len(preps)) == (("tenant", 2),)
        meshed = BatchCoalescer._run_batched(preps)
        # an indivisible batch declines the mesh rather than mis-sharding
        assert mesh_mod.tenant_mesh_axes(3) is None

        for i in range(len(preps)):
            solo_leaves = jax.tree_util.tree_leaves(jax.device_get(solo[i]))
            for variant in (plain[i], meshed[i]):
                leaves = jax.tree_util.tree_leaves(variant)
                assert all(
                    np.array_equal(np.asarray(a), np.asarray(b))
                    for a, b in zip(solo_leaves, leaves)
                )

    def test_solver_fault_is_structured_ejection(self, channel):
        """A chaos solver.dispatch fault during one tenant's solve comes
        back as a structured in-body error (not a batch-wide abort), and
        the tenant re-anchors cleanly afterwards."""
        _server, client, _clock = channel
        scenario = chaos.Scenario("tenant-eject", 7, {
            "solver.dispatch": chaos.PointSpec(first_n=1),
        })
        with chaos.armed(scenario):
            resp = _solve(client, "faulty")
        assert resp["error"]["kind"] == "ejected"
        assert "chaos" in resp["error"]["reason"]
        assert resp["tenant"]["sessionVersion"] == 0
        # chaos exhausted: the next solve re-anchors from scratch
        r2 = _solve(client, "faulty")
        assert r2["tenant"]["solveMode"] == "full"


class TestServerLoop:
    def test_service_capacity_env(self, monkeypatch):
        monkeypatch.setenv("KC_SERVICE_WORKERS", "7")
        monkeypatch.setenv("KC_SERVICE_QUEUE", "3")
        assert service_capacity() == (7, 10)
        # explicit arg wins over the env
        assert service_capacity(2) == (2, 5)
        monkeypatch.setenv("KC_SERVICE_QUEUE", "bogus")
        assert service_capacity(2) == (2, 34)  # default queue 32

    def test_server_side_deadline_aborts(self, monkeypatch):
        monkeypatch.setenv("KC_SERVICE_DEADLINE_S", "0.000001")
        server, port = serve(FakeCloudProvider(), tenant_config=_loose_config())
        client = SnapshotSolverClient(f"127.0.0.1:{port}")
        try:
            with pytest.raises(grpc.RpcError) as excinfo:
                _solve(client, "slowpoke")
            assert excinfo.value.code() == grpc.StatusCode.DEADLINE_EXCEEDED
            assert "server-side deadline" in excinfo.value.details()
        finally:
            client.close()
            server.stop(0)

    def test_stateless_requests_unaffected_by_tenant_plane(self, channel):
        """No tenant envelope = the original contract, byte for byte."""
        _server, client, _clock = channel
        pods = [make_pod(requests={"cpu": "500m"}) for _ in range(4)]
        response = client.solve_classes(pods, [make_provisioner()])
        assert "tenant" not in response
        placed = sum(len(n["podIndices"]) for n in response["newNodes"])
        assert placed == 4


class TestServiceRpcChaosPoint:
    def test_client_side_fault_raises_injected(self, channel):
        _server, client, _clock = channel
        scenario = chaos.Scenario("svc-client", 3, {
            "service.rpc": chaos.PointSpec(first_n=1),
        })
        with chaos.armed(scenario):
            with pytest.raises(chaos.InjectedFault):
                client.solve_classes(
                    [make_pod(requests={"cpu": "500m"})], [make_provisioner()]
                )
        assert scenario.fired_counts().get("service.rpc") == 1

    def test_server_side_fault_is_unavailable(self, channel):
        _server, client, _clock = channel
        # hit 0 is the client leg (passes), hit 1 the server leg (fires)
        scenario = chaos.Scenario("svc-server", 3, {
            "service.rpc": chaos.PointSpec(schedule=[1]),
        })
        with chaos.armed(scenario):
            with pytest.raises(grpc.RpcError) as excinfo:
                client.solve_classes(
                    [make_pod(requests={"cpu": "500m"})], [make_provisioner()]
                )
            assert excinfo.value.code() == grpc.StatusCode.UNAVAILABLE

    def test_server_partial_drops_response_after_solve(self, channel):
        _server, client, _clock = channel
        scenario = chaos.Scenario("svc-partial", 3, {
            "service.rpc": chaos.PointSpec(schedule=[1], kind="partial"),
        })
        with chaos.armed(scenario):
            with pytest.raises(grpc.RpcError) as excinfo:
                client.solve_classes(
                    [make_pod(requests={"cpu": "500m"})], [make_provisioner()]
                )
            assert excinfo.value.code() == grpc.StatusCode.UNAVAILABLE
            assert "partial" in excinfo.value.details()
        # the partial fault wasted a full solve — the point of the kind
        assert scenario.fired_counts().get("service.rpc") == 1


class TestTenantWireSchema:
    """Golden pins for the tenant envelope (service/SCHEMA.md)."""

    def test_tenant_response_envelope_fields(self, channel):
        _server, client, _clock = channel
        resp = _solve(client, "schema")
        assert set(resp["tenant"]) == {
            "id", "solveMode", "reason", "sessionVersion", "batched",
        }
        assert set(resp) == {
            "newNodes", "existingAssignments", "failedClassCounts",
            "residualClassCounts", "existingCommittedZones", "tenant",
        }

    def test_error_envelope_fields(self, channel):
        _server, client, _clock = channel
        scenario = chaos.Scenario("schema-eject", 5, {
            "solver.dispatch": chaos.PointSpec(first_n=1),
        })
        with chaos.armed(scenario):
            resp = _solve(client, "schema-err")
        assert set(resp) == {"error", "tenant"}
        assert set(resp["error"]) == {"kind", "reason"}
        assert set(resp["tenant"]) == {"id", "sessionVersion"}
