"""Seeded consolidation-plane fuzzing: host binary search vs TPU prefix
sweep vs remote sweep (VERDICT r4 #3).

Warm clusters drawn from a seeded shape space (utilization mix, oversized
nodes, catalog size, spot/on-demand mix, PDB/do-not-evict blockers) run
MultiNodeConsolidation three ways and must agree:

  - the host binary search over disruption-sorted prefixes
    (first_n_consolidation_option, multinodeconsolidation.go:86-113)
  - the TPU subset sweep (solver/consolidation.py: every prefix simulated in
    parallel lanes, re-grid until exact)
  - the remote sweep over the snapshot channel (/Consolidate), exactly as the
    controller ships it (_remote_search)

Contract (the sweep is a documented refinement of the binary search — it
examines EVERY prefix while the binary search assumes monotone validity —
so counts can only grow):

  1. the sweep never removes fewer nodes than the host search;
  2. whatever prefix the sweep picks, the HOST simulation of that same
     subset must independently validate it with the same action and, for
     REPLACE, the same post-filter instance-type option set (this is the
     cross-engine soundness bar: the sweep may be smarter about WHICH prefix,
     never about what a prefix means);
  3. equal-size prefixes agree exactly (same action, same nodes);
  4. the remote sweep returns the same command as the in-process sweep.
"""

import random

import pytest

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import (
    OP_IN,
    NodeSelectorRequirement,
)
from karpenter_core_tpu.cloudprovider import fake as fake_cp
from karpenter_core_tpu.controllers.deprovisioning import (
    Action,
    MultiNodeConsolidation,
    candidate_nodes,
)
from karpenter_core_tpu.models.snapshot import KernelUnsupported
from karpenter_core_tpu.solver.consolidation import TPUConsolidationSearch
from karpenter_core_tpu.testing import make_pod, make_provisioner
from karpenter_core_tpu.testing.harness import expect_provisioned, make_environment

pytestmark = pytest.mark.compile  # every seed compiles device sweeps

CT = labels_api.LABEL_CAPACITY_TYPE

POD_SIZES = ("100m", "250m", "400m", "600m", "900m")


def build_cluster(seed: int):
    """One seeded warm cluster, consolidation-enabled."""
    rng = random.Random(seed * 6271)
    n_types = rng.randrange(3, 9)
    env = make_environment(instance_types=fake_cp.instance_types(n_types))
    requirements = []
    if rng.random() < 0.5:
        # on-demand-only provisioner: exercises the ct-restricted pricing
        requirements.append(
            NodeSelectorRequirement(CT, OP_IN, [labels_api.CAPACITY_TYPE_ON_DEMAND])
        )
    env.kube.create(
        make_provisioner(consolidation_enabled=True, requirements=requirements)
    )
    n_nodes = rng.randrange(2, 7)
    big_pods = []
    for _ in range(n_nodes):
        pods = [
            make_pod(requests={"cpu": rng.choice(POD_SIZES)})
            for _ in range(rng.randrange(1, 4))
        ]
        if rng.random() < 0.6:
            # transient large pod leaves an oversized node behind: the shape
            # where replacement is strictly cheaper
            big = make_pod(requests={"cpu": 4})
            pods.append(big)
            big_pods.append(big)
        if rng.random() < 0.25:
            # do-not-evict blocker: the node must drop out of candidacy
            pods.append(
                make_pod(
                    requests={"cpu": "100m"},
                    annotations={"karpenter.sh/do-not-evict": "true"},
                )
            )
        expect_provisioned(env, *pods)
        env.make_all_nodes_ready()
    for big in big_pods:
        env.kube.delete(env.kube.get_pod(big.namespace, big.name), force=True)
    env.clock.step(21)
    return env


def get_candidates(env):
    dep = env.deprovisioning
    return sorted(
        candidate_nodes(
            env.cluster, env.kube, env.clock, env.provider,
            dep.multi_node_consolidation.should_deprovision,
        ),
        key=lambda c: c.disruption_cost,
    )


def option_names(command) -> set:
    return {
        it.name
        for r in (command.replacement_nodes or [])
        for it in r.instance_type_options
    }


def node_names(command) -> list:
    return [n.name for n in command.nodes_to_remove]


def assert_host_validates(env, candidates, tpu_cmd):
    """Contract #2: the host's own simulation of the sweep-chosen subset must
    agree on action and post-filter options."""
    k = len(tpu_cmd.nodes_to_remove)
    subset = candidates[:k]
    assert node_names(tpu_cmd) == [c.node.name for c in subset], (
        "sweep removed a non-prefix set"
    )
    mnc = env.deprovisioning.multi_node_consolidation
    host_cmd = mnc.compute_consolidation(*subset)
    if host_cmd.action == Action.REPLACE:
        host_cmd.replacement_nodes[0].instance_type_options = (
            MultiNodeConsolidation.filter_out_same_type(
                host_cmd.replacement_nodes[0], subset
            )
        )
        if not host_cmd.replacement_nodes[0].instance_type_options:
            host_cmd = type(host_cmd)(Action.DO_NOTHING)
    assert host_cmd.action == tpu_cmd.action, (
        f"host re-simulation of the sweep's {k}-prefix disagrees: "
        f"host={host_cmd.action} tpu={tpu_cmd.action}"
    )
    if tpu_cmd.action == Action.REPLACE:
        assert option_names(tpu_cmd) == option_names(host_cmd), (
            f"replacement option sets diverge on the same subset: "
            f"tpu={sorted(option_names(tpu_cmd))} host={sorted(option_names(host_cmd))}"
        )
        tpu_pods = {p.uid for r in tpu_cmd.replacement_nodes for p in r.pods}
        host_pods = {p.uid for r in host_cmd.replacement_nodes for p in r.pods}
        assert tpu_pods == host_pods, "replacement pod sets diverge"


@pytest.mark.parametrize("seed", range(50))
def test_fuzzed_consolidation_parity(seed):
    env = build_cluster(seed)
    candidates = get_candidates(env)
    if len(candidates) < 2:
        pytest.skip("seed yields <2 candidates (all blocked or initializing)")

    mnc = env.deprovisioning.multi_node_consolidation
    host_cmd = mnc.first_n_consolidation_option(candidates, len(candidates))

    search = TPUConsolidationSearch(env.provider, env.kube.list_provisioners())
    try:
        tpu_cmd = search.compute_command(
            candidates,
            pending_pods=[],
            state_nodes=env.cluster.snapshot_nodes(),
            bound_pods=env.kube.list_pods(),
        )
    except KernelUnsupported:
        pytest.skip("cluster shape routes to the host path by design")

    # contract #1: the exhaustive sweep never removes fewer
    assert len(tpu_cmd.nodes_to_remove) >= len(host_cmd.nodes_to_remove), (
        f"seed {seed}: sweep removed {node_names(tpu_cmd)} "
        f"< host {node_names(host_cmd)}"
    )
    # contract #3: equal prefixes agree exactly
    if len(tpu_cmd.nodes_to_remove) == len(host_cmd.nodes_to_remove):
        assert tpu_cmd.action == host_cmd.action, (
            f"seed {seed}: same prefix size, different action "
            f"(tpu={tpu_cmd.action} host={host_cmd.action})"
        )
        assert node_names(tpu_cmd) == node_names(host_cmd)
    # contract #2: host validates the sweep's subset
    if tpu_cmd.action in (Action.DELETE, Action.REPLACE):
        assert_host_validates(env, candidates, tpu_cmd)


@pytest.mark.parametrize("seed", [1, 7, 19, 33])
def test_fuzzed_remote_sweep_matches_in_process(seed):
    """Contract #4 on a sample of seeds: the /Consolidate wire path returns
    the same command as the in-process sweep (the wire ships candidates by
    name and replacements as launchable entries; any lossy field shows up as
    a divergence here)."""
    from karpenter_core_tpu.service.snapshot_channel import serve

    env = build_cluster(seed)
    candidates = get_candidates(env)
    if len(candidates) < 2:
        pytest.skip("seed yields <2 candidates")

    search = TPUConsolidationSearch(env.provider, env.kube.list_provisioners())
    try:
        local_cmd = search.compute_command(
            candidates,
            pending_pods=[],
            state_nodes=env.cluster.snapshot_nodes(),
            bound_pods=env.kube.list_pods(),
        )
    except KernelUnsupported:
        pytest.skip("cluster shape routes to the host path by design")

    server, port = serve(env.provider)
    try:
        mnc = env.deprovisioning.multi_node_consolidation
        mnc.use_tpu_kernel = True
        mnc.solver_endpoint = f"127.0.0.1:{port}"
        mnc._solver_client = None
        remote_cmd = mnc._tpu_search(candidates)
    finally:
        server.stop(0)
    assert remote_cmd is not None, "remote sweep fell back unexpectedly"
    assert remote_cmd.action == local_cmd.action
    assert node_names(remote_cmd) == node_names(local_cmd)
    if local_cmd.action == Action.REPLACE:
        assert option_names(remote_cmd) == option_names(local_cmd)
