"""Performance gate (parity with the reference's build-tag-gated benchmark,
scheduling_benchmark_test.go:48,178-182: ≥100 pods/sec for batches >100).

Excluded from the default run like the reference's `//go:build test_performance`
gate; enable with KC_TPU_PERF=1.  Thresholds here are for the *virtual CPU*
platform the suite runs on — the real-chip numbers live in bench.py.
"""

import os
import time

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("KC_TPU_PERF") != "1",
    reason="performance gate; enable with KC_TPU_PERF=1",
)

MIN_PODS_PER_SEC = 100.0  # the reference's CI floor


def test_kernel_throughput_floor():
    from karpenter_core_tpu.cloudprovider import fake as fake_cp
    from karpenter_core_tpu.ops import solve as solve_ops
    from karpenter_core_tpu.solver.tpu import TPUSolver
    from karpenter_core_tpu.testing import make_pods, make_provisioner

    provider = fake_cp.FakeCloudProvider(fake_cp.instance_types(400))
    solver = TPUSolver(provider, [make_provisioner()])
    pods = make_pods(2000, requests={"cpu": "500m", "memory": "512Mi"})

    # warm-up (compile)
    snapshot = solver.encode(pods)
    out = solve_ops.solve(snapshot)
    out.assign.block_until_ready()

    start = time.perf_counter()
    snapshot = solver.encode(pods)
    out = solve_ops.solve(snapshot)
    out.assign.block_until_ready()
    results = solver.decode(snapshot, out)
    elapsed = time.perf_counter() - start

    scheduled = sum(len(n.pods) for n in results.new_nodes)
    assert scheduled == len(pods)
    pods_per_sec = scheduled / elapsed
    assert pods_per_sec >= MIN_PODS_PER_SEC, (
        f"{pods_per_sec:.0f} pods/sec below the {MIN_PODS_PER_SEC} floor"
    )


def test_host_scheduler_throughput():
    """The exact host oracle must also beat the reference floor on the
    homogeneous shape (it is the fallback path)."""
    from karpenter_core_tpu.cloudprovider import fake as fake_cp
    from karpenter_core_tpu.operator.kubeclient import KubeClient
    from karpenter_core_tpu.solver.builder import build_scheduler
    from karpenter_core_tpu.testing import make_pods, make_provisioner

    kube = KubeClient()
    kube.create(make_provisioner())
    provider = fake_cp.FakeCloudProvider(fake_cp.instance_types(100))
    pods = make_pods(500, requests={"cpu": "500m"})
    start = time.perf_counter()
    scheduler = build_scheduler(kube, provider, None, pods, [], daemonset_pods=[])
    results = scheduler.solve(pods)
    elapsed = time.perf_counter() - start
    scheduled = sum(len(n.pods) for n in results.new_nodes)
    assert scheduled == len(pods)
    assert scheduled / elapsed >= MIN_PODS_PER_SEC


def test_mixed_batch_split_throughput():
    """A sprinkle of kernel-unsupported pods must not drag the batch onto the
    O(pods x nodes) host path: the split routes only the exotic pods there.
    2000 kernel pods + 1% exotic (specific-IP host ports) must still beat the
    reference floor end-to-end through the controller's split."""
    from karpenter_core_tpu.apis.objects import ContainerPort
    from karpenter_core_tpu.cloudprovider import fake as fake_cp
    from karpenter_core_tpu.controllers.provisioning import ProvisioningController
    from karpenter_core_tpu.operator.kubeclient import KubeClient
    from karpenter_core_tpu.operator.settings import Settings
    from karpenter_core_tpu.state.cluster import Cluster
    from karpenter_core_tpu.state.informer import start_informers
    from karpenter_core_tpu.testing import make_pod, make_pods, make_provisioner
    from karpenter_core_tpu.utils.clock import FakeClock

    clock = FakeClock()
    kube = KubeClient(clock)
    provider = fake_cp.FakeCloudProvider(fake_cp.instance_types(100))
    settings = Settings()
    cluster = Cluster(clock, kube, provider, settings)
    start_informers(cluster, kube)
    controller = ProvisioningController(
        kube, provider, cluster, settings=settings, clock=clock,
        use_tpu_kernel=True, tpu_kernel_min_pods=1,
    )
    kube.create(make_provisioner())

    n_pods, n_exotic = 2000, 20
    pods = make_pods(n_pods - n_exotic, requests={"cpu": "500m", "memory": "512Mi"})
    for i in range(n_exotic):
        pod = make_pod(labels={"app": "edge"}, requests={"cpu": "100m"})
        pod.spec.containers[0].ports.append(
            ContainerPort(host_port=9000 + i, host_ip="10.0.0.1")
        )
        pods.append(pod)

    split = controller._split_batch(pods)
    assert split is not None, "isolated exotic pods must split, not fall back"
    assert len(split[2]) == n_exotic

    # warm-up (compile)
    results, err = controller.schedule(pods, [])
    assert err is None

    start = time.perf_counter()
    results, err = controller.schedule(pods, [])
    elapsed = time.perf_counter() - start
    assert err is None
    scheduled = sum(len(n.pods) for n in results.new_nodes)
    assert scheduled == n_pods
    assert not results.failed_pods
    pods_per_sec = scheduled / elapsed
    assert pods_per_sec >= MIN_PODS_PER_SEC, (
        f"{pods_per_sec:.0f} pods/sec below the {MIN_PODS_PER_SEC} floor"
    )


def test_large_split_throughput_50k():
    """The headline scale with exotic contamination: 50k pods, 1% of which are
    kernel-unsupported (specific-IP host ports), must stay near kernel speed —
    the split must hand the host oracle only the 500 exotic pods, never the
    O(pods x nodes) whole batch (scheduler.go:96-133 is per-pod, so the
    reference degrades gracefully; our split is the tensor path's equivalent)."""
    from karpenter_core_tpu.apis.objects import ContainerPort
    from karpenter_core_tpu.cloudprovider import fake as fake_cp
    from karpenter_core_tpu.controllers.provisioning import ProvisioningController
    from karpenter_core_tpu.operator.kubeclient import KubeClient
    from karpenter_core_tpu.operator.settings import Settings
    from karpenter_core_tpu.state.cluster import Cluster
    from karpenter_core_tpu.state.informer import start_informers
    from karpenter_core_tpu.testing import make_pod, make_pods, make_provisioner
    from karpenter_core_tpu.utils.clock import FakeClock

    clock = FakeClock()
    kube = KubeClient(clock)
    provider = fake_cp.FakeCloudProvider(fake_cp.instance_types(100))
    settings = Settings()
    cluster = Cluster(clock, kube, provider, settings)
    start_informers(cluster, kube)
    controller = ProvisioningController(
        kube, provider, cluster, settings=settings, clock=clock,
        use_tpu_kernel=True, tpu_kernel_min_pods=1,
    )
    kube.create(make_provisioner())

    n_pods, n_exotic = 50_000, 500
    pods = make_pods(n_pods - n_exotic, requests={"cpu": "500m", "memory": "512Mi"})
    for i in range(n_exotic):
        pod = make_pod(labels={"app": "edge"}, requests={"cpu": "100m"})
        pod.spec.containers[0].ports.append(
            ContainerPort(host_port=2000 + i, host_ip="10.0.0.1")
        )
        pods.append(pod)

    split = controller._split_batch(pods)
    assert split is not None, "isolated exotic pods must split, not fall back"
    assert len(split[2]) == n_exotic

    # warm-up (compile)
    results, err = controller.schedule(pods, [])
    assert err is None

    start = time.perf_counter()
    results, err = controller.schedule(pods, [])
    elapsed = time.perf_counter() - start
    assert err is None
    scheduled = sum(len(n.pods) for n in results.new_nodes)
    assert scheduled == n_pods
    assert not results.failed_pods
    pods_per_sec = scheduled / elapsed
    assert pods_per_sec >= MIN_PODS_PER_SEC, (
        f"{pods_per_sec:.0f} pods/sec below the {MIN_PODS_PER_SEC} floor"
    )


def _custom_spread_pods(n):
    """The dominant host-routed combo family: spread over an ad-hoc label key
    (models/snapshot.py routes custom-key topologies to the host oracle)."""
    from karpenter_core_tpu.apis.objects import LabelSelector, TopologySpreadConstraint
    from karpenter_core_tpu.testing import make_pod

    return [
        make_pod(
            labels={"app": "combo-spread"},
            requests={"cpu": "250m"},
            topology_spread=[
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key="capacity.spread.4-1",
                    label_selector=LabelSelector(match_labels={"app": "combo-spread"}),
                )
            ],
        )
        for _ in range(n)
    ]


def _combo_env():
    from karpenter_core_tpu.apis.objects import NodeSelectorRequirement, OP_IN
    from karpenter_core_tpu.cloudprovider import fake as fake_cp
    from karpenter_core_tpu.controllers.provisioning import ProvisioningController
    from karpenter_core_tpu.operator.kubeclient import KubeClient
    from karpenter_core_tpu.operator.settings import Settings
    from karpenter_core_tpu.state.cluster import Cluster
    from karpenter_core_tpu.state.informer import start_informers
    from karpenter_core_tpu.testing import make_provisioner
    from karpenter_core_tpu.utils.clock import FakeClock

    clock = FakeClock()
    kube = KubeClient(clock)
    provider = fake_cp.FakeCloudProvider(fake_cp.instance_types(100))
    settings = Settings()
    cluster = Cluster(clock, kube, provider, settings)
    start_informers(cluster, kube)
    controller = ProvisioningController(
        kube, provider, cluster, settings=settings, clock=clock,
        use_tpu_kernel=True, tpu_kernel_min_pods=1, solver_endpoint="",
    )
    kube.create(
        make_provisioner(requirements=[
            NodeSelectorRequirement(key="capacity.spread.4-1", operator=OP_IN,
                                    values=["1", "2", "3", "4", "5"]),
        ])
    )
    return controller, kube


def test_combo_family_share_25_percent():
    """VERDICT r2 #6: a quarter of the batch host-routes (custom-key spread);
    the controller split must keep the whole 50k batch above the floor."""
    from karpenter_core_tpu.testing import make_pods

    controller, _ = _combo_env()
    n = 50_000

    # warm-up: compile the kernel buckets on a small same-shape batch so the
    # timed solve measures steady state, like the sibling gates do
    warm = make_pods(96, requests={"cpu": "500m"}) + _custom_spread_pods(32)
    results, err = controller.schedule(warm, [])
    assert err is None and sum(len(x.pods) for x in results.new_nodes) == len(warm)

    pods = make_pods(3 * n // 4, requests={"cpu": "500m"}) + _custom_spread_pods(n // 4)
    start = time.perf_counter()
    results, err = controller.schedule(pods, [])
    elapsed = time.perf_counter() - start
    assert err is None
    scheduled = sum(len(x.pods) for x in results.new_nodes)
    assert scheduled == n, f"only {scheduled}/{n} scheduled ({len(results.failed_pods)} failed)"
    assert scheduled / elapsed >= MIN_PODS_PER_SEC, (
        f"25% combo share: {scheduled / elapsed:.0f} pods/sec below the floor"
    )


def test_combo_family_share_100_percent():
    """VERDICT r2 #6: the whole batch is the host-routed combo family — the
    documented worst case must still hold the reference floor at 50k pods."""
    controller, _ = _combo_env()
    n = 50_000
    pods = _custom_spread_pods(n)
    start = time.perf_counter()
    results, err = controller.schedule(pods, [])
    elapsed = time.perf_counter() - start
    assert err is None
    scheduled = sum(len(x.pods) for x in results.new_nodes)
    assert scheduled == n, f"only {scheduled}/{n} scheduled ({len(results.failed_pods)} failed)"
    assert scheduled / elapsed >= MIN_PODS_PER_SEC, (
        f"100% combo share: {scheduled / elapsed:.0f} pods/sec below the floor"
    )
