"""Performance gate (parity with the reference's build-tag-gated benchmark,
scheduling_benchmark_test.go:48,178-182: ≥100 pods/sec for batches >100).

Excluded from the default run like the reference's `//go:build test_performance`
gate; enable with KC_TPU_PERF=1.  Thresholds here are for the *virtual CPU*
platform the suite runs on — the real-chip numbers live in bench.py.
"""

import os
import time

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("KC_TPU_PERF") != "1",
    reason="performance gate; enable with KC_TPU_PERF=1",
)

MIN_PODS_PER_SEC = 100.0  # the reference's CI floor


def test_kernel_throughput_floor():
    from karpenter_core_tpu.cloudprovider import fake as fake_cp
    from karpenter_core_tpu.ops import solve as solve_ops
    from karpenter_core_tpu.solver.tpu import TPUSolver
    from karpenter_core_tpu.testing import make_pods, make_provisioner

    provider = fake_cp.FakeCloudProvider(fake_cp.instance_types(400))
    solver = TPUSolver(provider, [make_provisioner()])
    pods = make_pods(2000, requests={"cpu": "500m", "memory": "512Mi"})

    # warm-up (compile)
    snapshot = solver.encode(pods)
    out = solve_ops.solve(snapshot)
    out.assign.block_until_ready()

    start = time.perf_counter()
    snapshot = solver.encode(pods)
    out = solve_ops.solve(snapshot)
    out.assign.block_until_ready()
    results = solver.decode(snapshot, out)
    elapsed = time.perf_counter() - start

    scheduled = sum(len(n.pods) for n in results.new_nodes)
    assert scheduled == len(pods)
    pods_per_sec = scheduled / elapsed
    assert pods_per_sec >= MIN_PODS_PER_SEC, (
        f"{pods_per_sec:.0f} pods/sec below the {MIN_PODS_PER_SEC} floor"
    )


def test_host_scheduler_throughput():
    """The exact host oracle must also beat the reference floor on the
    homogeneous shape (it is the fallback path)."""
    from karpenter_core_tpu.cloudprovider import fake as fake_cp
    from karpenter_core_tpu.operator.kubeclient import KubeClient
    from karpenter_core_tpu.solver.builder import build_scheduler
    from karpenter_core_tpu.testing import make_pods, make_provisioner

    kube = KubeClient()
    kube.create(make_provisioner())
    provider = fake_cp.FakeCloudProvider(fake_cp.instance_types(100))
    pods = make_pods(500, requests={"cpu": "500m"})
    start = time.perf_counter()
    scheduler = build_scheduler(kube, provider, None, pods, [], daemonset_pods=[])
    results = scheduler.solve(pods)
    elapsed = time.perf_counter() - start
    scheduled = sum(len(n.pods) for n in results.new_nodes)
    assert scheduled == len(pods)
    assert scheduled / elapsed >= MIN_PODS_PER_SEC
