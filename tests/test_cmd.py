"""Deploy entrypoints (karpenter_core_tpu/cmd) and the local bring-up."""

import subprocess


class TestEntrypoints:
    def test_load_cloud_provider(self):
        from karpenter_core_tpu.cloudprovider.fake import FakeCloudProvider
        from karpenter_core_tpu.cmd.operator import load_cloud_provider

        provider = load_cloud_provider(
            "karpenter_core_tpu.cloudprovider.fake:FakeCloudProvider"
        )
        assert isinstance(provider, FakeCloudProvider)

    def test_run_local_check(self):
        """deploy/run_local.sh --check brings up the deployed topology (one
        shared solver + leader-elected operator replicas) from scratch and
        probes everything — the deploy artifact's contract."""
        import os

        env = dict(os.environ)
        env.update(
            BASE_METRICS_PORT="18280",
            KC_SOLVER_LISTEN="127.0.0.1:18980", JAX_PLATFORMS="cpu",
            KC_TPU_KERNEL="0", KC_TPU_WARMUP="0",
        )
        # the topology runs on CPU here; drop the accelerator-tunnel trigger
        # so child interpreters don't block in the tunnel's sitecustomize
        # registration when the device link is down
        env.pop("PALLAS_AXON_POOL_IPS", None)
        proc = subprocess.run(
            ["deploy/run_local.sh", "--check"],
            capture_output=True, text=True, timeout=180, env=env,
            cwd=subprocess.os.path.dirname(subprocess.os.path.dirname(__file__)) or ".",
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "topology is up" in proc.stdout
        assert "one leader elected" in proc.stdout
