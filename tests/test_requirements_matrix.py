"""Requirement algebra operator matrix, ported from the reference's
requirement_test.go / requirements_test.go (885 LoC of pairwise operator
semantics).  The complement-set representation must behave as exact set
algebra under every operator pairing: In, NotIn, Exists, DoesNotExist, Gt,
Lt — intersection, membership, compatibility, and label normalization.
"""

import itertools

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_GT,
    OP_IN,
    OP_LT,
    OP_NOT_IN,
)
from karpenter_core_tpu.scheduling import Requirement, Requirements

KEY = "test.io/key"


def req(operator, *values):
    return Requirement(KEY, operator, list(values))


# the reference's canonical instances (requirement_test.go:30-46)
EXISTS = req(OP_EXISTS)
DOES_NOT_EXIST = req(OP_DOES_NOT_EXIST)
IN_A = req(OP_IN, "A")
IN_B = req(OP_IN, "B")
IN_AB = req(OP_IN, "A", "B")
NOT_IN_A = req(OP_NOT_IN, "A")
NOT_IN_B = req(OP_NOT_IN, "B")
NOT_IN_AB = req(OP_NOT_IN, "A", "B")
IN_1 = req(OP_IN, "1")
IN_9 = req(OP_IN, "9")
GT_1 = req(OP_GT, "1")
LT_1 = req(OP_LT, "1")
GT_9 = req(OP_GT, "9")
LT_9 = req(OP_LT, "9")


def members(requirement, universe=("A", "B", "C", "0", "1", "5", "9", "10")):
    """The requirement's member set over a finite probe universe — ground
    truth for checking the algebra as set operations."""
    return {v for v in universe if requirement.has(v)}


ALL = [
    EXISTS, DOES_NOT_EXIST, IN_A, IN_B, IN_AB, NOT_IN_A, NOT_IN_B, NOT_IN_AB,
    IN_1, IN_9, GT_1, LT_1, GT_9, LT_9,
]


class TestIntersectionAlgebra:
    """requirement_test.go Intersection matrix — checked as set algebra over
    a probe universe instead of 196 hand-written expectations."""

    def test_pairwise_intersection_is_set_intersection(self):
        for a, b in itertools.product(ALL, repeat=2):
            got = members(a.intersection(b))
            want = members(a) & members(b)
            assert got == want, f"{a!r} ∩ {b!r}: got {got}, want {want}"

    def test_intersection_commutes(self):
        for a, b in itertools.product(ALL, repeat=2):
            assert members(a.intersection(b)) == members(b.intersection(a))

    def test_intersection_associates(self):
        for a, b, c in itertools.product(
            [EXISTS, IN_AB, NOT_IN_A, GT_1, LT_9], repeat=3
        ):
            left = a.intersection(b).intersection(c)
            right = a.intersection(b.intersection(c))
            assert members(left) == members(right)

    def test_exists_is_identity(self):
        for a in ALL:
            assert members(EXISTS.intersection(a)) == members(a)

    def test_does_not_exist_is_annihilator(self):
        for a in ALL:
            assert members(DOES_NOT_EXIST.intersection(a)) == set()

    def test_gt_lt_band(self):
        band = GT_1.intersection(LT_9)
        assert members(band) == {"5"}
        empty = GT_9.intersection(LT_1)
        assert members(empty) == set()

    def test_in_preserved_through_bounds(self):
        assert members(IN_9.intersection(GT_1)) == {"9"}
        assert members(IN_1.intersection(GT_1)) == set()
        assert members(IN_1.intersection(LT_9)) == {"1"}


class TestMembership:
    """requirement_test.go Has()."""

    def test_in(self):
        assert IN_A.has("A") and not IN_A.has("B")

    def test_not_in(self):
        assert NOT_IN_A.has("B") and not NOT_IN_A.has("A")

    def test_exists_has_everything(self):
        assert EXISTS.has("A") and EXISTS.has("anything")

    def test_does_not_exist_has_nothing(self):
        assert not DOES_NOT_EXIST.has("A")

    def test_bounds_numeric_membership(self):
        assert GT_1.has("2") and not GT_1.has("1") and not GT_1.has("0")
        assert LT_9.has("8") and not LT_9.has("9") and not LT_9.has("10")
        # non-numeric values never satisfy a bound
        assert not GT_1.has("A") and not LT_9.has("A")

    def test_operator_roundtrip(self):
        assert IN_A.operator() == OP_IN
        assert NOT_IN_A.operator() == OP_NOT_IN
        assert EXISTS.operator() == OP_EXISTS
        assert DOES_NOT_EXIST.operator() == OP_DOES_NOT_EXIST


class TestRequirementsCompatibility:
    """requirements_test.go:50-290 compatibility matrix via the probe sets."""

    def _compatible(self, a, b):
        return Requirements(a).compatible(Requirements(b)) is None

    def test_pairwise_compatibility_matches_set_overlap(self):
        # ground truth mirrors requirements.go:189-206: overlap of member
        # sets, with one carve-out — an empty intersection is tolerated when
        # BOTH operators are negative (NotIn/DoesNotExist), the reference's
        # "unconstrained can still avoid" rule
        negative = (OP_NOT_IN, OP_DOES_NOT_EXIST)
        for a, b in itertools.product(ALL, repeat=2):
            want = bool(members(a) & members(b))
            if not want and a.operator() in negative and b.operator() in negative:
                want = True
            got = self._compatible(a, b)
            assert got == want, f"compatible({a!r}, {b!r}) = {got}, want {want}"

    def test_custom_key_undefined_on_receiver_errors(self):
        # requirements.go:123-133 — a custom label the receiver doesn't
        # define cannot be required (In), but CAN be avoided (NotIn)
        a = Requirements(Requirement("key-a", OP_IN, ["x"]))
        require_b = Requirements(Requirement("key-b", OP_IN, ["y"]))
        avoid_b = Requirements(Requirement("key-b", OP_NOT_IN, ["y"]))
        assert a.compatible(require_b) is not None
        assert a.compatible(avoid_b) is None

    def test_well_known_key_undefined_is_allowed(self):
        a = Requirements(Requirement("key-a", OP_IN, ["x"]))
        zone = Requirements(
            Requirement(labels_api.LABEL_TOPOLOGY_ZONE, OP_IN, ["test-zone-1"])
        )
        assert a.compatible(zone) is None

    def test_incremental_add_tightens(self):
        reqs = Requirements(req(OP_IN, "A", "B", "C"))
        reqs.add(req(OP_NOT_IN, "B"))
        assert sorted(reqs.get(KEY).values_list()) == ["A", "C"]
        reqs.add(req(OP_IN, "C"))
        assert reqs.get(KEY).values_list() == ["C"]


class TestLabelNormalization:
    """requirements_test.go:27-49 — deprecated label aliases normalize."""

    def test_beta_arch_normalizes(self):
        r = Requirement("beta.kubernetes.io/arch", OP_IN, ["amd64"])
        assert r.key == labels_api.LABEL_ARCH_STABLE

    def test_beta_os_normalizes(self):
        r = Requirement("beta.kubernetes.io/os", OP_IN, ["linux"])
        assert r.key == labels_api.LABEL_OS_STABLE

    def test_from_labels_normalizes(self):
        reqs = Requirements.from_labels({"beta.kubernetes.io/arch": "arm64"})
        assert reqs.has(labels_api.LABEL_ARCH_STABLE)
        assert reqs.get(labels_api.LABEL_ARCH_STABLE).has("arm64")

    def test_labels_roundtrip(self):
        reqs = Requirements(
            Requirement("a", OP_IN, ["1"]), Requirement("b", OP_IN, ["2"])
        )
        assert reqs.labels() == {"a": "1", "b": "2"}


class TestRequirementEquality:
    def test_equal_same_content(self):
        assert req(OP_IN, "A", "B") == req(OP_IN, "B", "A")
        assert hash(req(OP_IN, "A", "B")) == hash(req(OP_IN, "B", "A"))

    def test_unequal_different_operator(self):
        assert req(OP_IN, "A") != req(OP_NOT_IN, "A")

    def test_intersection_idempotent(self):
        for a in ALL:
            assert members(a.intersection(a)) == members(a)
