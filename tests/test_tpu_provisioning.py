"""End-to-end provisioning through the TPU kernel path (use_tpu_kernel=True)."""

import pytest

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.cloudprovider.fake import FakeCloudProvider
from karpenter_core_tpu.controllers.provisioning import ProvisioningController
from karpenter_core_tpu.events import Recorder
from karpenter_core_tpu.operator.kubeclient import KubeClient
from karpenter_core_tpu.operator.settings import Settings
from karpenter_core_tpu.state.cluster import Cluster
from karpenter_core_tpu.state.informer import start_informers
from karpenter_core_tpu.testing import make_pod, make_pods, make_provisioner
from karpenter_core_tpu.utils.clock import FakeClock

# end-to-end kernel provisioning compiles the solve -- the slow tier (`make test-all`)
pytestmark = pytest.mark.compile

def tpu_env(min_pods=1):
    clock = FakeClock()
    kube = KubeClient(clock)
    provider = FakeCloudProvider()
    settings = Settings()
    recorder = Recorder(clock=clock.now)
    cluster = Cluster(clock, kube, provider, settings)
    start_informers(cluster, kube)
    controller = ProvisioningController(
        kube, provider, cluster, recorder=recorder, settings=settings, clock=clock,
        use_tpu_kernel=True, tpu_kernel_min_pods=min_pods,
    )
    return kube, provider, cluster, recorder, controller

class TestTPUProvisioningPath:
    def test_kernel_path_launches_nodes(self):
        kube, provider, cluster, recorder, controller = tpu_env()
        kube.create(make_provisioner())
        for pod in make_pods(6, requests={"cpu": "900m"}):
            kube.create(pod)
        err = controller.reconcile(wait_for_batch=False)
        assert err is None
        nodes = kube.list_nodes()
        assert nodes, "kernel path should launch nodes"
        assert provider.create_calls
        assert all(
            labels_api.PROVISIONER_NAME_LABEL_KEY in n.metadata.labels for n in nodes
        )
        nominated = [e for e in recorder.events if e.reason == "Nominated"]
        assert len(nominated) == 6

    def test_unsupported_batch_falls_back_to_host(self):
        from karpenter_core_tpu.apis.objects import LabelSelector, PodAffinityTerm

        kube, provider, cluster, recorder, controller = tpu_env()
        kube.create(make_provisioner())
        # required pod affinity is kernel-unsupported: host path must handle it
        target = make_pod(labels={"app": "a"}, requests={"cpu": "100m"},
                          node_selector={labels_api.LABEL_TOPOLOGY_ZONE: "test-zone-1"})
        follower = make_pod(
            requests={"cpu": "100m"},
            pod_affinity=[
                PodAffinityTerm(
                    topology_key=labels_api.LABEL_TOPOLOGY_ZONE,
                    label_selector=LabelSelector(match_labels={"app": "a"}),
                )
            ],
        )
        kube.create(target)
        kube.create(follower)
        err = controller.reconcile(wait_for_batch=False)
        assert err is None
        assert kube.list_nodes(), "host fallback should still provision"
        nominated = [e for e in recorder.events if e.reason == "Nominated"]
        assert len(nominated) == 2

    def test_kernel_reuses_inflight_capacity(self):
        kube, provider, cluster, recorder, controller = tpu_env()
        kube.create(make_provisioner())
        kube.create(make_pod(requests={"cpu": "500m"}))
        controller.reconcile(wait_for_batch=False)
        assert len(provider.create_calls) == 1
        # second round: pod fits the in-flight node -> no new machine
        kube.create(make_pod(requests={"cpu": "500m"}))
        controller.reconcile(wait_for_batch=False)
        assert len(provider.create_calls) == 1
        assert len(kube.list_nodes()) == 1

class TestMixedBatchSplit:
    """Kernel-unsupported pods no longer drag the whole batch to the host
    path: isolated exotic shapes solve on the host AFTER the kernel pass."""

    def _exotic_pod(self, **kwargs):
        # specific-IP host port: a shape the kernel never models
        from karpenter_core_tpu.apis.objects import ContainerPort

        pod = make_pod(**kwargs)
        pod.spec.containers[0].ports.append(
            ContainerPort(host_port=8080, host_ip="10.0.0.1")
        )
        return pod

    def test_isolated_exotic_pod_splits(self):
        kube, provider, cluster, recorder, controller = tpu_env()
        kube.create(make_provisioner())
        plain = make_pods(8, requests={"cpu": "900m"})
        exotic = self._exotic_pod(labels={"app": "edge"}, requests={"cpu": "100m"})
        for pod in plain + [exotic]:
            kube.create(pod)
        pods = controller.get_pending_pods()
        split = controller._split_batch(pods)
        assert split is not None
        tpu_classes, tpu_pods, host_pods = split
        assert len(tpu_pods) == 8
        assert len(host_pods) == 1
        assert sum(len(c.pods) for c in tpu_classes) == 8
        err = controller.reconcile(wait_for_batch=False)
        assert err is None
        nominated = [e for e in recorder.events if e.reason == "Nominated"]
        assert len(nominated) == 9  # every pod found a home

    def test_entangled_selector_stays_whole_batch_host(self):
        from karpenter_core_tpu.apis.objects import LabelSelector, TopologySpreadConstraint

        kube, provider, cluster, recorder, controller = tpu_env()
        kube.create(make_provisioner())
        plain = make_pods(4, labels={"app": "web"}, requests={"cpu": "500m"})
        # exotic pod spreads over the SUPPORTED pods' labels: counts would
        # desynchronize across a split, so no split happens
        entangled = self._exotic_pod(
            labels={"app": "edge"},
            requests={"cpu": "100m"},
            topology_spread=[
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=labels_api.LABEL_TOPOLOGY_ZONE,
                    label_selector=LabelSelector(match_labels={"app": "web"}),
                )
            ],
        )
        for pod in plain + [entangled]:
            kube.create(pod)
        pods = controller.get_pending_pods()
        assert controller._split_batch(pods) is None
        err = controller.reconcile(wait_for_batch=False)  # host path still works
        assert err is None
        nominated = [e for e in recorder.events if e.reason == "Nominated"]
        assert len(nominated) == 5

    def test_shared_claim_stays_whole_batch_host(self):
        from karpenter_core_tpu.apis.objects import (
            ObjectMeta,
            PersistentVolumeClaim,
        )

        kube, provider, cluster, recorder, controller = tpu_env()
        kube.create(make_provisioner())
        kube.create(
            PersistentVolumeClaim(
                metadata=ObjectMeta(name="shared", namespace="default")
            )
        )
        plain = make_pod(requests={"cpu": "500m"}, pvcs=["shared"])
        exotic = self._exotic_pod(requests={"cpu": "100m"}, pvcs=["shared"])
        kube.create(plain)
        kube.create(exotic)
        pods = controller.get_pending_pods()
        assert controller._split_batch(pods) is None

    def test_statefulset_claim_overlap_checked_per_pod(self):
        """Claim identity is not class-invariant (StatefulSet classes hold
        pods with different claims), so the isolation check must inspect every
        pod — a shared claim hiding behind a non-representative pod blocks
        the split."""
        kube, provider, cluster, recorder, controller = tpu_env()
        kube.create(make_provisioner())
        sts = [
            make_pod(labels={"app": "db"}, requests={"cpu": "500m"}, pvcs=[claim])
            for claim in ("data-0", "data-1")
        ]
        exotic = self._exotic_pod(requests={"cpu": "100m"}, pvcs=["data-1"])
        assert controller._split_batch(sts + [exotic]) is None

    def test_split_respects_existing_capacity(self):
        """The host remainder must see the kernel's existing-node placements
        (no double-booking)."""
        kube, provider, cluster, recorder, controller = tpu_env()
        kube.create(make_provisioner())
        from karpenter_core_tpu.testing import make_node

        state_node = make_node(
            labels={
                labels_api.PROVISIONER_NAME_LABEL_KEY: "default",
                labels_api.LABEL_INSTANCE_TYPE_STABLE: "default-instance-type",
                labels_api.LABEL_CAPACITY_TYPE: "spot",
                labels_api.LABEL_NODE_INITIALIZED: "true",
                labels_api.LABEL_TOPOLOGY_ZONE: "test-zone-1",
            },
            allocatable={"cpu": 2, "memory": "4Gi", "pods": 10},
        )
        kube.create(state_node)
        # kernel pods fill the node exactly; the exotic pod must NOT also be
        # nominated onto it
        for pod in make_pods(2, requests={"cpu": 1}):
            kube.create(pod)
        exotic = self._exotic_pod(labels={"app": "edge"}, requests={"cpu": 1})
        kube.create(exotic)
        err = controller.reconcile(wait_for_batch=False)
        assert err is None
        # 2 kernel pods on the existing node + 1 new node for the exotic pod
        created = [n for n in kube.list_nodes() if n.name != state_node.name]
        assert len(created) == 1

    def test_split_shares_provisioner_limit_budget(self):
        """The host remainder must see the kernel pass's pessimistic node
        budget so the two solves cannot jointly overspend a provisioner limit
        (subtractMax, scheduler.go:273-290)."""
        kube, provider, cluster, recorder, controller = tpu_env()
        # budget for one 16-cpu node: the kernel pods consume it
        # (pessimistic subtractMax charge), leaving no room for the exotic pod
        kube.create(make_provisioner(limits={"cpu": 17.0}))
        for pod in make_pods(4, requests={"cpu": 3}):
            kube.create(pod)
        exotic = self._exotic_pod(labels={"app": "edge"}, requests={"cpu": 3})
        kube.create(exotic)
        pods = controller.get_pending_pods()
        results, err = controller.schedule(pods, [])
        assert err is None
        total_cpu_capacity = sum(
            max(it.capacity.get("cpu", 0) for it in n.instance_type_options)
            for n in results.new_nodes
        )
        assert total_cpu_capacity <= 17.0, (
            f"split solves jointly overspent the 17-cpu limit: "
            f"{total_cpu_capacity} across {len(results.new_nodes)} nodes"
        )
        assert results.failed_pods  # the exotic pod had no budget left

class TestCustomTopologyKeySplit:
    """Topologies on keys the kernel doesn't model (region-class / custom
    labels, models.snapshot._group_spec) route through the mixed-batch split:
    the bulk stays on the kernel, the custom-key pods solve on the host with
    shared capacity accounting — capability parity without a kernel plane per
    ad-hoc key (topology_test.go:492-783 exercises these keys on the host)."""

    def test_capacity_type_spread_pod_splits_to_host(self):
        from karpenter_core_tpu.apis.objects import LabelSelector, TopologySpreadConstraint

        kube, provider, cluster, recorder, controller = tpu_env()
        kube.create(make_provisioner())
        plain = make_pods(8, requests={"cpu": "900m"})
        ct_spread = [
            make_pod(
                name=f"ct-{i}", labels={"app": "edge"}, requests={"cpu": "100m"},
                topology_spread=[
                    TopologySpreadConstraint(
                        max_skew=1,
                        topology_key=labels_api.LABEL_CAPACITY_TYPE,
                        label_selector=LabelSelector(match_labels={"app": "edge"}),
                    )
                ],
            )
            for i in range(2)
        ]
        for pod in plain + ct_spread:
            kube.create(pod)
        pods = controller.get_pending_pods()
        split = controller._split_batch(pods)
        assert split is not None
        _, tpu_pods, host_pods = split
        assert len(tpu_pods) == 8
        assert len(host_pods) == 2
        err = controller.reconcile(wait_for_batch=False)
        assert err is None
        nominated = [e for e in recorder.events if e.reason == "Nominated"]
        assert len(nominated) == 10

    def test_cross_group_custom_key_anti_stays_whole_batch_host(self):
        """A custom-key ANTI term selecting the kernel pods' labels would
        desynchronize counts across a split — the whole batch must host-route."""
        from karpenter_core_tpu.apis.objects import LabelSelector, PodAffinityTerm

        kube, provider, cluster, recorder, controller = tpu_env()
        kube.create(make_provisioner())
        plain = make_pods(4, labels={"app": "web"}, requests={"cpu": "500m"})
        guard = make_pod(
            labels={"app": "edge"}, requests={"cpu": "100m"},
            pod_anti_affinity=[
                PodAffinityTerm(
                    topology_key=labels_api.LABEL_ARCH_STABLE,
                    label_selector=LabelSelector(match_labels={"app": "web"}),
                )
            ],
        )
        for pod in plain + [guard]:
            kube.create(pod)
        pods = controller.get_pending_pods()
        assert controller._split_batch(pods) is None
        err = controller.reconcile(wait_for_batch=False)
        assert err is None
        nominated = [e for e in recorder.events if e.reason == "Nominated"]
        # the single-arch catalog leaves no zero-count arch domain once the
        # web pods land, so the guard itself correctly fails to schedule
        assert len(nominated) == 4
