"""End-to-end provisioning through the TPU kernel path (use_tpu_kernel=True)."""

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.cloudprovider.fake import FakeCloudProvider
from karpenter_core_tpu.controllers.provisioning import ProvisioningController
from karpenter_core_tpu.events import Recorder
from karpenter_core_tpu.operator.kubeclient import KubeClient
from karpenter_core_tpu.operator.settings import Settings
from karpenter_core_tpu.state.cluster import Cluster
from karpenter_core_tpu.state.informer import start_informers
from karpenter_core_tpu.testing import make_pod, make_pods, make_provisioner
from karpenter_core_tpu.utils.clock import FakeClock


def tpu_env(min_pods=1):
    clock = FakeClock()
    kube = KubeClient(clock)
    provider = FakeCloudProvider()
    settings = Settings()
    recorder = Recorder(clock=clock.now)
    cluster = Cluster(clock, kube, provider, settings)
    start_informers(cluster, kube)
    controller = ProvisioningController(
        kube, provider, cluster, recorder=recorder, settings=settings, clock=clock,
        use_tpu_kernel=True, tpu_kernel_min_pods=min_pods,
    )
    return kube, provider, cluster, recorder, controller


class TestTPUProvisioningPath:
    def test_kernel_path_launches_nodes(self):
        kube, provider, cluster, recorder, controller = tpu_env()
        kube.create(make_provisioner())
        for pod in make_pods(6, requests={"cpu": "900m"}):
            kube.create(pod)
        err = controller.reconcile(wait_for_batch=False)
        assert err is None
        nodes = kube.list_nodes()
        assert nodes, "kernel path should launch nodes"
        assert provider.create_calls
        assert all(
            labels_api.PROVISIONER_NAME_LABEL_KEY in n.metadata.labels for n in nodes
        )
        nominated = [e for e in recorder.events if e.reason == "Nominated"]
        assert len(nominated) == 6

    def test_unsupported_batch_falls_back_to_host(self):
        from karpenter_core_tpu.apis.objects import LabelSelector, PodAffinityTerm

        kube, provider, cluster, recorder, controller = tpu_env()
        kube.create(make_provisioner())
        # required pod affinity is kernel-unsupported: host path must handle it
        target = make_pod(labels={"app": "a"}, requests={"cpu": "100m"},
                          node_selector={labels_api.LABEL_TOPOLOGY_ZONE: "test-zone-1"})
        follower = make_pod(
            requests={"cpu": "100m"},
            pod_affinity=[
                PodAffinityTerm(
                    topology_key=labels_api.LABEL_TOPOLOGY_ZONE,
                    label_selector=LabelSelector(match_labels={"app": "a"}),
                )
            ],
        )
        kube.create(target)
        kube.create(follower)
        err = controller.reconcile(wait_for_batch=False)
        assert err is None
        assert kube.list_nodes(), "host fallback should still provision"
        nominated = [e for e in recorder.events if e.reason == "Nominated"]
        assert len(nominated) == 2

    def test_kernel_reuses_inflight_capacity(self):
        kube, provider, cluster, recorder, controller = tpu_env()
        kube.create(make_provisioner())
        kube.create(make_pod(requests={"cpu": "500m"}))
        controller.reconcile(wait_for_batch=False)
        assert len(provider.create_calls) == 1
        # second round: pod fits the in-flight node -> no new machine
        kube.create(make_pod(requests={"cpu": "500m"}))
        controller.reconcile(wait_for_batch=False)
        assert len(provider.create_calls) == 1
        assert len(kube.list_nodes()) == 1
