"""The built-in soak scenario catalog.

Each entry is a builder ``(seed) -> SoakScenario`` so every invocation gets a
fresh trace; ``build(name, seed=None)`` is the lookup tools/soak.py and
tests/test_soak.py use.  ``deploy-storm-smoke`` is the deterministic tier-1
gate (``make soak``); the rest form the ``slow``-marked matrix.

SLO limits here are deliberately generous convergence bounds (the gate is
"the system keeps up under churn", not a latency benchmark); a scenario that
needs tighter bounds overrides them via ``SoakScenario.with_slo``.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from karpenter_core_tpu.soak.runner import (
    BACKEND_APISERVER,
    SoakScenario,
)

# the default convergence SLO: everything schedulable schedules promptly, no
# stranded machines, the solver path never degrades, terminal state is clean
_CONVERGENCE_RULES = [
    {"probe": "pending_age_p99_s", "agg": "max", "limit": 120.0},
    {"probe": "machine_leaks", "agg": "max", "limit": 0.0},
    {"probe": "degraded", "agg": "time_above", "above": 0.0, "limit": 0.0},
    {"probe": "pending_pods", "agg": "final", "limit": 0.0},
    {"probe": "solve_latency_s", "agg": "max", "limit": 30.0},  # advisory
]


def deploy_storm_smoke(seed: int = 1729) -> SoakScenario:
    """The tier-1 smoke: a rolling deploy storm against the real watch/list
    apiserver backend while the chaos plane kills the first watch
    establishments (410) — the ISSUE-6 acceptance scenario.  Bounded p99
    pending age, zero machine leaks, bounded degraded time, clean terminal
    state; the verdict must replay byte-identically from (scenario, seed)."""
    return SoakScenario(
        name="deploy-storm-smoke",
        seed=seed,
        generator="deploy-storm",
        params={
            "waves": 2, "replicas": 8, "wave_interval_s": 60.0,
            "start_s": 5.0, "teardown_lag_s": 10.0,
        },
        slo={"rules": _CONVERGENCE_RULES},
        tick_s=5.0,
        settle_ticks=12,
        backend=BACKEND_APISERVER,
        chaos_points={"watch.stream": {"first_n": 2, "code": 410}},
    )


def diurnal_consolidation(seed: int = 11) -> SoakScenario:
    """A compressed diurnal wave with consolidation on: the fleet must grow
    with the peak and shrink after it — consolidation lag is the probe that
    catches a fleet that only ever grows."""
    return SoakScenario(
        name="diurnal-consolidation",
        seed=seed,
        generator="diurnal",
        params={
            "duration_s": 1200.0, "period_s": 600.0,
            "base_rate_per_s": 0.01, "peak_rate_per_s": 0.08,
            "mean_lifetime_s": 300.0,
        },
        slo={"rules": _CONVERGENCE_RULES + [
            {"probe": "consolidation_lag_s", "agg": "max", "limit": 900.0},
        ]},
        tick_s=30.0,
        settle_ticks=40,
        consolidation=True,
        ttl_seconds_after_empty=60,
    )


def batch_flood_flaky_api(seed: int = 23) -> SoakScenario:
    """A batch flood while the kube API flakes (injected 500s): retries must
    absorb the faults without stranding machines or hot-looping."""
    return SoakScenario(
        name="batch-flood-flaky-api",
        seed=seed,
        generator="batch-flood",
        params={"jobs": 4, "pods_per_job": 25, "mean_runtime_s": 300.0},
        slo={"rules": _CONVERGENCE_RULES},
        tick_s=15.0,
        settle_ticks=40,
        chaos_points={
            "kubeapi.put": {"prob": 0.05, "code": 500, "stop_after": 6},
        },
    )


def mass_eviction_capacity(seed: int = 37) -> SoakScenario:
    """Mass eviction plus transient cloud-create failures on the reschedule
    wave — the correlated-failure shape (AZ drain during a capacity crunch)."""
    return SoakScenario(
        name="mass-eviction-capacity",
        seed=seed,
        generator="mass-eviction",
        params={"standing": 40, "evict_fraction": 0.5, "evict_at_s": 300.0},
        slo={"rules": _CONVERGENCE_RULES},
        tick_s=15.0,
        settle_ticks=40,
        chaos_points={
            "cloud.create": {"first_n": 2, "kind": "error"},
        },
    )


def spot_churn(seed: int = 67) -> SoakScenario:
    """Spot-market churn: a standing fleet under mass eviction + reschedule
    while the chaos plane injects insufficient-capacity faults on
    ``cloud.create`` — the spot-interruption shape (capacity vanishes under
    the reschedule wave, launches redraw onto surviving offerings).  The SLO
    adds the policy subsystem's economic probe: MEAN fleet cost per tick
    stays bounded (a churned fleet that lands on expensive offerings, or
    only ever grows, blows the bound while node counts still look healthy)
    on top of the p99 pending-age convergence rule.  Small enough that the
    tier-1 smoke (tests/test_policy.py) runs it directly; the slow matrix
    replays it with the rest of the catalog."""
    return SoakScenario(
        name="spot-churn",
        seed=seed,
        generator="mass-eviction",
        params={"standing": 24, "evict_fraction": 0.5, "evict_at_s": 180.0},
        slo={"rules": _CONVERGENCE_RULES + [
            # generous economic bound: the standing fleet prices well under
            # this; a leak/only-grow regression or a pathologically expensive
            # re-landing blows it (docs/POLICY.md "Soak surface")
            {"probe": "fleet_cost_per_tick", "agg": "mean", "limit": 10.0},
            {"probe": "fleet_cost_per_tick", "agg": "max", "limit": 30.0},
        ]},
        tick_s=15.0,
        settle_ticks=40,
        chaos_points={
            # spot interruptions: capacity errors on the reschedule wave's
            # creates, bounded so convergence is reachable
            "cloud.create": {
                "prob": 0.25, "kind": "error", "stop_after": 4,
                "data": {"mode": "insufficient-capacity"},
            },
        },
    )


def mixed_fleet_steady(seed: int = 41) -> SoakScenario:
    """Three provisioners under three different churn patterns at once —
    the multi-tenant shape where one noisy fleet must not starve another."""
    return SoakScenario(
        name="mixed-fleet-steady",
        seed=seed,
        generator="mixed-fleet",
        params={"provisioners": ("fleet-a", "fleet-b", "fleet-c"),
                "scale": 0.4},
        slo={"rules": _CONVERGENCE_RULES},
        tick_s=15.0,
        settle_ticks=40,
        provisioners=("fleet-a", "fleet-b", "fleet-c"),
    )


def churn_steady(seed: int = 53) -> SoakScenario:
    """Steady-state churn on a 10k-pod fleet through the TPU kernel solve
    path — the measured case the ROADMAP's incremental-solver item asks for:
    under sustained churn the full-re-solve-per-reconcile amortization
    visibly misses a per-reconcile solve-latency SLO (advisory wall-clock
    rule), while the incremental delta path holds it.  Slow matrix only (10k
    pod objects, kernel compiles); the small tier-1 smoke lives in
    tests/test_incremental.py.  KC_SOLVER_INCREMENTAL=0 reproduces the
    full-path miss on demand (docs/INCREMENTAL.md)."""
    return SoakScenario(
        name="churn-steady",
        seed=seed,
        generator="diurnal",
        # base == peak: a flat Poisson arrival stream with exponential
        # lifetimes — standing population ≈ rate × lifetime ≈ 9.6k pods
        params={
            "duration_s": 600.0, "period_s": 600.0,
            "base_rate_per_s": 16.0, "peak_rate_per_s": 16.0,
            "mean_lifetime_s": 600.0,
        },
        slo={"rules": _CONVERGENCE_RULES + [
            {"probe": "solve_latency_s", "agg": "mean", "limit": 1.0},
        ]},
        tick_s=30.0,
        settle_ticks=30,
        use_tpu_kernel=True,
    )


def churn_steady_sharded(seed: int = 59) -> SoakScenario:
    """The churn-steady regime grown to the SHARDED-path scale: a ~100k-pod
    standing fleet over a 2k-type catalog, provisioning solves routed through
    the shard_map mesh dispatcher (KC_SOLVER_MESH=1 over whatever devices the
    host exposes — docs/KERNEL_PERF.md "Layer 5"), under sustained churn.
    On top of the convergence rules it budgets WALL TIME per tick
    (``tick_wall_s``, advisory like every wall-clock probe): the sharded
    path must keep the whole reconcile loop inside the budget at a fleet the
    single-device path cannot hold comfortably.  Slow matrix only (compile +
    ~100k pod objects); the tier-1 smoke is a scaled-down clone in
    tests/test_mesh_dispatch.py."""
    return SoakScenario(
        name="churn-steady-sharded",
        seed=seed,
        generator="diurnal",
        # flat Poisson: standing population ≈ rate × lifetime ≈ 100k pods
        params={
            "duration_s": 600.0, "period_s": 600.0,
            "base_rate_per_s": 167.0, "peak_rate_per_s": 167.0,
            "mean_lifetime_s": 600.0,
        },
        slo={"rules": _CONVERGENCE_RULES + [
            {"probe": "solve_latency_s", "agg": "mean", "limit": 2.0},
            {"probe": "tick_wall_s", "agg": "mean", "limit": 60.0},
        ]},
        tick_s=30.0,
        settle_ticks=30,
        use_tpu_kernel=True,
        n_instance_types=2000,
        env={"KC_SOLVER_MESH": "1"},
    )


def hung_device(seed: int = 73) -> SoakScenario:
    """A churn-steady fleet whose device backend goes QUIET mid-run: a
    seeded ``solver.hang`` chaos fault (kind ``hang``, docs/CHAOS.md) stalls
    one monitored dispatch/fetch past its watchdog deadline.  The SLO
    asserts the hang-proofing contract end to end: pending pods keep
    draining through degraded host solves while the backend is quarantined
    (bounded degraded time + bounded p99 pending age + bounded tick wall),
    and the TPU path re-admits after the stall clears — the canary-verified
    half-open trial — so the run must FINISH un-degraded.  Slow matrix
    (kernel compiles); the tier-1 twin is tests/test_watchdog.py.  The
    verdict replays from (scenario, seed): the stall schedule is a pure
    function of the seed-replayable monitored-dispatch hit order, and the
    breaker/canary timing steps on FakeClock."""
    return SoakScenario(
        name="hung-device",
        seed=seed,
        generator="diurnal",
        # flat Poisson churn: standing population ≈ rate × lifetime ≈ 4.8k
        params={
            "duration_s": 300.0, "period_s": 300.0,
            "base_rate_per_s": 16.0, "peak_rate_per_s": 16.0,
            "mean_lifetime_s": 300.0,
        },
        slo={"rules": [
            {"probe": "pending_age_p99_s", "agg": "max", "limit": 240.0},
            {"probe": "machine_leaks", "agg": "max", "limit": 0.0},
            {"probe": "pending_pods", "agg": "final", "limit": 0.0},
            # degraded host progress is EXPECTED while quarantined — bound
            # the window instead of forbidding it, and require re-admission
            # by the end (final 0 = the canary verified and the device path
            # came back)
            {"probe": "degraded", "agg": "time_above", "above": 0.0,
             "limit": 120.0},
            {"probe": "degraded", "agg": "final", "limit": 0.0},
            # the hang tick pays one abandoned deadline + a canary — still
            # bounded wall time per tick (advisory, like every wall probe)
            {"probe": "tick_wall_s", "agg": "max", "limit": 60.0},
        ]},
        tick_s=15.0,
        settle_ticks=40,
        use_tpu_kernel=True,
        tpu_kernel_min_pods=128,
        chaos_points={
            # hits 9 and 10 stall until abandoned: each kernel solve hits
            # the point several times across its dispatch/fetch sites and a
            # SolveTimeout aborts the rest of its batch, so consecutive
            # indices land in two CONSECUTIVE batches — exactly the
            # TPU_KERNEL_MAX_FAILURES streak that opens the breaker and
            # quarantines the backend a couple of solves into the run
            "solver.hang": {"schedule": [9, 10], "kind": "hang"},
        },
        env={
            # small real-time deadlines so the abandoned call costs the run
            # seconds, not the production 120 s ceiling
            "KC_WATCHDOG_FLOOR_S": "0.2",
            "KC_WATCHDOG_COLD_MULT": "25",
            "KC_WATCHDOG_CEILING_S": "60",
        },
    )


CATALOG: Dict[str, Callable[[int], SoakScenario]] = {
    "deploy-storm-smoke": deploy_storm_smoke,
    "churn-steady": churn_steady,
    "churn-steady-sharded": churn_steady_sharded,
    "hung-device": hung_device,
    "diurnal-consolidation": diurnal_consolidation,
    "batch-flood-flaky-api": batch_flood_flaky_api,
    "mass-eviction-capacity": mass_eviction_capacity,
    "mixed-fleet-steady": mixed_fleet_steady,
    "spot-churn": spot_churn,
}

# the deterministic scenario `make soak` gates on (mirrors `make chaos`)
TIER1_SMOKE = "deploy-storm-smoke"


def build(name: str, seed: Optional[int] = None) -> SoakScenario:
    """Catalog lookup; ``seed`` overrides the scenario default."""
    if name not in CATALOG:
        raise ValueError(f"unknown soak scenario {name!r} (have {sorted(CATALOG)})")
    scenario = CATALOG[name]() if seed is None else CATALOG[name](int(seed))
    return scenario
