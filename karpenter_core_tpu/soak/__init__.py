"""soak/: the trace-driven soak subsystem.

Seeded workload-trace generators (``soak/generators.py``) replay realistic
churn — diurnal waves, deploy storms, batch floods, mass evictions, mixed
multi-provisioner fleets — against the full controller stack on a FakeClock
timeline, while an SLO engine (``soak/slo.py``) samples time-series probes
every simulated tick and renders a structured, seed-replayable verdict
report.  ``run_scenario`` (``soak/runner.py``) is the entry;
``scenarios.CATALOG`` holds the built-ins; ``tools/soak.py`` is the CLI and
``make soak`` the CI gate.  See docs/SOAK.md.
"""

from karpenter_core_tpu.soak.generators import GENERATORS, generate
from karpenter_core_tpu.soak.runner import SoakRunner, SoakScenario, run_scenario
from karpenter_core_tpu.soak.slo import (
    Observation,
    PROBES,
    SLOEngine,
    SLORule,
    SLOSpec,
    canonical_verdict,
    percentile,
    replay_digest,
)
from karpenter_core_tpu.soak.trace import TraceEvent, WorkloadTrace, merge, sort_events

__all__ = [
    "GENERATORS",
    "Observation",
    "PROBES",
    "SLOEngine",
    "SLORule",
    "SLOSpec",
    "SoakRunner",
    "SoakScenario",
    "TraceEvent",
    "WorkloadTrace",
    "canonical_verdict",
    "generate",
    "merge",
    "percentile",
    "replay_digest",
    "run_scenario",
    "sort_events",
]
