"""Multi-tenant soak: N synthetic tenants hammer ONE solver server.

The acceptance scenario for the multi-tenant solver service (ISSUE-12,
docs/SERVICE.md): ≥ 8 concurrent synthetic tenants drive per-tenant snapshot
streams against a single in-process gRPC server — rounds barrier-synchronized
so the batch coalescer actually sees concurrency — with the ``service.rpc``
and ``solver.dispatch`` chaos points armed, and (mid-stream) a server
kill/restart.  The verdict gates on:

  - **0 cross-tenant wrong answers**: every full response accounts for
    exactly the tenant's own pod classes (each class's placed + failed +
    residual == the count it sent, no foreign class indices, the tenant echo
    matches); delta responses must stay inside the tenant's class space.
  - **0 machine leaks**: the solver service never creates machines.
  - **every session re-anchors** after the restart: the first successful
    post-restart solve per tenant carries reason ``session-lost`` (a full
    solve — no stale lineage ever answers).
  - **p99 end-to-end latency** within the scenario SLO (wall-clock —
    advisory-grade like every wall probe, but the acceptance bound).

Sheds (RESOURCE_EXHAUSTED + retry-after), isolation, chaos faults, and
ejections are EXPECTED under chaos — tenants retry through them; the
verdict counts them as diagnostics, not failures, as long as every round
eventually completes.  Unlike the trace-driven soak (soak/runner.py) this
drives real threads against a real gRPC server, so the report is not
byte-replayable; the deterministic workload (DeterministicRNG per tenant)
still makes failures reproducible in shape.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from karpenter_core_tpu import chaos
from karpenter_core_tpu.utils import retry

# shape palette shared by every tenant: same shapes ⇒ same encode buckets ⇒
# the coalescer gets real batching opportunities (the production regime:
# many clusters, few distinct pod shapes)
_SHAPES: Tuple[Dict, ...] = (
    {"cpu": "500m"},
    {"cpu": "250m"},
    {"cpu": 1, "memory": "1Gi"},
)


@dataclass
class TenantSoakScenario:
    """One multi-tenant soak run."""

    name: str = "multi-tenant"
    seed: int = 1729
    tenants: int = 8
    rounds: int = 4
    pods_per_tenant: int = 10
    churn_fraction: float = 0.3
    # server kill/restart after this round completes (None = no restart)
    restart_after_round: Optional[int] = 1
    p99_slo_s: float = 90.0
    batch_window_s: float = 0.05
    max_attempts: int = 80
    # durable sessions (ISSUE-13, service/journal.py): a journal directory
    # arms the session journal; the mid-stream restart becomes a simulated
    # SIGKILL (journal abandoned un-flushed, no final checkpoint) and the
    # verdict additionally gates on the warm-resume fraction — sessions that
    # come back in delta mode instead of re-anchoring
    journal_dir: Optional[str] = None
    min_warm_fraction: float = 0.8
    chaos_points: Dict[str, dict] = field(default_factory=lambda: {
        "service.rpc": {"prob": 0.2, "stop_after": 6},
        "solver.dispatch": {"prob": 0.35, "stop_after": 2, "kind": "error"},
    })


class _ServerBox:
    """The live server handle tenants dial through; the restart swaps it."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.address: Optional[str] = None
        self.epoch = 0

    def set(self, address: str) -> None:
        with self.lock:
            self.address = address
            self.epoch += 1

    def get(self) -> Tuple[str, int]:
        with self.lock:
            return self.address, self.epoch


class _TenantDriver:
    """One synthetic tenant: a deterministic churning workload, retry-through
    faults, structural response verification."""

    def __init__(self, index: int, scenario: TenantSoakScenario,
                 box: _ServerBox) -> None:
        self.tenant_id = f"tenant-{index:02d}"
        self.scenario = scenario
        self.box = box
        self.rng = retry.DeterministicRNG(scenario.seed * 7919 + index)
        # two classes per tenant, drawn from the shared palette
        i = int(self.rng.random() * len(_SHAPES))
        j = (i + 1 + int(self.rng.random() * (len(_SHAPES) - 1))) % len(_SHAPES)
        half = max(scenario.pods_per_tenant // 2, 1)
        self.counts: List[Tuple[Dict, int]] = [
            (_SHAPES[i], half),
            (_SHAPES[j], max(scenario.pods_per_tenant - half, 1)),
        ]
        self.session_version = 0
        self.client = None
        self.client_epoch = -1
        self.stats = {
            "completed": 0, "sheds": 0, "transport_errors": 0,
            "client_faults": 0, "ejects": 0, "wrong_answers": 0,
            "incomplete_rounds": 0,
        }
        self.latencies: List[float] = []
        self.mode_counts: Dict[str, int] = {}
        self.relost = False
        self.errors: List[str] = []
        # journal soak bookkeeping: how this tenant's session came back
        # after the restart ("warm" | "reanchor"), and a digest per
        # completed round so two runs of the same seed can be compared
        # response-for-response (the bit-identity verdict)
        self.resume_outcome: Optional[str] = None
        self.round_digests: List[str] = []

    # -- plumbing --------------------------------------------------------------

    def _connect(self):
        from karpenter_core_tpu.service.snapshot_channel import (
            SnapshotSolverClient,
        )

        address, epoch = self.box.get()
        if self.client is None or epoch != self.client_epoch:
            if self.client is not None:
                try:
                    self.client.close()
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass
            self.client = SnapshotSolverClient(address)
            self.client_epoch = epoch
        return self.client

    def _churn(self) -> None:
        """Count churn: ±churn_fraction of the population, bounded ≥ 1 per
        class so the class set (and the encode bucket) stays stable."""
        budget = max(
            int(self.scenario.pods_per_tenant * self.scenario.churn_fraction), 1
        )
        for _ in range(budget):
            k = int(self.rng.random() * len(self.counts))
            shape, count = self.counts[k]
            delta = 1 if self.rng.random() < 0.5 else -1
            self.counts[k] = (shape, max(count + delta, 1))

    # -- verification ----------------------------------------------------------

    def _verify(self, resp: Dict, sent: List[int]) -> None:
        """Structural correctness: a wrong answer is any response that does
        not account for exactly this tenant's classes (the cross-tenant
        contamination detector)."""
        def fail(msg: str) -> None:
            self.stats["wrong_answers"] += 1
            self.errors.append(f"{self.tenant_id}: {msg}")

        echo = resp.get("tenant") or {}
        if echo.get("id") != self.tenant_id:
            fail(f"tenant echo {echo.get('id')!r}")
            return
        placed = [0] * len(sent)

        def absorb(counts) -> bool:
            for c, n in counts:
                if not (0 <= c < len(sent)) or n < 0:
                    fail(f"class index {c} count {n} out of range")
                    return False
                placed[c] += n
            return True

        for node in resp.get("newNodes", []):
            if not absorb(node.get("classCounts", [])):
                return
        for counts in resp.get("existingAssignments", {}).values():
            if not absorb(counts):
                return
        if not absorb(resp.get("failedClassCounts", [])):
            return
        if not absorb(resp.get("residualClassCounts", [])):
            return
        if echo.get("solveMode") == "full":
            if placed != sent:
                fail(f"full response accounts {placed} != sent {sent}")
        else:
            # delta responses carry only this tick's delta placements
            if any(p > s for p, s in zip(placed, sent)):
                fail(f"delta response overflows {placed} > sent {sent}")

    def _digest_round(self, resp: Dict) -> None:
        """Canonical digest of one round's response body.  Coalescing
        (``batched``) and the one-shot recovery echo are load-dependent, not
        answer-dependent, so they're excluded — everything else (placements,
        mode, reason, version) must be BIT-IDENTICAL between an interrupted
        run that resumed warm and an uninterrupted run of the same seed."""
        canon = dict(resp)
        echo = dict(canon.get("tenant") or {})
        echo.pop("batched", None)
        echo.pop("recovered", None)
        canon["tenant"] = echo
        self.round_digests.append(hashlib.sha256(
            json.dumps(canon, sort_keys=True, default=repr).encode()
        ).hexdigest())

    # -- one round -------------------------------------------------------------

    def run_round(self, expect_relost: bool) -> None:
        import grpc

        from karpenter_core_tpu.service import tenant as tenant_mod
        from karpenter_core_tpu.testing import factories

        self._churn()
        pod_classes = [
            (factories.make_pod(name=f"{self.tenant_id}-c{k}", requests=dict(shape)),
             count)
            for k, (shape, count) in enumerate(self.counts)
        ]
        sent = [count for _, count in self.counts]
        provisioners = [factories.make_provisioner()]
        t0 = time.perf_counter()
        for attempt in range(self.scenario.max_attempts):
            try:
                client = self._connect()
                resp = client.solve_tenant_classes(
                    pod_classes, provisioners,
                    tenant={
                        "id": self.tenant_id,
                        "sessionVersion": self.session_version,
                    },
                    timeout=30.0,
                )
            except chaos.InjectedFault:
                self.stats["client_faults"] += 1
                continue
            except grpc.RpcError as e:
                code = e.code()
                if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
                    self.stats["sheds"] += 1
                    hint = tenant_mod.parse_retry_after(e.details() or "")
                    time.sleep(min(hint or 0.1, 0.5))
                    continue
                self.stats["transport_errors"] += 1
                # server restart / chaos abort / isolation: re-dial and retry
                self.client_epoch = -1
                time.sleep(0.05)
                continue
            if "error" in resp:
                # structured ejection: our solve faulted, co-batched tenants
                # were served; our session may have reset — re-anchor
                self.stats["ejects"] += 1
                self.session_version = int(
                    (resp.get("tenant") or {}).get("sessionVersion") or 0
                )
                continue
            self.latencies.append(time.perf_counter() - t0)
            echo = resp["tenant"]
            mode = f"{echo.get('solveMode')}:{echo.get('reason')}"
            self.mode_counts[mode] = self.mode_counts.get(mode, 0) + 1
            if expect_relost and echo.get("reason") == "session-lost":
                self.relost = True
            if expect_relost and self.resume_outcome is None:
                # the first completed post-restart round decides the resume
                # outcome: a warm journal restore echoes recovered="warm";
                # everything else is the session-lost re-anchor family
                if echo.get("recovered") == "warm":
                    self.resume_outcome = "warm"
                elif echo.get("reason") == "session-lost":
                    self.resume_outcome = "reanchor"
                else:
                    self.resume_outcome = f"other:{echo.get('reason')}"
            self._digest_round(resp)
            self._verify(resp, sent)
            self.session_version = int(echo.get("sessionVersion") or 0)
            self.stats["completed"] += 1
            return
        self.stats["incomplete_rounds"] += 1
        self.errors.append(
            f"{self.tenant_id}: round never completed in "
            f"{self.scenario.max_attempts} attempts"
        )


# -- fleet failover soak (ISSUE-17, docs/FLEET.md) -----------------------------


@dataclass
class FleetSoakScenario:
    """The ``fleet-failover`` acceptance run: ≥3 REAL replica processes
    behind one in-process router, SIGKILL one replica mid-churn, and every
    tenant it held must resume WARM on another replica (checkpoint adoption,
    echo ``recovered="warm"``) inside the p99 SLO — with 0 cross-tenant
    wrong answers and 0 machine leaks fleet-wide."""

    name: str = "fleet-failover"
    seed: int = 1729
    replicas: int = 3
    tenants: int = 8
    rounds: int = 4
    pods_per_tenant: int = 8
    churn_fraction: float = 0.3
    # SIGKILL the most-loaded replica after this round completes
    kill_after_round: Optional[int] = 1
    p99_slo_s: float = 120.0
    max_attempts: int = 80
    min_warm_fraction: float = 0.95
    # checkpoint cadence 1: every solve leaves a restorable artifact, so the
    # SIGKILL window never catches a tenant without one
    ckpt_every: int = 1
    heartbeat_s: float = 0.25
    lease_ttl_s: float = 2.0
    startup_timeout_s: float = 180.0


def _free_ports(n: int) -> List[int]:
    """Reserve n distinct loopback ports (bind-all-then-close so two calls
    can't hand back the same port)."""
    import socket

    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


class _ReplicaProc:
    """One fleet replica as a real killable subprocess
    (``python -m karpenter_core_tpu.fleet.replica_main``)."""

    def __init__(self, rid: str, port: int, env: Dict[str, str],
                 stderr_path: str) -> None:
        import subprocess
        import sys

        self.rid = rid
        self.port = port
        # stderr goes to a file, not a pipe: replica logging must never
        # block on an unread pipe buffer mid-soak
        self.stderr_file = open(stderr_path, "wb")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "karpenter_core_tpu.fleet.replica_main"],
            env=env, stdout=subprocess.PIPE, stderr=self.stderr_file,
        )

    def wait_ready(self) -> None:
        """Block until the replica prints its ``PORT <n>`` readiness line."""
        line = self.proc.stdout.readline().decode(errors="replace").strip()
        if not line.startswith("PORT "):
            raise RuntimeError(
                f"replica {self.rid} failed to start (got {line!r}, "
                f"rc={self.proc.poll()}) — see {self.stderr_file.name}"
            )

    def sigkill(self) -> None:
        self.proc.kill()  # SIGKILL: no drain, no final checkpoint flush

    def close(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10.0)
            except Exception:  # noqa: BLE001 - teardown best-effort
                self.proc.kill()
                self.proc.wait(timeout=5.0)
        try:
            self.proc.stdout.close()
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass
        self.stderr_file.close()


def _fleet_machines(router_address: str) -> int:
    """Fleet-wide machine count via the router Health fan-out (each alive
    replica reports ``fleet.machines`` from its own cloud provider)."""
    import grpc
    import msgpack

    channel = grpc.insecure_channel(router_address)
    try:
        raw = channel.unary_unary(
            "/karpenter.v1.SnapshotSolver/Health"
        )(msgpack.packb({}), timeout=10.0)
        health = msgpack.unpackb(raw)
        return sum(
            int((r.get("fleet") or {}).get("machines", 0) or 0)
            for r in (health.get("fleet") or {}).get("replicas", {}).values()
            if r.get("status") == "ok"
        )
    finally:
        channel.close()


def run_fleet_failover(scenario: Optional[FleetSoakScenario] = None,
                       seed: Optional[int] = None,
                       fleet_dir: Optional[str] = None) -> dict:
    """Run the fleet-failover scenario; returns a soak-style report dict."""
    import os
    import shutil
    import tempfile

    from karpenter_core_tpu.fleet import FleetLocal, FleetMap
    from karpenter_core_tpu.fleet.router import serve_router
    from karpenter_core_tpu.service.tenant import TenantConfig
    from karpenter_core_tpu.soak.slo import percentile

    scenario = scenario or FleetSoakScenario()
    if seed is not None:
        scenario.seed = int(seed)
    own_dir = fleet_dir is None
    if own_dir:
        fleet_dir = tempfile.mkdtemp(prefix="kc-fleet-soak-")
    os.makedirs(fleet_dir, exist_ok=True)

    # reserve all ports up front so the fleet map is REAL on both sides:
    # replicas bind the mapped port, the router dials it, heartbeats land on
    # the router port — no placeholder maps, no discovery race
    ports = _free_ports(scenario.replicas + 1)
    router_port = ports[-1]
    router_address = f"127.0.0.1:{router_port}"
    rids = [f"r{i}" for i in range(scenario.replicas)]
    fleet_map_spec = ",".join(
        f"{rid}=127.0.0.1:{ports[i]}" for i, rid in enumerate(rids)
    )

    base_env = dict(os.environ)
    for stale in ("KC_JOURNAL_DIR", "KC_FLEET_BIND"):
        base_env.pop(stale, None)
    base_env.update({
        "JAX_PLATFORMS": "cpu",
        "KC_FLEET": "1",
        "KC_FLEET_DIR": fleet_dir,
        "KC_FLEET_MAP": fleet_map_spec,
        "KC_FLEET_ROUTER": router_address,
        "KC_FLEET_CKPT_EVERY": str(scenario.ckpt_every),
        "KC_FLEET_HEARTBEAT_S": str(scenario.heartbeat_s),
        "KC_FLEET_LEASE_TTL_S": str(scenario.lease_ttl_s),
        # per-replica journal (fleet.journal_dir) backs the peer-replay rung
        "KC_SESSION_JOURNAL": "1",
        # explicit KC_TENANT_RATE pin: replicas must not shed the soak load
        # (the pin also exercises the "operator pin beats fleet 1/N scaling"
        # admission contract)
        "KC_TENANT_RATE": "200", "KC_TENANT_BURST": "400",
        "KC_TENANT_QUEUE": "64",
        "KC_TENANT_BATCH_WINDOW_S": "0.02",
    })

    procs: Dict[str, _ReplicaProc] = {}
    for i, rid in enumerate(rids):
        env = dict(base_env)
        env["KC_FLEET_REPLICA"] = rid
        env["KC_FLEET_BIND"] = f"127.0.0.1:{ports[i]}"
        procs[rid] = _ReplicaProc(
            rid, ports[i], env, os.path.join(fleet_dir, f"{rid}.stderr.log")
        )
    router_server = None
    box = _ServerBox()
    drivers = [
        _TenantDriver(i, scenario, box) for i in range(scenario.tenants)
    ]
    t_wall = time.perf_counter()
    killed_rid: Optional[str] = None
    evicted: List[str] = []
    machine_leaks = 0
    try:
        for proc in procs.values():
            proc.wait_ready()
        fleet = FleetLocal(
            directory=fleet_dir,
            fleet_map=FleetMap.parse(fleet_map_spec),
            heartbeat_s=scenario.heartbeat_s,
            lease_ttl_s=scenario.lease_ttl_s,
        )
        router_server, _ = serve_router(
            fleet, address=f"127.0.0.1:{router_port}",
            tenant_config=TenantConfig(
                rate_per_s=200.0, burst=400,
                max_inflight=max(scenario.tenants * 2, 16),
            ),
            max_workers=max(scenario.tenants + 2, 8),
        )
        box.set(router_address)
        router = router_server.kc_router
        for round_idx in range(scenario.rounds):
            expect_relost = killed_rid is not None
            threads = [
                threading.Thread(
                    target=d.run_round, args=(expect_relost,), daemon=True
                )
                for d in drivers
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if (
                scenario.kill_after_round is not None
                and round_idx == scenario.kill_after_round
            ):
                # SIGKILL the most-loaded replica mid-churn: its tenants'
                # next routed solve fails over along the arc and the
                # adopting replica restores them WARM from the shared
                # checkpoint directory — no cooperation from the victim
                with router._lock:
                    placements = dict(router._placements)
                loads: Dict[str, int] = {}
                for rid in placements.values():
                    loads[rid] = loads.get(rid, 0) + 1
                killed_rid = max(
                    rids, key=lambda r: (loads.get(r, 0), r)
                )
                evicted = sorted(
                    t for t, rid in placements.items() if rid == killed_rid
                )
                procs[killed_rid].sigkill()
        machine_leaks = _fleet_machines(router_address)
    finally:
        for d in drivers:
            if d.client is not None:
                try:
                    d.client.close()
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass
        if router_server is not None:
            router_server.stop(grace=0)
            router_server.kc_router.close()
        for proc in procs.values():
            proc.close()
        if own_dir:
            shutil.rmtree(fleet_dir, ignore_errors=True)

    latencies = [v for d in drivers for v in d.latencies]
    wrong = sum(d.stats["wrong_answers"] for d in drivers)
    incomplete = sum(d.stats["incomplete_rounds"] for d in drivers)
    outcomes = {d.tenant_id: d.resume_outcome for d in drivers}
    warm_evicted = sum(1 for t in evicted if outcomes.get(t) == "warm")
    warm_fraction = warm_evicted / len(evicted) if evicted else 0.0
    p99 = percentile(latencies, 0.99)

    rules = [
        {"probe": "wrong_answers", "agg": "max", "limit": 0.0,
         "observed": float(wrong), "passed": wrong == 0},
        {"probe": "machine_leaks", "agg": "max", "limit": 0.0,
         "observed": float(machine_leaks), "passed": machine_leaks == 0},
        {"probe": "incomplete_rounds", "agg": "max", "limit": 0.0,
         "observed": float(incomplete), "passed": incomplete == 0},
        # the SIGKILL must actually have evicted someone, or the warm
        # fraction would pass vacuously
        {"probe": "evicted_tenants", "agg": "final", "limit": 1.0,
         "observed": float(len(evicted)), "passed": len(evicted) >= 1},
        {"probe": "warm_resume_fraction", "agg": "final",
         "limit": scenario.min_warm_fraction,
         "observed": round(warm_fraction, 3),
         "passed": warm_fraction >= scenario.min_warm_fraction},
        {"probe": "e2e_latency_p99_s", "agg": "max",
         "limit": scenario.p99_slo_s, "observed": round(p99, 3),
         "passed": p99 <= scenario.p99_slo_s},
    ]
    mode_counts: Dict[str, int] = {}
    for d in drivers:
        for k, v in d.mode_counts.items():
            mode_counts[k] = mode_counts.get(k, 0) + v
    return {
        "verdict": {
            "scenario": scenario.name,
            "seed": scenario.seed,
            "passed": all(r["passed"] for r in rules),
            "slo": rules,
            "tenants": scenario.tenants,
            "rounds": scenario.rounds,
            "replicas": scenario.replicas,
            "killed_replica": killed_rid,
            "warm_resumes": warm_evicted,
            "converged": incomplete == 0,
            "ticks": scenario.rounds,
        },
        "diagnostics": {
            "wall_s": round(time.perf_counter() - t_wall, 3),
            "latency_p99_s": round(p99, 3),
            "latency_max_s": round(max(latencies), 3) if latencies else 0.0,
            "mode_counts": mode_counts,
            "evicted": list(evicted),
            "outcomes": outcomes,
            "stats": {
                k: sum(d.stats[k] for d in drivers)
                for k in drivers[0].stats
            } if drivers else {},
            "errors": [e for d in drivers for e in d.errors][:20],
            "tenants": {
                d.tenant_id: {
                    "outcome": d.resume_outcome,
                    "digests": list(d.round_digests),
                }
                for d in drivers
            },
        },
    }


def run_multi_tenant(scenario: Optional[TenantSoakScenario] = None,
                     seed: Optional[int] = None) -> dict:
    """Run the scenario; returns a soak-style report dict (verdict +
    diagnostics)."""
    from karpenter_core_tpu.cloudprovider.fake import FakeCloudProvider
    from karpenter_core_tpu.service.snapshot_channel import serve
    from karpenter_core_tpu.service.tenant import TenantConfig
    from karpenter_core_tpu.soak.slo import percentile

    scenario = scenario or TenantSoakScenario()
    if seed is not None:
        scenario.seed = int(seed)
    provider = FakeCloudProvider()
    config = TenantConfig(
        rate_per_s=50.0, burst=100,
        max_inflight=max(scenario.tenants * 2, 16),
        batch_window_s=scenario.batch_window_s,
        max_batch=scenario.tenants,
    )
    box = _ServerBox()
    server, port = serve(
        provider, tenant_config=config, journal_dir=scenario.journal_dir
    )
    box.set(f"127.0.0.1:{port}")

    drivers = [
        _TenantDriver(i, scenario, box) for i in range(scenario.tenants)
    ]
    chaos_scenario = None
    if scenario.chaos_points:
        chaos_scenario = chaos.Scenario.from_dict({
            "name": f"{scenario.name}-chaos",
            "seed": scenario.seed,
            "points": dict(scenario.chaos_points),
        })
    t_wall = time.perf_counter()
    restarted = False
    try:
        if chaos_scenario is not None:
            chaos.arm(chaos_scenario)
        for round_idx in range(scenario.rounds):
            expect_relost = restarted
            threads = [
                threading.Thread(
                    target=d.run_round, args=(expect_relost,), daemon=True
                )
                for d in drivers
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if (
                scenario.restart_after_round is not None
                and round_idx == scenario.restart_after_round
            ):
                # kill/restart mid-stream.  Without a journal: in-memory
                # lineages die with the process and every tenant's next
                # solve re-anchors (reason session-lost).  With a journal
                # (ISSUE-13): simulated SIGKILL — the journal is abandoned
                # un-flushed (queued records drop, no final checkpoint,
                # exactly what a dead process leaves) and the restarted
                # server replays the durable chains back into WARM lineages
                # before the port binds.
                server.stop(grace=0)
                if server.kc_service.journal is not None:
                    server.kc_service.journal.abandon()
                server, port = serve(
                    provider, tenant_config=config,
                    journal_dir=scenario.journal_dir,
                )
                box.set(f"127.0.0.1:{port}")
                restarted = True
    finally:
        if chaos_scenario is not None:
            chaos.disarm()
        for d in drivers:
            if d.client is not None:
                try:
                    d.client.close()
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass
        server.stop(grace=0)
        server.kc_service.shutdown()

    latencies = [v for d in drivers for v in d.latencies]
    wrong = sum(d.stats["wrong_answers"] for d in drivers)
    incomplete = sum(d.stats["incomplete_rounds"] for d in drivers)
    machine_leaks = len(provider.created_machines())
    relost = sum(1 for d in drivers if d.relost)
    warm = sum(1 for d in drivers if d.resume_outcome == "warm")
    journal_on = scenario.journal_dir is not None
    # with the journal armed, warm-resumed sessions do NOT re-anchor — only
    # the remainder (broken chains, lost tail) report session-lost
    expected_relost = (scenario.tenants - warm if journal_on else
                       scenario.tenants) if restarted else 0
    p99 = percentile(latencies, 0.99)  # the SLO engine's nearest-rank

    rules = [
        {"probe": "wrong_answers", "agg": "max", "limit": 0.0,
         "observed": float(wrong), "passed": wrong == 0},
        {"probe": "machine_leaks", "agg": "max", "limit": 0.0,
         "observed": float(machine_leaks), "passed": machine_leaks == 0},
        {"probe": "incomplete_rounds", "agg": "max", "limit": 0.0,
         "observed": float(incomplete), "passed": incomplete == 0},
        {"probe": "sessions_relost", "agg": "final",
         "limit": float(expected_relost), "observed": float(relost),
         "passed": relost == expected_relost},
        {"probe": "e2e_latency_p99_s", "agg": "max",
         "limit": scenario.p99_slo_s, "observed": round(p99, 3),
         "passed": p99 <= scenario.p99_slo_s},
    ]
    if journal_on and restarted:
        frac = warm / scenario.tenants if scenario.tenants else 0.0
        rules.append({
            # limit here is a FLOOR (warm resumes must meet it), unlike the
            # ceilings above — the rule dict carries its own verdict
            "probe": "warm_resume_fraction", "agg": "final",
            "limit": scenario.min_warm_fraction,
            "observed": round(frac, 3),
            "passed": frac >= scenario.min_warm_fraction,
        })
    mode_counts: Dict[str, int] = {}
    for d in drivers:
        for k, v in d.mode_counts.items():
            mode_counts[k] = mode_counts.get(k, 0) + v
    batched_rounds = sum(
        v for k, v in mode_counts.items() if k.startswith("full")
    )
    report = {
        "verdict": {
            "scenario": scenario.name,
            "seed": scenario.seed,
            "passed": all(r["passed"] for r in rules),
            "slo": rules,
            "tenants": scenario.tenants,
            "rounds": scenario.rounds,
            "restarted": restarted,
            "converged": incomplete == 0,
            "ticks": scenario.rounds,
        },
        "diagnostics": {
            "wall_s": round(time.perf_counter() - t_wall, 3),
            "latency_p99_s": round(p99, 3),
            "latency_max_s": round(max(latencies), 3) if latencies else 0.0,
            "mode_counts": mode_counts,
            "full_solves": batched_rounds,
            "stats": {
                k: sum(d.stats[k] for d in drivers)
                for k in drivers[0].stats
            } if drivers else {},
            "errors": [e for d in drivers for e in d.errors][:20],
            # per-tenant resume outcomes + round digests: the journal soak
            # acceptance compares these against an uninterrupted run of the
            # same seed (warm tenants must match digest-for-digest)
            "tenants": {
                d.tenant_id: {
                    "outcome": d.resume_outcome,
                    "digests": list(d.round_digests),
                }
                for d in drivers
            },
        },
    }
    if journal_on:
        report["verdict"]["warm_resumes"] = warm
    if chaos_scenario is not None:
        report["diagnostics"]["chaos"] = {
            "hits": chaos_scenario.hit_counts(),
            "fired": chaos_scenario.fired_counts(),
        }
    return report
