"""Seeded workload-trace generators: the realistic churn mixes the soak
subsystem replays (diurnal waves, deploy storms, batch floods, mass
evictions, mixed multi-provisioner fleets).

Every generator is a pure function ``(seed, **params) -> WorkloadTrace``:
randomness comes exclusively from ``utils/retry.DeterministicRNG``
(splitmix64 — the chaos-plane determinism contract; the kcanalyze
``chaos-hygiene`` gate forbids the ``random`` module here), and timestamps
are emitted monotone so the runner replays list order directly.  Same
``(generator, seed, params)`` ⇒ byte-identical event stream
(``WorkloadTrace.to_jsonl()``), which is what makes every soak verdict
replayable from its printed ``(scenario, seed)`` pair.

See docs/SOAK.md for the add-a-generator guide.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.soak.trace import (
    ACTION_CREATE,
    ACTION_DELETE,
    ACTION_RESIZE,
    TraceEvent,
    WorkloadTrace,
    merge,
    pairs_of,
    sort_events,
)
from karpenter_core_tpu.utils.retry import DeterministicRNG

# the request-size palette (mirrors bench.py's diverse-pod mix)
SIZES: Sequence[Dict[str, str]] = (
    {"cpu": "250m", "memory": "256Mi"},
    {"cpu": "500m", "memory": "512Mi"},
    {"cpu": "1", "memory": "2Gi"},
    {"cpu": "2", "memory": "4Gi"},
)


def _exp(rng: DeterministicRNG, mean_s: float) -> float:
    """Exponentially-distributed positive duration (mean ``mean_s``)."""
    # rng.random() is in [0, 1); 1-u is in (0, 1] so log() is defined
    return -mean_s * math.log(1.0 - rng.random())


def _choice(rng: DeterministicRNG, seq: Sequence) -> object:
    return seq[min(int(rng.random() * len(seq)), len(seq) - 1)]


def _size(rng: DeterministicRNG):
    return pairs_of(_choice(rng, SIZES))


def diurnal_wave(
    seed: int,
    duration_s: float = 3600.0,
    period_s: float = 1800.0,
    base_rate_per_s: float = 0.02,
    peak_rate_per_s: float = 0.2,
    mean_lifetime_s: float = 900.0,
    prefix: str = "diurnal",
) -> WorkloadTrace:
    """Sinusoidal arrival rate between base and peak (one full day compressed
    into ``period_s``); each pod lives an exponential lifetime.  Arrivals use
    thinning: candidates at the peak rate, accepted with probability
    rate(t)/peak — exact for a time-varying Poisson process and fully
    deterministic given the seed."""
    rng = DeterministicRNG(seed)
    events: List[TraceEvent] = []
    t, i = 0.0, 0
    while True:
        t += _exp(rng, 1.0 / peak_rate_per_s)
        if t >= duration_s:
            break
        phase = math.sin(2.0 * math.pi * t / period_s - math.pi / 2.0)
        rate = base_rate_per_s + (peak_rate_per_s - base_rate_per_s) * (phase + 1.0) / 2.0
        if rng.random() >= rate / peak_rate_per_s:
            continue
        name = f"{prefix}-{i:05d}"
        i += 1
        events.append(TraceEvent(t, ACTION_CREATE, name, requests=_size(rng)))
        # lifetimes clamp to one mean past the horizon so the trace (and the
        # runner's tick budget) ends rather than trailing an exponential tail
        events.append(TraceEvent(
            min(t + _exp(rng, mean_lifetime_s), duration_s + mean_lifetime_s),
            ACTION_DELETE, name,
        ))
    return WorkloadTrace(
        name=f"{prefix}-wave", seed=seed, events=sort_events(events),
        duration_s=duration_s,
    )


def deploy_storm(
    seed: int,
    waves: int = 3,
    replicas: int = 50,
    wave_interval_s: float = 300.0,
    start_s: float = 10.0,
    rollout: bool = True,
    teardown_lag_s: float = 30.0,
    resize_fraction: float = 0.0,
    prefix: str = "deploy",
) -> WorkloadTrace:
    """Rolling deployments: each wave creates ``replicas`` identical pods in
    a sub-second burst; with ``rollout`` the previous wave is torn down
    ``teardown_lag_s`` after the new one lands (the delete storm that chases
    every deploy).  ``resize_fraction`` of each surviving wave is resized to
    the next size up mid-life — the in-place vertical-scaling churn."""
    rng = DeterministicRNG(seed)
    events: List[TraceEvent] = []
    for w in range(waves):
        at = start_s + w * wave_interval_s
        size = pairs_of(SIZES[w % len(SIZES)])
        bigger = pairs_of(SIZES[(w + 1) % len(SIZES)])
        for r in range(replicas):
            name = f"{prefix}-w{w}-{r:04d}"
            jitter = rng.random() * 0.5
            events.append(TraceEvent(
                at + jitter, ACTION_CREATE, name,
                requests=size,
                labels=pairs_of({"app": prefix, "wave": str(w)}),
                owner_kind="ReplicaSet",
            ))
            if rollout and w + 1 < waves:
                events.append(TraceEvent(
                    start_s + (w + 1) * wave_interval_s + teardown_lag_s
                    + rng.random() * 0.5,
                    ACTION_DELETE, name,
                ))
            elif resize_fraction > 0.0 and rng.random() < resize_fraction:
                events.append(TraceEvent(
                    at + wave_interval_s / 2.0, ACTION_RESIZE, name,
                    requests=bigger,
                ))
    duration = start_s + waves * wave_interval_s + teardown_lag_s
    return WorkloadTrace(
        name=f"{prefix}-storm", seed=seed, events=sort_events(events),
        duration_s=duration,
    )


def batch_flood(
    seed: int,
    jobs: int = 5,
    pods_per_job: int = 40,
    at_s: float = 10.0,
    mean_runtime_s: float = 600.0,
    prefix: str = "batch",
) -> WorkloadTrace:
    """A burst of batch jobs landing near-simultaneously: every job's pods
    arrive inside a few seconds, run an exponential runtime, and complete
    (delete).  The shape that punishes schedulers amortized for trickle
    arrivals."""
    rng = DeterministicRNG(seed)
    events: List[TraceEvent] = []
    for j in range(jobs):
        size = _size(rng)
        job_at = at_s + rng.random() * 5.0
        for p in range(pods_per_job):
            name = f"{prefix}-j{j}-{p:04d}"
            created = job_at + rng.random() * 2.0
            events.append(TraceEvent(
                created, ACTION_CREATE, name,
                requests=size,
                labels=pairs_of({"job": f"{prefix}-{j}"}),
                owner_kind="Job",
            ))
            events.append(TraceEvent(
                created + min(_exp(rng, mean_runtime_s), 4.0 * mean_runtime_s),
                ACTION_DELETE, name,
            ))
    events = sort_events(events)
    return WorkloadTrace(
        name=f"{prefix}-flood", seed=seed, events=events,
        duration_s=events[-1].at_s if events else at_s,
    )


def mass_eviction(
    seed: int,
    standing: int = 60,
    evict_fraction: float = 0.5,
    evict_at_s: float = 600.0,
    recreate_delay_s: float = 30.0,
    prefix: str = "evict",
) -> WorkloadTrace:
    """A standing fleet, then a correlated eviction (node pool rotation, AZ
    drain): a seeded fraction of the fleet is deleted inside one window and
    replacement pods (new names — the controller sees fresh unschedulables)
    arrive ``recreate_delay_s`` later."""
    rng = DeterministicRNG(seed)
    events: List[TraceEvent] = []
    for i in range(standing):
        name = f"{prefix}-{i:05d}"
        events.append(TraceEvent(
            rng.random() * 30.0, ACTION_CREATE, name, requests=_size(rng),
            labels=pairs_of({"app": prefix}),
        ))
        if rng.random() < evict_fraction:
            gone_at = evict_at_s + rng.random() * 10.0
            events.append(TraceEvent(gone_at, ACTION_DELETE, name))
            events.append(TraceEvent(
                gone_at + recreate_delay_s, ACTION_CREATE,
                f"{prefix}-r{i:05d}", requests=_size(rng),
                labels=pairs_of({"app": prefix}),
            ))
    duration = evict_at_s + 10.0 + recreate_delay_s
    return WorkloadTrace(
        name=f"{prefix}-mass", seed=seed, events=sort_events(events),
        duration_s=duration,
    )


def mixed_fleet(
    seed: int,
    provisioners: Sequence[str] = ("fleet-a", "fleet-b"),
    scale: float = 0.5,
    prefix: str = "mixed",
) -> WorkloadTrace:
    """Multi-provisioner fleets under different churn patterns at once: each
    provisioner gets its own sub-workload (round-robin over storm / flood /
    eviction shapes) pinned to it via a node selector on the provisioner-name
    label.  ``scale`` shrinks the standard sub-workload sizes."""
    subtraces: List[WorkloadTrace] = []
    shapes = (
        lambda s, p: deploy_storm(
            s, waves=2, replicas=max(int(24 * scale), 2),
            wave_interval_s=120.0, prefix=p,
        ),
        lambda s, p: batch_flood(
            s, jobs=3, pods_per_job=max(int(20 * scale), 2), prefix=p,
        ),
        lambda s, p: mass_eviction(
            s, standing=max(int(30 * scale), 4), evict_at_s=240.0, prefix=p,
        ),
    )
    for k, prov in enumerate(provisioners):
        sub = shapes[k % len(shapes)](seed + k + 1, f"{prefix}-{prov}")
        selector = pairs_of({labels_api.PROVISIONER_NAME_LABEL_KEY: prov})
        sub.events = [
            TraceEvent(
                e.at_s, e.action, e.pod, requests=e.requests, labels=e.labels,
                node_selector=selector, owner_kind=e.owner_kind,
            )
            for e in sub.events
        ]
        subtraces.append(sub)
    return merge(f"{prefix}-fleet", seed, subtraces)


# generator registry: the names scenarios and tools/soak.py use
GENERATORS = {
    "diurnal": diurnal_wave,
    "deploy-storm": deploy_storm,
    "batch-flood": batch_flood,
    "mass-eviction": mass_eviction,
    "mixed-fleet": mixed_fleet,
}


def generate(kind: str, seed: int, params: Optional[dict] = None) -> WorkloadTrace:
    """Build + validate a trace from the registry (the scenario/CLI entry)."""
    if kind not in GENERATORS:
        raise ValueError(f"unknown generator {kind!r} (have {sorted(GENERATORS)})")
    trace = GENERATORS[kind](seed, **(params or {}))
    trace.validate()
    return trace
