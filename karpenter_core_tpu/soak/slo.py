"""The SLO engine: time-series probes sampled each simulated tick, declarative
per-scenario SLO specs, and structured verdict reports.

Probes are *derived views* over what the runner observes each tick (pending
pod ages, machine leaks, degraded state, empty-node ages, last solve
latency).  Two classes:

  deterministic probes   computed purely from cluster state on the FakeClock
                         timeline — identical across replays of the same
                         ``(scenario, seed)``; these drive the verdict
  wall-clock probes      measured in real seconds (per-reconcile solve
                         latency) — reported and SLO-checked *advisorily*
                         under ``diagnostics`` so the verdict stays
                         replay-identical

The verdict report is JSON with two top-level sections: ``verdict`` (the
seed-replayable part — same ``(scenario, seed)`` ⇒ byte-identical canonical
JSON, fingerprinted by ``replay_digest``) and ``diagnostics`` (wall-clock
timings, chaos fired counts, anything thread-timing-dependent).  A failed
rule names the violated probe and the tick window where the probe was out of
bounds.  Probe samples are exported live as
``karpenter_soak_slo_probe{probe,scenario}`` gauges and SLO violations as
``karpenter_soak_slo_violations_total{probe,scenario}`` (metrics/registry.py)
so a long soak is watchable on ``/metrics`` while it runs.

See docs/SOAK.md for the spec schema and docs/OBSERVABILITY.md for the
metrics surface.
"""

from __future__ import annotations

import hashlib
import json
import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from karpenter_core_tpu.metrics.registry import (
    SOAK_SLO_PROBE,
    SOAK_SLO_VIOLATIONS,
)

# probe catalog: name -> deterministic? (wall-clock probes are advisory)
PROBES: Dict[str, bool] = {
    "pending_pods": True,
    "pending_age_p99_s": True,
    "machine_leaks": True,
    "degraded": True,
    "consolidation_lag_s": True,
    "nodes": True,
    # summed current-offering price of the live fleet, sampled per tick —
    # the policy subsystem's economic convergence surface (docs/POLICY.md):
    # a fleet that only grows, or that lands on expensive offerings under
    # spot churn, blows a mean bound here while node counts look healthy.
    # Deterministic: derived from node labels × the catalog price sheet on
    # the FakeClock timeline.
    "fleet_cost_per_tick": True,
    "solve_latency_s": False,
    # whole-tick wall time (events + scheduling + lifecycle + observe) in
    # real seconds — the per-tick wall budget the sharded-path soak gates on
    # (a mesh fleet that keeps up on solve_latency but drowns in decode or
    # bookkeeping blows this while every deterministic probe looks clean).
    # Wall-clock ⇒ advisory: excluded from the replayable verdict digest.
    "tick_wall_s": False,
    # host-side ingest/classification wall seconds of the last provisioning
    # batch (ProvisioningController.last_ingest_s): the per-tick encode-path
    # budget the delta-native ingest keeps flat while the fleet grows
    # (docs/KERNEL_PERF.md "Layer 6").  Wall-clock ⇒ advisory.
    "ingest_s": False,
    # hidden device→host fetch wall of the last kernel solve
    # (ProvisioningController.last_overlap_s — the utils.pipeline
    # ``pipeline.overlap`` record): copy seconds the pipelined loop spent
    # doing other work instead of blocking.  ≈0 on the serial controller
    # path; the overlap win itself is wall-clock-only, so this rides OFF
    # the replay digest exactly like tick_wall_s (docs/KERNEL_PERF.md
    # "Layer 7").  Wall-clock ⇒ advisory.
    "tick_overlap_s": False,
}

AGG_MAX = "max"
AGG_MEAN = "mean"
AGG_FINAL = "final"
AGG_TIME_ABOVE = "time_above"
AGGS = (AGG_MAX, AGG_MEAN, AGG_FINAL, AGG_TIME_ABOVE)


def percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile (deterministic, no interpolation); 0.0 on an
    empty series."""
    if not values:
        return 0.0
    ordered = sorted(values)
    rank = max(math.ceil(q * len(ordered)) - 1, 0)
    return ordered[min(rank, len(ordered) - 1)]


@dataclass
class Observation:
    """What the runner saw at one tick (raw inputs the probes derive from)."""

    pending_ages_s: List[float] = field(default_factory=list)
    machine_leaks: int = 0
    degraded: bool = False
    empty_node_ages_s: List[float] = field(default_factory=list)
    nodes: int = 0
    fleet_cost: float = 0.0  # summed current-offering price of live nodes
    solve_latency_s: float = 0.0  # wall seconds (advisory)
    tick_wall_s: float = 0.0  # whole-tick wall seconds (advisory)
    ingest_s: float = 0.0  # last batch's host ingest/classify wall (advisory)
    tick_overlap_s: float = 0.0  # hidden fetch wall of the last solve (advisory)

    def probe_values(self) -> Dict[str, float]:
        return {
            "pending_pods": float(len(self.pending_ages_s)),
            "pending_age_p99_s": percentile(self.pending_ages_s, 0.99),
            "machine_leaks": float(self.machine_leaks),
            "degraded": 1.0 if self.degraded else 0.0,
            "consolidation_lag_s": max(self.empty_node_ages_s, default=0.0),
            "nodes": float(self.nodes),
            "fleet_cost_per_tick": round(self.fleet_cost, 6),
            "solve_latency_s": self.solve_latency_s,
            "tick_wall_s": self.tick_wall_s,
            "ingest_s": self.ingest_s,
            "tick_overlap_s": self.tick_overlap_s,
        }


@dataclass
class SLORule:
    """One bound on one probe's time series."""

    probe: str
    limit: float
    agg: str = AGG_MAX
    above: float = 0.0  # the threshold integrated by time_above

    def __post_init__(self) -> None:
        if self.probe not in PROBES:
            raise ValueError(f"unknown SLO probe {self.probe!r} (have {sorted(PROBES)})")
        if self.agg not in AGGS:
            raise ValueError(f"unknown SLO aggregation {self.agg!r} (have {AGGS})")

    def to_dict(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            "probe": self.probe, "agg": self.agg, "limit": self.limit,
        }
        if self.agg == AGG_TIME_ABOVE:
            out["above"] = self.above
        return out


@dataclass
class SLOSpec:
    """A scenario's declarative SLO: a list of rules, all of which must hold."""

    rules: List[SLORule] = field(default_factory=list)

    @classmethod
    def from_dict(cls, spec: dict) -> "SLOSpec":
        rules = [
            rule if isinstance(rule, SLORule) else SLORule(**rule)
            for rule in (spec.get("rules") or [])
        ]
        return cls(rules=rules)

    def to_dict(self) -> Dict[str, object]:
        return {"rules": [rule.to_dict() for rule in self.rules]}


@dataclass
class _Series:
    ticks: List[int] = field(default_factory=list)
    times: List[float] = field(default_factory=list)
    values: List[float] = field(default_factory=list)


class SLOEngine:
    """Collects per-tick probe samples and evaluates an SLOSpec into a
    verdict report."""

    def __init__(self, scenario: str, seed: int, tick_s: float) -> None:
        self.scenario = scenario
        self.seed = seed
        self.tick_s = tick_s
        self.series: Dict[str, _Series] = {name: _Series() for name in PROBES}

    def observe(self, tick: int, t_s: float, obs: Observation) -> None:
        """Record one tick's samples and export them as live gauges."""
        for name, value in obs.probe_values().items():
            series = self.series[name]
            series.ticks.append(tick)
            series.times.append(round(t_s, 6))
            series.values.append(round(value, 6))
            SOAK_SLO_PROBE.labels(name, self.scenario).set(value)

    # -- evaluation ------------------------------------------------------------

    def _aggregate(self, rule: SLORule, series: _Series) -> float:
        values = series.values
        if not values:
            return 0.0
        if rule.agg == AGG_MAX:
            return max(values)
        if rule.agg == AGG_MEAN:
            return round(sum(values) / len(values), 6)
        if rule.agg == AGG_FINAL:
            return values[-1]
        # time_above: seconds the probe spent above rule.above (one tick of
        # credit per out-of-bounds sample)
        return round(float(sum(self.tick_s for v in values if v > rule.above)), 6)

    def _violation_window(self, rule: SLORule, series: _Series) -> Optional[dict]:
        """The tick window where the probe itself was out of bounds — what a
        failing verdict points the operator at."""
        threshold = rule.above if rule.agg == AGG_TIME_ABOVE else rule.limit
        bad = [i for i, v in enumerate(series.values) if v > threshold]
        if not bad and series.ticks:
            # mean/final can fail without any single sample exceeding the
            # limit; point at the end of the series
            bad = [len(series.values) - 1]
        if not bad:
            return None
        return {
            "first_tick": series.ticks[bad[0]],
            "last_tick": series.ticks[bad[-1]],
            "first_t_s": series.times[bad[0]],
            "last_t_s": series.times[bad[-1]],
            "samples_out_of_bounds": len(bad),
        }

    def evaluate(self, spec: SLOSpec) -> List[dict]:
        results = []
        for rule in spec.rules:
            series = self.series[rule.probe]
            observed = round(self._aggregate(rule, series), 6)
            passed = observed <= rule.limit
            result = dict(rule.to_dict())
            result.update({
                "observed": observed,
                "passed": passed,
                "wallclock": not PROBES[rule.probe],
            })
            if not passed:
                result["violation"] = self._violation_window(rule, series)
                SOAK_SLO_VIOLATIONS.labels(rule.probe, self.scenario).inc()
            results.append(result)
        return results

    def summaries(self, deterministic: bool) -> Dict[str, dict]:
        out = {}
        for name, is_det in sorted(PROBES.items()):
            if is_det != deterministic:
                continue
            values = self.series[name].values
            if not values:
                continue
            out[name] = {
                "min": round(min(values), 6),
                "max": round(max(values), 6),
                "mean": round(sum(values) / len(values), 6),
                "final": values[-1],
            }
        return out

    def report(self, spec: SLOSpec, extra: Optional[dict] = None,
               diagnostics: Optional[dict] = None) -> dict:
        """The structured verdict report.  ``verdict`` is the seed-replayable
        section (compare/digest it); ``diagnostics`` carries wall-clock and
        thread-timing-dependent detail."""
        results = self.evaluate(spec)
        deterministic = [r for r in results if not r["wallclock"]]
        advisory = [r for r in results if r["wallclock"]]
        ticks = max((len(s.ticks) for s in self.series.values()), default=0)
        verdict = {
            "scenario": self.scenario,
            "seed": self.seed,
            "tick_s": self.tick_s,
            "ticks": ticks,
            "passed": all(r["passed"] for r in deterministic),
            "slo": deterministic,
            "probes": self.summaries(deterministic=True),
        }
        verdict.update(extra or {})
        diag = {
            "timings": self.summaries(deterministic=False),
            "advisory_slo": advisory,
        }
        diag.update(diagnostics or {})
        return {"verdict": verdict, "diagnostics": diag}


def canonical_verdict(report: dict) -> str:
    """Canonical JSON of the replay-stable section."""
    return json.dumps(report["verdict"], sort_keys=True)


def replay_digest(report: dict) -> str:
    """sha256 fingerprint of the verdict — two runs of the same ``(scenario,
    seed)`` must produce the same digest (tests/test_soak.py pins it)."""
    return hashlib.sha256(canonical_verdict(report).encode()).hexdigest()
