"""Workload traces: replayable pod-event streams on a FakeClock timeline.

A trace is the unit the soak subsystem replays: an ordered list of
``TraceEvent``s (pod create / delete / resize) with offsets from trace start.
Traces are produced by the seeded generators in ``soak/generators.py`` and
consumed by ``soak/runner.py``, which applies each event against the kube
backend at its FakeClock time and runs the controller stack between ticks.

Determinism contract (tested in tests/test_soak.py):

  - same ``(generator, seed)`` ⇒ byte-identical ``to_jsonl()`` output (and
    therefore identical ``digest()``);
  - event timestamps are non-decreasing, so replay order is the list order;
  - a pod name is created before it is deleted or resized, and created at
    most once.

Events carry only value-typed fields (name, offsets, sorted key/value
tuples) so serialization is canonical without a custom encoder.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

ACTION_CREATE = "create"
ACTION_DELETE = "delete"
ACTION_RESIZE = "resize"
ACTIONS = (ACTION_CREATE, ACTION_DELETE, ACTION_RESIZE)

# replay order for events sharing a timestamp: deletes free capacity before
# creates claim it, resizes land last (they act on already-present pods)
_ACTION_ORDER = {ACTION_DELETE: 0, ACTION_CREATE: 1, ACTION_RESIZE: 2}


@dataclass(frozen=True)
class TraceEvent:
    """One pod lifecycle event at ``at_s`` seconds after trace start."""

    at_s: float
    action: str
    pod: str
    # sorted (key, value) tuples — canonical and hashable; values are the
    # resource-quantity / label strings the kube factories accept
    requests: Tuple[Tuple[str, str], ...] = ()
    labels: Tuple[Tuple[str, str], ...] = ()
    node_selector: Tuple[Tuple[str, str], ...] = ()
    owner_kind: str = ""

    def __post_init__(self) -> None:
        if self.action not in ACTIONS:
            raise ValueError(f"unknown trace action {self.action!r} (have {ACTIONS})")
        if self.at_s < 0:
            raise ValueError(f"negative event offset {self.at_s} for pod {self.pod!r}")

    def to_dict(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            "at_s": round(self.at_s, 6),
            "action": self.action,
            "pod": self.pod,
        }
        if self.requests:
            out["requests"] = dict(self.requests)
        if self.labels:
            out["labels"] = dict(self.labels)
        if self.node_selector:
            out["node_selector"] = dict(self.node_selector)
        if self.owner_kind:
            out["owner_kind"] = self.owner_kind
        return out

    @classmethod
    def from_dict(cls, data: Dict[str, object]) -> "TraceEvent":
        def pairs(key: str) -> Tuple[Tuple[str, str], ...]:
            return tuple(sorted((str(k), str(v)) for k, v in (data.get(key) or {}).items()))

        return cls(
            at_s=float(data["at_s"]),
            action=str(data["action"]),
            pod=str(data["pod"]),
            requests=pairs("requests"),
            labels=pairs("labels"),
            node_selector=pairs("node_selector"),
            owner_kind=str(data.get("owner_kind", "")),
        )


def pairs_of(mapping: Optional[Dict[str, object]]) -> Tuple[Tuple[str, str], ...]:
    """Canonical sorted-tuple form of a dict for TraceEvent fields."""
    return tuple(sorted((str(k), str(v)) for k, v in (mapping or {}).items()))


@dataclass
class WorkloadTrace:
    """A named, seeded, validated event stream."""

    name: str
    seed: int
    events: List[TraceEvent] = field(default_factory=list)
    duration_s: float = 0.0  # horizon; >= last event offset

    def __post_init__(self) -> None:
        if self.events:
            self.duration_s = max(self.duration_s, self.events[-1].at_s)

    def validate(self) -> None:
        """Raise ValueError on the first violated trace invariant."""
        created: set = set()
        last_at = 0.0
        for i, event in enumerate(self.events):
            # compare at serialization precision: sort_events orders by the
            # rounded offset, so sub-microsecond raw inversions inside one
            # bucket are legal (and invisible in the canonical stream)
            at = round(event.at_s, 6)
            if at < last_at:
                raise ValueError(
                    f"{self.name}: event {i} at {at}s precedes "
                    f"event {i - 1} at {last_at}s (timestamps must be monotone)"
                )
            last_at = at
            if event.action == ACTION_CREATE:
                if event.pod in created:
                    raise ValueError(f"{self.name}: pod {event.pod!r} created twice")
                created.add(event.pod)
            elif event.pod not in created:
                raise ValueError(
                    f"{self.name}: {event.action} of never-created pod {event.pod!r}"
                )

    # -- canonical serialization (the determinism surface) ---------------------

    def to_jsonl(self) -> str:
        """Byte-stable serialization: one sorted-keys JSON object per line,
        header line first.  Same (generator, seed) ⇒ identical string."""
        lines = [json.dumps(
            {"trace": self.name, "seed": self.seed,
             "events": len(self.events), "duration_s": round(self.duration_s, 6)},
            sort_keys=True,
        )]
        lines.extend(json.dumps(e.to_dict(), sort_keys=True) for e in self.events)
        return "\n".join(lines) + "\n"

    @classmethod
    def from_jsonl(cls, text: str) -> "WorkloadTrace":
        lines = [line for line in text.splitlines() if line.strip()]
        if not lines:
            raise ValueError("empty trace stream")
        header = json.loads(lines[0])
        return cls(
            name=str(header["trace"]),
            seed=int(header["seed"]),
            events=[TraceEvent.from_dict(json.loads(line)) for line in lines[1:]],
            duration_s=float(header.get("duration_s", 0.0)),
        )

    def digest(self) -> str:
        """sha256 of the canonical stream — the replay-identity fingerprint
        stamped into verdict reports."""
        return hashlib.sha256(self.to_jsonl().encode()).hexdigest()

    def counts(self) -> Dict[str, int]:
        out = {action: 0 for action in ACTIONS}
        for event in self.events:
            out[event.action] += 1
        return out


def sort_events(events: Iterable[TraceEvent]) -> List[TraceEvent]:
    """Deterministic replay order: time, then delete<create<resize, then pod
    name — no dependence on generation order."""
    return sorted(
        events,
        key=lambda e: (round(e.at_s, 6), _ACTION_ORDER[e.action], e.pod),
    )


def merge(name: str, seed: int, traces: Iterable[WorkloadTrace]) -> WorkloadTrace:
    """Compose traces (e.g. per-provisioner sub-workloads) into one stream."""
    traces = list(traces)
    events = sort_events(e for t in traces for e in t.events)
    duration = max((t.duration_s for t in traces), default=0.0)
    return WorkloadTrace(name=name, seed=seed, events=events, duration_s=duration)
