"""The soak scenario runner: trace + optional chaos + SLO spec, end to end.

A ``SoakScenario`` composes a workload trace (by generator name + params), an
optional chaos fault plan (the chaos/ plane's point specs), and a declarative
SLO spec, then ``run_scenario`` drives the full operator controller stack —
provisioning, node lifecycle, termination, deprovisioning — through the
existing suite harness on a FakeClock timeline:

  every tick:  apply due trace events (pod create / delete / resize, chaos
               faults retried next tick exactly as a real client would) →
               one scheduling round (provisioning + binder/kubelet
               emulation) → optional lifecycle + deprovisioning pass →
               sample every SLO probe → advance the clock

Each tick runs inside a ``soak.tick`` tracing span and every probe sample
lands on ``/metrics`` (``karpenter_soak_slo_probe``), so a soak run is
observable with the same tools as production.  The result is the SLO
engine's structured verdict report; the ``verdict`` section is replayable —
the same ``(scenario, seed)`` produces the identical report
(``slo.replay_digest``).

The ``apiserver`` backend runs the same scenario against a real watch/list
protocol (testing/fakeapiserver.py), which is what makes watch-drop chaos
(``watch.stream``) reachable.  See docs/SOAK.md.
"""

from __future__ import annotations

import math
import os
import time
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Tuple

from karpenter_core_tpu import chaos, tracing
from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.soak import generators
from karpenter_core_tpu.soak.slo import Observation, SLOEngine, SLOSpec
from karpenter_core_tpu.soak.trace import (
    ACTION_CREATE,
    ACTION_DELETE,
    TraceEvent,
    WorkloadTrace,
)
from karpenter_core_tpu.testing import factories, harness
from karpenter_core_tpu.utils import pod as pod_util
from karpenter_core_tpu.utils import resources as resources_util
from karpenter_core_tpu.utils import retry
from karpenter_core_tpu.utils.clock import FakeClock

BACKEND_MEMORY = "memory"
BACKEND_APISERVER = "apiserver"

# one definition of "safe to retry next tick", shared with the scheduling step
_RETRYABLE = harness.SCHEDULING_RETRYABLE


@dataclass
class SoakScenario:
    """One soak run: workload + faults + SLOs + pacing."""

    name: str
    seed: int
    generator: str
    params: dict = field(default_factory=dict)
    slo: dict = field(default_factory=dict)
    tick_s: float = 5.0
    settle_ticks: int = 40  # grace ticks past the trace horizon
    max_ticks: int = 0  # 0 = derived from the trace + settle_ticks
    backend: str = BACKEND_MEMORY
    chaos_points: Dict[str, dict] = field(default_factory=dict)
    provisioners: Tuple[str, ...] = ("default",)
    consolidation: bool = False
    ttl_seconds_after_empty: Optional[int] = None
    # route provisioning solves through the TPU kernel path (batches of at
    # least ``tpu_kernel_min_pods`` pending pods) — the churn-steady scenario
    # measures the full-re-solve vs incremental amortization there
    use_tpu_kernel: bool = False
    tpu_kernel_min_pods: int = 256
    # catalog size for the environment's fake cloud (0 = harness default) —
    # the sharded-path scenarios grow this to the scale where catalog
    # sharding matters (docs/KERNEL_PERF.md "Layer 5")
    n_instance_types: int = 0
    # env overrides applied for the duration of the run (restored after) —
    # how the sharded scenarios pin KC_SOLVER_MESH* without leaking into the
    # rest of the process
    env: Dict[str, str] = field(default_factory=dict)

    def with_seed(self, seed: int) -> "SoakScenario":
        return replace(self, seed=int(seed))

    def with_slo(self, slo: dict) -> "SoakScenario":
        return replace(self, slo=slo)

    def build_trace(self) -> WorkloadTrace:
        return generators.generate(self.generator, self.seed, self.params)

    def slo_spec(self) -> SLOSpec:
        return SLOSpec.from_dict(self.slo)

    def chaos_scenario(self) -> Optional[chaos.Scenario]:
        if not self.chaos_points:
            return None
        return chaos.Scenario.from_dict({
            "name": f"{self.name}-chaos",
            "seed": self.seed,
            "points": self.chaos_points,
        })


class SoakRunner:
    """Replays one scenario; ``run()`` returns the verdict report."""

    def __init__(self, scenario: SoakScenario) -> None:
        self.scenario = scenario
        self._first_empty: Dict[str, float] = {}

    # -- event application -----------------------------------------------------

    def _apply_event(self, env, event: TraceEvent,
                     retries: List[TraceEvent]) -> str:
        """Apply one trace event; returns "ok" / "skip".  Raises a retryable
        error (injected fault, CAS conflict) for the tick loop to requeue."""
        if event.action == ACTION_CREATE:
            env.kube.create(factories.make_pod(
                name=event.pod,
                requests=dict(event.requests) or None,
                labels=dict(event.labels) or None,
                node_selector=dict(event.node_selector) or None,
                owner_kind=event.owner_kind,
                creation_timestamp=env.clock.now(),
            ))
            return "ok"
        pod = env.kube.get_pod("default", event.pod)
        if event.action == ACTION_DELETE:
            if pod is None:
                # a chaos fault may still be holding the create in the retry
                # queue: cancel the pair instead of deleting a ghost
                for queued in list(retries):
                    if queued.pod == event.pod and queued.action == ACTION_CREATE:
                        retries.remove(queued)
                return "skip"
            env.kube.delete(pod, force=True)
            return "ok"
        # resize: in-place vertical scaling of a (possibly bound) pod
        if pod is None:
            if any(q.pod == event.pod and q.action == ACTION_CREATE
                   for q in retries):
                retries.append(event)  # create still pending: try again later
            return "skip"
        pod.spec.containers[0].resources.requests = (
            resources_util.parse_resource_list(dict(event.requests))
        )
        env.kube.apply(pod)
        return "ok"

    # -- probes ----------------------------------------------------------------

    @staticmethod
    def _fleet_cost(nodes, provider) -> float:
        """Summed current-offering price of the live fleet: each node's
        (instance type, capacity type, zone) labels looked up against the
        provider's price sheet (the ``fleet_cost_per_tick`` probe).  Nodes
        with unknown types/offerings contribute 0 — the probe measures the
        priced fleet, it does not fail the tick."""
        by_name = {it.name: it for it in provider.get_instance_types(None)}
        total = 0.0
        for node in nodes:
            it = by_name.get(
                node.metadata.labels.get(labels_api.LABEL_INSTANCE_TYPE_STABLE, "")
            )
            if it is None:
                continue
            offering = it.offerings.get(
                node.metadata.labels.get(labels_api.LABEL_CAPACITY_TYPE, ""),
                node.metadata.labels.get(labels_api.LABEL_TOPOLOGY_ZONE, ""),
            )
            if offering is not None:
                total += offering.price
        return total

    def _observe(self, env, now: float) -> Observation:
        pods = env.kube.list_pods()  # one LIST feeds both views below
        pending = harness.pending_pods(env, pods=pods)
        ages = sorted(
            round(max(now - p.metadata.creation_timestamp, 0.0), 6)
            for p in pending
        )
        nodes = env.kube.list_nodes()
        live = {n.name for n in nodes}
        bound: Dict[str, int] = {}
        for p in pods:
            if (
                p.spec.node_name
                and not pod_util.is_terminal(p)
                and not pod_util.is_owned_by_daemon_set(p)
                and not pod_util.is_owned_by_node(p)
            ):
                bound[p.spec.node_name] = bound.get(p.spec.node_name, 0) + 1
        for gone in [name for name in self._first_empty if name not in live]:
            del self._first_empty[gone]
        empty_ages = []
        for node in nodes:
            if node.metadata.labels.get(labels_api.LABEL_NODE_INITIALIZED) != "true":
                continue
            if bound.get(node.name):
                self._first_empty.pop(node.name, None)
                continue
            first = self._first_empty.setdefault(node.name, now)
            empty_ages.append(round(now - first, 6))
        return Observation(
            pending_ages_s=ages,
            machine_leaks=len(harness.machine_leaks(env)),
            degraded=env.provisioning.degraded(),
            empty_node_ages_s=sorted(empty_ages),
            nodes=len(nodes),
            fleet_cost=self._fleet_cost(nodes, env.provider),
            solve_latency_s=env.provisioning.last_reconcile_s or 0.0,
            # per-batch host ingest/classification wall: the advisory probe
            # that keeps the delta-native encode path honest under soak
            ingest_s=getattr(env.provisioning, "last_ingest_s", 0.0) or 0.0,
            # hidden device→host fetch wall of the last kernel solve (the
            # pipelined-loop overlap record, utils.pipeline; wall-clock-only
            # so it rides off the replay digest like tick_wall_s)
            tick_overlap_s=getattr(env.provisioning, "last_overlap_s", 0.0)
            or 0.0,
        )

    # -- the run ---------------------------------------------------------------

    def run(self) -> dict:
        scenario = self.scenario
        trace = scenario.build_trace()
        spec = scenario.slo_spec()
        clock = FakeClock()
        engine = SLOEngine(scenario.name, scenario.seed, scenario.tick_s)
        chaos_scenario = scenario.chaos_scenario()
        drain = (
            scenario.consolidation
            or scenario.ttl_seconds_after_empty is not None
        )
        t_wall = time.perf_counter()

        server = None
        kube_factory = None
        if scenario.backend == BACKEND_APISERVER:
            from karpenter_core_tpu.kubeapi.client import ApiServerClient
            from karpenter_core_tpu.testing.fakeapiserver import FakeApiServer

            server = FakeApiServer(bookmark_interval_s=0.2).start()
            url = server.url
            kube_factory = lambda c: ApiServerClient(  # noqa: E731
                url, c, backoff_base_s=0.05, backoff_cap_s=0.5,
                rng=retry.DeterministicRNG(scenario.seed),
            )
        elif scenario.backend != BACKEND_MEMORY:
            raise ValueError(f"unknown soak backend {scenario.backend!r}")

        env = None
        # scenario env overrides (e.g. KC_SOLVER_MESH for the sharded path),
        # restored in the finally below so runs can't leak config
        saved_env: Dict[str, Optional[str]] = {}
        for key, value in scenario.env.items():
            saved_env[key] = os.environ.get(key)
            os.environ[key] = value
        try:
            if chaos_scenario is not None:
                # armed BEFORE construction so startup watch establishment is
                # inside the fault window (the watch.stream point)
                chaos.arm(chaos_scenario, clock)
            instance_types = None
            if scenario.n_instance_types:
                from karpenter_core_tpu.cloudprovider import fake as fake_cp

                instance_types = fake_cp.instance_types(scenario.n_instance_types)
            env = harness.make_environment(
                instance_types=instance_types, kube_factory=kube_factory,
                clock=clock,
            )
            if scenario.use_tpu_kernel:
                env.provisioning.use_tpu_kernel = True
                env.provisioning.tpu_kernel_min_pods = scenario.tpu_kernel_min_pods
            for prov_name in scenario.provisioners:
                env.kube.create(factories.make_provisioner(
                    name=prov_name,
                    consolidation_enabled=True if scenario.consolidation else None,
                    ttl_seconds_after_empty=scenario.ttl_seconds_after_empty,
                ))
            total_ticks = scenario.max_ticks or (
                math.ceil(trace.duration_s / scenario.tick_s)
                + scenario.settle_ticks
            )
            applied = {"create": 0, "delete": 0, "resize": 0, "retried": 0}
            retries: List[TraceEvent] = []
            qi = 0
            converged_at: Optional[int] = None
            ticks_run = 0
            for tick in range(total_ticks):
                t = tick * scenario.tick_s
                ticks_run = tick + 1
                t_tick = time.perf_counter()
                with tracing.span("soak.tick", scenario=scenario.name,
                                  tick=tick, t_s=t):
                    due, retries = retries, []
                    while qi < len(trace.events) and trace.events[qi].at_s <= t:
                        due.append(trace.events[qi])
                        qi += 1
                    for event in due:
                        try:
                            if self._apply_event(env, event, retries) == "ok":
                                applied[event.action] += 1
                        except _RETRYABLE:
                            retries.append(event)
                            applied["retried"] += 1
                    try:
                        harness.step_scheduling_round(env)
                    except _RETRYABLE:
                        pass  # next tick retries the whole round
                    if drain:
                        try:
                            env.node_lifecycle.reconcile_all()
                        except _RETRYABLE:
                            pass  # next tick retries the lifecycle pass
                        try:
                            env.deprovisioning.reconcile()
                        except _RETRYABLE:
                            pass
                    obs = self._observe(env, clock.now())
                    # whole-tick wall cost (events + scheduling + lifecycle +
                    # observe) — the advisory per-tick wall budget the
                    # sharded scenarios gate on (slo.py "tick_wall_s")
                    obs.tick_wall_s = time.perf_counter() - t_tick
                    engine.observe(tick, t, obs)
                    if (
                        qi >= len(trace.events)
                        and not retries
                        and not obs.pending_ages_s
                        and obs.machine_leaks == 0
                        and (not drain or not obs.empty_node_ages_s)
                    ):
                        converged_at = tick
                        break
                clock.step(scenario.tick_s)

            extra = {
                "generator": scenario.generator,
                "backend": scenario.backend,
                "trace_digest": trace.digest(),
                "trace_events": trace.counts(),
                "events_applied": applied,
                "converged_at_tick": converged_at,
                "converged": converged_at is not None,
                "ticks_run": ticks_run,
            }
            diagnostics = {
                "wall_s": round(time.perf_counter() - t_wall, 3),
            }
            if chaos_scenario is not None:
                diagnostics["chaos"] = {
                    "spec": chaos_scenario.to_dict(),
                    "hits": chaos_scenario.hit_counts(),
                    "fired": chaos_scenario.fired_counts(),
                }
            return engine.report(spec, extra=extra, diagnostics=diagnostics)
        finally:
            for key, value in saved_env.items():
                if value is None:
                    os.environ.pop(key, None)
                else:
                    os.environ[key] = value
            if chaos_scenario is not None:
                chaos.disarm()
            if server is not None:
                if env is not None:
                    try:
                        env.kube.close()
                    except Exception:  # noqa: BLE001 - teardown best-effort
                        pass
                server.stop()


def run_scenario(scenario: SoakScenario) -> dict:
    """Run one scenario and return its verdict report."""
    return SoakRunner(scenario).run()
