"""Change-deduped logging helper.

Mirror of /root/reference/pkg/utils/pretty/changemonitor.go:31-45: HasChanged
returns True only when the value for a key differs from the last observation
or the entry's TTL has lapsed — used to log once per condition (and re-warn
after the TTL) instead of every reconcile.
"""

from __future__ import annotations

import time
from typing import Dict, Hashable, Tuple


class ChangeMonitor:
    def __init__(self, ttl_seconds: float = 24 * 3600.0, clock=time.monotonic) -> None:
        self.ttl = ttl_seconds
        self.clock = clock
        self._seen: Dict[Hashable, Tuple[Hashable, float]] = {}

    def has_changed(self, key: Hashable, value: Hashable) -> bool:
        now = self.clock()
        self._sweep(now)
        last = self._seen.get(key)
        # the timestamp only refreshes when the observation changes or expires
        # (go-cache Get does not extend expiry) so the TTL re-fire works under
        # continuous observation
        if last is not None:
            last_value, last_time = last
            if last_value == value and now - last_time <= self.ttl:
                return False
        self._seen[key] = (value, now)
        return True

    def _sweep(self, now: float) -> None:
        """Opportunistic eviction of expired entries (the reference runs a
        go-cache janitor); keeps memory bounded under pod churn."""
        if len(self._seen) < 1024:
            return
        expired = [k for k, (_, ts) in self._seen.items() if now - ts > self.ttl]
        for k in expired:
            del self._seen[k]
