"""Async double-buffered solve pipeline primitives (docs/KERNEL_PERF.md
"Layer 7 — the pipelined loop").

The production solve loop used to be serial per tick: dispatch solve[k],
block on the device→host fetch, host-materialize the results, only then
dispatch solve[k+1] — the accelerator idled for the whole fetch+materialize
tail.  This module holds the pieces that overlap those stages:

  FetchTicket       split "dispatch" from "fetch": construction starts
                    non-blocking ``copy_to_host_async`` on every output
                    array; ``wait()`` is the completion barrier (ONE batched
                    ``jax.device_get``).  Everything between construction
                    and the barrier — the next tick's planning, the previous
                    tick's host materialize — overlaps the copy (and, with
                    async dispatch, the device compute itself).  Each wait
                    emits a ``pipeline.overlap`` span: ``hidden_s`` (wall
                    between dispatch and the barrier — fetch+compute time
                    the loop spent doing other work) vs ``exposed_s`` (what
                    the barrier actually blocked).
  HostStagingRing   a small ring (KC_PIPELINE_DEPTH deep) of reusable host
                    staging buffers the ticket lands its arrays in.  Two
                    jobs: steady-state ticks stop allocating fresh host
                    arrays per fetch, and — because staged values are OWNED
                    copies — the zero-copy views a CPU ``device_get`` hands
                    back never pin a device buffer that the next tick wants
                    to donate (a pinned buffer silently degrades donation to
                    a realloc).  Shape drift reallocates and is counted.
  SolvePipeline     the generic depth-N ring driver for tick loops:
                    ``submit(dispatch)`` dispatches now and returns the
                    oldest in-flight tick's results once the ring is full;
                    ``drain()`` retires the tail.  A dispatch that raises
                    leaves the already-dispatched tickets consumable — no
                    wedged slot (the chaos leg in tests/test_pipeline.py).

Buffer donation rides the same switch: ``donation_enabled()`` gates the
``donate_argnums`` solve variants (utils/compilecache, parallel/mesh) that
let steady-state churn repairs reuse the warm carry's device memory instead
of reallocating per tick.  ``record_donation`` keeps the effectiveness
ledger (``donation_reallocs`` in bench.py's ``pipeline_line``).

``KC_PIPELINE=0`` switches all of it off and restores the serial loop
bit-for-bit; ``KC_PIPELINE_DEPTH`` (default 2) sizes the ring.
"""

from __future__ import annotations

import functools
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from karpenter_core_tpu import tracing
from karpenter_core_tpu.metrics import REGISTRY

STAGING_RING_OCCUPANCY = REGISTRY.gauge(
    "karpenter_staging_ring_occupancy",
    "Fraction of host staging-ring slots holding live buffers (filled at "
    "least once since ring construction).",
)
PIPELINE_OVERLAP_RATIO = REGISTRY.gauge(
    "karpenter_pipeline_overlap_ratio",
    "Dispatch/fetch overlap of the latest completed fetch, by ticket label: "
    "hidden_s / (hidden_s + exposed_s); 1.0 = the barrier never blocked.",
    ("label",),
)

_lock = threading.Lock()
_stats = {
    # warm dispatches whose donated carry buffer was actually consumed
    # (device memory reused in place)
    "donated": 0,
    # warm dispatches that re-allocated instead: donation off (KC_PIPELINE=0,
    # policy decode still needs the planes), unsupported by the backend, or
    # silently degraded because a host view still pinned the buffer
    "donation_reallocs": 0,
    # staging-ring slots REBUILT because an array's shape/dtype moved
    # (a slot's first fill is the working set, not drift — uncounted)
    "staging_reallocs": 0,
    # cancellation ledger (utils/watchdog.py): donated dispatches whose tick
    # was invalidated after a watchdog timeout — the donated carry is dead
    # and the lineage re-anchors, so donated == donation_canceled + live
    # donated dispatches at all times (the leak invariant
    # tests/test_watchdog.py pins)
    "donation_canceled": 0,
    # FetchTickets constructed minus tickets retired (first successful wait
    # or invalidate) — a leak-free loop returns this to 0 at quiesce
    "tickets_open": 0,
}
# last completed fetch's overlap record (provisioning surfaces it as the
# soak probe ``tick_overlap_s``; bench reads it per tick)
_last_overlap: Dict[str, float] = {"hidden_s": 0.0, "exposed_s": 0.0}


def pipeline_enabled() -> bool:
    """Process-wide switch: KC_PIPELINE=0 restores the serial solve loop
    (no deferred ticks, no donation, no staging) bit-for-bit."""
    return os.environ.get("KC_PIPELINE", "1") != "0"


def pipeline_depth() -> int:
    """Ring depth (staging slots / in-flight ticks + 1).  Default 2 — the
    double buffer: one tick in flight, one being consumed."""
    try:
        return max(int(os.environ.get("KC_PIPELINE_DEPTH", "2")), 2)
    except ValueError:
        return 2


@functools.lru_cache(maxsize=1)
def backend_supports_donation() -> bool:
    """One-shot runtime probe (memoized): donate a tiny buffer and check it
    was consumed.  Backends that ignore ``donate_argnums`` (older XLA:CPU)
    leave the input alive — donation there would only add warning noise,
    so the solve variants skip it and count reallocs instead."""
    try:
        import warnings

        import jax
        import jax.numpy as jnp

        x = jnp.zeros((8,), jnp.float32)
        probe = jax.jit(lambda a: a + 1.0, donate_argnums=(0,))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            probe(x).block_until_ready()
        return bool(x.is_deleted())
    except Exception:  # noqa: BLE001 - probe must never break the solve
        return False


def donation_enabled() -> bool:
    """Whether warm-carry dispatches should request buffer donation."""
    return pipeline_enabled() and backend_supports_donation()


def record_donation(engaged: bool) -> None:
    with _lock:
        _stats["donated" if engaged else "donation_reallocs"] += 1


def record_donation_canceled() -> None:
    """A donated dispatch's tick was invalidated (watchdog timeout): the
    donated buffer is dead without its results ever being applied — balance
    the ledger so leak checks can assert donated == canceled + live."""
    with _lock:
        _stats["donation_canceled"] += 1


def stats() -> Dict[str, int]:
    with _lock:
        return dict(_stats)


def reset_stats() -> None:
    with _lock:
        for k in _stats:
            _stats[k] = 0


def last_overlap() -> Dict[str, float]:
    """The most recent FetchTicket.wait() overlap record."""
    with _lock:
        return dict(_last_overlap)


def start_host_copy(tree) -> None:
    """Begin non-blocking device→host copies for every array in ``tree``
    that supports it (jax arrays; numpy/None leaves pass through)."""
    import jax

    for leaf in jax.tree_util.tree_leaves(tree):
        try:
            leaf.copy_to_host_async()
        except AttributeError:
            pass


def fetch_tree(tree, site: str = "pipeline.fetch"):
    """The batched serial-path fetch: start async copies on every leaf, then
    ONE ``jax.device_get`` over the whole tree — no array-by-array blocking
    (the ``decode.fetch`` contract, now shared by the tenant coalescer and
    the consolidation sweep).  The blocking ``device_get`` runs under the
    watchdog (utils/watchdog.py) so a hung device→host copy raises a bounded
    SolveTimeout instead of wedging the caller; ``site`` labels the deadline
    bucket (the consolidation sweep and tenant coalescer pass their own)."""
    import jax

    from karpenter_core_tpu.utils import watchdog

    start_host_copy(tree)
    return watchdog.run(site, jax.device_get, tree)


class HostStagingRing:
    """A ring of reusable host staging buffer sets.

    ``stage(arrays)`` copies a tuple of host arrays into the next slot's
    persistent buffers (allocating only when a shape/dtype moves — counted
    in ``staging_reallocs``) and returns the buffer views.  Slot ``k`` is
    rewritten only after ``depth-1`` further stage calls, which is exactly
    the double-buffer discipline: a retired tick's consumers are done with
    slot ``k`` before tick ``k+depth`` lands in it."""

    def __init__(self, depth: Optional[int] = None) -> None:
        self.depth = depth or pipeline_depth()
        self._slots: List[List[Optional[np.ndarray]]] = [
            [] for _ in range(self.depth)
        ]
        self._next = 0

    def stage(self, arrays: Tuple) -> Tuple:
        slot = self._slots[self._next]
        self._next = (self._next + 1) % self.depth
        out = []
        for i, a in enumerate(arrays):
            if a is None or not isinstance(a, np.ndarray):
                out.append(a)
                continue
            buf = slot[i] if i < len(slot) else None
            if buf is None or buf.shape != a.shape or buf.dtype != a.dtype:
                # only a REBUILD counts as a realloc — the first fill of a
                # slot is the ring's working set, not shape drift, and
                # counting it would put a false-positive baseline under the
                # ledger every reader checks for steady-state zero
                if buf is not None:
                    with _lock:
                        _stats["staging_reallocs"] += 1
                buf = np.empty_like(a)
            np.copyto(buf, a)
            while len(slot) <= i:
                slot.append(None)
            slot[i] = buf
            out.append(buf)
        STAGING_RING_OCCUPANCY.labels().set(
            sum(1 for s in self._slots if s) / self.depth
        )
        return tuple(out)


class FetchTicket:
    """One solve's device→host fetch, split from its dispatch.

    Construction starts async copies on every array (non-blocking);
    ``wait()`` is the completion barrier — idempotent, one batched
    ``device_get``, optionally staged through a HostStagingRing.  The
    overlap record (``hidden_s`` dispatch→barrier, ``exposed_s`` barrier
    block) lands on the ``pipeline.overlap`` span and ``last_overlap()``."""

    __slots__ = ("_arrays", "_host", "_ring", "_label", "_t_dispatch",
                 "_open", "_invalid", "hidden_s", "exposed_s", "planes")

    def __init__(self, arrays: Tuple, ring: Optional[HostStagingRing] = None,
                 label: str = "solve") -> None:
        self._arrays = arrays
        self._host: Optional[Tuple] = None
        self._ring = ring
        self._label = label
        self._t_dispatch = time.perf_counter()
        self._open = True
        self._invalid = False
        self.hidden_s = 0.0
        self.exposed_s = 0.0
        # decode's lazy big-plane bundle rides the ticket when the solver
        # attaches one (solver.tpu.begin_fetch) so deferred decodes never
        # re-touch possibly-donated device buffers
        self.planes = None
        with _lock:
            _stats["tickets_open"] += 1
        start_host_copy(arrays)

    def done(self) -> bool:
        return self._host is not None

    @property
    def staged(self) -> bool:
        return self._ring is not None

    def _close(self) -> None:
        if self._open:
            self._open = False
            with _lock:
                _stats["tickets_open"] -= 1

    def invalidate(self) -> None:
        """Cancel the ticket after a failed/abandoned barrier: drop the
        TICKET's device refs and retire it from the open ledger; a later
        wait() raises rather than touching the device again.  (A genuinely
        hung ``device_get`` still pins the arrays from its own stuck frame
        until it ever returns — the watchdog drops every reference it
        controls, but the abandoned call's are the call's own.)"""
        self._arrays = ()
        self.planes = None
        self._invalid = True
        self._close()

    def wait(self) -> Tuple:
        if self._invalid:
            raise RuntimeError(
                f"FetchTicket({self._label}) was invalidated after a "
                "watchdog timeout; its tick re-anchors instead"
            )
        if self._host is None:
            import jax

            from karpenter_core_tpu.utils import watchdog

            t_block = time.perf_counter()
            # the completion barrier is the hot path's most likely hang
            # point (a dead relay wedges the device→host copy silently):
            # bounded by the watchdog, keyed per ticket label so solve and
            # decode fetches budget separately
            host = watchdog.run(
                "pipeline.fetch", jax.device_get, self._arrays,
                key=self._label,
            )
            t_end = time.perf_counter()
            if self._ring is not None:
                host = self._ring.stage(tuple(host))
            self._host = tuple(host)
            # drop the device refs: a retained zero-copy view would pin the
            # buffers and silently block the next tick's donation
            self._arrays = ()
            self._close()
            self.hidden_s = max(t_block - self._t_dispatch, 0.0)
            self.exposed_s = max(t_end - t_block, 0.0)
            with _lock:
                _last_overlap["hidden_s"] = self.hidden_s
                _last_overlap["exposed_s"] = self.exposed_s
            total = self.hidden_s + self.exposed_s
            PIPELINE_OVERLAP_RATIO.labels(self._label).set(
                self.hidden_s / total if total > 0 else 0.0
            )
            with tracing.span(
                "pipeline.overlap", label=self._label,
                hidden_s=round(self.hidden_s, 6),
                exposed_s=round(self.exposed_s, 6),
                staged=self._ring is not None,
            ):
                pass
        return self._host


class SolvePipeline:
    """Depth-N ring driver for a deferred tick loop.

    ``submit(dispatch)`` calls ``dispatch()`` (which must return a handle
    with a ``result()`` method — e.g. solver.incremental.PendingResults),
    enqueues it, and once the ring holds ``depth - 1`` in-flight handles
    retires the OLDEST by calling its ``result()`` — so tick k's host
    materialize runs after tick k+1's dispatch, overlapped with its device
    compute.  ``drain()`` retires everything left.  A ``dispatch()`` that
    raises enqueues nothing; previously dispatched handles stay consumable
    via ``drain()`` — a mid-pipeline fault cannot wedge a ring slot."""

    def __init__(self, depth: Optional[int] = None) -> None:
        self.depth = depth or pipeline_depth()
        self._inflight: deque = deque()

    def submit(self, dispatch: Callable[[], object]):
        """Returns the oldest in-flight tick's results, or None while the
        ring is filling."""
        handle = dispatch()
        self._inflight.append(handle)
        if len(self._inflight) >= self.depth:
            return self._inflight.popleft().result()
        return None

    def drain(self) -> List[object]:
        out = []
        while self._inflight:
            out.append(self._inflight.popleft().result())
        return out

    def __len__(self) -> int:
        return len(self._inflight)


__all__ = [
    "FetchTicket",
    "HostStagingRing",
    "PIPELINE_OVERLAP_RATIO",
    "STAGING_RING_OCCUPANCY",
    "SolvePipeline",
    "backend_supports_donation",
    "donation_enabled",
    "fetch_tree",
    "last_overlap",
    "pipeline_depth",
    "pipeline_enabled",
    "record_donation",
    "record_donation_canceled",
    "reset_stats",
    "start_host_copy",
    "stats",
]
