"""Cold-start elimination: persistent compile + export caches.

The solve kernel's first run in a process pays trace + lower + XLA compile
(~23 s at the 50k-pod shapes — 2× the max batch window).  Two disk caches cut
a process restart to a few seconds:

  - the XLA persistent compilation cache (jax_compilation_cache_dir) reuses
    the compiled executable across processes
  - an exported-StableHLO cache skips the Python trace + MLIR lowering
    entirely: the first boot serializes the jitted program (jax.export),
    restarts deserialize it (~10 ms) and go straight to the (cached) compile

Entries key on the input shapes/dtypes, the kernel's static config, the
backend platform, and a hash of ops/solve.py — editing the kernel invalidates
automatically.  Every path falls back to the plain jit on any cache error.

The reference has no cold-start problem to mirror (Go compiles ahead of
time); parity demands ours be operationally invisible (VERDICT r1 #6).

Note: loading XLA:CPU persistent-cache entries written by another process
logs a machine-feature-mismatch warning (cpu_aot_loader.cc) even on the same
host — cosmetic there, but don't share a cache directory across heterogeneous
CPU hosts.  The TPU path (the production target) has no such constraint.
"""

from __future__ import annotations

import functools
import hashlib
import logging
import os
import threading
from typing import Dict, Optional

log = logging.getLogger(__name__)

_lock = threading.Lock()
_enabled = False
_registered = False
_memo: Dict[tuple, object] = {}
_in_flight: Dict[tuple, threading.Event] = {}
# in-process executable reuse accounting: "builds" counts solve_callable
# misses that had to lower+compile (even if the disk caches made it cheap),
# "memo_hits" counts solves served by an already-built executable.  The
# steady-state contract (tests/test_compile_reuse.py) is builds==constant
# across varied reconcile batches within the same shape buckets.
_stats = {"builds": 0, "memo_hits": 0}

# -- batch-occupancy / padding accounting -------------------------------------
#
# The shape-bucket padding (ops/solve.pad_planes) and the coalesced tenant
# batching both trade wasted rows for executable reuse; this ledger makes the
# trade measurable per (bucket, mesh): real class rows the snapshot shipped
# vs padded rows the kernel ran, and a padded-work proxy in "flops"
# (wasted rows × slots × passes × tenants — a relative yardstick for fusion
# tuning, not a hardware FLOP count).  Exported on /metrics and surfaced as
# ``detail.batch_occupancy`` by bench.py's tenant line.
from karpenter_core_tpu.metrics import REGISTRY

BATCH_OCCUPANCY = REGISTRY.gauge(
    "karpenter_batch_occupancy_ratio",
    "Real rows / padded rows of the latest solve dispatch, by shape bucket "
    "(padded class-row count) and mesh topology.",
    ("bucket", "mesh"),
)
PADDED_FLOPS = REGISTRY.counter(
    "karpenter_padded_flops_total",
    "Padded-row work dispatched to the kernel (wasted rows x slots x passes "
    "x tenants; a relative padding-cost proxy, not hardware FLOPs), by shape "
    "bucket and mesh topology.",
    ("bucket", "mesh"),
)

_occupancy: Dict[tuple, dict] = {}


def record_batch_occupancy(real_rows, padded_rows, n_slots, n_passes=1,
                           mesh_axes=None, tenants=1) -> None:
    """Record one dispatch's real-vs-padded class rows (one call per device
    dispatch — never on the traced hot path inside an executable).
    ``real_rows`` is per batch element (a float mean for coalesced batches);
    ``tenants`` scales the cumulative row/flops ledger."""
    real_rows = float(real_rows)
    padded_rows = max(int(padded_rows), 1)
    tenants = max(int(tenants), 1)
    bucket = str(padded_rows)
    mesh = repr(tuple(mesh_axes)) if mesh_axes else "none"
    ratio = min(real_rows / padded_rows, 1.0)
    wasted = max(padded_rows - real_rows, 0.0) * int(n_slots) * max(int(n_passes), 1) * tenants
    BATCH_OCCUPANCY.labels(bucket, mesh).set(ratio)
    PADDED_FLOPS.labels(bucket, mesh).inc(float(wasted))
    with _lock:
        entry = _occupancy.setdefault(
            (bucket, mesh),
            {"dispatches": 0, "real_rows": 0.0, "padded_rows": 0,
             "padded_flops": 0.0, "tenant_rows": 0},
        )
        entry["dispatches"] += 1
        entry["real_rows"] += real_rows * tenants
        entry["padded_rows"] += padded_rows * tenants
        entry["tenant_rows"] += tenants
        entry["padded_flops"] += float(wasted)


def occupancy_stats() -> Dict[str, dict]:
    """Cumulative per-(bucket, mesh) occupancy: ``{"<bucket>|<mesh>":
    {dispatches, real_rows, padded_rows, occupancy_ratio, padded_flops}}``."""
    with _lock:
        snapshot = {k: dict(v) for k, v in _occupancy.items()}
    out: Dict[str, dict] = {}
    for (bucket, mesh), entry in snapshot.items():
        entry["occupancy_ratio"] = (
            entry["real_rows"] / entry["padded_rows"]
            if entry["padded_rows"] else 0.0
        )
        out[f"{bucket}|{mesh}"] = entry
    return out


def reset_occupancy() -> None:
    with _lock:
        _occupancy.clear()


_slots_seen: set = set()

# feature-set hysteresis (mirror of _slots_seen): the SnapshotFeatures static
# flags key distinct trace variants, so naive keying would recompile whenever
# one reconcile batch happens to drop a constraint family the previous batch
# used.  Widening a requested set to an already-built superset executable is
# always sound (ops/solve.SnapshotFeatures docstring: enabled-but-unused
# phase families are runtime no-ops), so snap_features reuses the smallest
# covering variant and caps the variant count — past the cap every new set
# widens to all-on, bounding compilation at MAX_FEATURE_VARIANTS + 1
# executables per shape bucket (tests/test_compilecache.py asserts this).
_features_seen: set = set()
MAX_FEATURE_VARIANTS = 8


def snap_features(features):
    """Stabilize the solve's static feature set across nearby batches."""
    from karpenter_core_tpu.ops.solve import ALL_FEATURES, SnapshotFeatures

    if features is None:
        return ALL_FEATURES
    f = SnapshotFeatures(*features).canonical()
    with _lock:
        if f in _features_seen:
            return f
        covering = [g for g in _features_seen if g.covers(f)]
        if covering:
            # fewest extra flags = least superfluous traced work
            return min(covering, key=lambda g: (sum(g), tuple(g)))
        if len(_features_seen) >= MAX_FEATURE_VARIANTS:
            _features_seen.add(ALL_FEATURES)
            return ALL_FEATURES
        _features_seen.add(f)
        return f


def snap_slots(estimate: int, max_waste: int = 4) -> int:
    """Stabilize the solve's static slot count across nearby batches.

    n_slots is a compile-time constant; a batch whose estimate lands just past
    a power-of-two boundary would recompile even though an already-built
    executable has room.  Reuse the smallest previously-used slot count that
    covers the estimate within ``max_waste``x (slots cost solve compute, so
    unbounded reuse would trade a compile for a permanently slower solve)."""
    with _lock:
        covering = [s for s in _slots_seen if estimate <= s <= max_waste * estimate]
        if covering:
            return min(covering)
        _slots_seen.add(estimate)
        return estimate


def stats() -> Dict[str, int]:
    with _lock:
        return dict(_stats)


def reset_stats() -> None:
    with _lock:
        _stats.update(builds=0, memo_hits=0)


def reset_memo() -> None:
    """Simulate a process restart for tests: clear the executable memo AND the
    slot-count/feature-set hysteresis — a stale _slots_seen entry with no
    backing executable would snap later solves to a permanently oversized
    shape (and a stale feature set to a permanently wider trace)."""
    with _lock:
        _memo.clear()
        _slots_seen.clear()
        _features_seen.clear()
        _stats.update(builds=0, memo_hits=0)


def cache_dir() -> str:
    return os.environ.get(
        "KC_TPU_COMPILE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "karpenter_core_tpu"),
    )


def _machine_tag() -> str:
    """Fingerprint of the host's CPU capability set.  XLA:CPU executables in
    the persistent cache are AOT-compiled for the build machine's features;
    loading one on a different microarchitecture logs cpu_aot_loader errors
    and risks SIGILL or gather/scatter-averse code generated for the other
    machine.  The XLA cache is therefore segmented per machine tag, while the
    exported-StableHLO cache stays shared (StableHLO is portable)."""
    import platform as platform_mod

    basis = platform_mod.machine()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                # x86 spells it "flags", aarch64 "Features"
                if line.startswith(("flags", "Features")):
                    basis += line
                    break
    except OSError:
        pass
    return hashlib.sha256(basis.encode()).hexdigest()[:10]


def enable() -> None:
    """Idempotently turn on the persistent XLA compilation cache and register
    the kernel pytree types for jax.export serialization."""
    global _enabled, _registered
    import jax

    with _lock:
        if not _enabled:
            # The XLA persistent cache's CPU executable serialize/deserialize
            # path segfaults intermittently at suite scale (observed three
            # ways in one day: put_executable_and_time, get_executable_and
            # _time, and compile_or_get_cached — jaxlib's own cpu_aot_loader
            # warns its AOT results may SIGILL).  The executable cache is a
            # cold-start optimization for the RELAY-bound TPU backend; on CPU
            # the exported-StableHLO cache below already skips tracing, so
            # the crash risk buys little — leave it off unless forced
            # (KC_TPU_XLA_CACHE=1 forces on, =0 forces off on any backend).
            # Platform read from config/env, NOT jax.default_backend(): that
            # call would initialize the backend eagerly inside Operator.start
            # (multi-second TPU bring-up on the startup critical path, even
            # for remote-solve-only replicas that never solve locally).
            # ...and an EMPTY platform (auto-detection) counts as CPU: the
            # unpinned case is exactly the dev/CI box this guard protects,
            # while every deployed accelerator path names its platform
            # (JAX_PLATFORMS=axon/tpu in the env or a pinned config).
            forced = os.environ.get("KC_TPU_XLA_CACHE")
            platform = jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS", "")
            use_xla_cache = (
                forced != "0" if forced is not None
                else bool(platform) and not platform.startswith("cpu")
            )
            if use_xla_cache:
                directory = os.path.join(cache_dir(), f"xla-{_machine_tag()}")
                os.makedirs(directory, exist_ok=True)
                jax.config.update("jax_compilation_cache_dir", directory)
                # persist even fast compiles: over the axon relay a "fast"
                # compile still costs a round trip, and helpers add up
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0.0
                )
            _enabled = True
        if not _registered:
            from karpenter_core_tpu.ops import masks as mask_ops
            from karpenter_core_tpu.ops import solve as solve_ops

            for t in (
                solve_ops.ClassTensors,
                solve_ops.Statics,
                solve_ops.StaticArrays,
                solve_ops.NodeState,
                solve_ops.ExistingState,
                solve_ops.ExistingStatic,
                solve_ops.SolveOutputs,
                solve_ops.TopoCounts,
                solve_ops.WarmCarry,
                solve_ops.RepairPlan,
                mask_ops.ReqTensor,
            ):
                try:
                    jax.export.register_namedtuple_serialization(
                        t, serialized_name=f"kc.{t.__name__}"
                    )
                except ValueError:
                    pass  # already registered
                except AttributeError:
                    # this jax predates jax.export: solve_callable degrades to
                    # the plain jit on its own, but enable() is also called
                    # from operator.start()/bench bring-up and must not crash
                    # the process over a missing cache backend
                    log.warning(
                        "jax.export unavailable; persistent export cache disabled"
                    )
                    break
            _registered = True


_kernel_hash: Optional[str] = None


def _kernel_src_hash() -> str:
    global _kernel_hash
    if _kernel_hash is None:
        # every module traced into solve_core must invalidate the cache
        from karpenter_core_tpu.ops import masks as mask_ops
        from karpenter_core_tpu.ops import solve as solve_ops

        digest = hashlib.sha256()
        for module in (solve_ops, mask_ops):
            with open(module.__file__, "rb") as f:
                digest.update(f.read())
        _kernel_hash = digest.hexdigest()[:16]
    return _kernel_hash


_relax_hash: Optional[str] = None


def _relax_src_hash() -> str:
    global _relax_hash
    if _relax_hash is None:
        # relax_core traces solve.py helpers (_it_intersects/_capacity) and
        # masks.py, so all three modules invalidate the relax memo
        from karpenter_core_tpu.ops import masks as mask_ops
        from karpenter_core_tpu.ops import solve as solve_ops
        from karpenter_core_tpu.relax import kernel as relax_kernel

        digest = hashlib.sha256()
        for module in (relax_kernel, solve_ops, mask_ops):
            with open(module.__file__, "rb") as f:
                digest.update(f.read())
        _relax_hash = digest.hexdigest()[:16]
    return _relax_hash


def _leaf_sig(tree) -> tuple:
    import jax

    return tuple(
        (str(getattr(leaf, "dtype", type(leaf))), tuple(getattr(leaf, "shape", ())))
        for leaf in jax.tree_util.tree_leaves(tree)
    )


def solve_callable(
    cls,
    statics_arrays,
    n_slots: int,
    key_has_bounds,
    ex_state=None,
    ex_static=None,
    n_passes: int = 1,
    features=None,
    fuse_zones: bool = True,
    packed_masks: bool = True,
    warm_carry=None,
    repair_plan=None,
    mesh_axes=None,
    donate_carry: bool = False,
):
    """An AOT-compiled solve callable served through the export cache, or None
    when export-caching is unavailable (callers fall back to the plain jit).

    ``donate_carry`` selects the buffer-donating twin of the warm variant
    (utils.pipeline, docs/KERNEL_PERF.md "Layer 7"): the ``warm_carry``
    argument is donated so a steady-state churn repair reuses the carry's
    device memory in place instead of reallocating the full-width planes
    every tick.  It is part of the cache key — the donating and plain
    executables never share a memo slot — and only meaningful with
    ``warm_carry``.  Donating variants skip the exported-StableHLO disk
    cache (donation is a property of the lowering, not the exported module);
    the in-process memo and XLA's persistent cache still cover them.

    ``mesh_axes`` (hashable topology descriptor, e.g. ``(("catalog", 8),)``
    from parallel.mesh.solve_mesh_axes) selects the SHARDED variant: the
    same solve built as a ``shard_map`` over that mesh with the catalog axis
    partitioned (docs/KERNEL_PERF.md "Layer 5").  The topology is part of the
    cache key — one warm executable per mesh shape — and the degenerate
    1-device topology is its own key too, so flipping KC_SOLVER_MESH never
    silently reuses an executable built for another layout.  Mesh variants
    skip the exported-StableHLO disk cache (a shard_map program embeds its
    mesh; the XLA persistent cache still covers the compile).

    The returned callable takes (cls, statics_arrays[, ex_state, ex_static])
    — or (cls, statics_arrays, ex_static, warm_carry) for the warm-start
    repair variant — matching how it was built; it is memoized in-process so
    warm calls reuse the already-compiled executable.  Inputs may be host
    (numpy) or device pytrees — only shapes/dtypes matter, so callers can
    overlap the device upload with this compile (the relay makes both
    seconds-long).  A warm carry keys its own ``delta`` executable variant
    (``has_warm`` + the carry's leaf signature): the repair program resumes
    the scan from the carry instead of empty slots, and because
    solver.incremental reuses the previous padded tensors verbatim the repair
    shape is FIXED across reconciles — one delta executable stays warm for
    the whole churn regime (docs/INCREMENTAL.md)."""
    import jax

    try:
        enable()
        has_ex = ex_state is not None
        has_warm = warm_carry is not None
        has_repair = repair_plan is not None
        donate_carry = bool(donate_carry) and has_warm
        features = snap_features(features)
        key = (
            _kernel_src_hash(),
            jax.default_backend(),
            n_slots,
            tuple(key_has_bounds),
            n_passes,
            tuple(features),
            fuse_zones,
            packed_masks,
            has_ex,
            has_warm,
            donate_carry,
            mesh_axes,
            _leaf_sig(cls),
            _leaf_sig(statics_arrays),
            _leaf_sig(ex_state) if has_ex else None,
            _leaf_sig(ex_static) if (has_ex or has_warm) else None,
            _leaf_sig(warm_carry) if has_warm else None,
            _leaf_sig(repair_plan) if has_repair else None,
        )
        # in-flight dedup: the warmup thread and the first real batch race to
        # build the same key; the loser waits on the winner's build instead of
        # lowering+compiling the identical program twice
        while True:
            with _lock:
                fn = _memo.get(key)
                if fn is not None:
                    _stats["memo_hits"] += 1
                    return fn
                building = _in_flight.get(key)
                if building is None:
                    building = _in_flight[key] = threading.Event()
                    break  # this thread builds
            building.wait(timeout=600.0)

        try:
            return _build_and_memo(key, cls, statics_arrays, n_slots,
                                   key_has_bounds, ex_state, ex_static, n_passes,
                                   features, fuse_zones, packed_masks, warm_carry,
                                   repair_plan, mesh_axes, donate_carry)
        finally:
            with _lock:
                _in_flight.pop(key, None)
            building.set()
    except Exception as e:  # noqa: BLE001 - never break the solve path
        log.warning("export cache unavailable (%s), using plain jit", e)
        return None


def _base_solve_fn(has_warm, has_ex, n_slots, key_has_bounds, n_passes,
                   features, fuse_zones, packed_masks, catalog_axis=None):
    """The positional-signature solve body for one variant: (cls, statics[,
    ...]) matching how callers invoke the memoized executable.
    ``catalog_axis`` threads the mesh axis name into solve_core's exact
    cross-shard collectives (the shard_map build passes it; every other
    build leaves it None — same code, no collectives traced)."""
    from karpenter_core_tpu.ops import solve as solve_ops

    if has_warm:
        # the delta variant: ex_state rides inside the carry; ex_static is
        # passed separately because its tol/vol rows are per-class
        return lambda c, s, exst, w, rp: solve_ops.solve_core(
            c, s, n_slots, key_has_bounds, None, exst, n_passes=n_passes,
            features=features, fuse_zones=fuse_zones,
            packed_masks=packed_masks, warm_carry=w, repair_plan=rp,
            catalog_axis=catalog_axis,
        )
    if has_ex:
        return lambda c, s, exs, exst: solve_ops.solve_core(
            c, s, n_slots, key_has_bounds, exs, exst, n_passes=n_passes,
            features=features, fuse_zones=fuse_zones,
            packed_masks=packed_masks, catalog_axis=catalog_axis,
        )
    return lambda c, s: solve_ops.solve_core(
        c, s, n_slots, key_has_bounds, n_passes=n_passes,
        features=features, fuse_zones=fuse_zones, packed_masks=packed_masks,
        catalog_axis=catalog_axis,
    )


def _build_and_memo(key, cls, statics_arrays, n_slots, key_has_bounds,
                    ex_state, ex_static, n_passes, features=None,
                    fuse_zones=True, packed_masks=True, warm_carry=None,
                    repair_plan=None, mesh_axes=None, donate_carry=False):
    """Build one executable for ``key``: export-cache load (or trace+export),
    then AOT compile, then memoize.  Callers hold the key's in-flight slot.
    Mesh variants (``mesh_axes``) build jit(shard_map(...)) instead and skip
    the export cache — the memo (and XLA's persistent cache) keep them warm.
    ``donate_carry`` variants (warm only) also skip the export cache and
    build the jit with the warm-carry argument donated (position 3 of the
    warm signature ``(cls, statics, ex_static, warm_carry, repair_plan)``)."""
    import jax

    has_ex = ex_state is not None
    has_warm = warm_carry is not None
    # the warm signature's donated argument index (see _base_solve_fn)
    donate_argnums = (3,) if (donate_carry and has_warm) else ()
    digest = hashlib.sha256(repr(key).encode()).hexdigest()[:24]
    path = os.path.join(cache_dir(), f"solve-{digest}.stablehlo")
    if has_warm:
        struct_args = (cls, statics_arrays, ex_static, warm_carry, repair_plan)
    elif has_ex:
        struct_args = (cls, statics_arrays, ex_state, ex_static)
    else:
        struct_args = (cls, statics_arrays)
    structs = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), struct_args
    )
    if mesh_axes is not None:
        from karpenter_core_tpu.parallel import mesh as mesh_mod

        base_axis = _base_solve_fn(
            has_warm, has_ex, n_slots, key_has_bounds, n_passes, features,
            fuse_zones, packed_masks, catalog_axis=mesh_axes[0][0],
        )
        base_plain = _base_solve_fn(
            has_warm, has_ex, n_slots, key_has_bounds, n_passes, features,
            fuse_zones, packed_masks,
        )
        fn = mesh_mod.sharded_solve_callable(
            mesh_axes, base_axis, base_plain, structs,
            donate_argnums=donate_argnums,
        )
        with _lock:
            _memo[key] = fn
            _stats["builds"] += 1
        return fn
    if donate_argnums:
        # donation is a lowering property, not part of an exported StableHLO
        # module — build the donating jit directly and AOT-compile it; the
        # memo + XLA persistent cache keep it warm
        base = jax.jit(_base_solve_fn(
            has_warm, has_ex, n_slots, key_has_bounds, n_passes, features,
            fuse_zones, packed_masks,
        ), donate_argnums=donate_argnums)
        compiled = base.lower(*structs).compile()
        with _lock:
            _memo[key] = compiled
            _stats["builds"] += 1
        return compiled
    fn = None
    if os.path.exists(path):
        try:
            with open(path, "rb") as f:
                exported = jax.export.deserialize(f.read())
            fn = jax.jit(exported.call)
        except Exception as e:  # noqa: BLE001 - stale/corrupt entry
            log.warning("export cache load failed (%s), re-exporting", e)
            fn = None
    if fn is None:
        base = jax.jit(_base_solve_fn(
            has_warm, has_ex, n_slots, key_has_bounds, n_passes, features,
            fuse_zones, packed_masks,
        ))
        exported = jax.export.export(base)(*structs)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(exported.serialize())
        os.replace(tmp, path)
        fn = jax.jit(exported.call)
    # AOT-compile from shape structs so no device data is needed — callers
    # overlap the (slow, relay-bound) input upload with this compile
    compiled = fn.lower(*structs).compile()
    with _lock:
        _memo[key] = compiled
        _stats["builds"] += 1
    return compiled


def relax_callable(
    cls,
    statics_arrays,
    pol,
    n_slots: int,
    key_has_bounds,
    packed_masks: bool = True,
    mesh_axes=None,
):
    """The relax-family executable (karpenter_core_tpu/relax): the
    module-level ``relax/kernel._relax_jit`` partially applied with its static
    config, memoized in ``_memo`` under a ``"relax"``-prefixed key exactly
    like every scan variant, so the compile-reuse ledger (builds/memo_hits)
    and reset_memo cover both families uniformly.

    No new jit is constructed here — ``_relax_jit`` is a single module-level
    ``functools.partial(jax.jit, static_argnames=...)`` wrap (the same idiom
    as ``ops.solve._solve_jit``), so the retrace-budget analyzer's
    uncached-jit and static-args cross-checks see one cached entry and zero
    new baseline rows.  ``mesh_axes`` is key-only: the relax program is a
    plain jit whose inputs arrive sharded (GSPMD propagates the catalog
    partition), so topology changes re-key without rebuilding the wrapper.
    The exported-StableHLO disk cache is not used (the while_loop program
    traces in milliseconds at these shapes — the memo and XLA's persistent
    cache are enough)."""
    import jax

    from karpenter_core_tpu.relax import kernel as relax_kernel

    key = (
        "relax",
        _relax_src_hash(),
        jax.default_backend(),
        n_slots,
        tuple(key_has_bounds),
        packed_masks,
        mesh_axes,
        _leaf_sig(cls),
        _leaf_sig(statics_arrays),
        _leaf_sig(pol),
    )
    with _lock:
        fn = _memo.get(key)
        if fn is not None:
            _stats["memo_hits"] += 1
            return fn
    fn = functools.partial(
        relax_kernel._relax_jit,
        n_slots=int(n_slots),
        key_has_bounds=tuple(key_has_bounds),
        packed_masks=bool(packed_masks),
    )
    with _lock:
        _memo[key] = fn
        _stats["builds"] += 1
    return fn


def batched_solve_callable(
    n_tenants: int,
    cls,
    statics_arrays,
    n_slots: int,
    key_has_bounds,
    ex_state=None,
    ex_static=None,
    n_passes: int = 1,
    features=None,
    mesh_axes=None,
    warm_carry=None,
    repair_plan=None,
):
    """The coalesced multi-tenant executable: ``vmap`` of the solve body over
    a leading tenant axis (service/tenant.py stacks N compatible-bucket
    tenants' planes and unstacks the outputs).  Memoized in ``_memo`` like
    every other variant, keyed on the batch size + the per-tenant bucket
    signature, so steady coalescing reuses ONE batched executable per
    (bucket, N).  ``cls``/``statics_arrays``/``ex_*``/``warm_carry``/
    ``repair_plan`` are ONE tenant's (unstacked) pytrees — only shapes/dtypes
    matter.  ``mesh_axes`` (parallel.mesh.tenant_mesh_axes) selects the
    sharded twin: the same vmap body under a shard_map that splits the tenant
    axis across devices.

    ``warm_carry`` selects the fused-REPAIR variant (the vmapped twin of the
    solo delta executable): the positional signature becomes ``(cls, statics,
    ex_static, warm_carry, repair_plan)`` with a leading tenant axis on every
    leaf, exactly mirroring the solo ``delta`` variant key above —
    ``n_slots`` is then the (shared) repair-window width.  Fused repairs
    never donate: member carries are stacked copies, and the per-tenant
    output slices must stay readable after the dispatch.

    Per-element semantics are the solo program's exactly — the coalesced
    parity suite pins every co-batched tenant's outputs bit-identical to its
    solo solve (tests/test_tenant_service.py)."""
    import jax

    fuse_zones, packed_masks = kernel_flags()
    features = snap_features(features)
    has_warm = warm_carry is not None
    has_ex = ex_state is not None and not has_warm
    key = (
        "tenant-batch-repair" if has_warm else "tenant-batch",
        int(n_tenants),
        _kernel_src_hash(),
        jax.default_backend(),
        n_slots,
        tuple(key_has_bounds),
        n_passes,
        tuple(features),
        fuse_zones,
        packed_masks,
        has_ex,
        mesh_axes,
        _leaf_sig(cls),
        _leaf_sig(statics_arrays),
        _leaf_sig(ex_state) if has_ex else None,
        _leaf_sig(ex_static) if (has_ex or has_warm) else None,
        _leaf_sig(warm_carry) if has_warm else None,
        _leaf_sig(repair_plan) if has_warm else None,
    )
    with _lock:
        fn = _memo.get(key)
        if fn is not None:
            _stats["memo_hits"] += 1
            return fn
    base = _base_solve_fn(
        has_warm, has_ex, n_slots, key_has_bounds, n_passes, features,
        fuse_zones, packed_masks,
    )
    if has_warm:
        solo_args = (cls, statics_arrays, ex_static, warm_carry, repair_plan)
    elif has_ex:
        solo_args = (cls, statics_arrays, ex_state, ex_static)
    else:
        solo_args = (cls, statics_arrays)
    structs = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct((int(n_tenants),) + tuple(a.shape), a.dtype),
        solo_args,
    )
    if mesh_axes is not None:
        from karpenter_core_tpu.parallel import mesh as mesh_mod

        fn = mesh_mod.tenant_solve_callable(mesh_axes, base, structs)
    else:
        fn = jax.jit(jax.vmap(base))
    with _lock:
        _memo[key] = fn
        _stats["builds"] += 1
    return fn


def kernel_flags():
    """(fuse_zones, packed_masks) process defaults: both on, individually
    disengageable for triage via KC_KERNEL_FUSE_ZONES=0 /
    KC_KERNEL_PACKED_MASKS=0 (docs/KERNEL_PERF.md)."""
    return (
        os.environ.get("KC_KERNEL_FUSE_ZONES", "1") != "0",
        os.environ.get("KC_KERNEL_PACKED_MASKS", "1") != "0",
    )


def resolve_mesh_axes(mesh_axes, statics_arrays):
    """Resolve run_solve's ``mesh_axes`` argument to a concrete topology or
    None.  ``"auto"`` consults parallel.mesh.solve_mesh_axes (KC_SOLVER_MESH
    env + device count); any topology whose catalog axis does not divide the
    instance-type extent falls back to the unsharded path — production
    snapshots are encoded shard-aligned (models.snapshot), the guard covers
    planes prepared outside that path."""
    if mesh_axes == "auto":
        from karpenter_core_tpu.parallel import mesh as mesh_mod

        mesh_axes = mesh_mod.solve_mesh_axes()
    if mesh_axes is None:
        return None
    n_it = int(statics_arrays.it_alloc.shape[0])
    axis_size = int(mesh_axes[0][1])
    if axis_size < 1 or n_it % axis_size != 0:
        log.debug(
            "mesh dispatch skipped: catalog extent %d not a multiple of the "
            "mesh axis %r", n_it, mesh_axes,
        )
        return None
    return tuple(mesh_axes)


def run_solve(
    cls,
    statics_arrays,
    n_slots: int,
    key_has_bounds,
    ex_state=None,
    ex_static=None,
    n_passes: int = 1,
    features=None,
    warm_carry=None,
    repair_plan=None,
    pre_padded: bool = False,
    mesh_axes="auto",
    donate_carry="auto",
):
    """Solve through the export cache, falling back to the plain jit.

    Inputs may be host (numpy) pytrees — from ops.solve.prepare_host — or
    device arrays; the device upload runs on a worker thread overlapped with
    the (cache-served) compile, since both are seconds-long over the relay and
    independent.  ``features`` is the snapshot's SnapshotFeatures phase plan
    (None = all-on); it may be silently widened to a previously-built
    superset executable (snap_features).

    ``warm_carry`` selects the warm-start repair variant (solve_callable
    docstring); ``pre_padded`` skips the bucket padding for callers that
    already hold padded planes — mandatory with a warm carry, whose device
    arrays must not round-trip through numpy padding (pad_planes would force
    a device→host sync on them).

    ``mesh_axes`` routes the solve through the sharded shard_map dispatcher
    (parallel.mesh): a topology descriptor, None for the unsharded path, or
    ``"auto"`` (the default — KC_SOLVER_MESH env / device count decide, so
    every production entry point inherits the sharded path without threading
    anything).  The sharded solve is bit-identical to the unsharded one
    (docs/KERNEL_PERF.md "Layer 5").

    ``donate_carry``: ``"auto"`` (default) donates the warm carry's device
    buffers whenever the pipeline is armed (utils.pipeline.donation_enabled
    — KC_PIPELINE=0 switches it off, and backends that ignore donation skip
    it); True/False force.  Donation never changes results — only whether
    the repair reuses the carry's device memory in place.  The caller must
    not read the passed ``warm_carry`` after this call when donation is
    possible (the ``donated-read`` kcanalyze rule, docs/ANALYSIS.md)."""
    from concurrent.futures import ThreadPoolExecutor

    import jax

    from karpenter_core_tpu import tracing
    from karpenter_core_tpu.ops import solve as solve_ops
    from karpenter_core_tpu.utils import pipeline as pipeline_mod

    fuse_zones, packed_masks = kernel_flags()
    features = snap_features(features)
    mesh_axes = resolve_mesh_axes(mesh_axes, statics_arrays)
    if donate_carry == "auto":
        donate_carry = pipeline_mod.donation_enabled()
    donate_carry = bool(donate_carry) and warm_carry is not None
    # "dispatch" covers pad + upload + executable lookup + async kernel launch;
    # the separate "solve" span blocks on the outputs (tracing only) so device
    # compute is attributed to the solve, not to whichever span first touches
    # the result — the JAX-aware boundary docs/OBSERVABILITY.md describes.
    with tracing.span("dispatch", n_slots=n_slots, n_passes=n_passes,
                      warm=warm_carry is not None,
                      mesh=repr(mesh_axes) if mesh_axes else None):
        if (
            not pre_padded
            and warm_carry is None
            and os.environ.get("KC_TPU_SHAPE_BUCKETS", "1") != "0"
        ):
            real_rows = int(cls.count.shape[0])
            cls, statics_arrays, key_has_bounds, ex_state, ex_static = solve_ops.pad_planes(
                cls, statics_arrays, key_has_bounds, ex_state, ex_static
            )
            record_batch_occupancy(
                real_rows, int(cls.count.shape[0]), n_slots,
                n_passes=n_passes, mesh_axes=mesh_axes,
            )

        def _upload(tree):
            if mesh_axes is None:
                return jax.device_put(tree)
            from karpenter_core_tpu.parallel import mesh as mesh_mod

            return jax.device_put(
                tree,
                mesh_mod.mesh_shardings(tree, mesh_mod.mesh_for(mesh_axes)),
            )

        with ThreadPoolExecutor(max_workers=1) as pool:
            upload = pool.submit(_upload, (cls, statics_arrays, ex_state, ex_static))
            fn = solve_callable(
                cls, statics_arrays, n_slots, key_has_bounds, ex_state, ex_static,
                n_passes, features, fuse_zones, packed_masks, warm_carry,
                repair_plan, mesh_axes, donate_carry,
            )
            cls, statics_arrays, ex_state, ex_static = upload.result()
        if fn is None:
            out = solve_ops._solve_jit(
                cls, statics_arrays, n_slots, key_has_bounds, ex_state, ex_static,
                n_passes=n_passes, features=features, fuse_zones=fuse_zones,
                packed_masks=packed_masks, warm_carry=warm_carry,
                repair_plan=repair_plan,
            )
            if warm_carry is not None:
                pipeline_mod.record_donation(False)
        elif warm_carry is not None:
            out = fn(cls, statics_arrays, ex_static, warm_carry, repair_plan)
            # donation effectiveness ledger: a donated buffer is consumed at
            # dispatch; a live host view (or an undonated variant) degrades
            # to a realloc, which bench.pipeline_line surfaces
            probe = getattr(
                getattr(warm_carry, "state", None), "used", None
            )
            pipeline_mod.record_donation(
                donate_carry and bool(getattr(probe, "is_deleted", lambda: False)())
            )
        else:
            out = fn(cls, statics_arrays, ex_state, ex_static) if ex_state is not None else fn(cls, statics_arrays)
    if tracing.enabled():
        with tracing.span("solve", sync=out):
            pass
    return out
