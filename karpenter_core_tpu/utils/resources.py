"""Resource arithmetic over k8s-style quantity strings.

Mirrors the behavior of the reference's resource helpers
(/root/reference/pkg/utils/resources/resources.go:25-165) with a float-based
representation: a ResourceList is ``dict[str, float]``.  Floats are the natural
unit here because the tensor solver consumes resource vectors as float32 arrays;
milli-CPU precision (1e-3) is far above float64 rounding error for realistic
cluster quantities.
"""

from __future__ import annotations

import math
import re
from typing import TYPE_CHECKING, Dict, Iterable, Mapping

if TYPE_CHECKING:  # pragma: no cover - import cycle guard, typing only
    from karpenter_core_tpu.apis.objects import Container, Pod

ResourceList = Dict[str, float]

# Canonical resource names
CPU = "cpu"
MEMORY = "memory"
PODS = "pods"
EPHEMERAL_STORAGE = "ephemeral-storage"

_DECIMAL_SUFFIXES = {
    "n": 1e-9,
    "u": 1e-6,
    "m": 1e-3,
    "": 1.0,
    "k": 1e3,
    "M": 1e6,
    "G": 1e9,
    "T": 1e12,
    "P": 1e15,
    "E": 1e18,
}
_BINARY_SUFFIXES = {
    "Ki": 2**10,
    "Mi": 2**20,
    "Gi": 2**30,
    "Ti": 2**40,
    "Pi": 2**50,
    "Ei": 2**60,
}
_QUANTITY_RE = re.compile(r"^([+-]?[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)([a-zA-Z]*)$")


def parse_quantity(value: "str | int | float") -> float:
    """Parse a k8s quantity ('100m', '1Gi', '2', 1.5) into a float."""
    if isinstance(value, (int, float)):
        return float(value)
    m = _QUANTITY_RE.match(value.strip())
    if not m:
        raise ValueError(f"invalid quantity {value!r}")
    number, suffix = m.groups()
    if suffix in _BINARY_SUFFIXES:
        return float(number) * _BINARY_SUFFIXES[suffix]
    if suffix in _DECIMAL_SUFFIXES:
        return float(number) * _DECIMAL_SUFFIXES[suffix]
    raise ValueError(f"invalid quantity suffix {suffix!r} in {value!r}")


def format_quantity(value: float) -> str:
    """Render a float back into a compact quantity string."""
    if value == 0:
        return "0"
    if abs(value) >= 1 and float(value).is_integer():
        return str(int(value))
    milli = value * 1000
    if milli.is_integer():
        return f"{int(milli)}m"
    return repr(value)


def parse_resource_list(resources: Mapping[str, "str | int | float"]) -> ResourceList:
    return {k: parse_quantity(v) for k, v in resources.items()}


def merge(*resource_lists: Mapping[str, float]) -> ResourceList:
    """Sum resource lists element-wise (resources.go:47 Merge)."""
    result: ResourceList = {}
    for rl in resource_lists:
        for name, qty in rl.items():
            result[name] = result.get(name, 0.0) + qty
    return result


def subtract(lhs: Mapping[str, float], rhs: Mapping[str, float]) -> ResourceList:
    """lhs - rhs over lhs's keys (resources.go:63 Subtract)."""
    return {name: qty - rhs.get(name, 0.0) for name, qty in lhs.items()}


def max_resources(*resource_lists: Mapping[str, float]) -> ResourceList:
    """Element-wise max (resources.go:96 MaxResources)."""
    result: ResourceList = {}
    for rl in resource_lists:
        for name, qty in rl.items():
            if name not in result or qty > result[name]:
                result[name] = qty
    return result


def cmp(lhs: float, rhs: float) -> int:
    """Three-way compare with a relative tolerance absorbing float noise."""
    if math.isclose(lhs, rhs, rel_tol=1e-9, abs_tol=1e-9):
        return 0
    return -1 if lhs < rhs else 1


def fits(candidate: Mapping[str, float], total: Mapping[str, float]) -> bool:
    """True if candidate <= total for every resource (resources.go:152 Fits).

    Resources absent from ``total`` are treated as zero.
    """
    return all(cmp(qty, total.get(name, 0.0)) <= 0 for name, qty in candidate.items())


def _container_requests(container: "Container") -> ResourceList:
    """Limits are merged into requests when no request exists
    (resources.go:119 MergeResourceLimitsIntoRequests)."""
    requests = dict(container.resources.requests)
    for name, qty in container.resources.limits.items():
        requests.setdefault(name, qty)
    return requests


def ceiling(pod: "Pod") -> ResourceList:
    """Max(sum of containers, max of initContainers) (resources.go:81 Ceiling)."""
    requests: ResourceList = {}
    for container in pod.spec.containers:
        requests = merge(requests, _container_requests(container))
    for container in pod.spec.init_containers:
        requests = max_resources(requests, _container_requests(container))
    return requests


def requests_for_pods(*pods: "Pod") -> ResourceList:
    """Total requests of the pods, plus a 'pods' count resource
    (resources.go:26 RequestsForPods)."""
    merged = merge(*(ceiling(p) for p in pods)) if pods else {}
    merged[PODS] = float(len(pods))
    return merged


def limits_for_pods(*pods: "Pod") -> ResourceList:
    limits: ResourceList = {}
    for pod in pods:
        pod_limits: ResourceList = {}
        for container in pod.spec.containers:
            pod_limits = merge(pod_limits, container.resources.limits)
        for container in pod.spec.init_containers:
            pod_limits = max_resources(pod_limits, container.resources.limits)
        limits = merge(limits, pod_limits)
    limits[PODS] = float(len(pods))
    return limits


def is_zero(value: float) -> bool:
    return cmp(value, 0.0) == 0


def union_keys(*resource_lists: Mapping[str, float]) -> Iterable[str]:
    seen = {}
    for rl in resource_lists:
        for name in rl:
            seen.setdefault(name, None)
    return list(seen)
