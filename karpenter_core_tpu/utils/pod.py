"""Pod classification predicates (mirror of /root/reference/pkg/utils/pod/scheduling.go:25-106)."""

from __future__ import annotations

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import (
    POD_FAILED,
    POD_SUCCEEDED,
    Pod,
)


def is_provisionable(pod: Pod) -> bool:
    """Unscheduled, not preempted, and not owned by a node (daemonset-like)."""
    return (
        not is_scheduled(pod)
        and not is_preempting(pod)
        and failed_to_schedule(pod)
        and not is_owned_by_daemon_set(pod)
        and not is_owned_by_node(pod)
    )


def is_scheduled(pod: Pod) -> bool:
    return bool(pod.spec.node_name)


def is_preempting(pod: Pod) -> bool:
    return bool(pod.status.nominated_node_name)


def is_terminal(pod: Pod) -> bool:
    return pod.status.phase in (POD_FAILED, POD_SUCCEEDED)


def is_terminating(pod: Pod) -> bool:
    return pod.metadata.deletion_timestamp is not None


def failed_to_schedule(pod: Pod) -> bool:
    """True if the pod has a PodScheduled=False/Unschedulable condition."""
    for condition in pod.status.conditions:
        if condition.type == "PodScheduled" and condition.reason == "Unschedulable":
            return True
    return False


def is_owned_by_daemon_set(pod: Pod) -> bool:
    return _is_owned_by(pod, "DaemonSet")


def is_owned_by_node(pod: Pod) -> bool:
    return _is_owned_by(pod, "Node")


def _is_owned_by(pod: Pod, kind: str) -> bool:
    return any(ref.kind == kind for ref in pod.metadata.owner_references)


def has_do_not_evict(pod: Pod) -> bool:
    return pod.metadata.annotations.get(labels_api.DO_NOT_EVICT_POD_ANNOTATION_KEY) == "true"


def has_pod_anti_affinity(pod: Pod) -> bool:
    """True if the pod has required pod anti-affinity terms."""
    return (
        pod.spec.affinity is not None
        and pod.spec.affinity.pod_anti_affinity is not None
        and len(pod.spec.affinity.pod_anti_affinity.required) > 0
    )


def has_required_pod_affinity(pod: Pod) -> bool:
    return (
        pod.spec.affinity is not None
        and pod.spec.affinity.pod_affinity is not None
        and len(pod.spec.affinity.pod_affinity.required) > 0
    )


def tolerates_unschedulable_taint(pod: Pod) -> bool:
    from karpenter_core_tpu.apis.objects import Taint

    unschedulable = Taint(key="node.kubernetes.io/unschedulable", effect="NoSchedule")
    return any(t.tolerates_taint(unschedulable) for t in pod.spec.tolerations)


def is_owned_by_static_pod(pod: Pod) -> bool:
    return is_owned_by_node(pod)
