"""The central KC_* environment-flag registry.

Every ``KC_*`` flag read anywhere in the package MUST have a row here and a
row in the docs table (docs/FLAGS.md) — the ``env-flags`` analysis pass
(docs/ANALYSIS.md) enforces both directions: an unregistered read and a
registry row no code reads are both gate failures.  Harness-side flags
(KC_BENCH_*, KC_PERF_GATE_STRICT, KC_CHAOS_* seeds read by tests/tools) are
deliberately out of band: this table is the runtime surface operators tune.

The registry is DATA, parsed by the analysis pass without importing this
module; keep ``FLAGS`` a plain dict literal of ``flag -> one-line effect``.
Code does not need to read flags through this module — reads stay at their
point of use; the table exists so the whole surface is auditable in one
place.
"""

from __future__ import annotations

from typing import Dict

FLAGS: Dict[str, str] = {
    # -- solver kernel + encode ------------------------------------------------
    "KC_BUCKET_QUANTIZE": "pow2 shape-bucket ladder for cross-tenant fusion (padded FLOPs for fewer executables)",
    "KC_ENCODE_DEVICE_FINISH": "force device completion at encode boundaries (A/B pin for the async pipeline)",
    "KC_KERNEL_FUSE_ZONES": "fuse the per-zone kernel phases into one dispatch",
    "KC_KERNEL_PACKED_MASKS": "bit-packed compatibility masks inside the scan kernel",
    "KC_TPU_SHAPE_BUCKETS": "explicit shape-bucket edges for the compile cache (comma-separated pod counts)",
    "KC_TPU_COMPILE_CACHE": "enable/disable the per-(bucket, mesh) executable memo",
    "KC_TPU_XLA_CACHE": "directory for the persistent XLA compilation cache",
    "KC_TPU_KERNEL": "select the operator's solver kernel implementation",
    "KC_TPU_WARMUP": "pre-compile the solver executables at operator startup",
    "KC_SOLVER_MESH": "enable the sharded device-mesh solve path",
    "KC_SOLVER_MESH_DEVICES": "device count override for the solver mesh",
    "KC_SOLVER_MESH_SHAPE": "explicit mesh shape (e.g. '2x4') for the sharded solve",
    "KC_SOLVER_INCREMENTAL": "enable warm-start incremental solve sessions",
    "KC_DELTA_WINDOW": "repair-window width (slots re-opened around churned pods) for delta solves",
    "KC_DELTA_MAX_FRACTION": "churn fraction above which a delta solve falls back to full",
    "KC_DELTA_AUDIT_INTERVAL": "full-solve audit cadence for long delta chains",
    "KC_DEGRADED_MAX_PODS": "pod-count ceiling for the degraded (host fallback) solve path",
    "KC_SOLVER_MODE": "solver family routing: scan | relax | auto (PolicyConfig/provisioner spec wins over env)",
    "KC_RELAX_MAX_ITERS": "projected-gradient iteration cap for the relax solver family",
    "KC_RELAX_MIN_PODS": "pod-count threshold above which auto mode picks the relax family",
    # -- backend probe + watchdog ---------------------------------------------
    "KC_PROBE_TIMEOUT_S": "accelerator backend probe deadline",
    "KC_PROBE_LIVENESS_TIMEOUT_S": "liveness pre-check deadline before the full backend probe",
    "KC_PROBE_FAIL_TTL_S": "how long a failed backend probe is cached before re-probing",
    "KC_WATCHDOG": "adaptive watchdog over every blocking device interaction (0 = legacy unguarded waits)",
    "KC_WATCHDOG_FLOOR_S": "watchdog deadline floor",
    "KC_WATCHDOG_CEILING_S": "watchdog deadline ceiling",
    "KC_WATCHDOG_MARGIN": "multiplier over the observed p95 used as the adaptive deadline",
    "KC_WATCHDOG_COLD_MULT": "extra deadline multiplier for cold (first-compile) solves",
    "KC_WATCHDOG_CANARY_DEADLINE_S": "deadline for the known-answer canary solve that re-admits a quarantined backend",
    # -- async pipeline --------------------------------------------------------
    "KC_PIPELINE": "double-buffered dispatch/fetch solve loop (0 = serial A/B pin)",
    "KC_PIPELINE_DEPTH": "in-flight dispatch depth of the solve pipeline",
    # -- tracing + metrics -----------------------------------------------------
    "KC_TRACE": "decision-trace capture on/off",
    "KC_TRACE_CAPACITY": "trace ring-buffer capacity",
    "KC_TENANT_LABEL_MAX": "tenant-label cardinality cap for metrics (overflow buckets to 'other')",
    # -- service: admission, sessions, coalescer -------------------------------
    "KC_TENANT_RATE": "per-tenant token-bucket refill rate (solves/s)",
    "KC_TENANT_BURST": "per-tenant token-bucket burst capacity",
    "KC_TENANT_QUEUE": "per-tenant queue depth before shedding",
    "KC_TENANT_MAX_BYTES": "per-request wire-size ceiling",
    "KC_TENANT_SESSIONS": "resident per-tenant session cap (LRU eviction beyond)",
    "KC_TENANT_SESSION_TTL_S": "idle TTL before a tenant session is swept",
    "KC_TENANT_BREAKER_THRESHOLD": "consecutive-failure count that trips a tenant's circuit breaker",
    "KC_TENANT_BREAKER_RESET_S": "circuit-breaker half-open reset timeout",
    "KC_TENANT_BATCH_WINDOW_S": "batch-coalescer rendezvous window",
    "KC_TENANT_BATCH_MAX": "batch-coalescer maximum fused batch size",
    "KC_TENANT_WEIGHTS": "weighted fair-share map 'tenant=weight,...' shaping each bucket",
    "KC_TENANT_SLO_SOLVE_S": "per-solve latency SLO threshold fed to burn-rate accounting",
    "KC_TENANT_SLO_OBJECTIVE": "SLO objective (fraction of solves under threshold)",
    "KC_COALESCE_WINDOW": "repair-window slack allowed when fusing delta repairs across tenants",
    "KC_SERVICE_WORKERS": "gRPC server worker-thread count",
    "KC_SERVICE_QUEUE": "gRPC server max concurrent RPCs",
    "KC_SERVICE_DEADLINE_S": "server-side solve deadline",
    "KC_SERVICE_DRAIN_S": "graceful-drain window on shutdown",
    "KC_DRAIN_RETRY_AFTER_S": "retry-after hint returned while draining",
    # -- durable sessions (journal) -------------------------------------------
    "KC_SESSION_JOURNAL": "durable per-tenant session journal on/off",
    "KC_JOURNAL_DIR": "journal + checkpoint directory",
    "KC_JOURNAL_FSYNC": "fsync discipline for journal appends",
    "KC_JOURNAL_CHECKPOINT_EVERY": "journal compaction cadence (records per checkpoint)",
    "KC_JOURNAL_REPLAY_DEADLINE_S": "recovery replay time budget before degrading to re-anchor",
    "KC_JOURNAL_REPLAY_LOG_EVERY": "progress-log cadence during recovery replay",
    # -- fleet -----------------------------------------------------------------
    "KC_FLEET": "fleet mode master switch (0 = single-replica byte-identity pin)",
    "KC_FLEET_DIR": "shared fleet directory (leases, checkpoints, fleet map)",
    "KC_FLEET_MAP": "replica id -> address map for the consistent-hash ring",
    "KC_FLEET_REPLICA": "this process's replica id",
    "KC_FLEET_ROUTER": "run the fleet router front door",
    "KC_FLEET_BIND": "replica bind address",
    "KC_FLEET_HEARTBEAT_S": "replica lease heartbeat interval",
    "KC_FLEET_LEASE_TTL_S": "lease freshness TTL for liveness",
    "KC_FLEET_FORWARD_TIMEOUT_S": "router -> replica forwarding deadline",
    "KC_FLEET_REBALANCE_INTERVAL_S": "router load-aware rebalance cadence",
    "KC_FLEET_REBALANCE_FRACTION": "max fraction of placements moved per rebalance round",
    "KC_FLEET_CKPT_EVERY": "solves between cadence checkpoints of a tenant lineage",
    "KC_FLEET_CHECKPOINT_KEEP": "checkpoint generations retained per tenant",
    # -- policy layer ----------------------------------------------------------
    "KC_POLICY": "named policy profile selector",
    "KC_POLICY_ENABLED": "policy layer on/off",
    "KC_POLICY_COST_WEIGHT": "fleet-cost term weight in the placement objective",
    "KC_POLICY_THROUGHPUT_WEIGHT": "throughput term weight in the placement objective",
    "KC_POLICY_RISK_AVERSION": "spot-interruption risk aversion factor",
    "KC_POLICY_SPOT_PREFERENCE": "spot vs on-demand preference",
    "KC_POLICY_MAX_RESIZE_FRACTION": "cap on fleet fraction resized per policy round",
    "KC_POLICY_COUNTER_PROPOSALS": "emit ShapeHint counter-proposal events",
    # -- test harness hooks shipped in-package ---------------------------------
    "KC_LOCKCHECK": "run the opt-in suites under the runtime lockset tracer (testing/lockcheck.py)",
    # -- operator / wiring -----------------------------------------------------
    "KC_SOLVER_ADDRESS": "remote solver service address the operator dials",
    "KC_SOLVER_LISTEN": "solver service listen address",
    "KC_KUBE_BACKEND": "cluster-state backend selector (memory | apiserver)",
    "KC_KUBE_APISERVER": "kube-apiserver endpoint for the watch/list backend",
    "KC_LEASE_ENDPOINT": "remote lease-plane endpoint",
    "KC_LEASE_STATE": "lease-plane persistence path",
    "KC_NATIVE_SIG": "native (C) signature-interning twin on/off",
    "KC_FAKE_NODE_TAG": "tag applied to fake cloud-provider nodes",
}
