"""Process memory limit (--memory-limit).

Mirror of /root/reference/pkg/operator/options.go:67-70, which sets Go's
runtime soft memory limit at 90% of the container limit so GC backpressure
kicks in before the kubelet OOM-kills the pod.  CPython has no GC pacing
target, so the equivalent levers are:

  - an address-space rlimit at the configured bytes: allocation beyond it
    raises MemoryError inside the process (fail fast, crash loops visibly)
    instead of an opaque SIGKILL from the kernel OOM killer
  - more aggressive cyclic-GC thresholds, the closest analog to leaning on
    the collector harder as the limit approaches
"""

from __future__ import annotations

import gc
import logging

log = logging.getLogger(__name__)


def apply(limit_bytes: int) -> None:
    if limit_bytes <= 0:
        return
    try:
        import resource

        _, hard = resource.getrlimit(resource.RLIMIT_AS)
        resource.setrlimit(resource.RLIMIT_AS, (limit_bytes, hard))
        log.info("memory limit set: %d bytes (RLIMIT_AS soft)", limit_bytes)
    except (ImportError, ValueError, OSError) as e:
        log.warning("could not apply memory limit %d: %s", limit_bytes, e)
    gen0, gen1, gen2 = gc.get_threshold()
    gc.set_threshold(max(gen0 // 2, 100), gen1, gen2)
