"""Process memory limit (--memory-limit).

Mirror of /root/reference/pkg/operator/options.go:67-70, which sets Go's
runtime soft memory limit at 90% of the container limit so GC backpressure
kicks in before the kubelet OOM-kills the pod.  CPython has no GC pacing
target, and an address-space rlimit misfires badly (virtual mappings — thread
stacks, allocator arenas, jax runtime reservations — dwarf resident memory),
so the analog here is:

  - more aggressive cyclic-GC thresholds up front
  - an RSS watchdog sampling /proc/self/statm: above 90% of the limit it
    forces a full collection and logs loudly, giving the operator the same
    "lean on the collector before the OOM killer" behavior
"""

from __future__ import annotations

import gc
import logging
import os
import threading

log = logging.getLogger(__name__)

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096
_watchdog_started = False


def rss_bytes() -> int:
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE
    except (OSError, ValueError, IndexError):
        return 0


def apply(limit_bytes: int, poll_seconds: float = 10.0) -> None:
    global _watchdog_started
    if limit_bytes <= 0:
        return
    gen0, gen1, gen2 = gc.get_threshold()
    gc.set_threshold(max(gen0 // 2, 100), gen1, gen2)
    if _watchdog_started:
        return
    soft = int(limit_bytes * 0.9)

    def watch() -> None:
        import time

        while True:
            rss = rss_bytes()
            if rss > soft:
                collected = gc.collect()
                log.warning(
                    "memory watchdog: rss %d > %d (90%% of %d); gc collected %d",
                    rss, soft, limit_bytes, collected,
                )
            time.sleep(poll_seconds)

    threading.Thread(target=watch, name="memory-watchdog", daemon=True).start()
    _watchdog_started = True
    log.info("memory limit watchdog armed at %d bytes (90%% of %d)", soft, limit_bytes)
