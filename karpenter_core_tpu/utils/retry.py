"""The ONE backoff / retry-budget / circuit-breaker implementation.

Before this module the package carried three hand-rolled copies of the same
idea — ``controllers/deprovisioning.py`` (``_next_backoff`` + the
``WAIT_RETRY_*`` doubling loops), ``controllers/provisioning.py`` (the
consecutive-failure requeue backoff and the ad-hoc TPU-failure "circuit
breaker"), and ``kubeapi/reflector.py``'s inline watch-recovery math — each
with its own cap, its own jitter (or none), and its own idea of "reset".
Chaos scenarios (chaos/) exercise all of them; one implementation means one
set of invariants to test and one ``/metrics`` surface to watch.

Design constraints:

  - **Clock-driven.**  Everything that waits or times out takes a
    ``utils/clock.Clock`` so FakeClock suites can step through breaker
    half-open windows and budget refills deterministically.
  - **No ``random``.**  The chaos_hygiene determinism gate forbids the
    ``random`` module outside ``chaos/``; jitter comes from an explicit
    ``DeterministicRNG`` (splitmix64) whose seed callers — and chaos
    scenarios — control.  Replayability is the point: a chaos failure's
    backoff timing reproduces from its printed seed.
  - **Observable.**  Breaker state is a gauge (0 closed / 1 half-open /
    2 open) and transitions are a counter, both on ``/metrics``; transitions
    also land on the active tracing span.

The pre-refactor sequences are pinned by tests/test_retry.py equivalence
tests; do not change defaults without updating them.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Optional

from karpenter_core_tpu import tracing
from karpenter_core_tpu.metrics import REGISTRY
from karpenter_core_tpu.utils.clock import Clock

BREAKER_STATE = REGISTRY.gauge(
    "karpenter_circuit_breaker_state",
    "Circuit breaker state by name: 0 closed, 1 half-open, 2 open.",
    ("breaker",),
)
BREAKER_TRANSITIONS = REGISTRY.counter(
    "karpenter_circuit_breaker_transitions_total",
    "Circuit breaker state transitions by name and new state.",
    ("breaker", "state"),
)
RETRY_BUDGET_EXHAUSTED = REGISTRY.counter(
    "karpenter_retry_budget_exhausted_total",
    "Retry attempts denied because the retry budget was empty.",
    ("budget",),
)


class DeterministicRNG:
    """splitmix64-based uniform [0, 1) source: seeded, replayable, and free of
    the ``random`` module (the chaos determinism gate).  Thread-safe."""

    def __init__(self, seed: Optional[int] = None) -> None:
        if seed is None:
            seed = int.from_bytes(os.urandom(8), "little")
        self._state = seed & 0xFFFFFFFFFFFFFFFF
        self._lock = threading.Lock()

    def random(self) -> float:
        with self._lock:
            self._state = (self._state + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
            z = self._state
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
        z = z ^ (z >> 31)
        return (z >> 11) / float(1 << 53)


# jitter modes: NONE keeps the deterministic doubling the controllers pinned;
# HALF is the reflector's historical (0.5 + u) multiplier in [0.5d, 1.5d);
# FULL is AWS-style full jitter, uniform in (0, d]
JITTER_NONE = "none"
JITTER_HALF = "half"
JITTER_FULL = "full"


class Backoff:
    """Exponential backoff: ``delay(n) = min(base * factor^min(n-1, max_exponent),
    cap)``, optionally jittered.  Stateful (``next()``/``reset()``) for loop
    call sites and stateless (``for_attempt(n)``) for tests pinning sequences."""

    def __init__(
        self,
        base_s: float,
        cap_s: float,
        *,
        factor: float = 2.0,
        max_exponent: int = 32,
        jitter: str = JITTER_NONE,
        rng: Optional[DeterministicRNG] = None,
    ) -> None:
        if jitter not in (JITTER_NONE, JITTER_HALF, JITTER_FULL):
            raise ValueError(f"unknown jitter mode {jitter!r}")
        self.base_s = base_s
        self.cap_s = cap_s
        self.factor = factor
        self.max_exponent = max_exponent
        self.jitter = jitter
        self.rng = rng or DeterministicRNG()
        self._failures = 0

    @property
    def failures(self) -> int:
        return self._failures

    def for_attempt(self, attempt: int) -> float:
        """Deterministic (pre-jitter) delay for 1-based ``attempt``."""
        if attempt < 1:
            return 0.0
        exponent = min(attempt - 1, self.max_exponent)
        return min(self.base_s * (self.factor ** exponent), self.cap_s)

    def next(self) -> float:
        """Record one more consecutive failure; return the delay to wait."""
        self._failures += 1
        delay = self.for_attempt(self._failures)
        if self.jitter == JITTER_HALF:
            delay *= 0.5 + self.rng.random()
        elif self.jitter == JITTER_FULL:
            delay *= self.rng.random() or 1e-9
        return delay

    def reset(self) -> None:
        self._failures = 0


class RetryBudget:
    """Token-bucket retry budget: at most ``budget`` retries per rolling
    ``window_s``.  A hot-looping caller that burns the budget gets ``allow()
    == False`` until tokens refill — the backstop that turns a retry storm
    into a bounded trickle regardless of how fast individual backoffs reset."""

    def __init__(
        self,
        clock: Clock,
        budget: int = 10,
        window_s: float = 60.0,
        name: str = "default",
    ) -> None:
        self.clock = clock
        self.budget = float(budget)
        self.refill_per_s = budget / window_s if window_s > 0 else float("inf")
        self.name = name
        self._tokens = float(budget)
        self._last = clock.now()
        self._lock = threading.Lock()

    def _refill(self) -> None:
        now = self.clock.now()
        elapsed = max(0.0, now - self._last)
        self._last = now
        self._tokens = min(self.budget, self._tokens + elapsed * self.refill_per_s)

    def allow(self) -> bool:
        """Consume one retry token; False when the budget is exhausted."""
        with self._lock:
            self._refill()
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            RETRY_BUDGET_EXHAUSTED.labels(self.name).inc()
            return False

    def remaining(self) -> float:
        with self._lock:
            self._refill()
            return self._tokens

    def reconfigure(self, budget: int, window_s: float) -> None:
        """Reshape the bucket in place — the weighted fair-share admission
        path (service/tenant.py) rescales a tenant's rate/burst when its
        weight changes.  The current fill carries over PROPORTIONALLY: a
        weight bump neither grants a free full burst nor confiscates earned
        headroom, and ``next_token_s()`` hints stay exact because they read
        the same (budget, refill) fields."""
        with self._lock:
            self._refill()
            frac = self._tokens / self.budget if self.budget > 0 else 1.0
            self.budget = float(budget)
            self.refill_per_s = budget / window_s if window_s > 0 else float("inf")
            self._tokens = min(self.budget, frac * self.budget)

    def next_token_s(self) -> float:
        """Seconds until ``allow()`` would next succeed (0.0 = it would now).
        The retry-after hint a shed/admission-control response carries so a
        rejected caller backs off for exactly as long as the bucket needs,
        instead of guessing (service/tenant.py)."""
        with self._lock:
            self._refill()
            if self._tokens >= 1.0:
                return 0.0
            if self.refill_per_s <= 0:
                return float("inf")
            return (1.0 - self._tokens) / self.refill_per_s


# breaker states (gauge values on /metrics)
CLOSED = "closed"
HALF_OPEN = "half-open"
OPEN = "open"
_STATE_VALUES = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}


class CircuitBreaker:
    """closed → (``failure_threshold`` consecutive failures) → open →
    (``reset_timeout_s`` elapses) → half-open → one trial → closed on success,
    open again on failure.

    ``allow()`` is the gate: callers skip the protected path entirely while it
    returns False (the degraded-mode contract — no stalling on a dead
    backend), and the single half-open trial is what re-promotes the path.
    Thread-safe; all timing through the injected Clock."""

    def __init__(
        self,
        clock: Clock,
        *,
        failure_threshold: int = 2,
        reset_timeout_s: float = 30.0,
        name: str = "default",
        on_state_change: Optional[Callable[[str, str], None]] = None,
    ) -> None:
        self.clock = clock
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.name = name
        self.on_state_change = on_state_change
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._trial_inflight = False
        self._lock = threading.RLock()
        BREAKER_STATE.labels(name).set(0.0)

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    @property
    def failure_count(self) -> int:
        with self._lock:
            return self._failures

    def _transition(self, state: str) -> None:
        if state == self._state:
            return
        old, self._state = self._state, state
        BREAKER_STATE.labels(self.name).set(_STATE_VALUES[state])
        BREAKER_TRANSITIONS.labels(self.name, state).inc()
        tracing.add_event(
            "breaker.transition", breaker=self.name, from_state=old, to_state=state
        )
        if self.on_state_change is not None:
            self.on_state_change(old, state)

    def _maybe_half_open(self) -> None:
        if (
            self._state == OPEN
            and self.clock.now() - self._opened_at >= self.reset_timeout_s
        ):
            self._transition(HALF_OPEN)
            self._trial_inflight = False

    def allow(self) -> bool:
        """True when the protected path may be tried: always while closed,
        never while open, and exactly once per half-open window."""
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and not self._trial_inflight:
                self._trial_inflight = True
                return True
            return False

    def release_trial(self) -> None:
        """A granted half-open trial ended WITHOUT exercising the protected
        backend (shape routing, precondition error): free the trial slot so a
        later caller can still probe.  Without this, a no-verdict exit would
        wedge the breaker half-open forever — allow() latched the slot and
        only record_success/record_failure unlatch it."""
        with self._lock:
            self._trial_inflight = False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._trial_inflight = False
            self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._state == HALF_OPEN or self._failures >= self.failure_threshold:
                self._opened_at = self.clock.now()
                self._trial_inflight = False
                self._transition(OPEN)
