"""Hang-proof device interaction: watchdog-deadlined, cancelable dispatch.

The accelerator behind the axon relay fails by HANGING, not by erroring
(solver/backendprobe.py bounded the *probe*, but every bench since r02 has
shown the first real dispatch after a healthy probe can still wedge
forever), and the pipelined loop (utils/pipeline.py) keeps in-flight device
state — deferred ticks, donated carries, fetch tickets — that one silent
dispatch or device→host copy pins permanently.  The circuit breaker only
trips on *errors*; this module converts "device went quiet" into a bounded,
structured ``SolveTimeout`` the existing degraded-mode machinery can act on:

  MonitoredDispatch   runs one device interaction on a reusable worker
                      thread under an adaptive deadline.  Overrun abandons
                      the worker (the stuck XLA call is never joined on the
                      hot path — the poisoned thread parks as a daemon and
                      exits on its own if the call ever returns) and raises
                      ``SolveTimeout``.  ``run(site, fn, *args)`` is the
                      module-level convenience every call site uses.

  adaptive deadlines  per ``(site, key)`` — key carries the compile-cache
                      identity (shape bucket + mesh topology), so a 100k-pod
                      sharded solve and an 8-pod canary budget separately.
                      The deadline is an EWMA of observed *warm* latencies
                      times a safety margin, clamped to a floor/ceiling; a
                      cold key (compile not yet paid) gets the cold budget
                      instead.  Knobs (docs/KERNEL_PERF.md):

                        KC_WATCHDOG=0             disable (bit-for-bit the
                                                  pre-watchdog behavior:
                                                  calls run inline, no
                                                  threads, no chaos hits)
                        KC_WATCHDOG_FLOOR_S       min deadline (default 10 —
                                                  doubles as the spurious-
                                                  recompile guard)
                        KC_WATCHDOG_CEILING_S     max deadline (default 120)
                        KC_WATCHDOG_MARGIN        EWMA multiplier (default 8)
                        KC_WATCHDOG_COLD_MULT     floor multiplier for cold
                                                  keys (default 120 — i.e.
                                                  cold = ceiling by default)

  chaos               ``solver.hang`` (kind ``hang``) is the deterministic
                      stall-injection point, hit at every monitored dispatch
                      — ``delay_s`` bounds the stall (0 = hang until
                      abandoned), so a seeded scenario reproduces the exact
                      r02–r05 failure shape at dispatch or fetch sites.

  quarantine          ``BackendQuarantine`` closes the re-admission loop the
                      breaker leaves open: while the solver breaker is open
                      the backend is quarantined, and each half-open window
                      runs a deadline-bounded *canary* solve (tiny fixed
                      fleet, known answer) instead of risking a real batch —
                      only a verified canary re-admits the device path; a
                      canary with no backend evidence (no provisioners,
                      shape routing) releases the trial without a verdict.

Observability: ``karpenter_watchdog_timeouts_total{site}``, the
``karpenter_watchdog_deadline_headroom_ratio{site}`` gauge (how much of the
deadline the last completed call left unused), canary outcomes, and a
``solve.watchdog`` event on the active tracing span for every timeout.
"""

from __future__ import annotations

import contextvars
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from karpenter_core_tpu import chaos, tracing
from karpenter_core_tpu.metrics import REGISTRY

log = logging.getLogger(__name__)

# the deterministic stall-injection point (docs/CHAOS.md): hit once per
# monitored dispatch/fetch; kind "hang" stalls the monitored call past its
# deadline (delay_s bounds the stall; 0 hangs until the watchdog abandons it)
SOLVER_HANG = chaos.point("solver.hang")
KIND_HANG = "hang"

WATCHDOG_TIMEOUTS = REGISTRY.counter(
    "karpenter_watchdog_timeouts_total",
    "Monitored device interactions abandoned past their watchdog deadline, "
    "by site.",
    ("site",),
)
WATCHDOG_HEADROOM = REGISTRY.gauge(
    "karpenter_watchdog_deadline_headroom_ratio",
    "Fraction of the adaptive deadline left unused by the last completed "
    "monitored call at each site (1.0 = instant, 0.0 = finished at the "
    "deadline).",
    ("site",),
)
WATCHDOG_CANARY = REGISTRY.counter(
    "karpenter_watchdog_canary_total",
    "Quarantine canary solves by outcome (verified / wrong-answer / timeout "
    "/ error).",
    ("outcome",),
)


class SolveTimeout(RuntimeError):
    """A monitored device interaction overran its watchdog deadline.

    Subclasses RuntimeError deliberately: every existing backend-fault
    consumer (the provisioning solver breaker, the tenant plane's fault
    accounting) already treats an unexpected RuntimeError from the device
    path as a backend verdict, so a timeout feeds degraded mode without new
    plumbing — while structured consumers can still match the type."""

    def __init__(self, site: str, deadline_s: float, key=None) -> None:
        super().__init__(
            f"watchdog: {site} exceeded its {deadline_s:.2f}s deadline "
            f"(key={key!r}); the stuck call was abandoned"
        )
        self.site = site
        self.deadline_s = deadline_s
        self.key = key


def watchdog_enabled() -> bool:
    """Process-wide switch, read per call so tests/benches toggle it live.
    KC_WATCHDOG=0 restores the pre-watchdog behavior bit-for-bit: monitored
    calls run inline on the caller thread and the ``solver.hang`` point is
    never hit."""
    return os.environ.get("KC_WATCHDOG", "1") != "0"


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def floor_s() -> float:
    # the floor doubles as the spurious-timeout guard: a warm key whose next
    # call pays an unexpected (but legitimate) recompile must not be
    # abandoned — 10 s absorbs every steady-state compile while still
    # bounding a genuine hang to seconds, not minutes
    return max(_env_f("KC_WATCHDOG_FLOOR_S", 10.0), 0.001)


def ceiling_s() -> float:
    return max(_env_f("KC_WATCHDOG_CEILING_S", 120.0), floor_s())


def margin() -> float:
    return max(_env_f("KC_WATCHDOG_MARGIN", 8.0), 1.0)


def cold_mult() -> float:
    return max(_env_f("KC_WATCHDOG_COLD_MULT", 120.0), 1.0)


# EWMA smoothing for warm-latency observations (policy constant, not a knob:
# the margin/floor/ceiling band absorbs tuning)
_EWMA_ALPHA = 0.3

_lock = threading.Lock()
# (site, key) -> EWMA of warm latencies.  A key's FIRST completion (the cold
# run: XLA compile + export-cache population contaminate it) only marks the
# key seen; the EWMA seeds at the second completion.
_ewma: Dict[tuple, float] = {}
_seen: set = set()
_timeouts: Dict[str, int] = {}
_last_headroom: Dict[str, float] = {}


def reset_stats() -> None:
    """Forget every observation, deadline, and counter (tests/bench)."""
    with _lock:
        _ewma.clear()
        _seen.clear()
        _timeouts.clear()
        _last_headroom.clear()


def stats() -> Dict[str, object]:
    """Snapshot for bench detail / tests: per-site timeout counts and the
    last deadline-headroom ratio per site."""
    with _lock:
        return {
            "timeouts": dict(_timeouts),
            "headroom": {k: round(v, 4) for k, v in _last_headroom.items()},
        }


def deadline_for(site: str, key=None) -> float:
    """The adaptive deadline for one monitored call: EWMA × margin clamped
    to [floor, ceiling] once the key is warm; the cold budget
    (floor × cold_mult, clamped) before that."""
    lo, hi = floor_s(), ceiling_s()
    with _lock:
        ewma = _ewma.get((site, key))
    if ewma is None:
        return min(max(lo * cold_mult(), lo), hi)
    return min(max(ewma * margin(), lo), hi)


def _observe(site: str, key, elapsed_s: float, deadline_s: float) -> None:
    with _lock:
        k = (site, key)
        if k not in _seen:
            _seen.add(k)  # cold run: compile-contaminated, not a warm sample
        else:
            prev = _ewma.get(k)
            _ewma[k] = (
                elapsed_s if prev is None
                else prev + _EWMA_ALPHA * (elapsed_s - prev)
            )
        headroom = max(1.0 - elapsed_s / deadline_s, 0.0) if deadline_s > 0 else 0.0
        _last_headroom[site] = headroom
    WATCHDOG_HEADROOM.labels(site).set(headroom)


# -- the worker pool ----------------------------------------------------------
# Reusable daemon workers.  A timed-out worker is POISONED: it is dropped
# from the pool and never joined on the hot path — if the stuck call ever
# returns, the worker sees its poison flag and exits on its own.

class _Job:
    __slots__ = ("fn", "args", "kwargs", "ctx", "done", "abandoned",
                 "result", "error")

    def __init__(self, fn, args, kwargs, ctx) -> None:
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.ctx = ctx  # caller's contextvars (tracing span propagation)
        self.done = threading.Event()
        # set when the watchdog gives up on this job: an injected stall (and
        # any cooperative waiter) unblocks promptly instead of leaking a
        # sleeping thread per timeout
        self.abandoned = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None


class _Worker:
    __slots__ = ("_cond", "_job", "poisoned", "thread")

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._job: Optional[_Job] = None
        self.poisoned = False
        self.thread = threading.Thread(
            target=self._loop, name="kc-watchdog-worker", daemon=True
        )
        self.thread.start()

    def submit(self, job: _Job) -> None:
        with self._cond:
            self._job = job
            self._cond.notify()

    def _loop(self) -> None:
        while True:
            with self._cond:
                while self._job is None:
                    self._cond.wait()
                job, self._job = self._job, None
            try:
                job.result = job.ctx.run(job.fn, *job.args, **job.kwargs)
            except BaseException as e:  # noqa: BLE001 - routed to the caller
                job.error = e
            job.done.set()
            with _pool_lock:
                if self.poisoned:
                    return  # abandoned mid-call: retire quietly
                _idle.append(self)


_pool_lock = threading.Lock()
_idle: List[_Worker] = []


def _checkout() -> _Worker:
    with _pool_lock:
        if _idle:
            return _idle.pop()
    return _Worker()


def _poison(worker: _Worker, job: _Job) -> None:
    with _pool_lock:
        worker.poisoned = True
    job.abandoned.set()
    # drop the job's own references to the call: the stuck frame inside the
    # worker still pins whatever the hung call holds (unavoidable — the call
    # itself owns those refs until it returns), but nothing ELSE should.
    # An injected stall releases promptly (it waits on `abandoned` before
    # ever touching the arrays).
    job.fn = None
    job.args = ()
    job.kwargs = {}
    job.ctx = None


def _stalled(fn, fault, job: _Job):
    """Wrap ``fn`` with the injected stall: wait out ``delay_s`` (0 = until
    abandoned), then run normally — a stall shorter than the deadline is
    pure latency, a longer one becomes a SolveTimeout and the abandoned
    worker exits promptly instead of sleeping forever."""
    delay_s = float(fault.delay_s or 0.0)

    def stalled(*args, **kwargs):
        if delay_s > 0:
            job.abandoned.wait(delay_s)
        else:
            job.abandoned.wait()
        if job.abandoned.is_set():
            raise RuntimeError(fault.describe())  # never seen: job abandoned
        return fn(*args, **kwargs)

    return stalled


class MonitoredDispatch:
    """One site's deadline-bounded dispatch wrapper.

    ``run(fn, *args, key=..., **kwargs)`` executes ``fn`` on a pooled worker
    under the (site, key) adaptive deadline; ``deadline_s`` overrides it.
    Disabled (KC_WATCHDOG=0) it calls ``fn`` inline — zero threads, zero
    chaos hits, bit-for-bit today's behavior."""

    def __init__(self, site: str, deadline_s: Optional[float] = None) -> None:
        self.site = site
        self.deadline_s = deadline_s

    def run(self, fn: Callable, *args, key=None,
            deadline_s: Optional[float] = None, **kwargs):
        if not watchdog_enabled():
            return fn(*args, **kwargs)
        deadline = deadline_s or self.deadline_s or deadline_for(self.site, key)
        job = _Job(fn, args, kwargs, contextvars.copy_context())
        fault = SOLVER_HANG.hit(kinds=(KIND_HANG,), site=self.site)
        if fault is not None and fault.kind == KIND_HANG:
            job.fn = _stalled(fn, fault, job)
        worker = _checkout()
        t0 = time.perf_counter()
        worker.submit(job)
        if not job.done.wait(deadline):
            _poison(worker, job)
            with _lock:
                _timeouts[self.site] = _timeouts.get(self.site, 0) + 1
            WATCHDOG_TIMEOUTS.labels(self.site).inc()
            WATCHDOG_HEADROOM.labels(self.site).set(0.0)
            tracing.add_event(
                "solve.watchdog", site=self.site,
                deadline_s=round(deadline, 3), outcome="timeout",
                key=repr(key) if key is not None else None,
            )
            log.warning(
                "watchdog: %s overran its %.2fs deadline (key=%r); call "
                "abandoned", self.site, deadline, key,
            )
            raise SolveTimeout(self.site, deadline, key)
        elapsed = time.perf_counter() - t0
        if job.error is not None:
            # failed completions are NOT latency observations: a burst of
            # instant backend errors must not drag the warm EWMA (and with
            # it the deadline) toward the floor, or the first healthy
            # post-recovery call would spuriously time out
            raise job.error
        _observe(self.site, key, elapsed, deadline)
        return job.result


def run(site: str, fn: Callable, *args, key=None,
        deadline_s: Optional[float] = None, **kwargs):
    """Module-level MonitoredDispatch: the one-liner every device-touching
    call site wraps itself in (the kcanalyze ``unbounded-block`` rule flags
    raw blocking device calls that bypass it)."""
    return MonitoredDispatch(site).run(
        fn, *args, key=key, deadline_s=deadline_s, **kwargs
    )


# -- backend quarantine -------------------------------------------------------


class BackendQuarantine:
    """The re-admission ladder over an existing solver-backend breaker.

    The breaker (utils/retry.CircuitBreaker) already converts repeated
    faults into an open state and a periodic half-open trial — but the trial
    is a *real* workload batch, so re-admission risks production pods on an
    unproven device, and a backend that hangs (rather than errors) wedges
    the trial itself.  This wrapper makes the trial a deadline-bounded
    canary: a tiny fixed-fleet solve with a known answer, run through the
    watchdog.  Only a verified canary closes the breaker; anything else
    (wrong answer, timeout, error) re-opens it and the backend stays
    quarantined serving degraded host solves.

    ``canary`` is the probe callable: () -> True (the device answered AND
    the answer verified), False (answered wrong), or None (NO backend
    evidence — e.g. no provisioners to solve against, shape routing): a
    no-verdict releases the trial slot instead of re-opening the breaker,
    the same contract the legacy real-batch trial keeps for precondition
    errors.  It runs through a monitored dispatch at site ``solve.canary``
    so a hung canary is itself bounded."""

    def __init__(self, breaker, canary: Callable[[], bool],
                 deadline_s: Optional[float] = None) -> None:
        self.breaker = breaker
        self.canary = canary
        self.deadline_s = deadline_s

    def _deadline(self) -> Optional[float]:
        """Explicit ctor deadline, else KC_WATCHDOG_CANARY_DEADLINE_S (read
        per canary so tests/operators retune between windows), else the
        adaptive (site, key) deadline."""
        return self.deadline_s or _env_f(
            "KC_WATCHDOG_CANARY_DEADLINE_S", 0.0
        ) or None

    def quarantined(self) -> bool:
        from karpenter_core_tpu.utils import retry

        return self.breaker.state == retry.OPEN

    def try_readmit(self) -> bool:
        """Run one deadline-bounded canary against the quarantined backend.
        True = verified and re-admitted (breaker closed); False = still
        quarantined.  The caller must hold a granted half-open trial (a
        ``breaker.allow()`` that returned True in the half-open state)."""
        outcome = "error"
        try:
            ok = run(
                "solve.canary", self.canary, deadline_s=self._deadline()
            )
            if ok is None:
                outcome = "no-verdict"
            else:
                outcome = "verified" if ok else "wrong-answer"
        except SolveTimeout:
            outcome = "timeout"
        except Exception as e:  # noqa: BLE001 - a canary fault is a verdict
            log.warning("quarantine canary failed: %s", e)
            outcome = "error"
        WATCHDOG_CANARY.labels(outcome).inc()
        tracing.add_event("solve.watchdog", site="solve.canary",
                          outcome=outcome)
        if outcome == "verified":
            self.breaker.record_success()
            log.info(
                "backend quarantine: canary verified — device path "
                "re-admitted"
            )
            return True
        if outcome == "no-verdict":
            # the backend was never exercised (no provisioners, shape
            # routing): not a verdict either way — free the trial slot so a
            # later window can still probe, without burning a fresh
            # reset-timeout on a cluster-config condition
            self.breaker.release_trial()
            log.info(
                "backend quarantine: canary produced no backend evidence — "
                "trial released, backend stays quarantined"
            )
            return False
        self.breaker.record_failure()  # half-open failure re-opens the breaker
        log.warning(
            "backend quarantine: canary %s — backend stays quarantined",
            outcome,
        )
        return False


__all__ = [
    "BackendQuarantine",
    "KIND_HANG",
    "MonitoredDispatch",
    "SOLVER_HANG",
    "SolveTimeout",
    "deadline_for",
    "reset_stats",
    "run",
    "stats",
    "watchdog_enabled",
]
