"""Node utilities (mirror of /root/reference/pkg/utils/node/node.go:30-60)."""

from __future__ import annotations

from typing import List

from karpenter_core_tpu.apis.objects import Node, Pod
from karpenter_core_tpu.utils import pod as pod_util


def get_node_pods(kube_client, *nodes: Node) -> List[Pod]:
    """Reschedulable pods on the nodes: excludes node-owned, daemonset,
    terminal, and terminating pods."""
    pods: List[Pod] = []
    names = {n.name for n in nodes}
    for pod in kube_client.list_pods():
        if pod.spec.node_name not in names:
            continue
        if (
            pod_util.is_owned_by_node(pod)
            or pod_util.is_owned_by_daemon_set(pod)
            or pod_util.is_terminal(pod)
            or pod_util.is_terminating(pod)
        ):
            continue
        pods.append(pod)
    return pods


def all_node_pods(kube_client, node: Node) -> List[Pod]:
    return [p for p in kube_client.list_pods() if p.spec.node_name == node.name]


def get_condition(node: Node, condition_type: str):
    for condition in node.status.conditions:
        if condition.type == condition_type:
            return condition
    return None
