"""Clock abstraction: real and fake (test) clocks.

All TTL logic (batching windows, emptiness, expiration, consolidation
validation) goes through a Clock so suites can advance time deterministically —
the role clock.FakeClock plays throughout the reference's tests.
"""

from __future__ import annotations

import threading
import time as _time

from karpenter_core_tpu.chaos import plane as _chaos

# the clock.skew injection point: a standing offset applied to every now()
# while a scenario with a "clock.skew" spec is armed (zero-cost otherwise —
# chaos.current_skew_s is one global load + is-None check).  Registered here
# so the chaos-hygiene exactly-once gate owns the name.
CLOCK_SKEW = _chaos.point("clock.skew")


class Clock:
    def now(self) -> float:
        return _time.time() + _chaos.current_skew_s()

    def sleep(self, seconds: float) -> None:
        _time.sleep(seconds)


class FakeClock(Clock):
    def __init__(self, start: float = 1_000_000.0) -> None:
        self._now = start
        self._lock = threading.Lock()

    def now(self) -> float:
        with self._lock:
            return self._now + _chaos.current_skew_s()

    def sleep(self, seconds: float) -> None:
        self.step(seconds)

    def step(self, seconds: float) -> None:
        with self._lock:
            self._now += seconds

    def set_time(self, t: float) -> None:
        with self._lock:
            self._now = t
