"""Clock abstraction: real and fake (test) clocks.

All TTL logic (batching windows, emptiness, expiration, consolidation
validation) goes through a Clock so suites can advance time deterministically —
the role clock.FakeClock plays throughout the reference's tests.
"""

from __future__ import annotations

import threading
import time as _time


class Clock:
    def now(self) -> float:
        return _time.time()

    def sleep(self, seconds: float) -> None:
        _time.sleep(seconds)


class FakeClock(Clock):
    def __init__(self, start: float = 1_000_000.0) -> None:
        self._now = start
        self._lock = threading.Lock()

    def now(self) -> float:
        with self._lock:
            return self._now

    def sleep(self, seconds: float) -> None:
        self.step(seconds)

    def step(self, seconds: float) -> None:
        with self._lock:
            self._now += seconds

    def set_time(self, t: float) -> None:
        with self._lock:
            self._now = t
