"""The multi-tenant solver plane: admission, coalescing, sessions, isolation.

"Millions of users" is tens of thousands of clusters sharing one solver
fleet (ROADMAP; Tesserae is the scale frame).  This module is the robustness
layer that makes the sharing safe — one tenant's poison snapshot, slow
client, or burst must not take down the other N−1:

  admission     Every tenant request passes an ``AdmissionController``:
                a per-tenant token bucket (``utils/retry.RetryBudget``) plus
                a bounded global in-flight cap.  Past either bound the
                request is SHED — an explicit RESOURCE_EXHAUSTED response
                carrying a retry-after hint (the bucket's exact refill time,
                escalated by a per-tenant ``Backoff`` while the tenant keeps
                hammering) — instead of queueing without bound behind the
                worker pool.

  coalescing    Compatible requests batch into ONE device solve: tenants
                whose prepared planes share a shape bucket (the compile
                cache's padding makes this the common case) stack on a
                leading tenant axis and run a vmapped executable
                (``utils/compilecache.batched_solve_callable``; a mesh
                tenant axis when KC_SOLVER_MESH is on —
                ``parallel/mesh.TENANT_PARTITION_RULES``).  Batch membership
                is fault-contained: a tenant whose snapshot fails validation
                never reaches the batch, and a batch-program fault falls
                back to per-tenant solo runs — so every co-batched tenant's
                outputs are bit-identical to its solo solve, always.

  sessions      A per-tenant ``IncrementalSolveSession`` lineage lives
                server-side under an LRU + TTL eviction policy: steady
                same-supply churn repairs instead of re-solving.  Crash
                recovery is by re-anchor, never by trust: a client claiming
                a lineage this process doesn't hold (server restart, LRU/TTL
                eviction) gets a FULL solve with reason ``session-lost`` —
                no stale lineage ever answers.

  isolation     A per-tenant ``CircuitBreaker``: malformed / oversized
                snapshots and solve faults count against the tenant; past
                the threshold the tenant is isolated (UNAVAILABLE with a
                retry-after) until the breaker's half-open trial readmits
                it.  Other tenants never see the breaker.

Everything observable rides ``/metrics``: per-tenant queue/solve/decode
latency histograms and shed/eject/evict counters (docs/SERVICE.md).  All
timing policy (TTL, breaker windows, bucket refill) goes through the
injected ``utils/clock.Clock`` so FakeClock suites step it deterministically;
latency *measurement* uses the monotonic wall clock (diagnostics, not
policy).
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from karpenter_core_tpu import tracing
from karpenter_core_tpu.metrics import REGISTRY, tenant_label
from karpenter_core_tpu.utils import pipeline as pipeline_mod
from karpenter_core_tpu.utils import retry
from karpenter_core_tpu.utils.clock import Clock

TENANT_QUEUE_LATENCY = REGISTRY.histogram(
    "karpenter_tenant_queue_latency_seconds",
    "Per-tenant time from RPC receipt to solve start (admission + decode).",
    ("tenant",),
)
TENANT_SOLVE_LATENCY = REGISTRY.histogram(
    "karpenter_tenant_solve_latency_seconds",
    "Per-tenant solve time (session decide + device dispatch), coalesced or "
    "solo.",
    ("tenant",),
)
TENANT_DECODE_LATENCY = REGISTRY.histogram(
    "karpenter_tenant_decode_latency_seconds",
    "Per-tenant response decode/assembly time.",
    ("tenant",),
)
TENANT_SHED = REGISTRY.counter(
    "karpenter_tenant_shed_total",
    "Requests shed by admission control, by tenant and reason "
    "(rate / queue / isolated).",
    ("tenant", "reason"),
)
TENANT_EJECTED = REGISTRY.counter(
    "karpenter_tenant_ejected_total",
    "Tenant requests ejected with a structured error, by tenant and reason "
    "(malformed / oversized / solve-fault).",
    ("tenant", "reason"),
)
TENANT_SESSIONS_EVICTED = REGISTRY.counter(
    "karpenter_tenant_sessions_evicted_total",
    "Server-side tenant sessions evicted, by reason (lru / ttl).",
    ("reason",),
)
TENANT_SESSIONS_LIVE = REGISTRY.gauge(
    "karpenter_tenant_sessions_live",
    "Server-side tenant sessions currently resident.",
)
TENANT_BATCHES = REGISTRY.counter(
    "karpenter_tenant_batches_total",
    "Coalesced tenant solves dispatched, by batch size (1 = solo).",
    ("size",),
)
TENANT_ADMITTED = REGISTRY.counter(
    "karpenter_tenant_admitted_total",
    "Tenant requests accepted by admission control, by tenant.",
    ("tenant",),
)
TENANT_RETRY_AFTER = REGISTRY.histogram(
    "karpenter_tenant_retry_after_seconds",
    "Retry-after hints handed to shed tenant requests, by tenant.",
    ("tenant",),
    buckets=[0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60],
)
TENANT_DISPATCH = REGISTRY.counter(
    "karpenter_tenant_dispatch_total",
    "Tenant solve dispatches, by tenant and mode (coalesced / solo).",
    ("tenant", "mode"),
)
TENANT_REPAIR_DISPATCH = REGISTRY.counter(
    "karpenter_tenant_repair_dispatch_total",
    "Tenant delta/repair solve dispatches, by tenant and mode (coalesced = "
    "fused with compatible repair windows from other tenants, solo = "
    "unfused; KC_COALESCE_WINDOW=0 forces solo).",
    ("tenant", "mode"),
)
TENANT_SLO_BURN_RATE = REGISTRY.gauge(
    "karpenter_tenant_slo_burn_rate",
    "Multi-window error-budget burn rate over the declared per-tenant solve "
    "latency SLO (KC_TENANT_SLO_SOLVE_S / KC_TENANT_SLO_OBJECTIVE): the "
    "window's bad-solve fraction divided by the budget (1 - objective); "
    "1.0 = burning exactly the budget.",
    ("tenant", "window"),
)

# the shed/isolated detail string clients parse the hint out of
RETRY_AFTER_PREFIX = "retry-after-s="


def parse_retry_after(details: str) -> Optional[float]:
    """The retry-after hint out of a shed/isolated response's detail string,
    or None when absent/unparseable."""
    for token in (details or "").replace(";", " ").split():
        if token.startswith(RETRY_AFTER_PREFIX):
            try:
                return float(token[len(RETRY_AFTER_PREFIX):])
            except ValueError:
                return None
    return None


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_i(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


class SloTracker:
    """Per-tenant multi-window burn rate over a declared solve-latency SLO.

    The SLO is "fraction ``objective`` of solves finish under ``target_s``";
    the burn rate for a window is the window's observed bad fraction divided
    by the error budget (``1 - objective``) — the standard multi-window
    burn-rate alerting shape, so 1.0 means spending the budget exactly and
    14.4 on the short window is a page.  Samples are bounded per tenant and
    tenants are bounded by the metrics label-cardinality guard (overflow
    tenants pool their samples under ``"_other"``)."""

    WINDOWS = (("5m", 300.0), ("1h", 3600.0))
    MAX_SAMPLES = 4096

    def __init__(self, target_s: Optional[float] = None,
                 objective: Optional[float] = None) -> None:
        self.target_s = (
            target_s if target_s is not None
            else max(_env_f("KC_TENANT_SLO_SOLVE_S", 1.0), 1e-6)
        )
        objective = (
            objective if objective is not None
            else _env_f("KC_TENANT_SLO_OBJECTIVE", 0.99)
        )
        self.objective = min(max(objective, 0.0), 0.9999)
        self._lock = threading.Lock()
        # guarded tenant label -> deque[(monotonic_t, was_bad)]
        self._samples: Dict[str, "deque"] = {}

    def observe(self, tenant: str, solve_s: float,
                now: Optional[float] = None) -> None:
        """Record one solve under the guarded tenant label and refresh the
        tenant's burn-rate gauges for every window."""
        now = monotonic() if now is None else now
        bad = solve_s > self.target_s
        budget = 1.0 - self.objective
        with self._lock:
            samples = self._samples.get(tenant)
            if samples is None:
                samples = deque(maxlen=self.MAX_SAMPLES)
                self._samples[tenant] = samples
            samples.append((now, bad))
            horizon = self.WINDOWS[-1][1]
            while samples and now - samples[0][0] > horizon:
                samples.popleft()
            snapshot = list(samples)
        for window, span_s in self.WINDOWS:
            in_window = [b for (t, b) in snapshot if now - t <= span_s]
            if not in_window:
                burn = 0.0
            else:
                burn = (sum(in_window) / len(in_window)) / budget
            TENANT_SLO_BURN_RATE.labels(tenant, window).set(burn)

    def burn(self, tenant: str, window: str = "5m",
             now: Optional[float] = None) -> float:
        """Read one tenant's current burn rate (0.0 when unobserved) — the
        fleet router's load-aware rebalancing signal (fleet/router.py): a
        replica whose tenants burn hottest sheds placements first."""
        now = monotonic() if now is None else now
        span_s = dict(self.WINDOWS).get(window, self.WINDOWS[0][1])
        budget = 1.0 - self.objective
        with self._lock:
            samples = self._samples.get(tenant)
            snapshot = list(samples) if samples else []
        in_window = [b for (t, b) in snapshot if now - t <= span_s]
        if not in_window:
            return 0.0
        return (sum(in_window) / len(in_window)) / budget

    def reset(self) -> None:
        with self._lock:
            self._samples.clear()


# module singleton: TenantPlane.observe_latencies is static (the handler
# calls it without plumbing the plane through), so the tracker lives here
SLO_TRACKER = SloTracker()


# weighted fair-share bounds: a weight outside this band is someone fat-
# fingering an env var or a client inflating itself — clamp, don't trust
WEIGHT_MIN = 0.01
WEIGHT_MAX = 100.0


def parse_weights(spec: str) -> Dict[str, float]:
    """KC_TENANT_WEIGHTS: ``tenant-a=2.0,tenant-b=0.5`` — unparseable parts
    are skipped (a typo must not take admission down)."""
    out: Dict[str, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        key, _, value = part.partition("=")
        try:
            out[key.strip()] = min(max(float(value), WEIGHT_MIN), WEIGHT_MAX)
        except ValueError:
            continue
    return out


@dataclass
class TenantConfig:
    """Knobs for the tenant plane; all env-overridable (docs/SERVICE.md)."""

    # admission: per-tenant token bucket (sustained rate + burst) and the
    # bounded global solve queue
    rate_per_s: float = 10.0
    burst: int = 20
    max_inflight: int = 16
    # weighted fair share: per-tenant multipliers on rate AND burst
    # (KC_TENANT_WEIGHTS; the wire envelope's ``weight`` field covers tenants
    # the operator hasn't pinned — env wins, because the serving side owns
    # fairness policy, not the client claiming its own priority)
    weights: Dict[str, float] = field(default_factory=dict)
    # sessions: LRU capacity + idle TTL
    max_sessions: int = 256
    session_ttl_s: float = 900.0
    # isolation: per-tenant breaker
    breaker_threshold: int = 3
    breaker_reset_s: float = 30.0
    # coalescing: rendezvous window + cap (window 0 disables batching)
    batch_window_s: float = 0.01
    max_batch: int = 8
    # solve fusion (docs/SERVICE.md "Solve fusion"): repair/delta dispatches
    # join the coalescer's rendezvous too; KC_COALESCE_WINDOW=0 restores the
    # repairs-always-solo behavior (anchors keep coalescing)
    coalesce_repairs: bool = True
    # request bound: oversized snapshots count against the tenant's breaker
    max_request_bytes: int = 32 * 1024 * 1024
    # True when KC_TENANT_RATE was set explicitly: an operator pin is an
    # absolute per-process statement, so fleet_scaled() must NOT divide it
    # by the fleet size (docs/FLEET.md "Admission")
    rate_pinned: bool = False

    @classmethod
    def from_env(cls) -> "TenantConfig":
        return cls(
            rate_per_s=max(_env_f("KC_TENANT_RATE", 10.0), 0.001),
            rate_pinned="KC_TENANT_RATE" in os.environ,
            burst=max(_env_i("KC_TENANT_BURST", 20), 1),
            max_inflight=max(_env_i("KC_TENANT_QUEUE", 16), 1),
            max_sessions=max(_env_i("KC_TENANT_SESSIONS", 256), 1),
            session_ttl_s=_env_f("KC_TENANT_SESSION_TTL_S", 900.0),
            breaker_threshold=max(_env_i("KC_TENANT_BREAKER_THRESHOLD", 3), 1),
            breaker_reset_s=_env_f("KC_TENANT_BREAKER_RESET_S", 30.0),
            batch_window_s=_env_f("KC_TENANT_BATCH_WINDOW_S", 0.01),
            max_batch=max(_env_i("KC_TENANT_BATCH_MAX", 8), 1),
            coalesce_repairs=os.environ.get("KC_COALESCE_WINDOW", "1") != "0",
            max_request_bytes=max(
                _env_i("KC_TENANT_MAX_BYTES", 32 * 1024 * 1024), 1024
            ),
            weights=parse_weights(os.environ.get("KC_TENANT_WEIGHTS", "")),
        )

    def resolve_weight(self, tenant_id: str, wire_weight=None) -> float:
        """The tenant's fair-share weight: operator env pin wins, then the
        wire envelope's claim, then 1.0 — always clamped."""
        weight = self.weights.get(tenant_id)
        if weight is None:
            try:
                weight = float(wire_weight) if wire_weight is not None else 1.0
            except (TypeError, ValueError):
                weight = 1.0
        return min(max(weight, WEIGHT_MIN), WEIGHT_MAX)

    def bucket_shape(self, weight: float) -> Tuple[int, float]:
        """(budget, window_s) for a weighted tenant bucket: burst scales with
        the weight, and the window is derived from the SCALED budget so the
        refill rate is exactly ``rate_per_s * weight`` even after the burst
        rounds to an int — shed hints stay exact."""
        budget = max(int(round(self.burst * weight)), 1)
        return budget, budget / (self.rate_per_s * weight)

    def fleet_scaled(self, fleet_size: int) -> "TenantConfig":
        """The per-replica backstop shape for an N-replica fleet: the
        fleet-level buckets at the router already enforce the configured
        rate, so each replica grants 1/N of it — N replicas together can
        never over-admit a tenant that bypasses the router, and the fleetless
        (N<=1) config is returned unchanged.  An explicit ``KC_TENANT_RATE``
        pin wins: the operator said per-process, the fleet must not reshape
        it (satellite fix for the historical N× over-admission)."""
        n = int(fleet_size)
        if n <= 1 or self.rate_pinned:
            return self
        import dataclasses

        return dataclasses.replace(
            self,
            rate_per_s=max(self.rate_per_s / n, 0.001),
            burst=max(int(round(self.burst / n)), 1),
        )


@dataclass
class AdmissionDecision:
    admitted: bool
    reason: str = ""  # rate / queue / isolated when not admitted
    retry_after_s: float = 0.0
    # the tenant's entry (so the handler never re-looks it up) and whether
    # THIS admission latched the breaker's half-open trial — a no-verdict
    # exit must release exactly the trial it was granted, never a
    # concurrent request's
    entry: Optional["TenantEntry"] = None
    trial: bool = False

    def detail(self) -> str:
        """The grpc abort detail string (machine-parseable hint included)."""
        return (
            f"tenant-shed reason={self.reason} "
            f"{RETRY_AFTER_PREFIX}{self.retry_after_s:.3f}"
        )


# -- batch coalescing ---------------------------------------------------------


def bucket_key(prep, kw=None) -> tuple:
    """The shape-bucket identity of a SolvePrep: two preps with equal keys
    run the same solve program, so their batches can stack on a tenant axis.
    Mirrors the compile-cache key's static components (docs/SERVICE.md).

    ``kw`` (the dispatch kwargs of a repair solve) extends the key with the
    repair-window identity — the window width (``n_slots`` override) plus the
    warm-carry/repair-plan leaf signatures, mirroring the solo compile
    cache's ``delta`` variant key — so compatible repair windows from
    different tenants stack on one vmapped dispatch (docs/SERVICE.md
    "Solve fusion").  The per-tick ``count`` vector is values-only (its shape
    is already pinned by the cls signature), so it never splits a bucket."""
    from karpenter_core_tpu.utils import compilecache

    key = (
        compilecache._leaf_sig(prep.cls),
        compilecache._leaf_sig(prep.statics_arrays),
        compilecache._leaf_sig(prep.ex_state) if prep.ex_state is not None else None,
        compilecache._leaf_sig(prep.ex_static) if prep.ex_static is not None else None,
        int(prep.n_slots),
        tuple(prep.key_has_bounds),
        int(prep.n_passes),
        tuple(prep.features) if prep.features is not None else None,
    )
    if kw and kw.get("warm_carry") is not None:
        key += (
            "repair",
            int(kw.get("n_slots") or 0) or int(prep.n_slots),
            compilecache._leaf_sig(kw["warm_carry"]),
            compilecache._leaf_sig(kw["repair_plan"])
            if kw.get("repair_plan") is not None else None,
        )
    return key


class _Member:
    __slots__ = ("prep", "solo", "tenant", "kw", "done", "outputs", "error",
                 "batch_n")

    def __init__(self, prep, solo: Callable[[], object],
                 tenant: Optional[str] = None, kw=None) -> None:
        self.prep = prep
        self.solo = solo
        self.tenant = tenant
        self.kw = kw
        self.done = threading.Event()
        self.outputs = None
        self.error: Optional[BaseException] = None
        self.batch_n = 1


class _Group:
    __slots__ = ("members", "full", "closed")

    def __init__(self) -> None:
        self.members: List[_Member] = []
        self.full = threading.Event()
        self.closed = False


class BatchCoalescer:
    """Rendezvous concurrent compatible-bucket solves into one batched
    dispatch.  ``run(prep, solo)`` blocks until this request's outputs exist
    and returns ``(outputs, batch_size)``; ``solo`` is the caller's
    unbatched dispatch (used for singleton groups and as the per-tenant
    fault-containment fallback when a batch program faults)."""

    def __init__(self, window_s: float = 0.01, max_batch: int = 8) -> None:
        self.window_s = window_s
        self.max_batch = max_batch
        self._lock = threading.Lock()
        self._groups: Dict[tuple, _Group] = {}

    def run(self, prep, solo: Callable[[], object],
            tenant: Optional[str] = None, kw=None) -> Tuple[object, int]:
        if self.window_s <= 0 or self.max_batch <= 1:
            return solo(), 1
        key = bucket_key(prep, kw)
        member = _Member(prep, solo, tenant, kw)
        with self._lock:
            group = self._groups.get(key)
            # a full group is as good as closed: the leader may not have
            # woken from full.wait() yet, and appending past max_batch would
            # dispatch an unexpected batch size (fresh compile, uncapped
            # device cost) — late arrivals start the next group instead
            leader = (
                group is None or group.closed
                or len(group.members) >= self.max_batch
            )
            if leader:
                group = _Group()
                group.members.append(member)
                self._groups[key] = group
            else:
                group.members.append(member)
                if len(group.members) >= self.max_batch:
                    group.full.set()
        if not leader:
            # the leader always resolves every member in its finally block
            member.done.wait()
            if member.error is not None:
                raise member.error
            return member.outputs, member.batch_n
        # leader: hold the window open for co-batchers, then dispatch
        group.full.wait(self.window_s)
        with self._lock:
            group.closed = True
            if self._groups.get(key) is group:
                del self._groups[key]
            members = list(group.members)
        try:
            self._execute(members)
        finally:
            for m in members:
                m.done.set()
        if member.error is not None:
            raise member.error
        return member.outputs, member.batch_n

    def _execute(self, members: List[_Member]) -> None:
        TENANT_BATCHES.labels(str(len(members))).inc()
        if len(members) == 1:
            m = members[0]
            try:
                m.outputs = m.solo()
            except BaseException as e:  # noqa: BLE001 - routed to the caller
                m.error = e
            return
        try:
            outs = self._run_batched(
                [m.prep for m in members],
                tenants=[m.tenant for m in members if m.tenant is not None],
                kws=[m.kw for m in members],
            )
        except BaseException:  # noqa: BLE001 - batch fault: contain per tenant
            # fault containment: the batch PROGRAM faulted (device error,
            # chaos) — nothing tenant-attributable yet.  Re-run each member
            # solo: tenants whose solves are healthy still get their exact
            # answers; the faulty one surfaces its own error.
            for m in members:
                try:
                    m.outputs = m.solo()
                    m.batch_n = 1
                except BaseException as e:  # noqa: BLE001 - per-tenant verdict
                    m.error = e
            return
        for m, out in zip(members, outs):
            m.outputs = out
            m.batch_n = len(members)

    @staticmethod
    def _run_batched(preps, tenants=None, kws=None) -> List[object]:
        """One vmapped device dispatch over the stacked preps; returns
        per-tenant output slices (bit-identical to solo solves).  ``tenants``
        (optional member tenant ids, dispatch order) rides the span so a
        server-side trace names who co-batched.  ``kws`` (per-member dispatch
        kwargs, aligned with ``preps``) carries repair dispatches: members
        with a ``warm_carry`` stack their per-tick count vectors, synthesized
        ex-static planes, warm carries, and repair plans as batch leaves and
        run the vmapped REPAIR executable — the rendezvous key (bucket_key's
        repair extension) guarantees every member of one group agrees on the
        variant and the window width."""
        import jax

        from karpenter_core_tpu.parallel import mesh as mesh_mod
        from karpenter_core_tpu.utils import compilecache

        p0 = preps[0]
        kws = kws if kws is not None else [None] * len(preps)
        kw_of = lambda i: kws[i] or {}  # noqa: E731 - local accessor
        kw0 = kw_of(0)
        has_warm = kw0.get("warm_carry") is not None
        has_ex = p0.ex_state is not None and not has_warm
        n_slots = int(kw0.get("n_slots") or 0) or int(p0.n_slots)

        def stack(trees):
            return jax.tree_util.tree_map(
                lambda *ls: np.stack([np.asarray(x) for x in ls]), *trees
            )

        def member_cls(p, kw):
            count = kw.get("count")
            if count is None:
                return p.cls
            return p.cls._replace(count=np.asarray(count, dtype=np.int32))

        cls_list = [member_cls(p, kw_of(i)) for i, p in enumerate(preps)]
        # coalesced occupancy: the preps arrive bucket-padded, so the real
        # row count is recovered from the count vector (padded rows never
        # carry pods; a repair's count holds only this tick's delta pods) —
        # one ledger entry for the whole stacked dispatch
        padded_rows = int(np.asarray(p0.cls.count).shape[0])
        real_rows = sum(
            int(np.count_nonzero(np.asarray(c.count))) for c in cls_list
        ) / len(preps)
        compilecache.record_batch_occupancy(
            real_rows, padded_rows, n_slots, n_passes=p0.n_passes,
            mesh_axes=mesh_mod.tenant_mesh_axes(len(preps)),
            tenants=len(preps),
        )
        with tracing.span("solve.coalesced", tenants=len(preps),
                          n_slots=n_slots, repair=has_warm,
                          tenant=",".join(tenants) if tenants else None):
            args = [stack(cls_list),
                    stack([p.statics_arrays for p in preps])]
            ex_static0 = p0.ex_static
            if has_warm:
                from karpenter_core_tpu.ops import solve as solve_ops

                # the warm variant always takes the ex-static planes;
                # synthesize the empty ones exactly as the solo
                # run_prepared does for preps that never had a fleet
                ex_statics = []
                for p in preps:
                    if p.ex_static is not None:
                        ex_statics.append(p.ex_static)
                    else:
                        ex_statics.append(solve_ops.empty_existing_static(
                            p.cls.requests.shape[-1], p.cls.count.shape[0],
                            p.statics_arrays.grp_skew.shape[0],
                        ))
                ex_static0 = ex_statics[0]
                args.append(stack(ex_statics))
                args.append(stack([kw_of(i)["warm_carry"]
                                   for i in range(len(preps))]))
                args.append(stack([kw_of(i)["repair_plan"]
                                   for i in range(len(preps))]))
            elif has_ex:
                args.append(stack([p.ex_state for p in preps]))
                args.append(stack([p.ex_static for p in preps]))
            mesh_axes = mesh_mod.tenant_mesh_axes(len(preps))
            fn = compilecache.batched_solve_callable(
                len(preps), cls_list[0], p0.statics_arrays, n_slots,
                p0.key_has_bounds, None if has_warm else p0.ex_state,
                ex_static0, p0.n_passes, p0.features, mesh_axes,
                warm_carry=kw0.get("warm_carry"),
                repair_plan=kw0.get("repair_plan"),
            )
            if mesh_axes is not None:
                mesh = mesh_mod.mesh_for(mesh_axes)
                args = [
                    jax.device_put(a, mesh_mod.tenant_mesh_shardings(a, mesh))
                    for a in args
                ]
            # ONE batched fetch of the stacked outputs, sliced per tenant on
            # the host: decode consumes every plane anyway, and host slicing
            # avoids compiling a per-leaf-per-index gather op on device.
            # The fetch goes through the pipeline helper — async copies on
            # every leaf first, then one device_get — so the NEXT coalesced
            # group's dispatch (another worker thread) overlaps this group's
            # device→host copy instead of queueing behind per-array blocking
            # transfers (docs/KERNEL_PERF.md "Layer 7").
            outs = pipeline_mod.fetch_tree(fn(*args), site="tenant.batch")
            return [
                jax.tree_util.tree_map(lambda a, i=i: a[i], outs)
                for i in range(len(preps))
            ]


# -- per-tenant state ---------------------------------------------------------


@dataclass
class TenantEntry:
    """Everything the plane keeps per tenant."""

    tenant_id: str
    session: object  # solver.incremental.IncrementalSolveSession
    breaker: retry.CircuitBreaker
    bucket: retry.RetryBudget
    shed_backoff: retry.Backoff
    # re-entrant: the serve path holds it across the whole solve, and the
    # dispatch hook / checkpoint plane re-take it for their own field access
    # so every entry-field touch is lexically locked (shared-state pass)
    lock: threading.RLock = field(default_factory=threading.RLock)
    last_seen: float = 0.0
    supply_digest: Optional[str] = None
    last_batched: int = 1
    # weighted fair share: the resolved weight this entry's bucket was shaped
    # for (a change reshapes the bucket in place)
    weight: float = 1.0
    # durable sessions (service/journal.py): per-tenant record sequence (0 at
    # each anchor, +1 per delta) and the one-shot recovery echo ("warm")
    # surfaced on the first post-recovery response
    journal_tseq: int = 0
    recovered: Optional[str] = None
    # fleet checkpoints (fleet/checkpoint.py): the raw wire bytes of the last
    # FULL-solve request and its class-identity digests — what a peer replica
    # re-decodes to rebuild this lineage's snapshot — plus the solves-since-
    # last-checkpoint cadence counter
    anchor_request: Optional[bytes] = None
    anchor_uid_bases: Tuple[str, ...] = ()
    ckpt_ticks: int = 0
    # per-entry coalescer bypass: a recovery/failover replay dispatches THIS
    # tenant's solves solo (no rendezvous waits) without degrading concurrent
    # tenants' batching — the per-request property the dispatch hook reads
    # (the old plane-wide flag was racy under concurrent tenant requests)
    bypass_coalescer: bool = False


class TenantPlane:
    """Admission + sessions + breakers + the coalescer, as one unit the
    service owns.  Thread-safe; ``clock`` drives every timing POLICY (TTL,
    breaker reset, bucket refill) so FakeClock suites are deterministic."""

    def __init__(self, clock: Optional[Clock] = None,
                 config: Optional[TenantConfig] = None) -> None:
        self.clock = clock or Clock()
        self.config = config or TenantConfig.from_env()
        self.coalescer = BatchCoalescer(
            self.config.batch_window_s, self.config.max_batch
        )
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, TenantEntry]" = OrderedDict()
        self._inflight = 0
        self._last_sweep = self.clock.now()
        # graceful drain (docs/SERVICE.md): set, every admission sheds with a
        # retry-after hint while in-flight solves finish
        self._draining = False
        self._drain_hint_s = 5.0
        # session-drop hook: the durable-session journal records evictions so
        # recovery never resurrects a dropped lineage
        self.on_drop: Optional[Callable[[str], None]] = None
        # plane-wide coalescer bypass: only the pre-traffic restart recovery
        # sets it (no concurrent requests exist yet).  Replays DURING traffic
        # use the per-entry TenantEntry.bypass_coalescer instead — a
        # plane-wide flip would race concurrent tenants out of their batches.
        self._bypass_coalescer = False

    # -- session lifecycle -----------------------------------------------------

    def _new_entry(self, tenant_id: str, weight: float = 1.0) -> TenantEntry:
        from karpenter_core_tpu.solver.incremental import (
            FallbackPolicy,
            IncrementalSolveSession,
        )

        cfg = self.config
        budget, window_s = cfg.bucket_shape(weight)
        entry = TenantEntry(
            tenant_id=tenant_id,
            session=None,
            breaker=retry.CircuitBreaker(
                self.clock,
                failure_threshold=cfg.breaker_threshold,
                reset_timeout_s=cfg.breaker_reset_s,
                name=f"tenant:{tenant_id}",
            ),
            bucket=retry.RetryBudget(
                self.clock, budget=budget,
                window_s=window_s,
                name=f"tenant:{tenant_id}",
            ),
            shed_backoff=retry.Backoff(0.25, 30.0),
            last_seen=self.clock.now(),
            weight=weight,
        )
        session = IncrementalSolveSession(
            policy=FallbackPolicy.from_env(),
            run_prepared=lambda prep, **kw: self._dispatch(entry, prep, **kw),
        )
        entry.session = session
        return entry

    def _dispatch(self, entry: TenantEntry, prep, **kw):
        """The session's dispatch hook: plain full solves AND repair/delta
        dispatches (``warm_carry`` + ``repair_plan`` kwargs) are coalescing
        candidates — compatible repair windows from different tenants fuse on
        one vmapped dispatch (docs/SERVICE.md "Solve fusion").  Anything else
        parameterized (the bare slot-exhaustion retry) dispatches solo, as
        does a replaying entry (``bypass_coalescer`` — per entry, so one
        tenant's recovery replay never degrades concurrent tenants)."""
        solver = entry.session.solver
        tenant = tenant_label(entry.tenant_id)
        is_repair = (
            kw.get("warm_carry") is not None
            and kw.get("repair_plan") is not None
        )
        with entry.lock:
            bypass = entry.bypass_coalescer or self._bypass_coalescer
        fusable = not kw or (is_repair and self.config.coalesce_repairs)
        if bypass or not fusable:
            TENANT_DISPATCH.labels(tenant, "solo").inc()
            if is_repair:
                TENANT_REPAIR_DISPATCH.labels(tenant, "solo").inc()
            return solver.run_prepared(prep, **kw)
        outputs, batched = self.coalescer.run(
            prep, lambda: solver.run_prepared(prep, **kw),
            tenant=entry.tenant_id, kw=kw or None,
        )
        with entry.lock:
            entry.last_batched = batched
        mode = "coalesced" if batched > 1 else "solo"
        TENANT_DISPATCH.labels(tenant, mode).inc()
        if is_repair:
            TENANT_REPAIR_DISPATCH.labels(tenant, mode).inc()
        return outputs

    def checkout(self, tenant_id: str, weight: Optional[float] = None) -> TenantEntry:
        """The tenant's entry (created on first sight), LRU-touched; expired
        and over-capacity sessions are evicted on the way.  ``weight`` is the
        resolved fair-share weight (None = default); a change reshapes the
        entry's bucket in place, carrying the current fill proportionally."""
        now = self.clock.now()
        with self._lock:
            self._sweep_locked(now)
            entry = self._entries.get(tenant_id)
            if entry is None:
                entry = self._new_entry(
                    tenant_id, weight if weight is not None else 1.0
                )
                self._entries[tenant_id] = entry
                while len(self._entries) > self.config.max_sessions:
                    evicted_id, evicted = self._entries.popitem(last=False)
                    self._drop_entry(evicted, "lru")
            else:
                self._entries.move_to_end(tenant_id)
                if weight is not None and abs(weight - entry.weight) > 1e-9:
                    budget, window_s = self.config.bucket_shape(weight)
                    entry.bucket.reconfigure(budget, window_s)
                    entry.weight = weight
            entry.last_seen = now
            TENANT_SESSIONS_LIVE.labels().set(float(len(self._entries)))
            return entry

    def restore_entry(self, tenant_id: str) -> TenantEntry:
        """A fresh entry for journal recovery (service/journal.py): no
        admission, no LRU touch beyond registration — the restored lineage is
        attached by the caller after replay verifies."""
        with self._lock:
            entry = self._new_entry(
                tenant_id, self.config.resolve_weight(tenant_id)
            )
            self._entries[tenant_id] = entry
            while len(self._entries) > self.config.max_sessions:
                _evicted_id, evicted = self._entries.popitem(last=False)
                self._drop_entry(evicted, "lru")
            TENANT_SESSIONS_LIVE.labels().set(float(len(self._entries)))
            return entry

    def entries_snapshot(self) -> Dict[str, TenantEntry]:
        """A point-in-time copy of the resident tenant map (drain-time fleet
        checkpointing iterates it without holding the plane lock)."""
        with self._lock:
            return dict(self._entries)

    def discard_entry(self, tenant_id: str) -> None:
        """Remove a tenant whose recovery replay failed verification — the
        next request re-anchors ``session-lost`` exactly as if nothing had
        been journaled."""
        with self._lock:
            entry = self._entries.pop(tenant_id, None)
            if entry is not None:
                retry.BREAKER_STATE.delete_labels(f"tenant:{tenant_id}")
            TENANT_SESSIONS_LIVE.labels().set(float(len(self._entries)))

    def _sweep_locked(self, now: float) -> None:
        ttl = self.config.session_ttl_s
        if ttl <= 0:
            return
        # cadence-bound: a full scan per checkout would serialize every
        # tenant on the plane lock for O(resident sessions) work several
        # times per RPC — expiry only needs to be caught within a fraction
        # of the TTL, not on every access
        if now - self._last_sweep < max(ttl / 8.0, 1.0):
            return
        self._last_sweep = now
        expired = [
            tid for tid, e in self._entries.items() if now - e.last_seen > ttl
        ]
        for tid in expired:
            self._drop_entry(self._entries.pop(tid), "ttl")

    def _drop_entry(self, entry: TenantEntry, reason: str) -> None:
        TENANT_SESSIONS_EVICTED.labels(reason).inc()
        # the breaker gauge would otherwise report a dead tenant forever
        retry.BREAKER_STATE.delete_labels(f"tenant:{entry.tenant_id}")
        # journal the drop (enqueue-only; no lock ordering hazard: the
        # journal never takes plane locks) so recovery cannot resurrect an
        # evicted lineage
        if self.on_drop is not None:
            self.on_drop(entry.tenant_id)

    def sessions(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    # -- admission -------------------------------------------------------------

    def start_draining(self, retry_after_s: float = 5.0) -> None:
        """Graceful drain: every subsequent admission sheds with this
        retry-after hint; in-flight solves finish normally."""
        self._drain_hint_s = max(retry_after_s, 0.1)
        self._draining = True

    def admit(self, tenant_id: str, weight=None) -> AdmissionDecision:
        """Admission gate; an admitted request MUST be paired with
        ``release()``.  Order: draining → isolation (breaker) → global
        in-flight bound → per-tenant rate.  The queue check runs BEFORE the
        token bucket so global pressure caused by OTHER tenants never burns
        this tenant's own tokens (a queue-shed retry must not escalate into
        a rate shed).  ``weight`` is the wire envelope's fair-share claim
        (config.resolve_weight decides; an operator env pin wins)."""
        tenant = tenant_label(tenant_id)
        if self._draining:
            # no checkout: a draining server must not mint fresh sessions
            TENANT_SHED.labels(tenant, "draining").inc()
            TENANT_RETRY_AFTER.labels(tenant).observe(self._drain_hint_s)
            return AdmissionDecision(False, "draining", self._drain_hint_s)
        entry = self.checkout(
            tenant_id, weight=self.config.resolve_weight(tenant_id, weight)
        )
        if not entry.breaker.allow():
            hint = max(entry.breaker.reset_timeout_s, 1.0)
            TENANT_SHED.labels(tenant, "isolated").inc()
            TENANT_RETRY_AFTER.labels(tenant).observe(hint)
            return AdmissionDecision(False, "isolated", hint, entry=entry)
        granted_trial = entry.breaker.state == retry.HALF_OPEN
        with self._lock:
            queued = self._inflight >= self.config.max_inflight
            if not queued:
                self._inflight += 1
        if queued:
            if granted_trial:
                entry.breaker.release_trial()  # shed ≠ a backend verdict
            TENANT_SHED.labels(tenant, "queue").inc()
            hint = max(entry.shed_backoff.next(), 0.25)
            TENANT_RETRY_AFTER.labels(tenant).observe(hint)
            return AdmissionDecision(False, "queue", hint, entry=entry)
        if not entry.bucket.allow():
            with self._lock:
                self._inflight = max(0, self._inflight - 1)
            if granted_trial:
                entry.breaker.release_trial()
            hint = max(entry.bucket.next_token_s(), 0.05)
            # repeated sheds escalate the hint so a hammering client backs
            # off harder each time (reset on the next successful admit)
            hint = max(hint, entry.shed_backoff.next())
            TENANT_SHED.labels(tenant, "rate").inc()
            TENANT_RETRY_AFTER.labels(tenant).observe(hint)
            return AdmissionDecision(False, "rate", hint, entry=entry)
        entry.shed_backoff.reset()
        TENANT_ADMITTED.labels(tenant).inc()
        return AdmissionDecision(True, entry=entry, trial=granted_trial)

    def release(self, tenant_id: str) -> None:
        with self._lock:
            self._inflight = max(0, self._inflight - 1)

    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    # -- fault accounting ------------------------------------------------------

    def record_bad_request(self, entry: TenantEntry, reason: str) -> None:
        """Malformed / oversized snapshot: tenant-attributable, breaker
        counts it toward isolation."""
        TENANT_EJECTED.labels(tenant_label(entry.tenant_id), reason).inc()
        entry.breaker.record_failure()

    def record_fault(self, entry: TenantEntry) -> None:
        """This tenant's solve faulted (ejected from its batch)."""
        TENANT_EJECTED.labels(tenant_label(entry.tenant_id), "solve-fault").inc()
        entry.breaker.record_failure()

    def record_timeout(self, entry: TenantEntry) -> None:
        """This tenant's solve overran its watchdog deadline (a structured
        ejection, docs/SERVICE.md "Timeout ejection"): the abandoned device
        call never wedges the worker, and the tenant breaker counts it — a
        tenant whose snapshots reliably hang the backend isolates exactly
        like one whose snapshots fault it."""
        TENANT_EJECTED.labels(
            tenant_label(entry.tenant_id), "watchdog-timeout"
        ).inc()
        entry.breaker.record_failure()

    def record_ok(self, entry: TenantEntry) -> None:
        entry.breaker.record_success()

    # -- latency observation (diagnostic wall time, not policy) ---------------

    @staticmethod
    def observe_latencies(tenant_id: str, queue_s: float, solve_s: float,
                          decode_s: float) -> None:
        tenant = tenant_label(tenant_id)
        TENANT_QUEUE_LATENCY.labels(tenant).observe(max(queue_s, 0.0))
        TENANT_SOLVE_LATENCY.labels(tenant).observe(max(solve_s, 0.0))
        TENANT_DECODE_LATENCY.labels(tenant).observe(max(decode_s, 0.0))
        SLO_TRACKER.observe(tenant, max(solve_s, 0.0))


def monotonic() -> float:
    """Latency measurement clock (diagnostics only — timing POLICY goes
    through the injected utils/clock.Clock)."""
    return time.perf_counter()
