"""The snapshot channel: gRPC service in front of the TPU solver.

The north-star architecture (BASELINE.json) keeps the controller plane where
it is and ships cluster-state snapshots over gRPC to a solver sidecar on the
TPU host — this module is that channel.  Requests carry pods, provisioners,
and existing nodes (apis.codec wire dicts, msgpack-framed); responses carry
node decisions, existing-node nominations, and failures.

Implemented with gRPC generic method handlers (no codegen: the environment has
no protoc python plugin) — the method contract is documented here and stable:

    /karpenter.v1.SnapshotSolver/Solve         unary-unary, msgpack bytes
    /karpenter.v1.SnapshotSolver/SolveClasses  unary-unary, msgpack bytes
    /karpenter.v1.SnapshotSolver/Health        unary-unary, empty → msgpack bytes

SolveClasses is the class-columnar fast path: the controller plane dedups its
pending pods into shape classes (models.columnar.PodIngest) and ships ONE
representative pod + count per class — O(distinct shapes) on the wire instead
of O(pods) — and gets back per-node class counts it expands locally.  At 50k
pods / ~13 shapes that is a ~4000× smaller request than /Solve.

The MULTI-TENANT layer (service/tenant.py, docs/SERVICE.md): a SolveClasses
request carrying a ``tenant`` envelope routes through admission control
(token-bucket rate limits + a bounded global queue, RESOURCE_EXHAUSTED sheds
with a retry-after hint), a per-tenant server-side incremental-solve session
(LRU + TTL, ``session-lost`` re-anchor after restarts), per-tenant circuit
breakers, and the batch coalescer that stacks compatible-shape-bucket
tenants into ONE vmapped device solve.  Requests without the envelope keep
the original stateless contract exactly.

``service.rpc`` is the chaos point on this channel — the one major I/O
boundary the other six points don't cover.  It fires on both sides: the
client wrapper (error/timeout raised before the call leaves) and the server
handlers (error → UNAVAILABLE, timeout → DEADLINE_EXCEEDED, partial →
the solve runs but the response is dropped, latency through the armed
clock) — so chaos suites can flap the whole service and watch the
controller's solver breaker + degraded mode absorb it.
"""

from __future__ import annotations

import copy
import json
import logging
import os
import threading
import time
from concurrent import futures
from typing import Dict, List, Optional

import grpc
import msgpack

from karpenter_core_tpu import chaos, tracing
from karpenter_core_tpu import metrics as metrics_mod
from karpenter_core_tpu.apis import codec
from karpenter_core_tpu.models.snapshot import KernelUnsupported
from karpenter_core_tpu.service import journal as journal_mod
from karpenter_core_tpu.service import tenant as tenant_mod
from karpenter_core_tpu.solver.tpu import TPUSolver
from karpenter_core_tpu.state.cluster import StateNode
from karpenter_core_tpu.utils.watchdog import SolveTimeout

log = logging.getLogger(__name__)

SERVICE = "karpenter.v1.SnapshotSolver"

# the gRPC channel's injection point (docs/CHAOS.md): one Point, both
# transports — like kubeapi.put covers both kube backends
SERVICE_RPC = chaos.point("service.rpc")


class _AbortRequest(Exception):
    """Internal: carry a (code, details) abort decision out of helper depth
    to the handler boundary.  ``context.abort`` raises a BARE Exception, so
    calling it under a ``try/except Exception`` would get the abort re-caught
    and re-labeled INTERNAL — helpers raise this instead and the outermost
    handler translates it exactly once."""

    def __init__(self, code, details: str) -> None:
        super().__init__(details)
        self.code = code
        self.details = details


class _WireVolumeResolver:
    """Minimal kube-lookup surface for PVC→CSI-driver resolution
    (scheduling.VolumeUsage), backed by the request's ``claimDrivers`` map —
    the controller plane resolves claims against its apiserver and ships just
    the answers."""

    _PREFIX = "wire://"

    def __init__(self, claim_drivers) -> None:
        self.claim_drivers = dict(claim_drivers or {})

    def get_persistent_volume_claim(self, namespace: str, name: str):
        from karpenter_core_tpu.apis.objects import (
            ObjectMeta,
            PersistentVolumeClaim,
            PersistentVolumeClaimSpec,
        )

        driver = self.claim_drivers.get(f"{namespace}/{name}")
        if driver is None:
            return None
        return PersistentVolumeClaim(
            metadata=ObjectMeta(name=name, namespace=namespace),
            spec=PersistentVolumeClaimSpec(storage_class_name=self._PREFIX + driver),
        )

    def get_persistent_volume(self, name: str):
        return None

    def get_storage_class(self, name: str):
        from karpenter_core_tpu.apis.objects import ObjectMeta, StorageClass

        if name.startswith(self._PREFIX):
            return StorageClass(
                metadata=ObjectMeta(name=name), provisioner=name[len(self._PREFIX):]
            )
        return None


class SnapshotSolverService(grpc.GenericRpcHandler):
    """Solver endpoint: each solve request is one stateless snapshot solve.

    The service additionally hosts the coordination-lease plane
    (/LeaseGet, /LeaseApply): the solver is the deployment's one shared
    singleton (it owns the TPU), so operator replicas elect their leader
    through it — the role the apiserver's Lease object plays for the
    reference (operator.go:111-126).  Lease CAS is monotonic on a
    server-assigned resourceVersion; wall-clock staleness is judged by the
    electors, not here."""

    def __init__(self, cloud_provider, clock=None, tenant_config=None,
                 journal_dir=None, fleet=None) -> None:
        self.cloud_provider = cloud_provider
        # fleet membership (karpenter_core_tpu.fleet, docs/FLEET.md): when
        # this process is one replica of a routed fleet (``fleet`` argument
        # or KC_FLEET=1 + KC_FLEET_DIR), it writes tensor-level session
        # checkpoints to the shared directory, restores adopted tenants from
        # peers' checkpoints, and scales its LOCAL admission buckets to 1/N
        # as a backstop behind the router's fleet-level buckets.  None — the
        # default — leaves every byte of service behavior unchanged.
        from karpenter_core_tpu import fleet as fleet_mod

        self.fleet = fleet if fleet is not None else fleet_mod.FleetLocal.from_env()
        self._ckpt = None
        self._pulse = None  # ReplicaPulse, attached by serve()
        if self.fleet is not None and self.fleet.size > 1:
            tenant_config = (
                tenant_config or tenant_mod.TenantConfig.from_env()
            ).fleet_scaled(self.fleet.size)
        # the multi-tenant plane: admission + sessions + breakers + coalescer
        # (service/tenant.py).  ``clock`` drives every timing policy so
        # FakeClock suites can step TTLs and breaker windows.
        self.tenants = tenant_mod.TenantPlane(clock=clock, config=tenant_config)
        if self.fleet is not None:
            from karpenter_core_tpu.fleet.checkpoint import CheckpointPlane

            self._ckpt = CheckpointPlane(
                self.fleet.checkpoint_dir(), clock=self.tenants.clock,
                replica_id=self.fleet.replica_id,
                every=self.fleet.ckpt_every,
            )
            # a replica journals under the shared fleet root so peers can
            # replay its chains when a checkpoint is stale (the failover
            # ladder's middle rung); an explicit journal_dir or
            # KC_JOURNAL_DIR still wins
            if (
                journal_dir is None
                and os.environ.get("KC_SESSION_JOURNAL", "0") == "1"
                and not os.environ.get("KC_JOURNAL_DIR")
                and self.fleet.replica_id
            ):
                journal_dir = self.fleet.journal_dir()
        # durable sessions (service/journal.py, docs/SERVICE.md): when a
        # journal directory is configured, every completed tenant solve is
        # journaled and a restart replays the per-tenant chains back into
        # WARM lineages before the server takes traffic.  KC_SESSION_JOURNAL
        # enables it env-side; an explicit journal_dir argument always wins.
        self.journal = None
        if journal_dir is None and os.environ.get("KC_SESSION_JOURNAL", "0") == "1":
            journal_dir = os.environ.get("KC_JOURNAL_DIR", "")
            if not journal_dir:
                from karpenter_core_tpu.utils import compilecache

                journal_dir = os.path.join(
                    compilecache.cache_dir(), "session-journal"
                )
        if journal_dir:
            self.journal = journal_mod.SessionJournal(
                journal_dir,
                clock=self.tenants.clock,
                checkpoint_every=tenant_mod._env_i(
                    "KC_JOURNAL_CHECKPOINT_EVERY", 64
                ),
                fsync=os.environ.get("KC_JOURNAL_FSYNC", "1") != "0",
            )
            self._recover_sessions()
            self.journal.start()
            self.tenants.on_drop = self.journal.append_drop
        if self._ckpt is not None:
            # a dropped tenant's checkpoint must not resurrect it on a peer
            journal_drop = self.tenants.on_drop

            def _drop_everywhere(tenant_id: str, _j=journal_drop) -> None:
                if _j is not None:
                    _j(tenant_id)
                self._ckpt.drop(tenant_id)

            self.tenants.on_drop = _drop_everywhere
        # server-side per-RPC deadline: an abandoned/slow client cannot pin a
        # worker past this (0 disables); checked at the solve stage
        # boundaries, the coarsest-grained units of handler work
        self.deadline_s = tenant_mod._env_f("KC_SERVICE_DEADLINE_S", 120.0)
        self._leases: Dict[tuple, Dict] = {}
        self._lease_lock = threading.Lock()
        # best-effort durability: a solver restart that wiped the lease map
        # would let both electors race the re-create (a ~retry_period
        # dual-leader window even with the electors' conflict-demote); the
        # compile-cache volume the deployment already mounts carries the
        # lease state across restarts for free
        self._lease_path = os.environ.get("KC_LEASE_STATE", "")
        if not self._lease_path:
            from karpenter_core_tpu.utils import compilecache

            self._lease_path = os.path.join(compilecache.cache_dir(), "leases.json")
        self._load_leases()

    def _load_leases(self) -> None:
        try:
            with open(self._lease_path) as f:
                for entry in json.load(f):
                    self._leases[(entry.get("namespace", ""), entry["name"])] = entry
            log.info("lease plane restored %d lease(s) from %s",
                     len(self._leases), self._lease_path)
        except FileNotFoundError:
            pass
        except Exception as e:  # noqa: BLE001 - durability is best-effort
            log.warning("lease state load failed (%s), starting empty", e)

    def _persist_leases(self) -> None:
        """Write-through under the lease lock; atomic replace."""
        try:
            os.makedirs(os.path.dirname(self._lease_path), exist_ok=True)
            tmp = f"{self._lease_path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(list(self._leases.values()), f)
            os.replace(tmp, self._lease_path)
        except Exception as e:  # noqa: BLE001 - durability is best-effort
            log.debug("lease state persist failed: %s", e)

    # -- durable-session recovery (service/journal.py, docs/SERVICE.md) -------

    def _recover_sessions(self) -> None:
        """Replay the journal's per-tenant chains into warm server-side
        lineages, with never-trust verification: a replayed lineage must
        reproduce the journaled ``lineage_state`` exactly or the tenant is
        downgraded to the existing ``session-lost`` re-anchor.  Runs before
        the server accepts traffic."""
        chains, broken, stats = self.journal.recover()
        # outcome semantics: "corrupt" = the frame STREAM broke (torn tail /
        # CRC failure), counted per damaged file; a structurally broken
        # chain (tseq gap from a dropped append, version skew) is a
        # "reanchor" — the disk is fine, the tenant just re-anchors
        for status in (stats.get("checkpoint"), stats.get("journal")):
            if status in (journal_mod.STATUS_TORN, journal_mod.STATUS_CORRUPT):
                journal_mod.SESSION_RECOVERED.labels("corrupt").inc()
        for _tenant in sorted(broken):
            journal_mod.SESSION_RECOVERED.labels("reanchor").inc()
        if not chains:
            return
        plane = self.tenants
        # most-recent chains win the session cap (the LRU the crash erased)
        ordered = sorted(
            chains.items(), key=lambda kv: int(kv[1][-1].get("seq", 0))
        )
        if len(ordered) > plane.config.max_sessions:
            ordered = ordered[-plane.config.max_sessions:]
        warm = 0
        # warm-restart watchdog (docs/SERVICE.md): each tenant's replay runs
        # under a wall-clock deadline with a progress log every N frames —
        # one pathological chain (or a quiet device mid-replay; each solve
        # inside is already watchdog-bounded) downgrades THAT tenant to the
        # session-lost re-anchor instead of stalling the whole restart.
        # KC_JOURNAL_REPLAY_DEADLINE_S (default 30, 0 disables) bounds one
        # tenant; KC_JOURNAL_REPLAY_LOG_EVERY (default 16) paces the log.
        replay_deadline_s = tenant_mod._env_f(
            "KC_JOURNAL_REPLAY_DEADLINE_S", 30.0
        )
        log_every = max(tenant_mod._env_i("KC_JOURNAL_REPLAY_LOG_EVERY", 16), 1)
        plane._bypass_coalescer = True  # replay is solo: no rendezvous waits
        try:
            for tenant_id, chain in ordered:
                entry = plane.restore_entry(tenant_id)
                t_replay = time.perf_counter()
                # fleet checkpoint rung: when this tenant's published
                # checkpoint is EXACTLY as fresh as the journal tail (full
                # lineage-state equality, never timestamps), one deserialize
                # replaces the whole chain replay.  Stale or damaged
                # checkpoints fall through to replay below.
                if (
                    self._ckpt is not None
                    and self._fleet_restore_chain(entry, tenant_id, chain)
                ):
                    last = chain[-1]
                    entry.supply_digest = last.get("client_supply")
                    entry.journal_tseq = int(last.get("tseq", 0))
                    entry.recovered = "warm"
                    warm += 1
                    journal_mod.SESSION_RECOVERED.labels("warm").inc()
                    journal_mod.SESSION_REPLAY_DURATION.labels(
                        metrics_mod.tenant_label(tenant_id)
                    ).observe(time.perf_counter() - t_replay)
                    continue
                # trace linkage across the restart: the most recent journaled
                # record carrying a trace context names the originating trace
                # — the replay's spans adopt it (span_remote), so /debug/
                # traces shows the crashed solve and its warm replay as one
                # tree.  Old journals without the field replay untraced-
                # linked, exactly as before (schema-additive).
                trace_ctx = next(
                    (rec.get("trace") for rec in reversed(chain)
                     if rec.get("trace")), None,
                )
                try:
                    with tracing.span_remote("session.recover", trace_ctx,
                                             tenant=tenant_id,
                                             records=len(chain)):
                        for i, rec in enumerate(chain):
                            if (
                                replay_deadline_s > 0
                                and time.perf_counter() - t_replay
                                > replay_deadline_s
                            ):
                                raise journal_mod.RecoveryMismatch(
                                    f"replay exceeded its "
                                    f"{replay_deadline_s:.0f}s deadline at "
                                    f"frame {i}/{len(chain)}"
                                )
                            self._replay_record(entry, rec)
                            if (i + 1) % log_every == 0:
                                log.info(
                                    "session recovery: tenant %s frame "
                                    "%d/%d (%.1fs elapsed)", tenant_id,
                                    i + 1, len(chain),
                                    time.perf_counter() - t_replay,
                                )
                        state = entry.session.lineage_state()
                        want = chain[-1].get("state") or {}
                        if state != want:
                            raise journal_mod.RecoveryMismatch(
                                f"replayed lineage state diverged "
                                f"(have version {state.get('version')}, "
                                f"journal {want.get('version')})"
                            )
                except Exception as e:  # noqa: BLE001 - downgrade, never trust
                    log.warning(
                        "session recovery for tenant %s downgraded to "
                        "re-anchor: %s", tenant_id, e,
                    )
                    plane.discard_entry(tenant_id)
                    self.journal.append_drop(tenant_id)
                    journal_mod.SESSION_RECOVERED.labels("reanchor").inc()
                else:
                    last = chain[-1]
                    entry.supply_digest = last.get("client_supply")
                    entry.journal_tseq = int(last.get("tseq", 0))
                    entry.recovered = "warm"
                    warm += 1
                    journal_mod.SESSION_RECOVERED.labels("warm").inc()
                finally:
                    journal_mod.SESSION_REPLAY_DURATION.labels(
                        metrics_mod.tenant_label(tenant_id)
                    ).observe(time.perf_counter() - t_replay)
        finally:
            plane._bypass_coalescer = False
        log.info(
            "session journal recovery: %d/%d lineage(s) warm, %d broken "
            "chain(s) (checkpoint=%s journal=%s)",
            warm, len(ordered), len(broken),
            stats.get("checkpoint"), stats.get("journal"),
        )

    def _replay_record(self, entry, rec: dict) -> None:
        """Re-run one journaled solve from its stored wire request.  Solves
        are deterministic, so replaying the anchor + deltas reconstructs the
        crashed process's lineage bit for bit; the store version is seeded so
        the restored lineage answers to the exact version the client was
        last told."""
        from karpenter_core_tpu.policy import PolicyConfig
        from karpenter_core_tpu.solver.incremental import MODE_FULL

        req = msgpack.unpackb(rec["request"])
        (classes, uid_class, provisioners, daemonset_pods, state_nodes,
         bound, resolver) = self._decode_tenant_classes(req)
        solver = TPUSolver(
            self.cloud_provider, provisioners, daemonset_pods,
            kube_client=resolver,
            policy=PolicyConfig.from_wire(req.get("policy")),
        )
        session = entry.session
        session.rebind(solver)
        if rec.get("kind") == journal_mod.KIND_ANCHOR:
            session.reset()
            session.store.seed_version(int(rec.get("version", 1)) - 1)
            # fleet checkpoints serialize the lineage's anchor request so an
            # adopting peer can re-encode without this journal — capture it
            # on replay too, so a recovered replica checkpoints complete
            entry.anchor_request = bytes(rec["request"])
            entry.anchor_uid_bases = tuple(uid_class)
        session.solve(classes, state_nodes or None, bound)
        want_full = rec.get("kind") == journal_mod.KIND_ANCHOR
        if (session.last_mode == MODE_FULL) != want_full:
            raise journal_mod.RecoveryMismatch(
                f"replayed {rec.get('kind')} record resolved as "
                f"{session.last_mode}"
            )

    # -- fleet failover (karpenter_core_tpu.fleet, docs/FLEET.md) --------------

    @staticmethod
    def _reset_session_store(entry) -> None:
        """A failed warm restore can leave the session's store committed at
        the checkpoint's version; the next rung (replay, or the session-lost
        full solve) must start from a store that has never committed, or
        ``seed_version`` refuses."""
        from karpenter_core_tpu.models.store import SnapshotStore

        entry.session.reset()
        entry.session.store = SnapshotStore()

    def _fleet_restore_chain(self, entry, tenant_id: str, chain) -> bool:
        """Checkpoint rung of journal recovery: restore this tenant's fleet
        checkpoint in one deserialize iff its lineage state equals the
        journal chain's tail exactly.  Returns False (with the entry's
        session left fresh) on miss, staleness, or any restore failure."""
        from karpenter_core_tpu.fleet import checkpoint as ckpt_mod

        ckpt, _status = self._ckpt.load(tenant_id)
        if ckpt is None:
            return False
        want = chain[-1].get("state") or {}
        if ckpt.state != want:
            log.info(
                "fleet checkpoint for tenant %s is stale (version %s, "
                "journal %s): replaying the chain", tenant_id,
                ckpt.version, want.get("version"),
            )
            return False
        try:
            ckpt_mod.restore_session(ckpt, entry.session, self.cloud_provider)
        except Exception as e:  # noqa: BLE001 - downgrade, never trust
            log.warning(
                "fleet checkpoint restore for tenant %s failed (%s); "
                "replaying the chain", tenant_id, e,
            )
            self._reset_session_store(entry)
            return False
        entry.anchor_request = bytes(ckpt.anchor)
        entry.anchor_uid_bases = tuple(
            str(b) for b in ckpt.header.get("uid_bases", [])
        )
        entry.ckpt_ticks = 0
        return True

    def _fleet_adopt(self, tenant_id: str, entry, claimed: int) -> bool:
        """Cross-replica failover ladder: a client claiming a warm lineage
        this replica doesn't hold may be a routed-over tenant whose previous
        replica died or drained.  Rungs, cheapest first:

          warm      one checkpoint deserialize + never-trust verify
          replay    re-run the tenant's chain from a PEER's journal directory
          reanchor  give up; the caller runs the session-lost full solve

        Every rung must land the lineage at EXACTLY the version the client
        claims — anything else would answer session-lost anyway.  Outcomes
        count on ``karpenter_fleet_failover_total``."""
        from karpenter_core_tpu import fleet as fleet_mod
        from karpenter_core_tpu.fleet import checkpoint as ckpt_mod

        # the entry may carry a committed store from an earlier reset
        # lineage (reset drops the warm state, not the store) — both rungs
        # seed_version, which demands a never-committed store
        if entry.session.store.current is not None:
            self._reset_session_store(entry)
        ckpt, _status = self._ckpt.load(tenant_id)
        if ckpt is not None and ckpt.version == claimed:
            try:
                ckpt_mod.restore_session(
                    ckpt, entry.session, self.cloud_provider
                )
                entry.anchor_request = bytes(ckpt.anchor)
                entry.anchor_uid_bases = tuple(
                    str(b) for b in ckpt.header.get("uid_bases", [])
                )
                entry.ckpt_ticks = 0
                entry.supply_digest = ckpt.header.get("client_supply")
                entry.journal_tseq = int(ckpt.header.get("tseq", 0))
                fleet_mod.FAILOVER_TOTAL.labels("warm").inc()
                log.info(
                    "fleet failover: tenant %s adopted warm at version %d",
                    tenant_id, claimed,
                )
                return True
            except Exception as e:  # noqa: BLE001 - fall to the next rung
                log.warning(
                    "fleet failover: checkpoint restore for tenant %s "
                    "failed (%s); trying peer journals", tenant_id, e,
                )
                self._reset_session_store(entry)
        elif ckpt is not None:
            log.info(
                "fleet failover: checkpoint for tenant %s is at version %d, "
                "client claims %d; trying peer journals", tenant_id,
                ckpt.version, claimed,
            )
        if self._fleet_peer_replay(tenant_id, entry, claimed):
            fleet_mod.FAILOVER_TOTAL.labels("replay").inc()
            log.info(
                "fleet failover: tenant %s adopted by journal replay at "
                "version %d", tenant_id, claimed,
            )
            return True
        fleet_mod.FAILOVER_TOTAL.labels("reanchor").inc()
        return False

    @staticmethod
    def _read_peer_chain(directory: str, tenant_id: str):
        """Assemble one tenant's live chain from a peer replica's journal
        files, READ-ONLY — the peer's writer (if it still runs) owns the
        files; ``read_frames`` tolerates a torn tail by design."""
        ck, _ = journal_mod.read_frames(
            os.path.join(directory, "checkpoint.wal")
        )
        j, _ = journal_mod.read_frames(os.path.join(directory, "journal.wal"))
        if not ck and not j:
            return None
        mirror = journal_mod.ChainMirror()
        for rec in ck + j:
            mirror.apply(rec)
        if tenant_id in mirror.broken:
            return None
        return mirror.chains.get(tenant_id)

    def _fleet_peer_replay(self, tenant_id: str, entry, claimed: int) -> bool:
        """Replay rung: scan the shared fleet root for a PEER journal whose
        chain for this tenant ends at the claimed version, and replay it."""
        root = self.fleet.journal_root()
        try:
            peers = sorted(os.listdir(root))
        except OSError:
            return False
        plane = self.tenants
        for rid in peers:
            if rid == self.fleet.replica_id:
                continue
            chain = self._read_peer_chain(
                os.path.join(root, rid), tenant_id
            )
            if not chain or int(chain[-1].get("version", 0)) != claimed:
                continue
            # replay is solo by nature: the bypass is PER ENTRY, so only
            # this tenant's replayed solves skip the rendezvous — concurrent
            # tenants keep coalescing (the old plane-wide flag's save/
            # restore raced them out of their batches)
            entry.bypass_coalescer = True
            try:
                for rec in chain:
                    self._replay_record(entry, rec)
                state = entry.session.lineage_state()
                want = chain[-1].get("state") or {}
                if state != want:
                    raise journal_mod.RecoveryMismatch(
                        f"peer-replayed lineage diverged (have version "
                        f"{state.get('version')}, journal "
                        f"{want.get('version')})"
                    )
                last = chain[-1]
                entry.supply_digest = last.get("client_supply")
                entry.journal_tseq = int(last.get("tseq", 0))
                entry.ckpt_ticks = 0
                return True
            except Exception as e:  # noqa: BLE001 - next peer, never trust
                log.warning(
                    "fleet failover: peer %s journal replay for tenant %s "
                    "failed: %s", rid, tenant_id, e,
                )
                self._reset_session_store(entry)
            finally:
                entry.bypass_coalescer = False
        return False

    def _journal_solve(self, entry, tenant_id: str, mode: str,
                       supply_digest, request: bytes,
                       trace_ctx=None) -> None:
        """Append one completed tenant solve to the journal.  Called with the
        entry lock held — the verification state must snapshot the lineage
        the response was computed from; the actual I/O is enqueued.
        ``trace_ctx`` is the serving span's wire context: replay after a
        restart links back to the trace that originally produced the
        record (docs/OBSERVABILITY.md)."""
        version = entry.session.lineage_version()
        if self.journal is None or version <= 0:
            return  # nothing warm to recover (carry-less solve)
        if mode == "full":
            entry.journal_tseq = 0
            kind = journal_mod.KIND_ANCHOR
        else:
            entry.journal_tseq += 1
            kind = journal_mod.KIND_DELTA
        self.journal.append_solve(
            tenant=tenant_id,
            kind=kind,
            tseq=entry.journal_tseq,
            version=version,
            client_supply=supply_digest,
            state=entry.session.lineage_state(),
            request=bytes(request),
            trace_ctx=trace_ctx,
        )

    # -- graceful drain (SIGTERM path, docs/SERVICE.md) ------------------------

    def drain(self, timeout_s: Optional[float] = None,
              retry_after_s: Optional[float] = None) -> bool:
        """Stop admitting (sheds carry a retry-after hint), let in-flight
        solves finish, then flush + checkpoint the journal.  Returns True
        when the plane fully quiesced inside the timeout.  The caller stops
        the gRPC server afterwards."""
        if timeout_s is None:
            timeout_s = tenant_mod._env_f("KC_SERVICE_DRAIN_S", 30.0)
        if retry_after_s is None:
            retry_after_s = tenant_mod._env_f("KC_DRAIN_RETRY_AFTER_S", 5.0)
        if self._pulse is not None:
            # advertise the drain at the router FIRST (lease duration 0):
            # its next maintenance pass remaps this replica's arc, and the
            # adopting peers find the final checkpoints written below
            self._pulse.mark_draining()
        self.tenants.start_draining(retry_after_s)
        deadline = tenant_mod.monotonic() + max(timeout_s, 0.0)
        import time as _time

        while self.tenants.inflight() > 0 and tenant_mod.monotonic() < deadline:
            _time.sleep(0.02)
        drained = self.tenants.inflight() == 0
        if self._ckpt is not None:
            written = self._ckpt.write_all(self.tenants.entries_snapshot())
            if written:
                log.info(
                    "fleet drain: %d session checkpoint(s) published", written
                )
        if self.journal is not None:
            self.journal.close(checkpoint=True)
        if self._pulse is not None:
            self._pulse.stop()
        log.info("service drained (quiesced=%s)", drained)
        return drained

    def shutdown(self) -> None:
        """Non-drain teardown (tests, soak): release the journal cleanly
        without forcing a final checkpoint."""
        if self.journal is not None:
            self.journal.close(checkpoint=False)

    # -- grpc plumbing --------------------------------------------------------

    def service(self, handler_call_details):
        method = handler_call_details.method
        if method == f"/{SERVICE}/Solve":
            return grpc.unary_unary_rpc_method_handler(self._solve)
        if method == f"/{SERVICE}/SolveClasses":
            return grpc.unary_unary_rpc_method_handler(self._solve_classes)
        if method == f"/{SERVICE}/Health":
            return grpc.unary_unary_rpc_method_handler(self._health)
        if method == f"/{SERVICE}/Consolidate":
            return grpc.unary_unary_rpc_method_handler(self._consolidate)
        if method == f"/{SERVICE}/LeaseGet":
            return grpc.unary_unary_rpc_method_handler(self._lease_get)
        if method == f"/{SERVICE}/LeaseApply":
            return grpc.unary_unary_rpc_method_handler(self._lease_apply)
        return None

    # -- handlers -------------------------------------------------------------

    def _health(self, request: bytes, context) -> bytes:
        if self.fleet is None:
            # KC_FLEET off: byte-identical to the pre-fleet response
            return msgpack.packb({"status": "ok"})
        # fleet replicas self-describe: the router's health fan-out and the
        # soak's cross-process leak audit read these
        machines = 0
        created = getattr(self.cloud_provider, "created_machines", None)
        if callable(created):
            machines = len(created())
        return msgpack.packb({
            "status": "ok",
            "fleet": {
                "replica": self.fleet.replica_id,
                "sessions": len(self.tenants.entries_snapshot()),
                "machines": machines,
            },
        })

    def _rpc_chaos(self, context, method: str):
        """Server-transport leg of the ``service.rpc`` chaos point.  error →
        UNAVAILABLE now, timeout → DEADLINE_EXCEEDED now, latency applied by
        the plane (armed clock); a ``partial`` fault is RETURNED so the
        handler can do its full work and then drop the response — the
        wasted-work shape real partial failures have."""
        fault = SERVICE_RPC.hit(
            kinds=("error", "timeout", "partial"), side="server", method=method
        )
        if fault is None:
            return None
        if fault.kind == "partial":
            return fault
        if fault.kind == "timeout":
            context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, fault.describe())
        context.abort(grpc.StatusCode.UNAVAILABLE, fault.describe())

    def _deadline_guard(self, context, t0: float) -> None:
        """Server-side per-RPC deadline (KC_SERVICE_DEADLINE_S): checked at
        the solve-stage boundaries so an abandoned or glacial client cannot
        pin a worker forever; also drops work for clients that already
        disconnected.  Raises _AbortRequest (translated at the handler
        boundary)."""
        if context is None:
            return
        if not context.is_active():
            raise _AbortRequest(grpc.StatusCode.CANCELLED, "client disconnected")
        if self.deadline_s and tenant_mod.monotonic() - t0 > self.deadline_s:
            raise _AbortRequest(
                grpc.StatusCode.DEADLINE_EXCEEDED,
                f"server-side deadline {self.deadline_s:.1f}s exceeded",
            )

    def _consolidate(self, request: bytes, context) -> bytes:
        """Multi-node consolidation sweep on the device: every prefix of the
        disruption-sorted candidate list simulated in parallel
        (solver.consolidation.TPUConsolidationSearch).  Candidates reference
        nodes shipped in the ``nodes`` envelope by name; replacements come
        back as launchable entries whose pods are (nodeName, podIndex) refs
        into the shipped per-node pod lists."""
        from karpenter_core_tpu.controllers.deprovisioning import CandidateNode
        from karpenter_core_tpu.solver.consolidation import TPUConsolidationSearch

        partial = self._rpc_chaos(context, "Consolidate")
        try:
            req = msgpack.unpackb(request)
            provisioners, daemonset_pods, state_nodes, bound, resolver, node_pods = (
                self._decode_common(req)
            )
            pending = [codec.pod_from_dict(p) for p in req.get("pendingPods", [])]
            by_name = {sn.node.name: sn for sn in state_nodes}
            prov_by_name = {p.name: p for p in provisioners}
            its = {it.name: it for it in self.cloud_provider.get_instance_types(None)}
            from karpenter_core_tpu.utils import pod as pod_util

            def reschedulable(pods):
                # node_util.get_node_pods parity: the envelope ships ALL pods
                # (node utilization needs them) but CandidateNode.pods must be
                # only what a deletion would actually displace
                return [
                    p for p in pods
                    if not (
                        pod_util.is_owned_by_node(p)
                        or pod_util.is_owned_by_daemon_set(p)
                        or pod_util.is_terminal(p)
                        or pod_util.is_terminating(p)
                    )
                ]

            candidates = []
            for c in req.get("candidates", []):
                sn = by_name.get(c["name"])
                provisioner = prov_by_name.get(c["provisioner"])
                if sn is None or provisioner is None:
                    continue
                candidates.append(CandidateNode(
                    node=sn.node,
                    state_node=sn,
                    instance_type=its.get(c["instanceType"]),
                    capacity_type=c.get("capacityType", ""),
                    zone=c.get("zone", ""),
                    provisioner=provisioner,
                    disruption_cost=float(c.get("disruptionCost", 0.0)),
                    pods=reschedulable(node_pods.get(c["name"], [])),
                ))

            from karpenter_core_tpu.policy import PolicyConfig

            search = TPUConsolidationSearch(
                self.cloud_provider, provisioners,
                # the requesting replica's resolved policy config rides the
                # wire (PolicyConfig.to_wire) so remote sweeps score lanes by
                # fleet-cost delta exactly like in-process ones; absent =
                # pre-policy behavior, serving-side KC_POLICY=0 still wins
                policy=PolicyConfig.from_wire(req.get("policy")),
            )
            cmd = search.compute_command(
                candidates, pending_pods=pending,
                state_nodes=state_nodes, bound_pods=bound,
            )

            pod_ref = {}
            for name, pods in node_pods.items():
                for i, pod in enumerate(pods):
                    pod_ref[id(pod)] = (name, i)

            def domain_of(replacement, key) -> list:
                requirements = replacement.requirements
                if requirements.has(key):
                    return list(requirements.get(key).values_list())
                return []

            from karpenter_core_tpu.apis import labels as labels_api

            response = {
                "action": cmd.action.value,
                "nodesToRemove": [n.name for n in cmd.nodes_to_remove],
                "replacements": [
                    {
                        "provisioner": r.provisioner_name,
                        "instanceTypes": [it.name for it in r.instance_type_options],
                        "zones": domain_of(r, labels_api.LABEL_TOPOLOGY_ZONE),
                        # the sweep's price rules may pin spot-only
                        # (consolidation.go:227-267 parity) — the launch must
                        # keep that narrowing or an on-demand machine could
                        # cost more than the nodes it replaces
                        "capacityTypes": domain_of(r, labels_api.LABEL_CAPACITY_TYPE),
                        "requests": {k: float(v) for k, v in r.requests.items()},
                        "podRefs": [
                            pod_ref[id(p)] for p in r.pods if id(p) in pod_ref
                        ],
                    }
                    for r in cmd.replacement_nodes
                ],
            }
            payload = msgpack.packb(response)
        except KernelUnsupported as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, f"kernel unsupported: {e}")
        except Exception as e:  # noqa: BLE001 - surface as INTERNAL
            log.exception("consolidate request failed")
            context.abort(grpc.StatusCode.INTERNAL, str(e))
        if partial is not None:
            context.abort(grpc.StatusCode.UNAVAILABLE, partial.describe())
        return payload

    def _lease_get(self, request: bytes, context) -> bytes:
        req = msgpack.unpackb(request)
        with self._lease_lock:
            stored = self._leases.get((req.get("namespace", ""), req["name"]))
            return msgpack.packb({"lease": dict(stored) if stored else None})

    def _lease_apply(self, request: bytes, context) -> bytes:
        """Create/update with compare-and-swap on resourceVersion.

        expectedVersion absent/None = create (conflict if the lease exists);
        otherwise the update only lands if the stored version still matches.
        Returns {ok, conflict, lease} — on conflict the stored lease rides
        along so the caller sees who won without a second round trip."""
        req = msgpack.unpackb(request)
        lease = dict(req["lease"])
        key = (lease.get("namespace", ""), lease["name"])
        expected = req.get("expectedVersion")
        with self._lease_lock:
            stored = self._leases.get(key)
            if expected is None:
                if stored is not None:
                    return msgpack.packb(
                        {"ok": False, "conflict": True, "lease": dict(stored)}
                    )
                lease["resourceVersion"] = 1
            else:
                if stored is None or stored["resourceVersion"] != expected:
                    return msgpack.packb({
                        "ok": False, "conflict": True,
                        "lease": dict(stored) if stored else None,
                    })
                lease["resourceVersion"] = stored["resourceVersion"] + 1
            self._leases[key] = lease
            self._persist_leases()
            return msgpack.packb({"ok": True, "conflict": False, "lease": dict(lease)})

    @staticmethod
    def _decode_common(req):
        """(provisioners, daemonset_pods, state_nodes, bound_pods, resolver)
        from the request envelope shared by /Solve and /SolveClasses.

        ``claimDrivers`` ({"<ns>/<claim>": csi-driver}) lets the controller
        plane ship its PVC→driver resolution so volume attach limits bind on
        this side of the wire too; node entries may carry ``volumeLimits``
        ({driver: allocatable count}) from their CSINode."""
        # no claimDrivers → volumes stay unconstrained (the pre-existing wire
        # contract); a provided map makes every referenced claim resolvable
        # and unresolved ones route to FAILED_PRECONDITION like other
        # kernel-unsupported shapes
        claim_drivers = req.get("claimDrivers")
        resolver = _WireVolumeResolver(claim_drivers) if claim_drivers else None
        provisioners = [
            codec.provisioner_from_dict(p) for p in req.get("provisioners", [])
        ]
        daemonset_pods = [
            codec.pod_from_dict(p) for p in req.get("daemonsetPods", [])
        ]
        state_nodes = []
        bound = []
        node_pods = {}
        for n in req.get("nodes", []):
            state_node = StateNode(codec.node_from_dict(n["node"]), resolver)
            for driver, limit in (n.get("volumeLimits") or {}).items():
                state_node._volume_limits[driver] = int(limit)
            pods_here = []
            for p in n.get("pods", []):
                pod = codec.pod_from_dict(p)
                state_node.update_for_pod(pod)
                bound.append(pod)
                pods_here.append(pod)
            node_pods[state_node.node.name] = pods_here
            state_nodes.append(state_node)
        return provisioners, daemonset_pods, state_nodes, bound, resolver, node_pods

    @staticmethod
    def _classes_payload(results, class_counts) -> Dict:
        """The SolveClasses response body for one TPUSolveResults;
        ``class_counts(pods) -> [(class_index, count)]`` supplies the
        caller's pod→request-class mapping (identity-based on the stateless
        path, uid-based on the tenant path)."""
        return {
            "newNodes": [
                {
                    "provisioner": n.provisioner_name,
                    "instanceTypes": n.instance_type_names,
                    "zones": n.zones,
                    "capacityTypes": n.capacity_types,
                    "requests": n.requests,
                    "classCounts": class_counts(n.pods),
                }
                for n in results.new_nodes
            ],
            "existingAssignments": {
                name: class_counts(placed)
                for name, placed in results.existing_assignments.items()
            },
            "failedClassCounts": class_counts(results.failed_pods),
            # spread residuals: classes the kernel may have under-placed
            # vs the host oracle — the controller plane re-routes them
            # through its host scheduler with seeded topology counts
            # (provisioning._solve_host_remainder), so the wire path keeps
            # the same no-shape-schedules-fewer guarantee as in-process
            "residualClassCounts": class_counts(results.spread_residual_pods),
            # zone commitments the solve stamped onto zone-less existing
            # nodes: the re-route must see the same pins
            "existingCommittedZones": dict(results.existing_committed_zones),
        }

    def _solve_classes(self, request: bytes, context) -> bytes:
        t0 = tenant_mod.monotonic()
        partial = self._rpc_chaos(context, "SolveClasses")
        try:
            req = msgpack.unpackb(request)
        except Exception as e:  # noqa: BLE001 - not even msgpack
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"malformed request: {e}")
        try:
            if isinstance(req, dict) and req.get("tenant"):
                response = self._solve_classes_tenant(req, context, request, t0)
            else:
                response = self._solve_classes_stateless(req, context, t0)
        except _AbortRequest as a:
            context.abort(a.code, a.details)
        if partial is not None:
            context.abort(grpc.StatusCode.UNAVAILABLE, partial.describe())
        return response

    def _solve_classes_stateless(self, req, context, t0: float) -> bytes:
        """The original stateless contract: every request is one snapshot
        solve, no admission, no session."""
        from karpenter_core_tpu.models.snapshot import build_pod_ladder

        try:
            entries = req.get("podClasses", [])
            reps = [codec.pod_from_dict(e["pod"]) for e in entries]
            classes = []
            for rep, entry in zip(reps, entries):
                cls = build_pod_ladder(rep)
                cls.pods = [rep] * int(entry["count"])
                classes.append(cls)
            req_idx = {id(rep): i for i, rep in enumerate(reps)}
            provisioners, daemonset_pods, state_nodes, bound, resolver, _ = (
                self._decode_common(req)
            )

            from karpenter_core_tpu.policy import PolicyConfig

            solver = TPUSolver(
                self.cloud_provider, provisioners, daemonset_pods,
                kube_client=resolver,
                # policy over the wire (regression: a CPU controller replica
                # with the objective enabled previously fell back SILENTLY to
                # first-fit selection on remote solves — the field never
                # crossed the channel)
                policy=PolicyConfig.from_wire(req.get("policy")),
            )
            self._deadline_guard(context, t0)
            snapshot = solver.encode_classes(
                classes, state_nodes=state_nodes or None, bound_pods=bound
            )
            results = solver.solve_encoded(snapshot, state_nodes or None, bound)
            self._deadline_guard(context, t0)

            def class_counts(pods) -> list:
                counts: Dict[int, int] = {}
                for p in pods:
                    i = req_idx[id(p)]
                    counts[i] = counts.get(i, 0) + 1
                return sorted(counts.items())

            return msgpack.packb(self._classes_payload(results, class_counts))
        except KernelUnsupported as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, f"kernel unsupported: {e}")
        except _AbortRequest:
            raise
        except Exception as e:  # noqa: BLE001 - surface as INTERNAL
            log.exception("solve-classes request failed")
            context.abort(grpc.StatusCode.INTERNAL, str(e))

    # -- the tenant path (docs/SERVICE.md) ------------------------------------

    @staticmethod
    def _materialize_class(rep, count: int, uid_base: str) -> List:
        """``count`` bookkeeping copies of the class representative with
        distinct, REQUEST-STABLE uids (``<class-digest>#<j>``).  Pods within
        an equivalence class are fungible, so synthetic member identities
        capture count deltas exactly — the per-tenant incremental session
        diffs successive requests' memberships without per-pod uids ever
        crossing the wire (the O(classes) win stays)."""
        pods = []
        for j in range(count):
            pod = copy.copy(rep)
            pod.metadata = copy.copy(rep.metadata)
            pod.metadata.uid = f"{uid_base}#{j}"
            pods.append(pod)
        return pods

    @classmethod
    def _decode_tenant_classes(cls_, req):
        """(classes, uid_base -> request class index, decode_common tail).

        A classmethod so the fleet checkpoint restore (fleet/checkpoint.py
        restore_session) can re-decode a checkpointed anchor request without
        a service instance — the adopting replica re-derives classes and
        synthetic uids from the same bytes the serving replica encoded."""
        from karpenter_core_tpu.models.snapshot import build_pod_ladder
        from karpenter_core_tpu.models.store import class_key, stable_digest

        entries = req.get("podClasses", [])
        classes = []
        uid_class: Dict[str, int] = {}
        for i, entry in enumerate(entries):
            rep = codec.pod_from_dict(entry["pod"])
            cls = build_pod_ladder(rep)
            cls.pods = [rep]  # class_key derives from the representative
            # class identity digest: CROSS-PROCESS stable (stable_digest
            # canonicalizes the key's frozensets), so a fleet checkpoint's
            # membership bookkeeping — keyed by these synthetic uids — reads
            # back identically on the replica that adopts the tenant
            # (fleet/checkpoint.py); same-process lineages see no change
            uid_base = stable_digest(class_key(cls))[:16]
            if uid_base in uid_class:
                raise ValueError(f"duplicate pod class at index {i}")
            uid_class[uid_base] = i
            cls.pods = cls_._materialize_class(rep, int(entry["count"]), uid_base)
            classes.append(cls)
        provisioners, daemonset_pods, state_nodes, bound, resolver, _ = (
            cls_._decode_common(req)
        )
        return classes, uid_class, provisioners, daemonset_pods, state_nodes, bound, resolver

    def _solve_classes_tenant(self, req, context, request: bytes, t0: float) -> bytes:
        from karpenter_core_tpu.policy import PolicyConfig

        nbytes = len(request)
        envelope = req.get("tenant") or {}
        tid = str(envelope.get("id") or "")
        if not tid:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "tenant.id required")
        plane = self.tenants
        decision = plane.admit(tid, weight=envelope.get("weight"))
        if not decision.admitted:
            if decision.reason == "draining":
                # graceful drain: the server is going away — an explicit
                # UNAVAILABLE with the hint, so clients re-dial elsewhere
                context.abort(
                    grpc.StatusCode.UNAVAILABLE,
                    "tenant-draining "
                    f"{tenant_mod.RETRY_AFTER_PREFIX}{decision.retry_after_s:.3f}",
                )
            if decision.reason == "isolated":
                context.abort(
                    grpc.StatusCode.UNAVAILABLE,
                    "tenant-isolated "
                    f"{tenant_mod.RETRY_AFTER_PREFIX}{decision.retry_after_s:.3f}",
                )
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, decision.detail())
        # a half-open breaker trial latched by THIS admit must be freed on
        # every exit that reaches no verdict (kernel-unsupported, deadline)
        # or the tenant would wedge half-open forever — record_* calls are
        # the verdicts, everything else releases in the finally.  Only the
        # trial this request was granted (decision.trial) is ever released:
        # a concurrent request's latch is not ours to free.
        entry = decision.entry
        verdict = False
        try:
            if nbytes > plane.config.max_request_bytes:
                verdict = True
                plane.record_bad_request(entry, "oversized")
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"tenant-ejected reason=oversized bytes={nbytes} "
                    f"limit={plane.config.max_request_bytes}",
                )
            try:
                (classes, uid_class, provisioners, daemonset_pods, state_nodes,
                 bound, resolver) = self._decode_tenant_classes(req)
            except Exception as e:  # noqa: BLE001 - tenant-attributable
                verdict = True
                plane.record_bad_request(entry, "malformed")
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"tenant-ejected reason=malformed: {e}",
                )
            solver = TPUSolver(
                self.cloud_provider, provisioners, daemonset_pods,
                kube_client=resolver,
                policy=PolicyConfig.from_wire(req.get("policy")),
            )
            claimed = int(envelope.get("sessionVersion") or 0)
            supply_digest = envelope.get("supplyDigest")
            self._deadline_guard(context, t0)
            with entry.lock:
                have = entry.session.lineage_version()
                if claimed != have:
                    # the client's lineage and ours diverged: a restarted /
                    # evicted server (claimed > 0, have == 0 — the
                    # ``session-lost`` re-anchor), a restarted client
                    # (claimed == 0, have > 0), or plain version skew.  The
                    # answer is always the same: drop the lineage, full
                    # solve, never a stale delta.  In a fleet, the first
                    # shape gets one more chance: the tenant may have been
                    # routed here after its previous replica died — adopt
                    # the lineage warm from the shared checkpoints (or a
                    # peer's journal) before giving up.
                    adopted = (
                        bool(claimed) and not have
                        and self._ckpt is not None
                        and self._fleet_adopt(tid, entry, claimed)
                    )
                    if adopted:
                        entry.recovered = entry.recovered or "warm"
                        if (
                            supply_digest is not None
                            and entry.supply_digest is not None
                            and supply_digest != entry.supply_digest
                        ):
                            entry.session.force_full("supply-digest")
                    else:
                        entry.session.reset()
                        entry.session.force_full(
                            "session-lost" if claimed else "client-reanchor"
                        )
                elif (
                    have
                    and supply_digest is not None
                    and entry.supply_digest is not None
                    and supply_digest != entry.supply_digest
                ):
                    # versions agree but the client's view of its supply
                    # moved in a way our decode may not capture: trust the
                    # digest, re-anchor.  force_full OVERWRITES any leftover
                    # forced reason — a journal-recovered session whose
                    # earlier owed re-anchor never ran must report
                    # ``supply-digest`` here, not echo a stale
                    # ``session-lost`` into the mode counter and span
                    entry.session.force_full("supply-digest")
                entry.session.rebind(solver)
                # last_batched is written by the coalescer hook — full
                # solves AND fused repairs reach it (docs/SERVICE.md "Solve
                # fusion") — reset so a solve that short-circuits before the
                # hook doesn't echo a stale batch size
                entry.last_batched = 1
                t_solve = tenant_mod.monotonic()
                # the envelope's optional trace context stitches this
                # server-side segment into the client's trace tree; the
                # serving span's own wire context is captured for the
                # journal so replay-after-restart links back to it
                trace_ctx = envelope.get("trace")
                server_ctx = None
                try:
                    with tracing.span_remote("solve.tenant", trace_ctx,
                                             tenant=tid,
                                             classes=len(classes)):
                        server_ctx = tracing.wire_context()
                        results = entry.session.solve(
                            classes, state_nodes or None, bound
                        )
                except KernelUnsupported as e:
                    # a host-routable batch shape, not abuse: no breaker
                    # verdict (the finally frees any half-open trial)
                    tenant_mod.TENANT_EJECTED.labels(tid, "unsupported").inc()
                    context.abort(
                        grpc.StatusCode.FAILED_PRECONDITION,
                        f"kernel unsupported: {e}",
                    )
                except SolveTimeout as e:
                    # the device went quiet under this tenant's solve: a
                    # STRUCTURED timeout ejection, never a wedged worker —
                    # the watchdog already abandoned the stuck call and the
                    # session canceled/re-anchored its pipeline state
                    # (solver/incremental), so the worker thread is free the
                    # moment this returns.  The timeout is a backend
                    # verdict, not tenant abuse, but it still counts on the
                    # tenant breaker: a tenant whose snapshots reliably hang
                    # the device is indistinguishable from poison and must
                    # isolate (docs/SERVICE.md "Timeout ejection").
                    verdict = True
                    plane.record_timeout(entry)
                    log.warning(
                        "tenant %s solve timed out at %s (deadline %.2fs); "
                        "ejected with watchdog-timeout", tid, e.site,
                        e.deadline_s,
                    )
                    return msgpack.packb({
                        "error": {
                            "kind": "ejected",
                            "reason": str(e),
                            "timeout": {
                                "site": e.site,
                                "deadlineS": round(e.deadline_s, 3),
                            },
                        },
                        "tenant": {
                            "id": tid,
                            "sessionVersion": entry.session.lineage_version(),
                        },
                    })
                except Exception as e:  # noqa: BLE001 - eject, batch survives
                    verdict = True
                    plane.record_fault(entry)
                    log.warning("tenant %s solve ejected: %s", tid, e)
                    return msgpack.packb({
                        "error": {"kind": "ejected", "reason": str(e)},
                        "tenant": {
                            "id": tid,
                            "sessionVersion": entry.session.lineage_version(),
                        },
                    })
                solve_s = tenant_mod.monotonic() - t_solve
                entry.supply_digest = supply_digest
                mode, reason = entry.session.last_mode, entry.session.last_reason
                version = entry.session.lineage_version()
                batched = entry.last_batched
                # one-shot recovery echo: the first response after a warm
                # journal restore tells the client its lineage survived.
                # Captured here, CONSUMED only when the response actually
                # returns — a deadline/disconnect abort past this point must
                # not eat the marker (the client never saw it)
                recovered = entry.recovered
                # durable sessions: journal the completed solve (enqueue
                # only; framing/fsync ride the writer thread off this path)
                self._journal_solve(entry, tid, mode, supply_digest, request,
                                    trace_ctx=server_ctx or trace_ctx)
                if self._ckpt is not None:
                    if mode == "full":
                        # the anchor request is what an adopting peer
                        # re-decodes: a full solve re-anchors the lineage,
                        # so it replaces the captured anchor wholesale
                        entry.anchor_request = bytes(request)
                        entry.anchor_uid_bases = tuple(uid_class)
                    # cadence checkpoint: full solves always, deltas every
                    # KC_FLEET_CKPT_EVERY ticks; never raises (counted on
                    # karpenter_fleet_checkpoint_total instead)
                    self._ckpt.after_solve(tid, entry, mode)
            self._deadline_guard(context, t0)

            t_decode = tenant_mod.monotonic()

            def class_counts(pods) -> list:
                counts: Dict[int, int] = {}
                for p in pods:
                    i = uid_class[p.uid.rsplit("#", 1)[0]]
                    counts[i] = counts.get(i, 0) + 1
                return sorted(counts.items())

            response = self._classes_payload(results, class_counts)
            response["tenant"] = {
                "id": tid,
                "solveMode": mode,
                "reason": reason,
                "sessionVersion": version,
                "batched": batched,
            }
            if recovered:
                response["tenant"]["recovered"] = recovered
                with entry.lock:
                    entry.recovered = None
            verdict = True
            plane.record_ok(entry)
            plane.observe_latencies(
                tid,
                queue_s=t_solve - t0,
                solve_s=solve_s,
                decode_s=tenant_mod.monotonic() - t_decode,
            )
            return msgpack.packb(response)
        finally:
            if not verdict and decision.trial:
                entry.breaker.release_trial()
            plane.release(tid)

    def _solve(self, request: bytes, context) -> bytes:
        t0 = tenant_mod.monotonic()
        partial = self._rpc_chaos(context, "Solve")
        try:
            req = msgpack.unpackb(request)
            pods = [codec.pod_from_dict(p) for p in req.get("pods", [])]
            provisioners, daemonset_pods, state_nodes, bound, resolver, _ = (
                self._decode_common(req)
            )

            from karpenter_core_tpu.policy import PolicyConfig

            solver = TPUSolver(
                self.cloud_provider, provisioners, daemonset_pods,
                kube_client=resolver,
                policy=PolicyConfig.from_wire(req.get("policy")),
            )
            self._deadline_guard(context, t0)
            results = solver.solve(pods, state_nodes=state_nodes or None, bound_pods=bound)
            self._deadline_guard(context, t0)

            pod_index = {p.uid: i for i, p in enumerate(pods)}
            response = {
                "newNodes": [
                    {
                        "provisioner": n.provisioner_name,
                        "instanceTypes": n.instance_type_names,
                        "zones": n.zones,
                        "capacityTypes": n.capacity_types,
                        "requests": n.requests,
                        "podIndices": [pod_index[p.uid] for p in n.pods if p.uid in pod_index],
                    }
                    for n in results.new_nodes
                ],
                "existingAssignments": {
                    name: [pod_index[p.uid] for p in placed if p.uid in pod_index]
                    for name, placed in results.existing_assignments.items()
                },
                "failedPodIndices": [
                    pod_index[p.uid] for p in results.failed_pods if p.uid in pod_index
                ],
                "residualPodIndices": [
                    pod_index[p.uid]
                    for p in results.spread_residual_pods if p.uid in pod_index
                ],
                "existingCommittedZones": dict(results.existing_committed_zones),
            }
            payload = msgpack.packb(response)
        except KernelUnsupported as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, f"kernel unsupported: {e}")
        except _AbortRequest as a:
            context.abort(a.code, a.details)
        except Exception as e:  # noqa: BLE001 - surface as INTERNAL
            log.exception("solve request failed")
            context.abort(grpc.StatusCode.INTERNAL, str(e))
        if partial is not None:
            context.abort(grpc.StatusCode.UNAVAILABLE, partial.describe())
        return payload


def service_capacity(max_workers: Optional[int] = None) -> tuple:
    """(workers, max_concurrent_rpcs) for ``serve``: KC_SERVICE_WORKERS sizes
    the solver pool (no more hardcoded 4), KC_SERVICE_QUEUE bounds how many
    additional RPCs may WAIT behind the busy workers — anything past that is
    rejected by the transport with RESOURCE_EXHAUSTED instead of piling up
    unboundedly (the admission controller's token buckets shed per-tenant
    load far earlier; this is the transport backstop)."""
    workers = (
        max_workers if max_workers is not None
        else max(tenant_mod._env_i("KC_SERVICE_WORKERS", 4), 1)
    )
    queue = max(tenant_mod._env_i("KC_SERVICE_QUEUE", 32), 0)
    return workers, workers + queue


def install_drain_handler(server, service, grace_s: float = 1.0) -> bool:
    """SIGTERM → graceful drain (stop admitting with retry-after hints,
    finish in-flight solves, flush + checkpoint the journal) → server stop.
    Main-thread only (signal module restriction); returns False when the
    handler could not be installed."""
    import signal

    if threading.current_thread() is not threading.main_thread():
        return False

    def _drain_and_stop() -> None:
        service.drain()
        server.stop(grace=grace_s)

    def _on_term(signum, frame) -> None:
        threading.Thread(
            target=_drain_and_stop, name="kc-service-drain", daemon=True
        ).start()

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:
        return False
    return True


def serve(
    cloud_provider,
    address: str = "127.0.0.1:0",
    max_workers: Optional[int] = None,
    clock=None,
    tenant_config=None,
    metrics_port: Optional[int] = None,
    journal_dir: Optional[str] = None,
    drain_on_sigterm: bool = False,
    fleet=None,
):
    """Start the sidecar; returns (server, bound_port).

    ``max_workers`` None reads KC_SERVICE_WORKERS (default 4); the request
    queue is bounded (KC_SERVICE_QUEUE) and per-RPC work is deadlined
    (KC_SERVICE_DEADLINE_S).  ``clock``/``tenant_config`` thread into the
    multi-tenant plane (service/tenant.py).  ``metrics_port`` (0 = ephemeral)
    additionally serves the process /metrics — the per-tenant latency
    histograms and shed/eject/evict counters — over HTTP; the started
    OperatorHTTP rides ``server.kc_http``.

    ``journal_dir`` (or KC_SESSION_JOURNAL=1 + KC_JOURNAL_DIR) enables the
    durable-session journal: recovery replay runs HERE, before the port
    binds, so the first request a client lands already sees warm lineages.
    ``drain_on_sigterm`` installs the graceful-drain SIGTERM handler
    (main-thread processes only)."""
    from karpenter_core_tpu.utils import compilecache

    compilecache.enable()  # sidecar restarts reuse compiled solve kernels
    workers, max_rpcs = service_capacity(max_workers)
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=workers),
        maximum_concurrent_rpcs=max_rpcs,
    )
    service = SnapshotSolverService(
        cloud_provider, clock=clock, tenant_config=tenant_config,
        journal_dir=journal_dir, fleet=fleet,
    )
    server.add_generic_rpc_handlers((service,))
    port = server.add_insecure_port(address)
    server.start()
    # the service (and its tenant plane) stays reachable for operators/tests
    server.kc_service = service
    server.kc_http = None
    server.kc_pulse = None
    fleet_local = service.fleet
    if (
        fleet_local is not None
        and fleet_local.replica_id
        and fleet_local.router_address
    ):
        # fleet replica: heartbeat this process's lease at the router so the
        # ring routes here (and remaps the moment the lease goes stale)
        from karpenter_core_tpu.fleet.lease import ReplicaPulse

        pulse = ReplicaPulse(
            RemoteLeaseStore(fleet_local.router_address),
            fleet_local.replica_id,
            clock=service.tenants.clock,
            heartbeat_s=fleet_local.heartbeat_s,
            ttl_s=fleet_local.lease_ttl_s,
        )
        pulse.start()
        service._pulse = pulse
        server.kc_pulse = pulse
    if drain_on_sigterm:
        install_drain_handler(server, service)
    if metrics_port is not None:
        from karpenter_core_tpu.operator.httpserver import OperatorHTTP

        server.kc_http = OperatorHTTP(
            metrics_port=metrics_port, health_port=0
        ).start()
    log.info(
        "snapshot solver listening on port %d (%d workers, %d max rpcs)",
        port, workers, max_rpcs,
    )
    return server, port


def _policy_wire(policy) -> Dict:
    """Normalize a client ``policy`` argument (PolicyConfig, wire dict, or
    None) to the request's wire entry.  {} for None keeps the msgpack schema
    stable while decoding as "no policy" on the serving side."""
    if policy is None:
        return {}
    if isinstance(policy, dict):
        return dict(policy)
    return policy.to_wire()


class SnapshotSolverClient:
    """Controller-plane client for the channel."""

    def __init__(self, address: str) -> None:
        self.channel = grpc.insecure_channel(address)
        self._solve = self.channel.unary_unary(f"/{SERVICE}/Solve")
        self._solve_classes = self.channel.unary_unary(f"/{SERVICE}/SolveClasses")
        self._health = self.channel.unary_unary(f"/{SERVICE}/Health")
        self._consolidate = self.channel.unary_unary(f"/{SERVICE}/Consolidate")
        self._lease_get = self.channel.unary_unary(f"/{SERVICE}/LeaseGet")
        self._lease_apply = self.channel.unary_unary(f"/{SERVICE}/LeaseApply")

    @staticmethod
    def _client_chaos(method: str) -> None:
        """Client-transport leg of the ``service.rpc`` point: error/timeout
        faults surface as a raised InjectedFault BEFORE the call leaves —
        exactly what a dead/black-holed channel looks like to the caller
        (the provisioning solver breaker counts it); latency rides the armed
        clock inside the plane."""
        fault = SERVICE_RPC.hit(
            kinds=("error", "timeout"), side="client", method=method
        )
        if fault is not None:
            raise chaos.InjectedFault(fault)

    def health(self) -> Dict:
        return msgpack.unpackb(self._health(msgpack.packb({})))

    def consolidate(
        self,
        candidates: List[Dict],
        pending_pods: List,
        provisioners: List,
        nodes: Optional[List[Dict]] = None,
        claim_drivers: Optional[Dict[str, str]] = None,
        policy=None,
        timeout: float = 120.0,
    ) -> Dict:
        """Remote multi-node consolidation sweep.

        ``candidates``: [{name, instanceType, capacityType, zone, provisioner,
        disruptionCost}] in disruption order, referencing ``nodes`` entries by
        name.  ``policy`` (policy.PolicyConfig or a wire dict) makes the
        remote sweep score lanes by fleet-cost delta like an in-process one.
        Returns the raw response: {action, nodesToRemove: [name],
        replacements: [{provisioner, instanceTypes, zones, capacityTypes,
        requests, podRefs: [[nodeName, podIndex]]}]}."""
        self._client_chaos("Consolidate")
        request = msgpack.packb(
            {
                "candidates": candidates,
                "pendingPods": [codec.pod_to_dict(p) for p in pending_pods],
                "provisioners": [codec.provisioner_to_dict(p) for p in provisioners],
                "nodes": nodes or [],
                "claimDrivers": claim_drivers or {},
                "policy": _policy_wire(policy),
            }
        )
        return msgpack.unpackb(self._consolidate(request, timeout=timeout))

    def lease_get(self, name: str, namespace: str = "", timeout: float = 5.0):
        response = msgpack.unpackb(
            self._lease_get(msgpack.packb({"name": name, "namespace": namespace}),
                            timeout=timeout)
        )
        return response["lease"]

    def lease_apply(self, lease: Dict, expected_version=None, timeout: float = 5.0) -> Dict:
        response = msgpack.unpackb(
            self._lease_apply(
                msgpack.packb({"lease": lease, "expectedVersion": expected_version}),
                timeout=timeout,
            )
        )
        return response

    def solve(
        self,
        pods: List,
        provisioners: List,
        nodes: Optional[List[Dict]] = None,
        daemonset_pods: Optional[List] = None,
        claim_drivers: Optional[Dict[str, str]] = None,
        policy=None,
        timeout: float = 60.0,
    ) -> Dict:
        """nodes: [{"node": node_dict, "pods": [...], "volumeLimits": {...}}];
        claim_drivers: {"<ns>/<claim>": csi-driver} resolved by this plane so
        volume attach limits bind on the solver side; policy: the replica's
        resolved policy.PolicyConfig (or wire dict) so the remote objective
        stage selects offerings exactly like an in-process solve."""
        self._client_chaos("Solve")
        request = msgpack.packb(
            {
                "pods": [codec.pod_to_dict(p) for p in pods],
                "provisioners": [codec.provisioner_to_dict(p) for p in provisioners],
                "daemonsetPods": [codec.pod_to_dict(p) for p in daemonset_pods or []],
                "nodes": nodes or [],
                "claimDrivers": claim_drivers or {},
                "policy": _policy_wire(policy),
            }
        )
        return msgpack.unpackb(self._solve(request, timeout=timeout))

    def solve_classes(
        self,
        pods: List,
        provisioners: List,
        nodes: Optional[List[Dict]] = None,
        daemonset_pods: Optional[List] = None,
        claim_drivers: Optional[Dict[str, str]] = None,
        members: Optional[List[List[int]]] = None,
        policy=None,
        timeout: float = 60.0,
    ) -> Dict:
        """Class-columnar solve: dedup ``pods`` into shape classes locally,
        ship one representative + count per class, and expand the per-node
        class counts back into this caller's pod objects.  Returns the same
        dict shape as solve() (podIndices refer to the ``pods`` argument).

        ``members`` — precomputed class membership (lists of indices into
        ``pods``), for callers that already classified the batch (the
        provisioning controller's split does) so the O(pods) signature pass
        doesn't run twice on the hot path.  ``policy`` — the replica's
        resolved policy.PolicyConfig (or wire dict); without it a remote
        solve silently ran first-fit selection while the replica believed
        the objective was on."""
        self._client_chaos("SolveClasses")
        if members is None:
            from karpenter_core_tpu.models.snapshot import _class_signature

            by_sig: Dict[tuple, List[int]] = {}
            for i, pod in enumerate(pods):
                by_sig.setdefault(_class_signature(pod), []).append(i)
            members = list(by_sig.values())
        request = msgpack.packb(
            {
                "podClasses": [
                    {"pod": codec.pod_to_dict(pods[idxs[0]]), "count": len(idxs)}
                    for idxs in members
                ],
                "provisioners": [codec.provisioner_to_dict(p) for p in provisioners],
                "daemonsetPods": [codec.pod_to_dict(p) for p in daemonset_pods or []],
                "nodes": nodes or [],
                "claimDrivers": claim_drivers or {},
                "policy": _policy_wire(policy),
            }
        )
        response = msgpack.unpackb(self._solve_classes(request, timeout=timeout))
        cursors = [0] * len(members)

        def take(counts) -> List[int]:
            indices: List[int] = []
            for c, n in counts:
                start = cursors[c]
                indices.extend(members[c][start : start + n])
                cursors[c] = start + n
            return indices

        return {
            "newNodes": [
                {
                    "provisioner": n["provisioner"],
                    "instanceTypes": n["instanceTypes"],
                    "zones": n["zones"],
                    "requests": n["requests"],
                    "podIndices": take(n["classCounts"]),
                }
                for n in response["newNodes"]
            ],
            "existingAssignments": {
                name: take(counts)
                for name, counts in response["existingAssignments"].items()
            },
            "failedPodIndices": take(response["failedClassCounts"]),
            "residualPodIndices": take(response.get("residualClassCounts", [])),
            "existingCommittedZones": response.get("existingCommittedZones", {}),
        }

    def solve_tenant_classes(
        self,
        pod_classes: List[tuple],
        provisioners: List,
        tenant: Dict,
        nodes: Optional[List[Dict]] = None,
        daemonset_pods: Optional[List] = None,
        claim_drivers: Optional[Dict[str, str]] = None,
        policy=None,
        timeout: float = 60.0,
    ) -> Dict:
        """The multi-tenant protocol (docs/SERVICE.md): ship
        ``pod_classes`` ([(representative Pod, count)]) with a ``tenant``
        envelope ({id, sessionVersion, supplyDigest}) and get the RAW
        class-count response back, plus its ``tenant`` echo ({id, solveMode,
        reason, sessionVersion, batched}).  Delta responses carry only the
        delta's placements, so no client-side pod expansion happens here —
        the caller owns the count→pod mapping.  A response carrying
        ``error`` is this tenant's structured ejection (its co-batched
        tenants were answered normally); sheds/isolation surface as
        RESOURCE_EXHAUSTED / UNAVAILABLE RpcErrors whose details carry a
        ``retry-after-s=`` hint (service.tenant.parse_retry_after)."""
        self._client_chaos("SolveClasses")
        envelope = dict(tenant)
        ctx = tracing.wire_context()
        if ctx is not None and "trace" not in envelope:
            # stamp the caller's active span so the server-side segment
            # joins the same trace tree (schema-additive; SCHEMA.md)
            envelope["trace"] = ctx
        request = msgpack.packb(
            {
                "podClasses": [
                    {"pod": codec.pod_to_dict(pod), "count": int(count)}
                    for pod, count in pod_classes
                ],
                "provisioners": [codec.provisioner_to_dict(p) for p in provisioners],
                "daemonsetPods": [codec.pod_to_dict(p) for p in daemonset_pods or []],
                "nodes": nodes or [],
                "claimDrivers": claim_drivers or {},
                "policy": _policy_wire(policy),
                "tenant": envelope,
            }
        )
        return msgpack.unpackb(self._solve_classes(request, timeout=timeout))

    def close(self) -> None:
        self.channel.close()


class RemoteLeaseStore:
    """Lease store backed by the solver service's lease plane.

    Exposes the same get/create/update_with_version surface the in-process
    KubeClient gives LeaderElector, so an operator replica can elect through
    the shared solver instead of its private in-memory store — which is what
    makes the two-replica deployment's HA story real (VERDICT r2 #4; the
    reference's analog is the apiserver-hosted Lease, operator.go:111-126).
    """

    def __init__(self, client: "SnapshotSolverClient | str") -> None:
        self.client = (
            SnapshotSolverClient(client) if isinstance(client, str) else client
        )

    @staticmethod
    def _to_wire(lease) -> Dict:
        return {
            "name": lease.metadata.name,
            "namespace": lease.metadata.namespace,
            "holderIdentity": lease.spec.holder_identity,
            "leaseDurationSeconds": lease.spec.lease_duration_seconds,
            "acquireTime": lease.spec.acquire_time,
            "renewTime": lease.spec.renew_time,
            "leaseTransitions": lease.spec.lease_transitions,
        }

    @staticmethod
    def _from_wire(wire: Dict):
        from karpenter_core_tpu.apis.objects import Lease, LeaseSpec, ObjectMeta

        lease = Lease(
            metadata=ObjectMeta(
                name=wire["name"], namespace=wire.get("namespace", "")
            ),
            spec=LeaseSpec(
                holder_identity=wire.get("holderIdentity", ""),
                lease_duration_seconds=wire.get("leaseDurationSeconds", 15),
                acquire_time=wire.get("acquireTime", 0.0),
                renew_time=wire.get("renewTime", 0.0),
                lease_transitions=wire.get("leaseTransitions", 0),
            ),
        )
        lease.metadata.resource_version = wire.get("resourceVersion", 0)
        return lease

    def get(self, kind, name: str, namespace: str = ""):
        wire = self.client.lease_get(name, namespace or "")
        return self._from_wire(wire) if wire is not None else None

    def create(self, lease):
        from karpenter_core_tpu.operator.kubeclient import ConflictError

        response = self.client.lease_apply(self._to_wire(lease), expected_version=None)
        if not response["ok"]:
            raise ConflictError(f"lease {lease.metadata.name} already exists")
        return self._from_wire(response["lease"])

    def update_with_version(self, lease, expected_resource_version):
        from karpenter_core_tpu.operator.kubeclient import ConflictError

        response = self.client.lease_apply(
            self._to_wire(lease), expected_version=expected_resource_version
        )
        if not response["ok"]:
            raise ConflictError(f"lease {lease.metadata.name} version conflict")
        return self._from_wire(response["lease"])
