"""The snapshot channel: gRPC service in front of the TPU solver.

The north-star architecture (BASELINE.json) keeps the controller plane where
it is and ships cluster-state snapshots over gRPC to a solver sidecar on the
TPU host — this module is that channel.  Requests carry pods, provisioners,
and existing nodes (apis.codec wire dicts, msgpack-framed); responses carry
node decisions, existing-node nominations, and failures.

Implemented with gRPC generic method handlers (no codegen: the environment has
no protoc python plugin) — the method contract is documented here and stable:

    /karpenter.v1.SnapshotSolver/Solve         unary-unary, msgpack bytes
    /karpenter.v1.SnapshotSolver/SolveClasses  unary-unary, msgpack bytes
    /karpenter.v1.SnapshotSolver/Health        unary-unary, empty → msgpack bytes

SolveClasses is the class-columnar fast path: the controller plane dedups its
pending pods into shape classes (models.columnar.PodIngest) and ships ONE
representative pod + count per class — O(distinct shapes) on the wire instead
of O(pods) — and gets back per-node class counts it expands locally.  At 50k
pods / ~13 shapes that is a ~4000× smaller request than /Solve.
"""

from __future__ import annotations

import logging
from concurrent import futures
from typing import Dict, List, Optional

import grpc
import msgpack

from karpenter_core_tpu.apis import codec
from karpenter_core_tpu.models.snapshot import KernelUnsupported
from karpenter_core_tpu.solver.tpu import TPUSolver
from karpenter_core_tpu.state.cluster import StateNode

log = logging.getLogger(__name__)

SERVICE = "karpenter.v1.SnapshotSolver"


class _WireVolumeResolver:
    """Minimal kube-lookup surface for PVC→CSI-driver resolution
    (scheduling.VolumeUsage), backed by the request's ``claimDrivers`` map —
    the controller plane resolves claims against its apiserver and ships just
    the answers."""

    _PREFIX = "wire://"

    def __init__(self, claim_drivers) -> None:
        self.claim_drivers = dict(claim_drivers or {})

    def get_persistent_volume_claim(self, namespace: str, name: str):
        from karpenter_core_tpu.apis.objects import (
            ObjectMeta,
            PersistentVolumeClaim,
            PersistentVolumeClaimSpec,
        )

        driver = self.claim_drivers.get(f"{namespace}/{name}")
        if driver is None:
            return None
        return PersistentVolumeClaim(
            metadata=ObjectMeta(name=name, namespace=namespace),
            spec=PersistentVolumeClaimSpec(storage_class_name=self._PREFIX + driver),
        )

    def get_persistent_volume(self, name: str):
        return None

    def get_storage_class(self, name: str):
        from karpenter_core_tpu.apis.objects import ObjectMeta, StorageClass

        if name.startswith(self._PREFIX):
            return StorageClass(
                metadata=ObjectMeta(name=name), provisioner=name[len(self._PREFIX):]
            )
        return None


class SnapshotSolverService(grpc.GenericRpcHandler):
    """Stateless solver endpoint: each request is one snapshot solve."""

    def __init__(self, cloud_provider) -> None:
        self.cloud_provider = cloud_provider

    # -- grpc plumbing --------------------------------------------------------

    def service(self, handler_call_details):
        method = handler_call_details.method
        if method == f"/{SERVICE}/Solve":
            return grpc.unary_unary_rpc_method_handler(self._solve)
        if method == f"/{SERVICE}/SolveClasses":
            return grpc.unary_unary_rpc_method_handler(self._solve_classes)
        if method == f"/{SERVICE}/Health":
            return grpc.unary_unary_rpc_method_handler(self._health)
        return None

    # -- handlers -------------------------------------------------------------

    def _health(self, request: bytes, context) -> bytes:
        return msgpack.packb({"status": "ok"})

    @staticmethod
    def _decode_common(req):
        """(provisioners, daemonset_pods, state_nodes, bound_pods, resolver)
        from the request envelope shared by /Solve and /SolveClasses.

        ``claimDrivers`` ({"<ns>/<claim>": csi-driver}) lets the controller
        plane ship its PVC→driver resolution so volume attach limits bind on
        this side of the wire too; node entries may carry ``volumeLimits``
        ({driver: allocatable count}) from their CSINode."""
        # no claimDrivers → volumes stay unconstrained (the pre-existing wire
        # contract); a provided map makes every referenced claim resolvable
        # and unresolved ones route to FAILED_PRECONDITION like other
        # kernel-unsupported shapes
        claim_drivers = req.get("claimDrivers")
        resolver = _WireVolumeResolver(claim_drivers) if claim_drivers else None
        provisioners = [
            codec.provisioner_from_dict(p) for p in req.get("provisioners", [])
        ]
        daemonset_pods = [
            codec.pod_from_dict(p) for p in req.get("daemonsetPods", [])
        ]
        state_nodes = []
        bound = []
        for n in req.get("nodes", []):
            state_node = StateNode(codec.node_from_dict(n["node"]), resolver)
            for driver, limit in (n.get("volumeLimits") or {}).items():
                state_node._volume_limits[driver] = int(limit)
            for p in n.get("pods", []):
                pod = codec.pod_from_dict(p)
                state_node.update_for_pod(pod)
                bound.append(pod)
            state_nodes.append(state_node)
        return provisioners, daemonset_pods, state_nodes, bound, resolver

    def _solve_classes(self, request: bytes, context) -> bytes:
        from karpenter_core_tpu.models.snapshot import build_pod_ladder

        try:
            req = msgpack.unpackb(request)
            entries = req.get("podClasses", [])
            reps = [codec.pod_from_dict(e["pod"]) for e in entries]
            classes = []
            for rep, entry in zip(reps, entries):
                cls = build_pod_ladder(rep)
                cls.pods = [rep] * int(entry["count"])
                classes.append(cls)
            req_idx = {id(rep): i for i, rep in enumerate(reps)}
            provisioners, daemonset_pods, state_nodes, bound, resolver = (
                self._decode_common(req)
            )

            solver = TPUSolver(
                self.cloud_provider, provisioners, daemonset_pods,
                kube_client=resolver,
            )
            snapshot = solver.encode_classes(
                classes, state_nodes=state_nodes or None, bound_pods=bound
            )
            results = solver.solve_encoded(snapshot, state_nodes or None, bound)

            def class_counts(pods) -> list:
                counts: Dict[int, int] = {}
                for p in pods:
                    i = req_idx[id(p)]
                    counts[i] = counts.get(i, 0) + 1
                return sorted(counts.items())

            response = {
                "newNodes": [
                    {
                        "provisioner": n.provisioner_name,
                        "instanceTypes": n.instance_type_names,
                        "zones": n.zones,
                        "requests": n.requests,
                        "classCounts": class_counts(n.pods),
                    }
                    for n in results.new_nodes
                ],
                "existingAssignments": {
                    name: class_counts(placed)
                    for name, placed in results.existing_assignments.items()
                },
                "failedClassCounts": class_counts(results.failed_pods),
            }
            return msgpack.packb(response)
        except KernelUnsupported as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, f"kernel unsupported: {e}")
        except Exception as e:  # noqa: BLE001 - surface as INTERNAL
            log.exception("solve-classes request failed")
            context.abort(grpc.StatusCode.INTERNAL, str(e))

    def _solve(self, request: bytes, context) -> bytes:
        try:
            req = msgpack.unpackb(request)
            pods = [codec.pod_from_dict(p) for p in req.get("pods", [])]
            provisioners, daemonset_pods, state_nodes, bound, resolver = (
                self._decode_common(req)
            )

            solver = TPUSolver(
                self.cloud_provider, provisioners, daemonset_pods,
                kube_client=resolver,
            )
            results = solver.solve(pods, state_nodes=state_nodes or None, bound_pods=bound)

            pod_index = {p.uid: i for i, p in enumerate(pods)}
            response = {
                "newNodes": [
                    {
                        "provisioner": n.provisioner_name,
                        "instanceTypes": n.instance_type_names,
                        "zones": n.zones,
                        "requests": n.requests,
                        "podIndices": [pod_index[p.uid] for p in n.pods if p.uid in pod_index],
                    }
                    for n in results.new_nodes
                ],
                "existingAssignments": {
                    name: [pod_index[p.uid] for p in placed if p.uid in pod_index]
                    for name, placed in results.existing_assignments.items()
                },
                "failedPodIndices": [
                    pod_index[p.uid] for p in results.failed_pods if p.uid in pod_index
                ],
            }
            return msgpack.packb(response)
        except KernelUnsupported as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, f"kernel unsupported: {e}")
        except Exception as e:  # noqa: BLE001 - surface as INTERNAL
            log.exception("solve request failed")
            context.abort(grpc.StatusCode.INTERNAL, str(e))


def serve(cloud_provider, address: str = "127.0.0.1:0", max_workers: int = 4):
    """Start the sidecar; returns (server, bound_port)."""
    from karpenter_core_tpu.utils import compilecache

    compilecache.enable()  # sidecar restarts reuse compiled solve kernels
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((SnapshotSolverService(cloud_provider),))
    port = server.add_insecure_port(address)
    server.start()
    log.info("snapshot solver listening on port %d", port)
    return server, port


class SnapshotSolverClient:
    """Controller-plane client for the channel."""

    def __init__(self, address: str) -> None:
        self.channel = grpc.insecure_channel(address)
        self._solve = self.channel.unary_unary(f"/{SERVICE}/Solve")
        self._solve_classes = self.channel.unary_unary(f"/{SERVICE}/SolveClasses")
        self._health = self.channel.unary_unary(f"/{SERVICE}/Health")

    def health(self) -> Dict:
        return msgpack.unpackb(self._health(msgpack.packb({})))

    def solve(
        self,
        pods: List,
        provisioners: List,
        nodes: Optional[List[Dict]] = None,
        daemonset_pods: Optional[List] = None,
        claim_drivers: Optional[Dict[str, str]] = None,
        timeout: float = 60.0,
    ) -> Dict:
        """nodes: [{"node": node_dict, "pods": [...], "volumeLimits": {...}}];
        claim_drivers: {"<ns>/<claim>": csi-driver} resolved by this plane so
        volume attach limits bind on the solver side."""
        request = msgpack.packb(
            {
                "pods": [codec.pod_to_dict(p) for p in pods],
                "provisioners": [codec.provisioner_to_dict(p) for p in provisioners],
                "daemonsetPods": [codec.pod_to_dict(p) for p in daemonset_pods or []],
                "nodes": nodes or [],
                "claimDrivers": claim_drivers or {},
            }
        )
        return msgpack.unpackb(self._solve(request, timeout=timeout))

    def solve_classes(
        self,
        pods: List,
        provisioners: List,
        nodes: Optional[List[Dict]] = None,
        daemonset_pods: Optional[List] = None,
        claim_drivers: Optional[Dict[str, str]] = None,
        timeout: float = 60.0,
    ) -> Dict:
        """Class-columnar solve: dedup ``pods`` into shape classes locally,
        ship one representative + count per class, and expand the per-node
        class counts back into this caller's pod objects.  Returns the same
        dict shape as solve() (podIndices refer to the ``pods`` argument)."""
        from karpenter_core_tpu.models.snapshot import _class_signature

        by_sig: Dict[tuple, List[int]] = {}
        for i, pod in enumerate(pods):
            by_sig.setdefault(_class_signature(pod), []).append(i)
        members = list(by_sig.values())
        request = msgpack.packb(
            {
                "podClasses": [
                    {"pod": codec.pod_to_dict(pods[idxs[0]]), "count": len(idxs)}
                    for idxs in members
                ],
                "provisioners": [codec.provisioner_to_dict(p) for p in provisioners],
                "daemonsetPods": [codec.pod_to_dict(p) for p in daemonset_pods or []],
                "nodes": nodes or [],
                "claimDrivers": claim_drivers or {},
            }
        )
        response = msgpack.unpackb(self._solve_classes(request, timeout=timeout))
        cursors = [0] * len(members)

        def take(counts) -> List[int]:
            indices: List[int] = []
            for c, n in counts:
                start = cursors[c]
                indices.extend(members[c][start : start + n])
                cursors[c] = start + n
            return indices

        return {
            "newNodes": [
                {
                    "provisioner": n["provisioner"],
                    "instanceTypes": n["instanceTypes"],
                    "zones": n["zones"],
                    "requests": n["requests"],
                    "podIndices": take(n["classCounts"]),
                }
                for n in response["newNodes"]
            ],
            "existingAssignments": {
                name: take(counts)
                for name, counts in response["existingAssignments"].items()
            },
            "failedPodIndices": take(response["failedClassCounts"]),
        }

    def close(self) -> None:
        self.channel.close()
