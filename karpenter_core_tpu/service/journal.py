"""Durable solver sessions: the crash-consistent session journal.

PR 12's multi-tenant service survives a restart only by discarding every
tenant lineage and re-anchoring with full solves (``session-lost``) —
correct, but one process crash becomes a fleet-wide cold-solve storm.  This
module is the durable-state layer that brings a crashed or drained solver
back **warm**:

  frames      An append-only write-ahead journal of per-tenant solve
              records.  Every frame is ``u32 length | u32 crc32c | msgpack
              payload`` after a file magic; a record carries the tenant, a
              global ``seq``, a per-tenant ``tseq``, the solve kind
              (``anchor`` = full, ``delta`` = repair), the lineage version,
              the client's supply digest, a cross-process-stable
              verification ``state`` (IncrementalSolveSession.lineage_state:
              snapshot-store plane digests, supply+policy anchor, cumulative
              assignment signature), and the RAW wire request bytes.

  replay      Recovery does not deserialize tensors — it REPLAYS.  A
              tenant's live chain is its last anchor plus the deltas since
              (the fallback policy's audit interval bounds the chain); each
              record's stored request is decoded and re-solved through a
              fresh session, and because solves are deterministic the
              replayed lineage is bit-identical to the one that crashed.
              Never-trust verification: the replayed ``lineage_state`` must
              equal the journaled one field for field, and the client's next
              request still passes the version/supply-digest checks — any
              mismatch, torn frame, truncated tail, or CRC failure
              downgrades that tenant to the existing ``session-lost``
              re-anchor.  A wrong answer is impossible; the worst case is
              always a full solve.

  checkpoints Every ``checkpoint_every`` appends the writer compacts: the
              live chains (an anchor obsoletes everything before it; a
              ``drop`` record removes an evicted tenant) are rewritten to
              ``checkpoint.wal`` (tmp + fsync + atomic rename) and the
              journal is truncated.  A crash between rename and truncate
              leaves duplicate frames — global ``seq`` dedup makes the
              replay see each record once — and a checkpoint "newer" than a
              stale journal resolves the same way.

  discipline  Appends are enqueued off the RPC hot path; a single writer
              thread frames, writes, flushes, and (by default) fsyncs each
              record.  Any I/O failure — real ENOSPC, a failed fsync, or an
              injected ``store.io`` chaos fault — fails the journal CLOSED:
              journaling stops (counted on
              ``karpenter_journal_failures_total``), serving continues, and
              the next recovery reads the valid durable prefix.  The journal
              degrades availability of warmth, never correctness.

``store.io`` is the chaos point on this boundary (docs/CHAOS.md): ``error``
(ENOSPC via ``data.errno``, fsync failure via ``data.op="fsync"``) and
``partial`` (a torn half-frame lands, then the journal dies — the shape a
kill -9 mid-append leaves on disk) on append hits; ``error`` on checkpoint
hits leaves a stale checkpoint behind while the journal keeps growing.

Recovery outcomes land on ``karpenter_session_recovered_total{outcome}``:
``warm`` (lineage restored + verified), ``reanchor`` (replay failed or
verification mismatched — the tenant re-anchors), ``corrupt`` (the frame
stream itself broke under that tenant's chain).

All timestamps ride the injected ``utils/clock.Clock`` (the kcanalyze
``wallclock`` rule covers ``service/``), so recovery suites run on
FakeClock.  Triage flags: ``KC_SESSION_JOURNAL``, ``KC_JOURNAL_DIR``,
``KC_JOURNAL_CHECKPOINT_EVERY``, ``KC_JOURNAL_FSYNC`` (docs/SERVICE.md).
"""

from __future__ import annotations

import logging
import os
import queue
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

import msgpack

from karpenter_core_tpu import chaos, tracing
from karpenter_core_tpu.metrics import REGISTRY
from karpenter_core_tpu.utils.clock import Clock

log = logging.getLogger(__name__)

# the store-I/O injection point (docs/CHAOS.md): torn write / partial frame /
# ENOSPC / fsync error on appends, stale checkpoint on compactions
STORE_IO = chaos.point("store.io")

SESSION_RECOVERED = REGISTRY.counter(
    "karpenter_session_recovered_total",
    "Session recovery outcomes at restart: warm (lineage replayed and "
    "verified), reanchor (a tenant chain was structurally broken or failed "
    "replay/verification — that tenant re-anchors session-lost), corrupt "
    "(a journal/checkpoint frame stream was torn or CRC-failed — counted "
    "per damaged file).",
    ("outcome",),
)
JOURNAL_RECORDS = REGISTRY.counter(
    "karpenter_journal_records_total",
    "Session-journal records accepted for append, by kind "
    "(anchor / delta / drop).",
    ("kind",),
)
JOURNAL_FAILURES = REGISTRY.counter(
    "karpenter_journal_failures_total",
    "Session-journal I/O failures by operation (append / fsync / checkpoint "
    "/ queue); any append/fsync failure fails the journal closed.",
    ("op",),
)
JOURNAL_ACTIVE = REGISTRY.gauge(
    "karpenter_journal_active",
    "1 while the session journal is accepting appends (0 = disabled or "
    "failed closed).",
)
JOURNAL_FSYNC_LATENCY = REGISTRY.histogram(
    "karpenter_journal_fsync_latency_seconds",
    "Per-record journal fsync latency on the writer thread (absent when "
    "KC_JOURNAL_FSYNC=0).",
    buckets=[0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
             0.05, 0.1, 0.25, 0.5, 1],
)
JOURNAL_CHECKPOINT_BYTES = REGISTRY.histogram(
    "karpenter_journal_checkpoint_bytes",
    "Compacted checkpoint size in bytes, per compaction.",
    buckets=[1024, 4096, 16384, 65536, 262144, 1048576, 4194304, 16777216,
             67108864, 268435456],
)
SESSION_REPLAY_DURATION = REGISTRY.histogram(
    "karpenter_session_replay_duration_seconds",
    "Per-tenant journal replay time during warm recovery, by (guarded) "
    "tenant label.",
    ("tenant",),
)

MAGIC = b"KCWJ1\n"
_FRAME_HEAD = struct.Struct("<II")  # payload length, crc32c(payload)
MAX_FRAME_BYTES = 64 * 1024 * 1024

KIND_ANCHOR = "anchor"
KIND_DELTA = "delta"

# frame-stream read statuses
STATUS_OK = "ok"
STATUS_EMPTY = "empty"
STATUS_MISSING = "missing"
STATUS_TORN = "torn"       # truncated mid-frame (crash tail)
STATUS_CORRUPT = "corrupt"  # CRC / framing / decode failure


class RecoveryMismatch(Exception):
    """A replayed lineage disagreed with its journaled verification state —
    the never-trust downgrade to ``session-lost``."""


# -- crc32c (Castagnoli) ------------------------------------------------------
# zlib.crc32 is CRC-32/ISO-HDLC; storage stacks standardize on Castagnoli for
# its better burst-error detection, and the table-driven form below is fast
# enough for the writer thread (frames are O(request) small).

def _crc32c_table() -> Tuple[int, ...]:
    poly = 0x82F63B78
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        table.append(crc)
    return tuple(table)


_CRC_TABLE = _crc32c_table()


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC_TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


# -- frame codec --------------------------------------------------------------


def encode_frame(record: dict) -> bytes:
    payload = msgpack.packb(record)
    return _FRAME_HEAD.pack(len(payload), crc32c(payload)) + payload


def read_frames(path: str, magic: bytes = MAGIC) -> Tuple[List[dict], str]:
    """Decode every intact frame from ``path``; stops at the FIRST torn or
    corrupt frame (WAL discipline: framing after corruption cannot be
    trusted).  Returns (records, status).  ``magic`` lets other crc32c-framed
    files (the fleet session checkpoints, fleet/checkpoint.py) share the
    exact same read discipline without sharing the journal's file identity."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        return [], STATUS_MISSING
    except OSError as e:  # unreadable volume: recover what we can't — nothing
        log.warning("journal read failed for %s: %s", path, e)
        return [], STATUS_CORRUPT
    if not data:
        return [], STATUS_EMPTY
    if not data.startswith(magic):
        return [], STATUS_CORRUPT
    records: List[dict] = []
    off = len(magic)
    n = len(data)
    while off < n:
        if off + _FRAME_HEAD.size > n:
            return records, STATUS_TORN
        length, crc = _FRAME_HEAD.unpack_from(data, off)
        if length > MAX_FRAME_BYTES:
            return records, STATUS_CORRUPT
        start = off + _FRAME_HEAD.size
        end = start + length
        if end > n:
            return records, STATUS_TORN
        payload = data[start:end]
        if crc32c(payload) != crc:
            return records, STATUS_CORRUPT
        try:
            rec = msgpack.unpackb(payload)
        except Exception:  # noqa: BLE001 - a framed-but-unparseable payload
            return records, STATUS_CORRUPT
        if isinstance(rec, dict):
            records.append(rec)
        off = end
    return records, STATUS_OK


# -- chain assembly -----------------------------------------------------------


class ChainMirror:
    """Per-tenant live-chain state, driven one record at a time.  The SAME
    implementation serves recovery (assembling chains from the frame stream)
    and the writer (tracking what the next checkpoint must retain), so the
    two can never disagree about which records are live."""

    def __init__(self, max_chain: int = 64) -> None:
        self.max_chain = max_chain
        self.chains: Dict[str, List[dict]] = {}
        self.broken: set = set()
        self._last_seq: Dict[str, int] = {}

    def apply(self, rec: dict) -> None:
        tenant = rec.get("tenant")
        if not isinstance(tenant, str) or not tenant:
            return
        seq = int(rec.get("seq", -1))
        # duplicate suppression: a crash between checkpoint-rename and
        # journal-truncate leaves the same frames in both files, and a stale
        # journal older than the checkpoint replays already-compacted seqs
        if seq <= self._last_seq.get(tenant, -1):
            return
        self._last_seq[tenant] = seq
        kind = rec.get("t")
        if kind == "drop":
            self.chains.pop(tenant, None)
            self.broken.discard(tenant)
            return
        if kind != "solve":
            return
        if rec.get("kind") == KIND_ANCHOR:
            self.chains[tenant] = [rec]
            self.broken.discard(tenant)
            return
        chain = self.chains.get(tenant)
        if (
            chain is None
            or int(rec.get("tseq", -1)) != int(chain[-1].get("tseq", -1)) + 1
            or int(rec.get("version", -1)) != int(chain[0].get("version", -2))
        ):
            # a delta with no anchor, a gap in the tenant sequence, or a
            # version that moved without an anchor: the chain cannot be
            # replayed faithfully — forget it (the tenant re-anchors)
            self.chains.pop(tenant, None)
            self.broken.add(tenant)
            return
        chain.append(rec)
        if len(chain) > self.max_chain:
            self.chains.pop(tenant, None)
            self.broken.add(tenant)

    def live_records(self) -> List[dict]:
        """Every record a compaction must keep, in global seq order."""
        out = [rec for chain in self.chains.values() for rec in chain]
        out.sort(key=lambda r: int(r.get("seq", 0)))
        return out

    def max_seq(self) -> int:
        return max(self._last_seq.values(), default=-1)


def assemble_chains(
    records: List[dict], max_chain: int = 64
) -> Tuple[Dict[str, List[dict]], set]:
    """(tenant -> live chain, broken tenants) from a decoded frame stream."""
    mirror = ChainMirror(max_chain)
    for rec in records:
        mirror.apply(rec)
    return mirror.chains, mirror.broken


# -- the journal --------------------------------------------------------------


class SessionJournal:
    """Append-only, crc32c-framed, fsync-disciplined session journal with
    periodic compacted checkpoints (module docstring).  Appends are enqueued
    (never blocking the RPC path) and written by one background thread;
    ``recover()`` is called before ``start()`` so the restart sequence is
    read → replay → compact → resume appending."""

    def __init__(
        self,
        directory: str,
        clock: Optional[Clock] = None,
        checkpoint_every: int = 64,
        fsync: bool = True,
        max_chain: int = 64,
        queue_depth: int = 1024,
        queue_bytes: int = 64 * 1024 * 1024,
    ) -> None:
        self.directory = directory
        self.journal_path = os.path.join(directory, "journal.wal")
        self.checkpoint_path = os.path.join(directory, "checkpoint.wal")
        self.clock = clock or Clock()
        self.checkpoint_every = max(int(checkpoint_every), 0)
        self.fsync = fsync
        self.max_chain = max_chain
        os.makedirs(directory, exist_ok=True)
        self._queue: "queue.Queue" = queue.Queue(maxsize=queue_depth)
        # records hold raw request bytes (up to KC_TENANT_MAX_BYTES each), so
        # the queue must be bounded by BYTES as well as count — a slow disk
        # under large-snapshot tenants would otherwise buffer gigabytes
        # before the count bound ever fired
        self.queue_bytes = queue_bytes
        self._queued_bytes = 0
        self._seq_lock = threading.Lock()
        self._seq = 0
        self._mirror = ChainMirror(max_chain)
        self._appends_since_ckpt = 0
        self._fd = None
        self._failed = False
        self._closed = False
        self._writer: Optional[threading.Thread] = None

    # -- restart-time read side ------------------------------------------------

    def recover(self) -> Tuple[Dict[str, List[dict]], set, Dict[str, str]]:
        """Read checkpoint + journal tail and assemble the live per-tenant
        chains.  Returns (chains, broken tenants, read statuses).  Also
        seeds the writer's mirror and seq counter so post-recovery appends
        and compactions continue the same history."""
        ck_records, ck_status = read_frames(self.checkpoint_path)
        j_records, j_status = read_frames(self.journal_path)
        mirror = ChainMirror(self.max_chain)
        for rec in ck_records + j_records:
            mirror.apply(rec)
        self._mirror = mirror
        with self._seq_lock:
            self._seq = mirror.max_seq() + 1
        stats = {"checkpoint": ck_status, "journal": j_status,
                 "frames": str(len(ck_records) + len(j_records))}
        if ck_status not in (STATUS_OK, STATUS_MISSING, STATUS_EMPTY) or \
                j_status not in (STATUS_OK, STATUS_MISSING, STATUS_EMPTY):
            log.warning(
                "session journal read degraded (checkpoint=%s journal=%s): "
                "replaying the valid durable prefix only", ck_status, j_status,
            )
        return dict(mirror.chains), set(mirror.broken), stats

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        """Compact the recovered state and begin accepting appends."""
        try:
            self._compact_locked()
        except OSError as e:
            self._fail("checkpoint", f"startup compaction failed: {e}")
            return
        self._writer = threading.Thread(
            target=self._run, name="kc-session-journal", daemon=True
        )
        self._writer.start()
        JOURNAL_ACTIVE.labels().set(1.0)

    def active(self) -> bool:
        """Accepting appends.  True between construction and close/failure —
        including BEFORE start(): recovery enqueues drop records for chains
        that failed verification, and the writer drains them the moment it
        comes up."""
        return not self._failed and not self._closed

    def close(self, checkpoint: bool = True, timeout_s: float = 10.0) -> None:
        """Graceful shutdown: drain the queue, optionally write a final
        compacted checkpoint (the drain path), release the file."""
        if self._closed:
            return
        self._closed = True
        JOURNAL_ACTIVE.labels().set(0.0)
        if self._writer is None:
            self._close_fd()
            return
        if checkpoint:
            done = threading.Event()
            self._put(("ckpt", done))
        self._put(("stop", None))
        self._writer.join(timeout=timeout_s)
        # the writer closes the fd on ITS way out; a timed-out join means it
        # may still be mid-write — leaking the fd briefly beats racing it

    def abandon(self) -> None:
        """SIGKILL semantics for tests/soak: stop WITHOUT draining — queued
        (not-yet-durable) records are dropped, no final checkpoint, the file
        is released exactly as a dead process would leave it."""
        if self._closed:
            return
        self._closed = True
        self._failed = True  # writer discards whatever it still dequeues
        JOURNAL_ACTIVE.labels().set(0.0)
        if self._writer is None:
            self._close_fd()
            return
        self._put(("stop", None))
        self._writer.join(timeout=2.0)

    def checkpoint_now(self, timeout_s: float = 10.0) -> bool:
        """Force a compaction (the drain path calls this via close); returns
        False when the writer could not confirm in time."""
        if not self.active():
            return False
        done = threading.Event()
        self._put(("ckpt", done))
        return done.wait(timeout_s)

    # -- append side (RPC hot path: enqueue only) ------------------------------

    def append_solve(
        self,
        tenant: str,
        kind: str,
        tseq: int,
        version: int,
        client_supply: Optional[str],
        state: Dict[str, object],
        request: bytes,
        trace_ctx: Optional[Dict[str, str]] = None,
    ) -> None:
        """Record one completed tenant solve.  Called with the tenant entry
        lock held — everything here is dict construction plus a non-blocking
        enqueue; framing, I/O, and fsync happen on the writer thread.

        ``trace_ctx`` is the solve's tracing wire context
        (``tracing.wire_context()``: ``{"traceId", "spanId"}``) when the
        solve ran traced — journaled so a warm-restart replay links its
        spans back to the originating trace.  The field is OPTIONAL on read
        (schema v1 additive, service/SCHEMA.md): journals written before it
        existed, or with tracing off, replay exactly as before."""
        if not self.active():
            return
        rec = {
            "t": "solve",
            "tenant": tenant,
            "kind": kind,
            "tseq": int(tseq),
            "version": int(version),
            "client_supply": client_supply,
            "state": dict(state),
            "request": bytes(request),
            "ts": self.clock.now(),
        }
        if trace_ctx:
            rec["trace"] = {str(k): str(v) for k, v in trace_ctx.items()}
        self._enqueue(rec, kind)

    def append_drop(self, tenant: str) -> None:
        """The tenant's session left the plane (LRU/TTL eviction, failed
        recovery): recovery must not resurrect the lineage."""
        if not self.active():
            return
        self._enqueue({"t": "drop", "tenant": tenant, "ts": self.clock.now()}, "drop")

    def _enqueue(self, rec: dict, kind: str) -> None:
        cost = len(rec.get("request") or b"") + 256
        with self._seq_lock:
            if self._queued_bytes + cost > self.queue_bytes:
                # never block an RPC on the journal (and never buffer
                # unbounded bytes); a dropped append only costs warmth at
                # the next recovery (the chain breaks at the gap)
                JOURNAL_FAILURES.labels("queue").inc()
                return
            rec["seq"] = self._seq
            self._seq += 1
            self._queued_bytes += cost
        try:
            self._queue.put_nowait(("rec", rec, cost))
            JOURNAL_RECORDS.labels(kind).inc()
        except queue.Full:
            with self._seq_lock:
                self._queued_bytes -= cost
            JOURNAL_FAILURES.labels("queue").inc()

    def _put(self, item) -> None:
        try:
            self._queue.put(item, timeout=1.0)
        except queue.Full:
            JOURNAL_FAILURES.labels("queue").inc()

    # -- writer thread ---------------------------------------------------------

    def _run(self) -> None:
        while True:
            item = self._queue.get()
            op, payload = item[0], item[1]
            if op == "stop":
                break
            if op == "ckpt":
                if not self._failed:
                    try:
                        self._compact_locked()
                    except (OSError, ValueError) as e:
                        self._fail("checkpoint", str(e))
                if payload is not None:
                    payload.set()
                continue
            with self._seq_lock:
                self._queued_bytes -= item[2]
            if not self._failed:
                self._write_record(payload)
        # the writer owns the fd while it runs: only IT closes on exit, so a
        # close()/abandon() join timeout can never yank the file out from
        # under a write in flight
        self._close_fd()

    def _ensure_fd(self):
        if self._fd is None:
            self._fd = open(self.journal_path, "ab")
            if self._fd.tell() == 0:
                self._fd.write(MAGIC)
                self._fd.flush()
                if self.fsync:
                    os.fsync(self._fd.fileno())
        return self._fd

    def _write_record(self, rec: dict) -> None:
        frame = encode_frame(rec)
        fault = STORE_IO.hit(
            kinds=("error", "partial"), op="append", tenant=rec.get("tenant", "")
        )
        try:
            fd = self._ensure_fd()
            if fault is not None:
                if fault.kind == "partial":
                    # torn write: a prefix lands (what a kill -9 mid-append
                    # leaves on disk), then the journal dies
                    fd.write(frame[: max(len(frame) // 2, 1)])
                    fd.flush()
                    raise OSError(fault.describe())
                if (fault.data or {}).get("op") == "fsync":
                    # the write lands, durability doesn't
                    fd.write(frame)
                    fd.flush()
                    raise OSError(fault.describe())
                errno_ = int((fault.data or {}).get("errno", 0))
                raise OSError(errno_, fault.describe())
            fd.write(frame)
            fd.flush()
            if self.fsync:
                t0 = time.perf_counter()
                os.fsync(fd.fileno())
                JOURNAL_FSYNC_LATENCY.labels().observe(
                    time.perf_counter() - t0
                )
        except (OSError, ValueError) as e:
            # ValueError = operation on a closed file (a teardown race):
            # same verdict as a disk error — fail closed, keep serving
            self._fail("append", str(e))
            return
        self._mirror.apply(rec)
        self._appends_since_ckpt += 1
        if self.checkpoint_every and self._appends_since_ckpt >= self.checkpoint_every:
            fault = STORE_IO.hit(kinds=("error",), op="checkpoint")
            if fault is not None:
                # stale checkpoint: compaction skipped, the old checkpoint
                # stays on disk and the journal keeps growing — recovery is
                # still exact (seq dedup), only compaction is deferred
                JOURNAL_FAILURES.labels("checkpoint").inc()
                self._appends_since_ckpt = 0
                return
            try:
                self._compact_locked()
            except OSError as e:
                self._fail("checkpoint", str(e))

    def _compact_locked(self) -> None:
        """Rewrite the live chains as the checkpoint (tmp + fsync + atomic
        rename + directory fsync), then truncate the journal.  Runs on the
        writer thread (or synchronously from start(), before the writer
        exists)."""
        with tracing.span("journal.checkpoint",
                          tenants=len(self._mirror.chains)):
            tmp = f"{self.checkpoint_path}.tmp.{os.getpid()}"
            ckpt_bytes = len(MAGIC)
            with open(tmp, "wb") as f:
                f.write(MAGIC)
                for rec in self._mirror.live_records():
                    frame = encode_frame(rec)
                    ckpt_bytes += len(frame)
                    f.write(frame)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.checkpoint_path)
            JOURNAL_CHECKPOINT_BYTES.labels().observe(float(ckpt_bytes))
            self._fsync_dir()
            # rotate the journal: everything live is in the checkpoint now
            self._close_fd()
            with open(self.journal_path, "wb") as f:
                f.write(MAGIC)
                f.flush()
                os.fsync(f.fileno())
            self._fsync_dir()
            self._appends_since_ckpt = 0

    def _fsync_dir(self) -> None:
        try:
            dfd = os.open(self.directory, os.O_RDONLY)
        except OSError:
            return
        try:
            os.fsync(dfd)
        except OSError:
            pass
        finally:
            os.close(dfd)

    def _close_fd(self) -> None:
        if self._fd is not None:
            try:
                self._fd.close()
            except OSError:
                pass
            self._fd = None

    def _fail(self, op: str, reason: str) -> None:
        """Fail CLOSED: journaling stops, serving continues.  A disk that
        can't take writes must never take the solver down with it — the cost
        is bounded (colder recovery), the alternative is an outage."""
        self._failed = True
        JOURNAL_FAILURES.labels(op).inc()
        JOURNAL_ACTIVE.labels().set(0.0)
        tracing.add_event("journal.failed", op=op, reason=reason)
        log.warning(
            "session journal failed closed (%s: %s) — serving continues "
            "without durability; sessions re-anchor at the next restart",
            op, reason,
        )
        self._close_fd()
