"""Runtime lockset tracer: the dynamic half of the shared-state gate.

The static ``shared-state`` pass (analysis/passes/shared_state.py) proves
what it can see lexically; calls through callbacks, duck-typed hooks, and
closures are invisible to it.  This module is the witness for that blind
spot: while a :class:`LockCheck` is active it

  - patches the ``threading.Lock`` / ``threading.RLock`` factories so every
    lock constructed in the window is a traced proxy that maintains a
    per-thread *held lockset*, and
  - instruments watched classes (``__setattr__`` + ``__getattribute__``) so
    every instance-field access records a ``(field, held lockset)``
    observation.

Observations feed the classic Eraser state machine per ``(object, field)``:
Virgin -> Exclusive(first thread) -> Shared -> SharedModified, with the
candidate lockset refined by intersection on every access.  A field reaches
a *violation* when it is SharedModified (written with two or more threads
involved) and the intersection is empty — some access pair shares no lock.
Fields only ever touched by one thread (init-only, or genuinely
thread-confined) never report, which is the runtime analogue of the static
pass's init-only escape analysis.

Opt-in: the chaos matrix and the fleet-failover soak enable the tracer when
``KC_LOCKCHECK=1`` (see docs/CHAOS.md); tests can also use ``LockCheck``
directly as a context manager around any threaded section.

Scope and honesty:

  - Only locks *constructed inside the window* are traced; module-level
    locks created at import time are invisible (construct the system under
    test inside the window — every test here does).
  - ``with lock:`` never reaches a profile hook for ``__enter__`` on this
    interpreter, which is why the proxies patch the factories instead of
    ``sys.setprofile``: the proxy's own ``__enter__`` is the trace point.
  - The instrumentation is deliberately heavyweight (every attribute access
    takes the tracer's bookkeeping lock); it is a test harness, never a
    production path.
"""

from __future__ import annotations

import _thread
import os
import threading
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple, Type

__all__ = ["LockCheck", "LockCheckError", "Violation", "enabled"]

# real primitive types, captured before any factory patching
_REAL_LOCK_TYPES = (type(_thread.allocate_lock()), type(threading.RLock()))

# Eraser states per (object, field)
_VIRGIN = 0          # never accessed
_EXCLUSIVE = 1       # one thread only — init / thread-confined
_SHARED = 2          # read by a second thread, no writes since
_SHARED_MODIFIED = 3  # written with >= 2 threads involved: lockset gates


def enabled() -> bool:
    """True when the opt-in suites should run under the tracer."""
    return os.environ.get("KC_LOCKCHECK", "0") == "1"


class LockCheckError(AssertionError):
    """Raised by :meth:`LockCheck.assert_clean` — subclasses AssertionError
    so a violation fails a pytest the same way a bare assert would."""


@dataclass
class Violation:
    """A field some access pair touched with no common lock held."""

    cls: str
    fld: str
    threads: int
    writes: int
    locksets: Tuple[FrozenSet[str], ...]  # distinct observed locksets

    def render(self) -> str:
        shapes = sorted(
            "{" + ", ".join(sorted(s)) + "}" if s else "{}"
            for s in self.locksets
        )
        return (
            f"{self.cls}.{self.fld}: {self.threads} thread(s), "
            f"{self.writes} write(s), empty lockset intersection "
            f"(observed locksets: {', '.join(shapes)})"
        )


class _FieldState:
    __slots__ = ("state", "first_thread", "threads", "writes",
                 "lockset", "seen_locksets")

    def __init__(self) -> None:
        self.state = _VIRGIN
        self.first_thread: Optional[int] = None
        self.threads: Set[int] = set()
        self.writes = 0
        self.lockset: Optional[FrozenSet[str]] = None  # refined intersection
        self.seen_locksets: Set[FrozenSet[str]] = set()


class _Tracer:
    """Held-lockset bookkeeping + the Eraser table.  The internal mutex is a
    raw ``_thread`` lock so the tracer never observes itself."""

    def __init__(self) -> None:
        self._mu = _thread.allocate_lock()
        self._held: Dict[int, Dict[str, int]] = {}  # thread id -> key -> depth
        self._fields: Dict[Tuple[int, str], _FieldState] = {}
        self._field_cls: Dict[Tuple[int, str], str] = {}
        self._lock_seq = 0
        self.active = False

    # -- lock side ------------------------------------------------------------

    def next_lock_key(self, kind: str) -> str:
        with self._mu:
            self._lock_seq += 1
            return f"{kind}#{self._lock_seq}"

    def on_acquire(self, key: str) -> None:
        tid = _thread.get_ident()
        with self._mu:
            held = self._held.setdefault(tid, {})
            held[key] = held.get(key, 0) + 1

    def on_release(self, key: str) -> None:
        tid = _thread.get_ident()
        with self._mu:
            held = self._held.get(tid)
            if not held or key not in held:
                return  # released on a thread that never traced the acquire
            held[key] -= 1
            if held[key] <= 0:
                del held[key]

    # -- field side -----------------------------------------------------------

    def record(self, obj: object, fld: str, write: bool) -> None:
        if not self.active:
            return
        tid = _thread.get_ident()
        key = (id(obj), fld)
        with self._mu:
            lockset = frozenset(self._held.get(tid, ()))
            st = self._fields.get(key)
            if st is None:
                st = self._fields[key] = _FieldState()
                self._field_cls[key] = type(obj).__name__
            st.threads.add(tid)
            if write:
                st.writes += 1
            # Eraser transitions
            if st.state == _VIRGIN:
                st.state = _EXCLUSIVE
                st.first_thread = tid
            elif st.state == _EXCLUSIVE and tid != st.first_thread:
                # standard Eraser: a second-thread READ of a field only the
                # first thread wrote is read-sharing (publish-once), not yet
                # a race; a second-thread WRITE gates immediately.  The
                # candidate lockset starts at this first shared access, so
                # lock-free init writes before the object escaped don't
                # poison the intersection.
                st.state = _SHARED_MODIFIED if write else _SHARED
                st.lockset = None
            elif st.state == _SHARED and write:
                st.state = _SHARED_MODIFIED
            if st.state in (_SHARED, _SHARED_MODIFIED):
                st.seen_locksets.add(lockset)
                st.lockset = (lockset if st.lockset is None
                              else st.lockset & lockset)

    def violations(self) -> List[Violation]:
        with self._mu:
            out = []
            for key, st in self._fields.items():
                if st.state == _SHARED_MODIFIED and not st.lockset:
                    out.append(Violation(
                        cls=self._field_cls[key], fld=key[1],
                        threads=len(st.threads), writes=st.writes,
                        locksets=tuple(sorted(st.seen_locksets,
                                              key=sorted)),
                    ))
            out.sort(key=lambda v: (v.cls, v.fld))
            return out

    def observations(self) -> Dict[Tuple[str, str], Set[FrozenSet[str]]]:
        """(class, field) -> distinct locksets observed while shared —
        the raw evidence behind :meth:`violations`, exposed for tests."""
        with self._mu:
            return {
                (self._field_cls[key], key[1]): set(st.seen_locksets)
                for key, st in self._fields.items()
                if st.seen_locksets
            }


def _unwrap(lock: object) -> object:
    return lock._lc_lock if isinstance(lock, _TracedLock) else lock


class _TracedLock:
    """Delegating proxy around a real ``threading`` lock.  Implements the
    private RLock protocol (``_is_owned`` etc.) so ``threading.Condition``
    built on a traced lock keeps working — including updating the held set
    across ``wait()``'s release/reacquire."""

    __slots__ = ("_lc_lock", "_lc_tracer", "_lc_key")

    def __init__(self, real: object, tracer: _Tracer, key: str) -> None:
        self._lc_lock = real
        self._lc_tracer = tracer
        self._lc_key = key

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lc_lock.acquire(blocking, timeout)
        if got:
            self._lc_tracer.on_acquire(self._lc_key)
        return got

    def release(self) -> None:
        self._lc_tracer.on_release(self._lc_key)
        self._lc_lock.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: object) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lc_lock.locked()

    # RLock protocol used by threading.Condition; plain locks lack it, so
    # fall back exactly the way Condition itself would on a bare Lock
    def _is_owned(self) -> bool:
        inner = self._lc_lock
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def _release_save(self):  # Condition.wait: full release
        self._lc_tracer.on_release(self._lc_key)
        inner = self._lc_lock
        if hasattr(inner, "_release_save"):
            return inner._release_save()
        inner.release()
        return None

    def _acquire_restore(self, state) -> None:  # Condition.wait: reacquire
        inner = self._lc_lock
        if hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(state)
        else:
            inner.acquire()
        self._lc_tracer.on_acquire(self._lc_key)

    def __repr__(self) -> str:
        return f"<traced {self._lc_key} of {self._lc_lock!r}>"


class LockCheck:
    """Context manager activating the tracer.

    ::

        with LockCheck(watch=(TenantEntry, CheckpointPlane)) as lc:
            ...construct the system under test, run the threaded scenario...
        lc.assert_clean()

    ``watch`` classes get their ``__setattr__``/``__getattribute__``
    instrumented for the duration; every ``threading.Lock()`` /
    ``threading.RLock()`` constructed inside the window is traced.  Nesting
    is not supported (one tracer owns the factories).
    """

    _active: Optional["LockCheck"] = None

    def __init__(self, watch: Tuple[Type, ...] = ()) -> None:
        self.tracer = _Tracer()
        self._watch = tuple(watch)
        self._saved_factories: Dict[str, object] = {}
        self._saved_methods: List[Tuple[Type, str, object]] = []

    # -- lifecycle ------------------------------------------------------------

    def __enter__(self) -> "LockCheck":
        if LockCheck._active is not None:
            raise RuntimeError("LockCheck does not nest")
        LockCheck._active = self
        tracer = self.tracer
        self._saved_factories = {
            "Lock": threading.Lock, "RLock": threading.RLock,
        }
        real_lock, real_rlock = threading.Lock, threading.RLock

        def traced_lock():
            return _TracedLock(real_lock(), tracer,
                               tracer.next_lock_key("Lock"))

        def traced_rlock():
            return _TracedLock(real_rlock(), tracer,
                               tracer.next_lock_key("RLock"))

        threading.Lock = traced_lock  # type: ignore[assignment]
        threading.RLock = traced_rlock  # type: ignore[assignment]
        for cls in self._watch:
            self._instrument(cls)
        tracer.active = True
        return self

    def __exit__(self, *exc: object) -> None:
        self.tracer.active = False
        threading.Lock = self._saved_factories["Lock"]  # type: ignore
        threading.RLock = self._saved_factories["RLock"]  # type: ignore
        for cls, name, orig in reversed(self._saved_methods):
            if orig is None:
                try:
                    delattr(cls, name)
                except AttributeError:
                    pass
            else:
                setattr(cls, name, orig)
        self._saved_methods.clear()
        LockCheck._active = None

    # -- instrumentation ------------------------------------------------------

    def _instrument(self, cls: Type) -> None:
        tracer = self.tracer

        orig_setattr = cls.__dict__.get("__setattr__")
        base_setattr = cls.__setattr__

        def traced_setattr(self, name, value):
            if not name.startswith("_lc_") and not _is_lockish(value):
                tracer.record(self, name, write=True)
            base_setattr(self, name, value)

        self._saved_methods.append((cls, "__setattr__", orig_setattr))
        cls.__setattr__ = traced_setattr

        orig_getattribute = cls.__dict__.get("__getattribute__")

        def traced_getattribute(self, name):
            value = object.__getattribute__(self, name)
            if name.startswith("__") or name.startswith("_lc_"):
                return value
            try:
                inst = object.__getattribute__(self, "__dict__")
            except AttributeError:
                return value
            # only instance DATA fields count: methods/class attrs are not
            # shared mutable state, and lock fields are the guards themselves
            if name in inst and not _is_lockish(value):
                tracer.record(self, name, write=False)
            return value

        self._saved_methods.append(
            (cls, "__getattribute__", orig_getattribute))
        cls.__getattribute__ = traced_getattribute

    # -- results --------------------------------------------------------------

    def violations(self) -> List[Violation]:
        return self.tracer.violations()

    def observations(self):
        return self.tracer.observations()

    def assert_clean(self) -> None:
        bad = self.violations()
        if bad:
            lines = "\n  ".join(v.render() for v in bad)
            raise LockCheckError(
                f"lockcheck: {len(bad)} field(s) with an empty lockset "
                f"intersection across a shared access pair:\n  {lines}"
            )


def _is_lockish(value: object) -> bool:
    return isinstance(value, (_TracedLock,) + _REAL_LOCK_TYPES) or \
        isinstance(value, (threading.Condition, threading.Event,
                           threading.Semaphore))
