"""Suite harness: environment wiring + expectation helpers.

Mirror of the role of /root/reference/pkg/test/{environment.go,
expectations/expectations.go}: builds the kube client, cluster state,
informers, fake provider, fake clock, and recorder; ``expect_provisioned``
emulates kube-scheduler by binding pods to their nominated nodes
(expectations.go:215-233 binds manually because no kubelet/scheduler runs).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

from karpenter_core_tpu import chaos
from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import Node, NodeCondition, Pod
from karpenter_core_tpu.cloudprovider.fake import FakeCloudProvider
from karpenter_core_tpu.controllers.deprovisioning import DeprovisioningController
from karpenter_core_tpu.controllers.node import NodeController
from karpenter_core_tpu.controllers.provisioning import ProvisioningController
from karpenter_core_tpu.controllers.termination import TerminationController
from karpenter_core_tpu.events import Recorder
from karpenter_core_tpu.operator.kubeclient import (
    ConflictError,
    KubeClient,
    NotFoundError,
)
from karpenter_core_tpu.operator.settings import Settings
from karpenter_core_tpu.state.cluster import Cluster
from karpenter_core_tpu.state.informer import start_informers
from karpenter_core_tpu.utils.clock import FakeClock

# What one scheduling/controller step may raise and still be safely retried
# next round: injected chaos faults, read-after-delete races, and apiserver
# CAS conflicts.  Shared by step_scheduling_round and the soak tick loop so
# the two paths can never disagree on retryability.
SCHEDULING_RETRYABLE = (chaos.InjectedFault, NotFoundError, ConflictError)


@dataclass
class Environment:
    kube: KubeClient
    cluster: Cluster
    provider: FakeCloudProvider
    clock: FakeClock
    recorder: Recorder
    settings: Settings
    provisioning: Optional[ProvisioningController] = None
    node_lifecycle: Optional[NodeController] = None
    termination: Optional[TerminationController] = None
    deprovisioning: Optional[DeprovisioningController] = None
    bindings: Dict[str, str] = field(default_factory=dict)  # pod uid -> node name

    def bind(self, pod: Pod, node_name: str) -> None:
        """Emulate kube-scheduler binding."""
        pod.spec.node_name = node_name
        self.kube.apply(pod)
        self.bindings[pod.uid] = node_name

    def make_node_ready(self, node: Node) -> None:
        """Emulate kubelet registration: Ready condition + real capacity, then
        run the lifecycle chain so the node initializes (the role of
        ExpectMakeNodesReady in the reference suites).  Stillborn machines
        (the fake provider's create-succeeds-but-never-registers failure
        mode) have no kubelet to emulate, so they are skipped."""
        if node.spec.provider_id in self.provider.stillborn_ids:
            return
        ready = next((c for c in node.status.conditions if c.type == "Ready"), None)
        if ready is None:
            node.status.conditions.append(NodeCondition(type="Ready", status="True"))
        else:
            ready.status = "True"
        if not node.status.allocatable or not node.status.capacity:
            it_name = node.metadata.labels.get(labels_api.LABEL_INSTANCE_TYPE_STABLE)
            for it in self.provider.get_instance_types(None):
                if it.name == it_name:
                    node.status.capacity = dict(it.capacity)
                    node.status.allocatable = it.allocatable()
                    break
        node.metadata.labels.setdefault(labels_api.LABEL_HOSTNAME, node.name)
        self.kube.apply(node)
        self.node_lifecycle.reconcile(node)

    def make_all_nodes_ready(self) -> None:
        for node in self.kube.list_nodes():
            self.make_node_ready(node)


def make_environment(
    instance_types=None, settings: Optional[Settings] = None, kube_factory=None,
    clock: Optional[FakeClock] = None,
) -> Environment:
    """``kube_factory(clock)`` swaps the kube backend (default: in-memory
    KubeClient) — the apiserver-parity suites pass an ApiServerClient factory
    bound to a fake apiserver and re-run the same scenarios byte-identically.
    ``clock`` lets a caller that must exist before the environment (e.g. the
    soak runner arming a chaos scenario over construction-time watch
    establishment) share its FakeClock."""
    clock = clock or FakeClock()
    kube = kube_factory(clock) if kube_factory is not None else KubeClient(clock)
    provider = FakeCloudProvider(instance_types)
    settings = settings or Settings()
    recorder = Recorder(clock=clock.now)
    cluster = Cluster(clock, kube, provider, settings)
    start_informers(cluster, kube)
    env = Environment(
        kube=kube,
        cluster=cluster,
        provider=provider,
        clock=clock,
        recorder=recorder,
        settings=settings,
    )
    env.provisioning = ProvisioningController(
        kube, provider, cluster, recorder=recorder, settings=settings, clock=clock
    )
    env.node_lifecycle = NodeController(clock, kube, provider, cluster, settings)
    env.termination = TerminationController(clock, kube, provider, recorder=recorder)
    env.deprovisioning = DeprovisioningController(
        clock, kube, env.provisioning, provider, recorder, cluster, settings
    )
    # suites run with short waits; replacements auto-initialize via the hook
    env.deprovisioning._wait_attempts = 3
    env.deprovisioning.on_replacements_launched = lambda names: [
        env.make_node_ready(env.kube.get_node(n)) for n in names if env.kube.get_node(n)
    ]

    # finalize deleting nodes synchronously (the role the termination watch
    # loop plays in the real operator); guard against re-entrancy since
    # termination itself updates the node
    finalizing = set()

    def on_node_event(event_type: str, node: Node) -> None:
        if event_type != "MODIFIED" or node.metadata.deletion_timestamp is None:
            return
        if node.name in finalizing:
            return
        finalizing.add(node.name)
        try:
            for _ in range(8):
                if env.termination.reconcile(node) is None:
                    break
        finally:
            finalizing.discard(node.name)

    kube.watch(Node, on_node_event, replay=False)
    return env


def pending_pods(env: Environment, pods: Optional[list] = None) -> list:
    """Unbound, non-terminating pods — the convergence target the chaos and
    soak harnesses share.  Pass ``pods`` to filter an already-fetched list
    instead of issuing another LIST."""
    if pods is None:
        pods = env.kube.list_pods()
    return [
        p for p in pods
        if not p.spec.node_name and p.metadata.deletion_timestamp is None
    ]


def machine_leaks(env: Environment) -> list:
    """Provider machines with no live node object — stranded cloud instances
    nothing will ever delete (provider ids, empty when healthy)."""
    node_ids = {n.spec.provider_id for n in env.kube.list_nodes()}
    return [
        m.status.provider_id
        for m in env.provider.created_machines()
        if m.status.provider_id not in node_ids
    ]


def step_scheduling_round(env: Environment, reconcile: bool = True) -> Optional[str]:
    """One provisioning pass plus the kube-scheduler/kubelet emulation: bind
    nominated pods, make registered nodes ready.  Chaos-fault tolerant — an
    injected kubeapi fault landing on the emulation's own writes is simply
    retried next round, exactly as the real binder/kubelet would.  Returns
    the provisioning error, if any.  The shared inner step of the chaos
    convergence loops (tests/test_chaos_matrix.py) and the soak runner's
    tick (soak/runner.py)."""
    env.recorder.reset()
    err = env.provisioning.reconcile(wait_for_batch=False) if reconcile else None
    for uid, node_name in nominations(env.recorder).items():
        pod = next(
            (p for p in env.kube.list_pods()
             if p.uid == uid and not p.spec.node_name),
            None,
        )
        if pod is not None and env.kube.get_node(node_name) is not None:
            try:
                env.bind(pod, node_name)
            except SCHEDULING_RETRYABLE:
                pass  # rebind next round
    for node in env.kube.list_nodes():
        try:
            env.make_node_ready(node)
        except SCHEDULING_RETRYABLE:
            pass  # kubelet re-registers next round
    return err


def nominations(recorder) -> Dict[str, str]:
    """pod uid -> nominated node name, parsed from the recorder's Nominated
    events (the one place the event-message format is interpreted)."""
    out: Dict[str, str] = {}
    for event in recorder.events:
        if event.reason == "Nominated":
            out[event.involved_object.uid] = event.message.rsplit(" ", 1)[-1]
    return out


def expect_provisioned(env: Environment, *pods: Pod) -> Dict[str, Optional[Node]]:
    """Create pods, run one provisioning pass, bind nominated pods; returns
    pod uid -> bound Node (None when unscheduled)."""
    for pod in pods:
        if env.kube.get_pod(pod.namespace, pod.name) is None:
            env.kube.create(pod)
    env.recorder.reset()
    env.provisioning.reconcile(wait_for_batch=False)

    nominations_by_uid = nominations(env.recorder)

    out: Dict[str, Optional[Node]] = {}
    for pod in pods:
        node_name = nominations_by_uid.get(pod.uid)
        if node_name is None:
            out[pod.uid] = None
            continue
        env.bind(pod, node_name)
        out[pod.uid] = env.kube.get_node(node_name)
    return out


def expect_scheduled(env: Environment, result: Dict[str, Optional[Node]], pod: Pod) -> Node:
    node = result.get(pod.uid)
    assert node is not None, f"expected pod {pod.name} to be scheduled"
    return node


def expect_not_scheduled(env: Environment, result: Dict[str, Optional[Node]], pod: Pod) -> None:
    assert result.get(pod.uid) is None, f"expected pod {pod.name} to be unscheduled"
