"""Independent placement-validity oracle (VERDICT r4 #2).

Validates DECODED placements — the final kube state after a provisioning
pass, regardless of which engine (TPU kernel or host oracle) produced them —
against the scheduling contract the reference enforces per placement:

  - node capacity: sum of bound pod requests fits allocatable
    (resources Fits, node.go:143-145 analog)
  - taints tolerated (scheduling.Taints.Tolerates, suite parity)
  - required node affinity / node selector vs the node's labels
  - host ports disjoint per node
  - CSI volume attach limits per node
  - topology spread maxSkew over reachable domains
    (topologygroup.go:155-182 formula)
  - required pod affinity / anti-affinity satisfied per domain

This is deliberately NOT a reuse of solver/ or scheduling/ logic beyond the
raw data helpers (quantity parsing, LabelSelector.matches): the point is an
independent reading of the same contract, so a kernel that schedules the
right COUNT in the wrong PLACES (skew-violating zones, anti-conflicting
hosts, over capacity) fails loudly even when count-parity holds.

Known allowances (each mirrors reference semantics, not validator laxity):

  - a node label absent for a WELL-KNOWN key is skipped in requirement
    checks: launched nodes carry only single-valued labels (fake create /
    labels.go:127-129); a multi-valued zone stays unresolved until kubelet
    registration, and the solve already proved set-compatibility
  - spread skew measures the min over domains REACHABLE for the pod: zones
    satisfying the pod's own node requirements with at least one available
    offering for some launchable provisioner (the kernel's documented
    refinement over the reference's blind min pick; ROADMAP r2 #9) — plus
    frozen unreachable domains, whose counts still bound the fill from below
    exactly as the reference measures them (topology_test.go:124-162)
  - hostname-spread skew uses the min over hostnames of nodes ELIGIBLE for
    the pod (tolerated, compatible), because hostname domains are minted per
    node (topology.go:231-276) and new empty nodes opened mid-batch for
    other classes are not admissible targets
"""

from __future__ import annotations

from collections import Counter, defaultdict
from typing import Dict, List, Optional

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_GT,
    OP_IN,
    OP_LT,
    OP_NOT_IN,
    Node,
    Pod,
)
from karpenter_core_tpu.utils import resources as resources_util

ZONE = labels_api.LABEL_TOPOLOGY_ZONE
HOSTNAME = labels_api.LABEL_HOSTNAME
CT = labels_api.LABEL_CAPACITY_TYPE


def _expr_matches(expr, labels: Dict[str, str], skip_missing_well_known: bool) -> bool:
    """One NodeSelectorRequirement vs a concrete label map."""
    value = labels.get(expr.key)
    if value is None:
        if expr.operator == OP_DOES_NOT_EXIST:
            return True
        if expr.operator == OP_NOT_IN:
            return True
        # a missing well-known label is an unresolved (multi-valued) axis on
        # an unregistered node, not a violation — see module docstring
        return skip_missing_well_known and expr.key in labels_api.WELL_KNOWN_LABELS
    if expr.operator == OP_IN:
        return value in expr.values
    if expr.operator == OP_NOT_IN:
        return value not in expr.values
    if expr.operator == OP_EXISTS:
        return True
    if expr.operator == OP_DOES_NOT_EXIST:
        return False
    if expr.operator == OP_GT:
        try:
            return int(value) > int(next(iter(expr.values)))
        except ValueError:
            return False
    if expr.operator == OP_LT:
        try:
            return int(value) < int(next(iter(expr.values)))
        except ValueError:
            return False
    return True


def _pod_node_requirements_ok(pod: Pod, node_labels: Dict[str, str]) -> Optional[str]:
    """Required node affinity + nodeSelector vs node labels; None when ok."""
    for key, value in (pod.spec.node_selector or {}).items():
        have = node_labels.get(key)
        if have is None:
            if key in labels_api.WELL_KNOWN_LABELS:
                continue
            return f"nodeSelector {key}={value}: label absent"
        if have != value:
            return f"nodeSelector {key}={value}: node has {have}"
    affinity = pod.spec.affinity
    if (
        affinity is None
        or affinity.node_affinity is None
        or affinity.node_affinity.required is None
    ):
        return None
    terms = affinity.node_affinity.required.node_selector_terms
    if not terms:
        return None
    # terms OR together; expressions within a term AND together
    for term in terms:
        if all(
            _expr_matches(e, node_labels, skip_missing_well_known=True)
            for e in term.match_expressions
        ):
            return None
    return "required node affinity unsatisfied by node labels"


def _tolerates(pod: Pod, node: Node) -> Optional[str]:
    for taint in node.spec.taints or []:
        if taint.effect == "PreferNoSchedule":
            continue
        tolerated = False
        for tol in pod.spec.tolerations or []:
            if tol.key and tol.key != taint.key:
                continue
            operator = tol.operator or "Equal"
            if operator == "Exists":
                tolerated = True
            elif operator == "Equal" and tol.value == taint.value:
                tolerated = True
            if tolerated and tol.effect and tol.effect != taint.effect:
                tolerated = False
            if tolerated:
                break
        if not tolerated:
            return f"taint {taint.key}={taint.value}:{taint.effect} not tolerated"
    return None


def _selector_matches(term, pod: Pod, other: Pod) -> bool:
    """Does ``other`` match an affinity term carried by ``pod``?  Terms match
    within the pod's own namespace unless namespaces are given (the repo's
    namespace-scope group semantics)."""
    namespaces = set(getattr(term, "namespaces", None) or ())
    selector = getattr(term, "namespace_selector", None)
    if namespaces:
        if other.metadata.namespace not in namespaces:
            return False
    elif selector is None and other.metadata.namespace != pod.metadata.namespace:
        return False
    elif selector is not None and not selector.matches(other.metadata.labels):
        # namespaceSelector selects namespaces by label; the in-memory stand-in
        # has no namespace objects, so suites label pods with their namespace
        # labels — host-routed anyway (models/snapshot.py classifier)
        return False
    if term.label_selector is None:
        return False
    return term.label_selector.matches(other.metadata.labels)


class PlacementValidator:
    """Validates the bound-pod/node state of an Environment's kube client."""

    def __init__(self, env, pods: Optional[List[Pod]] = None):
        self.env = env
        self.kube = env.kube
        # all bound pods participate in counts; `pods` only scopes which
        # placements get per-pod checks (default: every bound pod)
        self.all_pods = [p for p in self.kube.list_pods() if p.spec.node_name]
        self.scoped = [p for p in (pods or self.all_pods) if p.spec.node_name]
        self.nodes: Dict[str, Node] = {n.name: n for n in self.kube.list_nodes()}
        self.by_node: Dict[str, List[Pod]] = defaultdict(list)
        for p in self.all_pods:
            self.by_node[p.spec.node_name].append(p)
        self._catalog = None

    # -- domain helpers -------------------------------------------------------

    def _node_zone(self, node_name: str) -> Optional[str]:
        node = self.nodes.get(node_name)
        if node is None:
            return None
        return node.metadata.labels.get(ZONE)

    def _catalog_offerings(self):
        """[(zones set, ct set, requirements)] per launchable instance type,
        unioned over provisioners."""
        if self._catalog is None:
            out = []
            for prov in self.kube.list_provisioners():
                for it in self.env.provider.get_instance_types(prov):
                    zones = {o.zone for o in it.offerings if o.available}
                    cts = {o.capacity_type for o in it.offerings if o.available}
                    out.append((zones, cts, it.requirements, prov))
            self._catalog = out
        return self._catalog

    def _reachable_zones(self, pod: Pod) -> set:
        """Zones with at least one available offering compatible with the
        pod's OWN node requirements (the group's reachable universe)."""
        want_zones = self._pod_value_filter(pod, ZONE)
        want_ct = self._pod_value_filter(pod, CT)
        reachable = set()
        for zones, cts, it_reqs, prov in self._catalog_offerings():
            if want_ct is not None and not (cts & want_ct):
                continue
            if not self._pod_arch_os_ok(pod, it_reqs):
                continue
            z = zones if want_zones is None else (zones & want_zones)
            reachable |= z
        return reachable

    def _pod_value_filter(self, pod: Pod, key: str) -> Optional[set]:
        """The pod's required In-values for a key, or None when unconstrained
        (NotIn/other operators return None: they rarely bound the universe)."""
        values = None
        for k, v in (pod.spec.node_selector or {}).items():
            if k == key:
                values = {v}
        affinity = pod.spec.affinity
        if (
            affinity is not None
            and affinity.node_affinity is not None
            and affinity.node_affinity.required is not None
        ):
            for term in affinity.node_affinity.required.node_selector_terms:
                for e in term.match_expressions:
                    if e.key == key and e.operator == OP_IN:
                        values = set(e.values) if values is None else values & set(e.values)
        return values

    def _pod_arch_os_ok(self, pod: Pod, it_reqs) -> bool:
        for key in (labels_api.LABEL_ARCH_STABLE, labels_api.LABEL_OS_STABLE):
            want = self._pod_value_filter(pod, key)
            if want is None:
                continue
            r = it_reqs.get(key)
            if r is not None and not (set(r.values) & want):
                return False
        return True

    def _eligible_hostnames(self, pod: Pod) -> set:
        """Hostname domains admissible for the pod: nodes it tolerates and
        whose labels satisfy its requirements."""
        out = set()
        for node in self.nodes.values():
            if _tolerates(pod, node) is not None:
                continue
            if _pod_node_requirements_ok(pod, node.metadata.labels) is not None:
                continue
            out.add(node.name)
        return out

    # -- checks ---------------------------------------------------------------

    def violations(self) -> List[str]:
        out: List[str] = []
        out += self._check_nodes()
        out += self._check_pod_placements()
        out += self._check_spreads()
        out += self._check_affinity()
        return out

    def _check_nodes(self) -> List[str]:
        out = []
        for name, pods in self.by_node.items():
            node = self.nodes.get(name)
            if node is None:
                out.append(f"node {name}: bound pods but node object missing")
                continue
            alloc = node.status.allocatable or {}
            if alloc:
                total = resources_util.merge(
                    *(resources_util.ceiling(p) for p in pods)
                )
                total = resources_util.merge(total, {"pods": float(len(pods))})
                for res, used in total.items():
                    have = alloc.get(res)
                    if res == "pods" and have is None:
                        continue
                    if have is None or used > have + 1e-6:
                        out.append(
                            f"node {name}: {res} over allocatable "
                            f"({used} > {have})"
                        )
            ports = Counter()
            for p in pods:
                for c in p.spec.containers:
                    for port in c.ports or []:
                        if port.host_port:
                            key = (port.host_ip or "", port.host_port, port.protocol or "TCP")
                            ports[key] += 1
            for key, n in ports.items():
                if n > 1:
                    out.append(f"node {name}: host port {key} bound {n} times")
            out += self._check_volume_limits(name, node, pods)
        return out

    def _check_volume_limits(self, name: str, node: Node, pods: List[Pod]) -> List[str]:
        csinode = self.kube.get_csi_node(name)
        if csinode is None:
            return []
        limits = {
            d.name: d.allocatable_count
            for d in (csinode.drivers or [])
            if d.allocatable_count is not None
        }
        if not limits:
            return []
        attached = defaultdict(set)
        scoped_uids = {p.uid for p in self.scoped}
        scoped_drivers = set()  # drivers this validation's own pods attach
        for p in pods:
            for volume in getattr(p.spec, "volumes", None) or []:
                src = volume.persistent_volume_claim
                if src is None:
                    continue
                pvc = self.kube.get_persistent_volume_claim(
                    p.metadata.namespace, src.claim_name
                )
                if pvc is None:
                    continue
                driver = ""
                if pvc.spec.volume_name:
                    pv = self.kube.get_persistent_volume(pvc.spec.volume_name)
                    if pv is not None:
                        driver = pv.spec.csi_driver
                elif pvc.spec.storage_class_name:
                    sc = self.kube.get_storage_class(pvc.spec.storage_class_name)
                    if sc is not None:
                        driver = sc.provisioner
                if driver:
                    attached[driver].add((p.metadata.namespace, src.claim_name))
                    if p.uid in scoped_uids:
                        scoped_drivers.add(driver)
        out = []
        for driver, claims in attached.items():
            limit = limits.get(driver)
            if limit is not None and len(claims) > limit and driver in scoped_drivers:
                # attach limits constrain placements made AGAINST them: only a
                # violation when a pod under validation contributed — a limit
                # registered after earlier pods were bound doesn't invalidate
                # those earlier placements (volumeusage.go validates per add)
                out.append(
                    f"node {name}: {len(claims)} {driver} attachments > limit {limit}"
                )
        return out

    def _check_pod_placements(self) -> List[str]:
        out = []
        for p in self.scoped:
            node = self.nodes.get(p.spec.node_name)
            if node is None:
                out.append(f"pod {p.metadata.name}: bound to missing node {p.spec.node_name}")
                continue
            err = _tolerates(p, node)
            if err:
                out.append(f"pod {p.metadata.name} on {node.name}: {err}")
            err = _pod_node_requirements_ok(p, node.metadata.labels)
            if err:
                out.append(f"pod {p.metadata.name} on {node.name}: {err}")
        return out

    def _check_spreads(self) -> List[str]:
        out = []
        # group pods by identical constraint identity (key, selector repr)
        for p in self.scoped:
            for c in p.spec.topology_spread_constraints or []:
                if getattr(c, "when_unsatisfiable", "DoNotSchedule") != "DoNotSchedule":
                    continue
                if c.topology_key == ZONE:
                    out += self._check_zone_spread(p, c)
                elif c.topology_key == HOSTNAME:
                    out += self._check_host_spread(p, c)
        return out

    def _zone_counts(self, pod: Pod, constraint) -> Counter:
        counts = Counter()
        for other in self.all_pods:
            if constraint.label_selector is None or not constraint.label_selector.matches(
                other.metadata.labels
            ):
                continue
            if other.metadata.namespace != pod.metadata.namespace:
                continue
            zone = self._node_zone(other.spec.node_name)
            if zone is not None:
                counts[zone] += 1
        return counts

    def _check_zone_spread(self, pod: Pod, constraint) -> List[str]:
        zone = self._node_zone(pod.spec.node_name)
        if zone is None:
            return []  # unresolved zone: registration will commit it
        counts = self._zone_counts(pod, constraint)
        universe = self._reachable_zones(pod)
        universe.add(zone)
        # frozen unreachable domains still bound the fill from below when the
        # global universe is wider (topology_test.go:124-162: their counts
        # participate in the min) — conservatively measure against reachable
        # domains plus any domain that already has members
        universe |= set(counts)
        low = min(counts.get(z, 0) for z in universe)
        skew = counts.get(zone, 0) - low
        if skew > constraint.max_skew:
            return [
                f"pod {pod.metadata.name}: zone spread skew {skew} > "
                f"maxSkew {constraint.max_skew} in {zone} "
                f"(counts {dict(counts)}, universe {sorted(universe)})"
            ]
        return []

    def _check_host_spread(self, pod: Pod, constraint) -> List[str]:
        counts = Counter()
        for other in self.all_pods:
            if constraint.label_selector is None or not constraint.label_selector.matches(
                other.metadata.labels
            ):
                continue
            if other.metadata.namespace != pod.metadata.namespace:
                continue
            counts[other.spec.node_name] += 1
        universe = self._eligible_hostnames(pod)
        universe.add(pod.spec.node_name)
        low = min(counts.get(h, 0) for h in universe)
        skew = counts.get(pod.spec.node_name, 0) - low
        if skew > constraint.max_skew:
            return [
                f"pod {pod.metadata.name}: hostname spread skew {skew} > "
                f"maxSkew {constraint.max_skew} on {pod.spec.node_name} "
                f"(counts {dict(counts)})"
            ]
        return []

    def _check_affinity(self) -> List[str]:
        out = []
        for p in self.scoped:
            affinity = p.spec.affinity
            if affinity is None:
                continue
            if affinity.pod_affinity is not None:
                for term in affinity.pod_affinity.required:
                    out += self._check_affinity_term(p, term)
            if affinity.pod_anti_affinity is not None:
                for term in affinity.pod_anti_affinity.required:
                    out += self._check_anti_term(p, term)
        return out

    def _same_domain(self, term, a_node: str, b_node: str) -> Optional[bool]:
        if term.topology_key == HOSTNAME:
            return a_node == b_node
        if term.topology_key == ZONE:
            za, zb = self._node_zone(a_node), self._node_zone(b_node)
            if za is None or zb is None:
                return None  # unresolved: registration decides
            return za == zb
        return None  # custom keys are host-routed; no label plane to check

    def _check_affinity_term(self, pod: Pod, term) -> List[str]:
        found_unresolved = False
        for other in self.all_pods:
            if not _selector_matches(term, pod, other):
                continue
            same = self._same_domain(term, pod.spec.node_name, other.spec.node_name)
            if same:
                return []
            if same is None:
                found_unresolved = True
        if found_unresolved:
            return []  # a matching pod sits on an unresolved-zone node
        return [
            f"pod {pod.metadata.name}: required pod affinity "
            f"({term.topology_key}) has no matching pod in its domain"
        ]

    def _check_anti_term(self, pod: Pod, term) -> List[str]:
        out = []
        for other in self.all_pods:
            if other.uid == pod.uid:
                continue
            if not _selector_matches(term, pod, other):
                continue
            if self._same_domain(term, pod.spec.node_name, other.spec.node_name):
                out.append(
                    f"pod {pod.metadata.name}: anti-affinity "
                    f"({term.topology_key}) violated by {other.metadata.name} "
                    f"in the same domain"
                )
        return out


def validate_placements(env, pods: Optional[List[Pod]] = None) -> List[str]:
    """All placement-contract violations in the environment's current bound
    state; empty list = valid.  ``pods`` scopes per-pod checks (counts always
    consider every bound pod)."""
    return PlacementValidator(env, pods).violations()


def expect_valid_placements(env, pods: Optional[List[Pod]] = None) -> None:
    violations = validate_placements(env, pods)
    assert not violations, "placement violations:\n  " + "\n  ".join(violations)
