"""Hermetic kube-apiserver speaking the list/watch subset kubeapi/ consumes.

A threaded HTTP server storing raw wire dicts (no object model — the typed
codecs live entirely client-side, so an encode bug can't be masked by a
matching server-side decode).  Implements enough of the real protocol to
exercise every failure path:

  - typed GET/LIST/POST/PUT/DELETE with a global resourceVersion counter and
    per-object optimistic concurrency (PUT with a nonzero resourceVersion
    conflicts on mismatch, rv 0 replaces unconditionally — real apiserver
    semantics for an empty resourceVersion)
  - chunked watch streams (``?watch=true&resourceVersion=N``): JSON event
    lines, periodic BOOKMARK keepalives, resume from any uncompacted rv
  - forced ``410 Gone``: ``compact()`` raises the history floor so resumes
    from older rvs get the ERROR-410 event that drives client relists
  - abrupt connection drops: ``drop_watch_connections()`` resets every live
    stream mid-flight (the reflector's backoff/rewatch path)
  - injected request failures: ``fail_next(n)`` makes the next n plain
    requests return 500 (retry/backoff paths)

Usage:

    server = FakeApiServer().start()
    client = ApiServerClient(server.url, clock)
    ...
    server.stop()
"""

from __future__ import annotations

import json
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlsplit

from karpenter_core_tpu.kubeapi import resources as resources_mod


class _Store:
    """One resource's objects, keyed (namespace, name) / (name,)."""

    def __init__(self, spec) -> None:
        self.spec = spec
        self.objects: Dict[tuple, dict] = {}


class FakeApiServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 bookmark_interval_s: float = 2.0) -> None:
        self._host, self._port = host, port
        self.bookmark_interval_s = bookmark_interval_s
        self.lock = threading.Condition()
        self._rv = 0
        self._compacted_below = 0  # rvs < this are gone from history
        # (rv, spec, event_type, wire) — the watch history
        self._events: List[Tuple[int, object, str, dict]] = []
        self._stores: Dict[tuple, _Store] = {}
        self._fail_next = 0
        self._fail_code = 500
        self._fail_next_watch = 0
        self._fail_watch_code = 500
        self._drop_epoch = 0  # bumped by drop_watch_connections()
        self._active_watches = 0
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.requests_served = 0

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "FakeApiServer":
        server = self

        class Handler(_Handler):
            fake = server

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fakeapiserver", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self.drop_watch_connections()
            self._httpd.shutdown()
            self._httpd.server_close()
            self._thread.join(timeout=5)

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    @property
    def resource_version(self) -> int:
        with self.lock:
            return self._rv

    @property
    def active_watch_count(self) -> int:
        """Live watch streams — tests synchronize on this before injecting
        drops (a drop only resets streams open at the time)."""
        with self.lock:
            return self._active_watches

    def wait_for_watches(self, n: int = 1, timeout_s: float = 5.0) -> bool:
        deadline = time.monotonic() + timeout_s
        with self.lock:
            while self._active_watches < n:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self.lock.wait(timeout=remaining)
            return True

    # -- failure injection ------------------------------------------------------

    def drop_watch_connections(self) -> None:
        """Abruptly terminate every live watch stream (stream-drop path)."""
        with self.lock:
            self._drop_epoch += 1
            self.lock.notify_all()

    def compact(self, below_rv: Optional[int] = None) -> None:
        """Discard watch history below ``below_rv`` (default: everything so
        far) — resumes from older rvs then get the 410 ERROR event."""
        with self.lock:
            floor = self._rv + 1 if below_rv is None else below_rv
            self._compacted_below = max(self._compacted_below, floor)
            self._events = [e for e in self._events if e[0] >= self._compacted_below]

    def fail_next(self, n: int, code: int = 500) -> None:
        with self.lock:
            self._fail_next, self._fail_code = n, code

    def fail_next_watch(self, n: int, code: int = 500) -> None:
        """Fail the next ``n`` watch ESTABLISHMENTS (the HTTP request itself
        returns ``code`` before any stream starts).  Distinct from
        ``drop_watch_connections()``, which only kills streams already
        established — this is the reflector's initial-connect backoff path."""
        with self.lock:
            self._fail_next_watch, self._fail_watch_code = n, code

    # -- store helpers (also the test-side seeding/assertion surface) ----------

    def store_for(self, spec) -> _Store:
        key = (spec.group, spec.plural)
        store = self._stores.get(key)
        if store is None:
            store = self._stores[key] = _Store(spec)
        return store

    def object_count(self, kind: type) -> int:
        spec = resources_mod.spec_for(kind)
        with self.lock:
            return len(self.store_for(spec).objects)

    def wire_objects(self, kind: type) -> List[dict]:
        spec = resources_mod.spec_for(kind)
        with self.lock:
            return [json.loads(json.dumps(o)) for o in self.store_for(spec).objects.values()]

    # -- mutation core (always under self.lock) --------------------------------

    def _key(self, spec, namespace: Optional[str], wire: dict) -> tuple:
        meta = wire.get("metadata", {})
        name = meta.get("name", "")
        if spec.namespaced:
            return (namespace or meta.get("namespace", "default"), name)
        return (name,)

    def _record(self, spec, event_type: str, wire: dict) -> dict:
        self._rv += 1
        wire = dict(wire)
        meta = dict(wire.get("metadata", {}))
        meta["resourceVersion"] = self._rv
        wire["metadata"] = meta
        self._events.append((self._rv, spec, event_type, wire))
        self.lock.notify_all()
        return wire


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    fake: FakeApiServer = None  # bound by FakeApiServer.start

    def log_message(self, fmt, *args):  # quiet
        pass

    # -- plumbing --------------------------------------------------------------

    def _send_json(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, message: str) -> None:
        self._send_json(code, {"kind": "Status", "code": code, "message": message})

    def _read_body(self) -> dict:
        length = int(self.headers.get("Content-Length", 0) or 0)
        data = self.rfile.read(length) if length else b"{}"
        return json.loads(data or b"{}")

    def _route(self):
        parts = urlsplit(self.path)
        spec, namespace, name = resources_mod.parse_path(parts.path)
        query = parse_qs(parts.query)
        return spec, namespace, name, query

    def _maybe_fail(self) -> bool:
        fake = self.fake
        with fake.lock:
            fake.requests_served += 1
            if fake._fail_next > 0:
                fake._fail_next -= 1
                code = fake._fail_code
            else:
                return False
        self._error(code, "injected failure")
        return True

    # -- verbs -----------------------------------------------------------------

    def do_GET(self):
        try:
            spec, namespace, name, query = self._route()
        except KeyError:
            return self._error(404, f"unknown route {self.path}")
        if query.get("watch", ["false"])[0] == "true":
            return self._watch(spec, query)
        if self._maybe_fail():
            return
        fake = self.fake
        with fake.lock:
            store = fake.store_for(spec)
            if name is None:
                items = [
                    obj for key, obj in store.objects.items()
                    if namespace is None or not spec.namespaced or key[0] == namespace
                ]
                return self._send_json(200, {
                    "kind": f"{spec.kind_name}List",
                    "apiVersion": spec.api_version,
                    "metadata": {"resourceVersion": fake._rv},
                    "items": items,
                })
            key = (namespace or "default", name) if spec.namespaced else (name,)
            obj = store.objects.get(key)
        if obj is None:
            return self._error(404, f"{spec.kind_name} {name} not found")
        self._send_json(200, obj)

    def do_POST(self):
        if self._maybe_fail():
            return
        try:
            spec, namespace, name, _ = self._route()
        except KeyError:
            return self._error(404, f"unknown route {self.path}")
        wire = self._read_body()
        fake = self.fake
        with fake.lock:
            store = fake.store_for(spec)
            key = fake._key(spec, namespace, wire)
            if key in store.objects:
                return self._error(409, f"{spec.kind_name} {key} already exists")
            if not wire.get("metadata", {}).get("creationTimestamp"):
                wire.setdefault("metadata", {})["creationTimestamp"] = time.time()
            stored = fake._record(spec, "ADDED", wire)
            store.objects[key] = stored
        self._send_json(201, stored)

    def do_PUT(self):
        if self._maybe_fail():
            return
        try:
            spec, namespace, name, _ = self._route()
        except KeyError:
            return self._error(404, f"unknown route {self.path}")
        wire = self._read_body()
        fake = self.fake
        with fake.lock:
            store = fake.store_for(spec)
            key = fake._key(spec, namespace, wire)
            stored = store.objects.get(key)
            if stored is None:
                return self._error(404, f"{spec.kind_name} {key} not found")
            expected = int(wire.get("metadata", {}).get("resourceVersion", 0) or 0)
            current = int(stored.get("metadata", {}).get("resourceVersion", 0) or 0)
            if expected and expected != current:
                return self._error(
                    409,
                    f"{spec.kind_name} {key} resourceVersion {current} != {expected}",
                )
            updated = fake._record(spec, "MODIFIED", wire)
            store.objects[key] = updated
        self._send_json(200, updated)

    def do_DELETE(self):
        if self._maybe_fail():
            return
        try:
            spec, namespace, name, _ = self._route()
        except KeyError:
            return self._error(404, f"unknown route {self.path}")
        fake = self.fake
        with fake.lock:
            store = fake.store_for(spec)
            key = (namespace or "default", name) if spec.namespaced else (name,)
            stored = store.objects.pop(key, None)
            if stored is None:
                return self._error(404, f"{spec.kind_name} {name} not found")
            gone = fake._record(spec, "DELETED", stored)
        self._send_json(200, gone)

    # -- the watch stream ------------------------------------------------------

    def _chunk(self, data: bytes) -> None:
        self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()

    def _send_event(self, event: dict) -> None:
        self._chunk(json.dumps(event).encode() + b"\n")

    def _watch(self, spec, query) -> None:
        fake = self.fake
        since_rv = int(query.get("resourceVersion", ["0"])[0] or 0)
        with fake.lock:
            if fake._fail_next_watch > 0:
                fake._fail_next_watch -= 1
                code = fake._fail_watch_code
                fail_establishment = True
            else:
                fail_establishment = False
        if fail_establishment:
            return self._error(code, "injected watch establishment failure")
        with fake.lock:
            if since_rv and since_rv + 1 < fake._compacted_below:
                # history below the floor is gone: the resume point is stale
                gone = True
            else:
                gone = False
            epoch = fake._drop_epoch
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        if gone:
            self._send_event({
                "type": "ERROR",
                "object": {"kind": "Status", "code": 410, "message": "too old resource version"},
            })
            self._chunk(b"")  # terminating chunk
            return
        last_sent = since_rv
        last_bookmark = time.monotonic()
        with fake.lock:
            fake._active_watches += 1
            fake.lock.notify_all()
        try:
            while True:
                with fake.lock:
                    if fake._drop_epoch != epoch:
                        # abrupt drop: kill the socket without a clean close
                        raise ConnectionAbortedError("injected watch drop")
                    pending = [
                        (rv, etype, wire)
                        for rv, espec, etype, wire in fake._events
                        if rv > last_sent and espec is spec
                    ]
                    if not pending:
                        fake.lock.wait(timeout=0.1)
                        pending = [
                            (rv, etype, wire)
                            for rv, espec, etype, wire in fake._events
                            if rv > last_sent and espec is spec
                        ]
                    current_rv = fake._rv
                for rv, etype, wire in pending:
                    self._send_event({"type": etype, "object": wire})
                    last_sent = rv
                now = time.monotonic()
                if now - last_bookmark >= fake.bookmark_interval_s:
                    self._send_event({
                        "type": "BOOKMARK",
                        "object": {"metadata": {"resourceVersion": current_rv}},
                    })
                    last_sent = max(last_sent, current_rv)
                    last_bookmark = now
        except ConnectionAbortedError:
            # terminate mid-stream with no terminating chunk: the client sees
            # a truncated chunked body (IncompleteRead/EOF), i.e. a real drop
            try:
                self.connection.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            self.close_connection = True
        except (BrokenPipeError, ConnectionResetError, OSError):
            self.close_connection = True
        finally:
            with fake.lock:
                fake._active_watches -= 1
                fake.lock.notify_all()
