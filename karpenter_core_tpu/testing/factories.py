"""Test object factories with option merging.

Mirrors the role of /root/reference/pkg/test/{pods.go,nodes.go,provisioner.go}:
compact constructors for pods/nodes/provisioners used across the suite and the
benchmarks.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import (
    Affinity,
    Container,
    ContainerPort,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodCondition,
    PodSpec,
    PodStatus,
    PreferredSchedulingTerm,
    ResourceRequirements,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)
from karpenter_core_tpu.apis.v1alpha5 import (
    Consolidation,
    Limits,
    Provisioner,
    ProvisionerSpec,
)
from karpenter_core_tpu.utils import resources as resources_util

_names = itertools.count(1)


def _name(prefix: str) -> str:
    return f"{prefix}-{next(_names):05d}"


def make_pod(
    name: Optional[str] = None,
    namespace: str = "default",
    labels: Optional[Dict[str, str]] = None,
    annotations: Optional[Dict[str, str]] = None,
    requests: Optional[Dict[str, "str | float"]] = None,
    limits: Optional[Dict[str, "str | float"]] = None,
    node_selector: Optional[Dict[str, str]] = None,
    node_name: str = "",
    node_requirements: Optional[List[NodeSelectorRequirement]] = None,
    node_preferences: Optional[List[NodeSelectorRequirement]] = None,
    tolerations: Optional[List[Toleration]] = None,
    topology_spread: Optional[List[TopologySpreadConstraint]] = None,
    pod_affinity: Optional[List[PodAffinityTerm]] = None,
    pod_anti_affinity: Optional[List[PodAffinityTerm]] = None,
    pod_affinity_preferred: Optional[List[WeightedPodAffinityTerm]] = None,
    pod_anti_affinity_preferred: Optional[List[WeightedPodAffinityTerm]] = None,
    host_ports: Optional[List[int]] = None,
    pvcs: Optional[List[str]] = None,
    owner_kind: str = "",
    priority: Optional[int] = None,
    phase: str = "Pending",
    unschedulable: bool = True,
    creation_timestamp: float = 0.0,
    deletion_cost: Optional[float] = None,
) -> Pod:
    """A pending, unschedulable-marked pod by default."""
    meta = ObjectMeta(
        name=name or _name("pod"),
        namespace=namespace,
        labels=dict(labels or {}),
        annotations=dict(annotations or {}),
        creation_timestamp=creation_timestamp,
    )
    if deletion_cost is not None:
        meta.annotations["controller.kubernetes.io/pod-deletion-cost"] = str(deletion_cost)
    if owner_kind:
        meta.owner_references.append(OwnerReference(kind=owner_kind, name=f"owner-{meta.name}"))

    container = Container(
        resources=ResourceRequirements(
            requests=resources_util.parse_resource_list(requests or {}),
            limits=resources_util.parse_resource_list(limits or {}),
        ),
        ports=[ContainerPort(host_port=p) for p in (host_ports or [])],
    )

    affinity = None
    node_affinity = None
    if node_requirements or node_preferences:
        node_affinity = NodeAffinity(
            required=(
                NodeSelector(
                    node_selector_terms=[NodeSelectorTerm(match_expressions=list(node_requirements))]
                )
                if node_requirements
                else None
            ),
            preferred=(
                [
                    PreferredSchedulingTerm(
                        weight=1, preference=NodeSelectorTerm(match_expressions=list(node_preferences))
                    )
                ]
                if node_preferences
                else []
            ),
        )
    if node_affinity or pod_affinity or pod_anti_affinity or pod_affinity_preferred or pod_anti_affinity_preferred:
        affinity = Affinity(
            node_affinity=node_affinity,
            pod_affinity=(
                PodAffinity(
                    required=list(pod_affinity or []),
                    preferred=list(pod_affinity_preferred or []),
                )
                if pod_affinity or pod_affinity_preferred
                else None
            ),
            pod_anti_affinity=(
                PodAntiAffinity(
                    required=list(pod_anti_affinity or []),
                    preferred=list(pod_anti_affinity_preferred or []),
                )
                if pod_anti_affinity or pod_anti_affinity_preferred
                else None
            ),
        )

    status = PodStatus(phase=phase)
    if unschedulable and not node_name:
        status.conditions.append(
            PodCondition(type="PodScheduled", status="False", reason="Unschedulable")
        )

    from karpenter_core_tpu.apis.objects import (
        PersistentVolumeClaimVolumeSource,
        Volume,
    )

    return Pod(
        metadata=meta,
        spec=PodSpec(
            node_selector=dict(node_selector or {}),
            node_name=node_name,
            affinity=affinity,
            tolerations=list(tolerations or []),
            containers=[container],
            topology_spread_constraints=list(topology_spread or []),
            priority=priority,
            volumes=[
                Volume(
                    name=f"vol-{claim}",
                    persistent_volume_claim=PersistentVolumeClaimVolumeSource(claim_name=claim),
                )
                for claim in (pvcs or [])
            ],
        ),
        status=status,
    )


def make_pods(count: int, **kwargs) -> List[Pod]:
    return [make_pod(**kwargs) for _ in range(count)]


def make_daemonset_pod(**kwargs) -> Pod:
    kwargs.setdefault("owner_kind", "DaemonSet")
    return make_pod(**kwargs)


def make_node(
    name: Optional[str] = None,
    labels: Optional[Dict[str, str]] = None,
    annotations: Optional[Dict[str, str]] = None,
    taints: Optional[List[Taint]] = None,
    allocatable: Optional[Dict[str, "str | float"]] = None,
    capacity: Optional[Dict[str, "str | float"]] = None,
    provider_id: str = "",
    ready: bool = True,
    unschedulable: bool = False,
    finalizers: Optional[List[str]] = None,
    creation_timestamp: float = 0.0,
) -> Node:
    node_name = name or _name("node")
    labels = dict(labels or {})
    labels.setdefault(labels_api.LABEL_HOSTNAME, node_name)
    from karpenter_core_tpu.apis.objects import NodeCondition

    return Node(
        metadata=ObjectMeta(
            name=node_name,
            labels=labels,
            annotations=dict(annotations or {}),
            finalizers=list(finalizers or []),
            creation_timestamp=creation_timestamp,
        ),
        spec=NodeSpec(
            taints=list(taints or []),
            unschedulable=unschedulable,
            provider_id=provider_id or f"fake://{node_name}",
        ),
        status=NodeStatus(
            capacity=resources_util.parse_resource_list(
                capacity or allocatable or {"cpu": 16, "memory": "128Gi", "pods": 110}
            ),
            allocatable=resources_util.parse_resource_list(
                allocatable or capacity or {"cpu": 16, "memory": "128Gi", "pods": 110}
            ),
            conditions=[NodeCondition(type="Ready", status="True" if ready else "False")],
        ),
    )


def make_provisioner(
    name: str = "default",
    requirements: Optional[List[NodeSelectorRequirement]] = None,
    labels: Optional[Dict[str, str]] = None,
    taints: Optional[List[Taint]] = None,
    startup_taints: Optional[List[Taint]] = None,
    limits: Optional[Dict[str, "str | float"]] = None,
    weight: Optional[int] = None,
    ttl_seconds_after_empty: Optional[int] = None,
    ttl_seconds_until_expired: Optional[int] = None,
    consolidation_enabled: Optional[bool] = None,
    policy: Optional[Dict] = None,
) -> Provisioner:
    return Provisioner(
        metadata=ObjectMeta(name=name, namespace=""),
        spec=ProvisionerSpec(
            requirements=list(requirements or []),
            labels=dict(labels or {}),
            taints=list(taints or []),
            startup_taints=list(startup_taints or []),
            limits=Limits(resources=resources_util.parse_resource_list(limits)) if limits else None,
            weight=weight,
            ttl_seconds_after_empty=ttl_seconds_after_empty,
            ttl_seconds_until_expired=ttl_seconds_until_expired,
            consolidation=(
                Consolidation(enabled=consolidation_enabled)
                if consolidation_enabled is not None
                else None
            ),
            policy=dict(policy) if policy else None,
        ),
    )
