from karpenter_core_tpu.testing.factories import (
    make_node,
    make_pod,
    make_pods,
    make_provisioner,
    make_daemonset_pod,
)

__all__ = ["make_node", "make_pod", "make_pods", "make_provisioner", "make_daemonset_pod"]
