"""Objective planes: the dense per-(instance type, zone, capacity type)
economics the batched objective kernel scores over.

Three planes ride every encoded snapshot (models.snapshot.EncodedSnapshot
``pol_price`` / ``pol_risk`` / ``pol_throughput``):

  price        f32[I, Z, CT] — the offering price sheet, +inf where no
               offering exists (mirrors ``it_price``; kept as its own plane
               so the ``policy`` digest group versions the price sheet
               independently of the feasibility planes)
  risk         f32[I, Z, CT] — interruption-risk prior in [0, 1].  Seeded
               from two places: per-offering ``interruption_rate`` (the
               cloud's own spot-reclaim signal, FakeCloudProvider.
               set_interruption_rate in tests) and the chaos plane's
               first-class capacity knobs — an instance type with pending
               ``capacity_errors`` is observably failing creates right now,
               which is the strongest interruption prior there is
  throughput   f32[I] — heterogeneity weight per instance type
               (PolicyConfig.throughput; Gavel's throughput matrices reduce
               to this per-type vector when the pod side is a single job
               class per solve)

``models.store.snapshot_digests`` digests the three planes as the ``policy``
group; ``policy_input_digest`` is the NO-ENCODE twin the incremental session
folds into its supply digest, so a price or risk update escalates the next
solve to full without anyone encoding anything (docs/INCREMENTAL.md).
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, NamedTuple, Optional

import numpy as np

# a type that is actively failing creates with InsufficientCapacityError is
# treated as (nearly) certain to interrupt — the chaos capacity knob is the
# failure-side twin of the spot-reclaim prior
CAPACITY_ERROR_RISK = 0.9


class ObjectivePlanes(NamedTuple):
    price: np.ndarray  # f32[I, Z, CT] (+inf where no offering)
    risk: np.ndarray  # f32[I, Z, CT] interruption-risk prior in [0, 1]
    throughput: np.ndarray  # f32[I]


def build_planes(
    it_names: List[str],
    zones: List[str],
    capacity_types: List[str],
    it_by_name: Dict[str, object],
    config=None,
    provider=None,
) -> ObjectivePlanes:
    """Build the objective planes on the snapshot's axes.

    ``it_by_name`` maps instance-type name -> cloudprovider.InstanceType;
    ``provider`` (optional) contributes the chaos-side capacity-error prior
    (any object with a ``capacity_errors`` dict — FakeCloudProvider's
    first-class failure knob)."""
    i_n, z_n, ct_n = len(it_names), len(zones), len(capacity_types)
    price = np.full((i_n, z_n, ct_n), np.inf, dtype=np.float32)
    risk = np.zeros((i_n, z_n, ct_n), dtype=np.float32)
    throughput = np.zeros(i_n, dtype=np.float32)
    zone_idx = {z: i for i, z in enumerate(zones)}
    ct_idx = {c: i for i, c in enumerate(capacity_types)}
    capacity_errors = getattr(provider, "capacity_errors", None) or {}
    for i, name in enumerate(it_names):
        it = it_by_name.get(name)
        if it is None:
            continue
        if config is not None:
            throughput[i] = config.throughput_of(name)
        pending_ice = capacity_errors.get(name, 0) > 0
        for off in it.offerings:
            if not off.available:
                continue
            z = zone_idx.get(off.zone)
            c = ct_idx.get(off.capacity_type)
            if z is None or c is None:
                continue
            price[i, z, c] = off.price
            rate = float(getattr(off, "interruption_rate", 0.0) or 0.0)
            if pending_ice:
                rate = max(rate, CAPACITY_ERROR_RISK)
            risk[i, z, c] = min(max(rate, 0.0), 1.0)
    return ObjectivePlanes(price=price, risk=risk, throughput=throughput)


def attach_planes(snapshot, it_by_name, config=None, provider=None) -> None:
    """Stamp the objective planes onto an encoded snapshot (the ``policy``
    digest group models.store versions).  Cheap — one pass over the catalog's
    offerings — so it runs on every encode whether or not the objective is
    enabled: the planes must exist for the digest to detect a price-sheet
    change even while policy is off."""
    planes = build_planes(
        snapshot.it_names, snapshot.zones, snapshot.capacity_types,
        it_by_name, config=config, provider=provider,
    )
    snapshot.pol_price = planes.price
    snapshot.pol_risk = planes.risk
    snapshot.pol_throughput = planes.throughput


def planes_of(snapshot) -> Optional[ObjectivePlanes]:
    price = getattr(snapshot, "pol_price", None)
    if price is None:
        return None
    return ObjectivePlanes(
        price=price,
        risk=snapshot.pol_risk,
        throughput=snapshot.pol_throughput,
    )


def policy_input_digest(instance_types, config=None, provider=None) -> str:
    """Content digest of the policy-relevant solve INPUTS — offering prices,
    interruption-rate priors, the config knobs, and the provider's live
    capacity-error state — computed without encoding anything.  The
    incremental session appends this to its supply digest: a ``set_price``
    on the provider (or a risk/weight change, or a type starting/stopping
    to ICE) flips it and the fallback policy escalates to a full solve with
    reason ``supply-changed`` (the regression tests/test_policy.py pins).

    ``instance_types`` is the solver's provisioner-name -> [InstanceType]
    map (or any iterable of lists).  ``provider`` contributes the
    capacity-error prior the risk planes fold in (``build_planes``): only
    the BINARY pending-or-not set per type is hashed — exactly what the
    plane encodes — so an ICE count ticking 3→2 does not escalate, while
    the 0↔pending transitions (risk appearing/clearing) do."""
    h = hashlib.sha256()
    capacity_errors = getattr(provider, "capacity_errors", None) or {}
    h.update(repr(sorted(
        name for name, count in capacity_errors.items() if count > 0
    )).encode())
    if isinstance(instance_types, dict):
        groups = [instance_types[k] for k in sorted(instance_types)]
    else:
        groups = [list(instance_types)]
    for its in groups:
        for it in its:
            h.update(it.name.encode())
            h.update(repr(sorted(
                (
                    o.zone, o.capacity_type, o.available, o.price,
                    float(getattr(o, "interruption_rate", 0.0) or 0.0),
                )
                for o in it.offerings
            )).encode())
        h.update(b"\x1e")
    if config is not None:
        h.update(config.digest().encode())
    return h.hexdigest()
