"""PolicyConfig: the policy-objective knob surface.

One frozen config object flows from the operator's environment and the
Provisioner CRD's ``spec.policy`` block into every consumer — the snapshot
planes (policy.planes), the objective kernel (ops.objective), policy-aware
consolidation (solver.consolidation), and the counter-proposal engine.

Defaults are today's behavior EXACTLY: ``enabled=False`` means no objective
selection, no consolidation re-scoring, no counter-proposals — the solve
pipeline is bit-identical to a build without this package.  ``KC_POLICY=0``
is the process-wide kill switch: it forces ``enabled=False`` even when a
Provisioner's spec asks for the objective (triage lever, docs/POLICY.md).
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass, replace
from typing import Optional, Tuple


def policy_enabled() -> bool:
    """The process-wide kill switch: KC_POLICY=0 disables the objective stage
    everywhere regardless of per-provisioner spec (mirrors
    KC_SOLVER_INCREMENTAL's contract)."""
    return os.environ.get("KC_POLICY", "1") != "0"


@dataclass(frozen=True)
class PolicyConfig:
    """Objective weights + enable flags.

    The objective score of one (instance type i, zone z, capacity type ct)
    offering cell is

        score = cost_weight * price[i,z,ct] * (1 + risk_aversion * risk[i,z,ct])
                - throughput_weight * throughput[i]

    minimized over the node's feasible cells (ops.objective).  With the
    default weights the score IS the offering price, so selection is exactly
    ``Offerings.cheapest()`` — the host-oracle parity tier-1 pins.
    """

    enabled: bool = False
    cost_weight: float = 1.0
    # heterogeneity (Gavel-style): per-instance-type throughput weights make
    # a pricier type win when its throughput more than pays for the delta
    throughput_weight: float = 0.0
    # risk aversion scales the interruption-risk prior into an expected-cost
    # premium: 0 = price-only, 1 = a certain interruption doubles the price
    risk_aversion: float = 0.0
    # prefer spot over on-demand on exact score ties (the host convention:
    # worst_launch_price consults spot before on-demand, and consolidation
    # pins spot when both remain allowed)
    spot_preference: bool = True
    # counter-proposals: emit ShapeHint events for pods a bounded resize
    # would make schedulable on a strictly cheaper fleet
    counter_proposals: bool = False
    max_resize_fraction: float = 0.5
    # per-instance-type throughput weights, as a hashable sorted tuple of
    # (instance-type name, weight); types absent default to 0.0
    throughput: Tuple[Tuple[str, float], ...] = ()
    # solver-family routing (solver/modes.py): "" = defer to KC_SOLVER_MODE
    # env / scan; "scan" | "relax" | "auto" pins the family for this config
    # (spec wins over env).  Deliberately OUTSIDE digest(): the mode changes
    # which program runs, not the objective inputs — the incremental session
    # escalates on a flip via its own "mode-changed" reason instead.
    solver_mode: str = ""

    # -- construction ----------------------------------------------------------

    @classmethod
    def from_env(cls) -> "PolicyConfig":
        def _f(name: str, default: float) -> float:
            try:
                return float(os.environ.get(name, default))
            except ValueError:
                return default

        def _b(name: str, default: bool) -> bool:
            raw = os.environ.get(name)
            if raw is None:
                return default
            return raw not in ("0", "false", "False", "")

        return cls(
            enabled=_b("KC_POLICY_ENABLED", False) and policy_enabled(),
            cost_weight=_f("KC_POLICY_COST_WEIGHT", 1.0),
            throughput_weight=_f("KC_POLICY_THROUGHPUT_WEIGHT", 0.0),
            risk_aversion=_f("KC_POLICY_RISK_AVERSION", 0.0),
            spot_preference=_b("KC_POLICY_SPOT_PREFERENCE", True),
            counter_proposals=_b("KC_POLICY_COUNTER_PROPOSALS", False),
            max_resize_fraction=_f("KC_POLICY_MAX_RESIZE_FRACTION", 0.5),
            solver_mode=os.environ.get("KC_SOLVER_MODE", ""),
        )

    def merged(self, spec: Optional[dict]) -> "PolicyConfig":
        """Overlay a Provisioner ``spec.policy`` dict (wire-cased keys).
        Unknown keys are ignored; the KC_POLICY kill switch still wins."""
        if not spec:
            return self
        fields = {}
        mapping = {
            "enabled": ("enabled", bool),
            "costWeight": ("cost_weight", float),
            "throughputWeight": ("throughput_weight", float),
            "riskAversion": ("risk_aversion", float),
            "spotPreference": ("spot_preference", bool),
            "counterProposals": ("counter_proposals", bool),
            "maxResizeFraction": ("max_resize_fraction", float),
            "solverMode": ("solver_mode", str),
        }
        for wire_key, (attr, cast) in mapping.items():
            if wire_key in spec:
                try:
                    fields[attr] = cast(spec[wire_key])
                except (TypeError, ValueError):
                    continue
        if isinstance(spec.get("throughput"), dict):
            fields["throughput"] = tuple(
                sorted((str(k), float(v)) for k, v in spec["throughput"].items())
            )
        out = replace(self, **fields)
        if out.enabled and not policy_enabled():
            out = replace(out, enabled=False)
        return out

    @classmethod
    def resolve(cls, provisioners=None) -> "PolicyConfig":
        """The config one reconcile runs under: env defaults overlaid by the
        highest-weight provisioner that declares a ``spec.policy`` block
        (one fleet, one objective — mirrors how template preference order is
        already weight-driven)."""
        from karpenter_core_tpu.apis.v1alpha5 import order_by_weight

        config = cls.from_env()
        for provisioner in order_by_weight(list(provisioners or [])):
            spec = getattr(provisioner.spec, "policy", None)
            if spec:
                return config.merged(spec)
        return config

    # -- wire ------------------------------------------------------------------

    def to_wire(self) -> dict:
        """The ``spec.policy``-shaped dict the snapshot channel ships so a
        remote solve runs under THIS controller replica's resolved config
        (service.snapshot_channel).  Wire-cased keys, same schema as the
        Provisioner CRD block — one vocabulary on both sides."""
        return {
            "enabled": bool(self.enabled),
            "costWeight": float(self.cost_weight),
            "throughputWeight": float(self.throughput_weight),
            "riskAversion": float(self.risk_aversion),
            "spotPreference": bool(self.spot_preference),
            "counterProposals": bool(self.counter_proposals),
            "maxResizeFraction": float(self.max_resize_fraction),
            "throughput": {name: weight for name, weight in self.throughput},
            "solverMode": str(self.solver_mode),
        }

    @classmethod
    def from_wire(cls, spec: Optional[dict]) -> Optional["PolicyConfig"]:
        """Decode a request's ``policy`` entry (``to_wire`` output / a raw
        spec.policy dict).  None/empty → None, the pre-policy pipeline — the
        wire default, so old clients keep exactly the old behavior.  The
        serving side's KC_POLICY=0 kill switch still wins (``merged``), so a
        solver operator can disable the stage fleet-wide regardless of what
        the controller replicas ask for."""
        if not spec:
            return None
        return cls().merged(dict(spec))

    # -- identity --------------------------------------------------------------

    def throughput_of(self, name: str) -> float:
        for it_name, weight in self.throughput:
            if it_name == name:
                return weight
        return 0.0

    def digest(self) -> str:
        """Stable content digest of every objective-relevant knob — part of
        the incremental session's policy input digest, so flipping a weight
        escalates the next solve to full exactly like a price change."""
        h = hashlib.sha256()
        h.update(repr((
            self.enabled, self.cost_weight, self.throughput_weight,
            self.risk_aversion, self.spot_preference, self.throughput,
        )).encode())
        return h.hexdigest()
