"""Counter-proposals: the solver proposing pod shapes instead of only
accepting or rejecting them ("Toward Co-adapting ML Job Shape and Cluster
Topology", PAPERS.md).

When a pod is unschedulable as specified — or schedulable only onto an
expensive shape — but a BOUNDED resize (shrink every over-subscribed resource
by at most ``PolicyConfig.max_resize_fraction``) would fit a strictly cheaper
fleet, the provisioning controller emits a ``ShapeHint`` event on the pod
(events.shape_hint) and bumps ``karpenter_policy_counterproposals_total``.
The hint is advisory: nothing mutates the pod — the workload owner (or an
admission webhook acting for them) decides whether the trade is acceptable.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

from karpenter_core_tpu.utils import resources as resources_util


@dataclass(frozen=True)
class ShapeHint:
    """One concrete counter-proposal for an unschedulable (or expensively
    schedulable) pod shape."""

    suggested_requests: Dict[str, float]
    instance_type: str
    price: float  # cheapest available offering of the fitting type
    shrink_fraction: float  # largest per-resource shrink the proposal needs
    # price of the cheapest shape that fits the pod AS SPECIFIED
    # (inf = unschedulable without the resize)
    current_price: float

    def message(self) -> str:
        shaped = ", ".join(
            f"{name}={resources_util.format_quantity(value)}"
            for name, value in sorted(self.suggested_requests.items())
        )
        if self.current_price == float("inf"):
            verdict = "pod is unschedulable as specified"
        else:
            verdict = (
                f"cheapest fit as specified costs {self.current_price:.4f}"
            )
        return (
            f"{verdict}; shrinking requests by "
            f"{self.shrink_fraction:.0%} (to {shaped}) fits "
            f"{self.instance_type} at {self.price:.4f}"
        )


def _cheapest_offering_price(it) -> float:
    cheapest = it.offerings.available().cheapest()
    return cheapest.price if cheapest is not None else float("inf")


def propose_resize(
    requests: resources_util.ResourceList,
    instance_types: List,
    config,
) -> Optional[ShapeHint]:
    """A ShapeHint when a bounded shrink of ``requests`` fits a strictly
    cheaper fleet, else None.

    For each catalog type the needed shrink is the largest per-resource
    overshoot fraction ``(request - allocatable) / request`` over the
    requested resources; a type needing more than ``max_resize_fraction`` is
    out of bounds.  Among in-bounds types the cheapest available offering
    wins; the proposal stands only when that price strictly beats the
    cheapest fit of the unmodified shape (inf when nothing fits — the
    "unschedulable but a resize would fit" case the ISSUE names)."""
    if not requests or config is None or not config.counter_proposals:
        return None
    current_price = float("inf")
    best: Optional[ShapeHint] = None
    for it in instance_types:
        alloc = it.allocatable()
        shrink = 0.0
        fits_now = True
        suggested: Dict[str, float] = {}
        for name, req in requests.items():
            if req <= 0:
                continue
            have = alloc.get(name, 0.0)
            if have < req:
                fits_now = False
                shrink = max(shrink, (req - have) / req)
                suggested[name] = have
            else:
                suggested[name] = req
        price = _cheapest_offering_price(it)
        if price == float("inf"):
            continue
        if fits_now:
            current_price = min(current_price, price)
            continue
        if shrink > config.max_resize_fraction or any(
            v <= 0 for v in suggested.values()
        ):
            continue
        if best is None or price < best.price:
            best = ShapeHint(
                suggested_requests=suggested,
                instance_type=it.name,
                price=price,
                shrink_fraction=shrink,
                current_price=float("inf"),  # filled below
            )
    if best is None or best.price >= current_price:
        return None
    return ShapeHint(
        suggested_requests=best.suggested_requests,
        instance_type=best.instance_type,
        price=best.price,
        shrink_fraction=best.shrink_fraction,
        current_price=current_price,
    )
