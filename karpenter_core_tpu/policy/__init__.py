"""Policy-objective subsystem: cost-, heterogeneity-, and interruption-aware
batched solves (docs/POLICY.md).

The kernel answers feasibility ("which node fits"); this package answers the
economic question on top of it ("which *fleet* is cheapest/fastest/safest"):

  - ``config``: the ``PolicyConfig`` knob surface (weights, risk aversion,
    enable flags) resolved from env + the Provisioner's ``spec.policy`` block;
    default == today's behavior exactly, ``KC_POLICY=0`` is the kill switch.
  - ``planes``: the dense objective planes (price / interruption-risk /
    throughput over the instance-type × zone × capacity-type axes) attached
    to every encoded snapshot and digested as the ``policy`` plane group in
    ``models.store`` so a price-sheet change invalidates the incremental
    warm-start lineage like any other supply change.
  - ``counterproposal``: the ShapeHint engine — when a pod is unschedulable
    (or schedulable only expensively) and a bounded resize would fit a
    strictly cheaper fleet, propose the shape instead of only rejecting it.

The batched scoring/argmin kernel itself lives in ``ops.objective`` (it is
device code, next to the solve kernel it runs after).
"""

from karpenter_core_tpu.policy.config import PolicyConfig, policy_enabled
from karpenter_core_tpu.policy.counterproposal import ShapeHint, propose_resize
from karpenter_core_tpu.policy.planes import (
    ObjectivePlanes,
    attach_planes,
    build_planes,
    policy_input_digest,
)

__all__ = [
    "PolicyConfig",
    "policy_enabled",
    "ObjectivePlanes",
    "attach_planes",
    "build_planes",
    "policy_input_digest",
    "ShapeHint",
    "propose_resize",
]
