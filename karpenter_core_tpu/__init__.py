"""karpenter_core_tpu — a TPU-native node-provisioning autoscaler framework.

A ground-up re-design of the capabilities of aws/karpenter-core (reference at
/root/reference) around one idea: *scheduling and consolidation are batch tensor
programs over a cluster snapshot*.  The event-driven Go controller mesh of the
reference becomes a thin async reconciler shell (``controllers/``, ``operator/``)
around a pure, jittable solver core (``ops/``, ``models/``) that runs on TPU via
jit/shard_map, sharded over a ``jax.sharding.Mesh`` for multi-chip scale
(``parallel/``).

Layer map (mirrors SURVEY.md §1):
  - ``apis``            — API types: Provisioner, Machine, label taxonomy, settings.
  - ``scheduling``      — host-side constraint algebra (Requirements, Taints,
                          HostPortUsage, VolumeUsage); the exact-semantics oracle.
  - ``models``          — dense tensor encodings of cluster snapshots (pods, nodes,
                          instance types, offerings) and the value-vocabulary codec.
  - ``ops``             — JAX kernels: requirement-mask algebra, bin-pack solve,
                          topology reductions, consolidation search.
  - ``parallel``        — device-mesh sharding of the solve (pod axis DP,
                          candidate-subset vmap, Monte-Carlo replicas).
  - ``solver``          — the Scheduler: host relaxation/queue loop around the
                          jitted kernels; Node/ExistingNode/MachineTemplate.
  - ``cloudprovider``   — vendor SPI + fake provider + instance-type catalogs.
  - ``state``           — in-memory cluster state cache (the solve's input snapshot).
  - ``controllers``     — provisioning, deprovisioning, node lifecycle, termination,
                          inflight checks, counter, metrics scrapers.
  - ``operator``        — operator runtime: options, settings, controller framework.
  - ``events``/``metrics``/``utils`` — cross-cutting.
"""

__version__ = "0.1.0"
