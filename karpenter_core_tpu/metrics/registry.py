"""Prometheus-style metrics registry.

Mirror of the role of /root/reference/pkg/metrics/constants.go:41-66 and the
controller-runtime registry: counters/gauges/histograms/summaries with label
sets, a shared default registry, DurationBuckets, and the ``measure`` closure
timer used around scheduling and deprovisioning evaluations.  Exposition is
text-format compatible (``Registry.render``) for scraping.
"""

from __future__ import annotations

import bisect
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

NAMESPACE = "karpenter"

# metrics/constants.go:46-55 DurationBuckets
DURATION_BUCKETS = [
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 120, 180, 300,
]
# SummaryObjectives p0/p50/p90/p99 (constants.go:57-59)
SUMMARY_OBJECTIVES = [0.0, 0.5, 0.9, 0.99]


class _Metric:
    kind = ""

    def __init__(self, name: str, help_: str, label_names: Iterable[str] = ()) -> None:
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}

    def labels(self, *values: str, **kwargs: str):
        if kwargs:
            values = tuple(kwargs.get(name, "") for name in self.label_names)
        if len(values) != len(self.label_names):
            raise ValueError(f"{self.name}: expected labels {self.label_names}")
        key = tuple(str(v) for v in values)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._new_child()
                self._children[key] = child
            return child

    def _new_child(self):
        raise NotImplementedError

    def clear(self) -> None:
        with self._lock:
            self._children.clear()

    def samples(self) -> List[Tuple[str, Dict[str, str], float]]:
        raise NotImplementedError

    def samples_with_exemplars(self):
        """samples() widened with a per-sample exemplar slot (None for metric
        kinds without exemplar support)."""
        return [(name, labels, value, None) for name, labels, value in self.samples()]

    def _label_dicts(self):
        with self._lock:
            return [
                (dict(zip(self.label_names, key)), child)
                for key, child in self._children.items()
            ]


class _CounterChild:
    def __init__(self) -> None:
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount

    add = inc


class Counter(_Metric):
    kind = "counter"

    def _new_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def samples(self):
        return [(self.name, labels, c.value) for labels, c in self._label_dicts()]


class _GaugeChild:
    def __init__(self) -> None:
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = value

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.value -= amount


class Gauge(_Metric):
    kind = "gauge"

    def _new_child(self):
        return _GaugeChild()

    def set(self, value: float) -> None:
        self.labels().set(value)

    def samples(self):
        return [(self.name, labels, g.value) for labels, g in self._label_dicts()]

    def delete_labels(self, *values: str) -> None:
        key = tuple(str(v) for v in values)
        with self._lock:
            self._children.pop(key, None)


class _HistogramChild:
    def __init__(self, buckets: List[float]) -> None:
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)
        self.total = 0.0
        self.count = 0
        # bucket idx -> (exemplar labels, observed value, wall time): the last
        # observation per bucket that carried an exemplar (e.g. a trace id)
        self.exemplars: Dict[int, Tuple[Dict[str, str], float, float]] = {}
        # exposition emits cumulative buckets that must satisfy +Inf == _count;
        # an unlocked mid-observe scrape would transiently violate it
        self._lock = threading.Lock()

    def observe(self, value: float, exemplar: Optional[Dict[str, str]] = None) -> None:
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self.counts[idx] += 1
            self.total += value
            self.count += 1
            if exemplar:
                self.exemplars[idx] = (dict(exemplar), float(value), time.time())

    def snapshot(self):
        """(counts, total, count, exemplars) read atomically for exposition."""
        with self._lock:
            return list(self.counts), self.total, self.count, dict(self.exemplars)


def _format_bound(bound: float) -> str:
    return format(bound, "g")


def _escape_label_value(value: str) -> str:
    """Classic Prometheus text-format label-value escaping: backslash, the
    double quote, and line feed are the three characters the grammar reserves
    (https://prometheus.io/docs/instrumenting/exposition_formats/)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_, label_names=(), buckets: Optional[List[float]] = None):
        super().__init__(name, help_, label_names)
        self.buckets = list(buckets or DURATION_BUCKETS)

    def _new_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, value: float, exemplar: Optional[Dict[str, str]] = None) -> None:
        self.labels().observe(value, exemplar=exemplar)

    def samples(self):
        return [(name, labels, value) for name, labels, value, _ in self.samples_with_exemplars()]

    def samples_with_exemplars(self):
        """Prometheus histogram exposition: cumulative ``_bucket{le=...}``
        lines (including ``le="+Inf"``) plus ``_count``/``_sum``.  The fourth
        element carries the bucket's exemplar (or None)."""
        out = []
        for labels, h in self._label_dicts():
            counts, total, count, exemplars = h.snapshot()
            cumulative = 0
            for i, bound in enumerate(h.buckets):
                cumulative += counts[i]
                out.append((
                    self.name + "_bucket",
                    {**labels, "le": _format_bound(bound)},
                    float(cumulative),
                    exemplars.get(i),
                ))
            cumulative += counts[-1]
            out.append((
                self.name + "_bucket",
                {**labels, "le": "+Inf"},
                float(cumulative),
                exemplars.get(len(h.buckets)),
            ))
            out.append((self.name + "_count", labels, float(count), None))
            out.append((self.name + "_sum", labels, total, None))
        return out


class _SummaryChild:
    def __init__(self) -> None:
        self.values: List[float] = []

    def observe(self, value: float) -> None:
        bisect.insort(self.values, value)

    def quantile(self, q: float) -> float:
        if not self.values:
            return float("nan")
        idx = min(int(q * len(self.values)), len(self.values) - 1)
        return self.values[idx]


class Summary(_Metric):
    kind = "summary"

    def _new_child(self):
        return _SummaryChild()

    def observe(self, value: float) -> None:
        self.labels().observe(value)

    def samples(self):
        out = []
        for labels, s in self._label_dicts():
            out.append((self.name + "_count", labels, float(len(s.values))))
            out.append((self.name + "_sum", labels, float(sum(s.values))))
            for q in SUMMARY_OBJECTIVES:
                out.append(
                    (self.name, {**labels, "quantile": str(q)}, s.quantile(q))
                )
        return out


class Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                return existing
            self._metrics[metric.name] = metric
            return metric

    def counter(self, name, help_="", label_names=()) -> Counter:
        return self.register(Counter(name, help_, label_names))  # type: ignore[return-value]

    def gauge(self, name, help_="", label_names=()) -> Gauge:
        return self.register(Gauge(name, help_, label_names))  # type: ignore[return-value]

    def histogram(self, name, help_="", label_names=(), buckets=None) -> Histogram:
        return self.register(Histogram(name, help_, label_names, buckets))  # type: ignore[return-value]

    def summary(self, name, help_="", label_names=()) -> Summary:
        return self.register(Summary(name, help_, label_names))  # type: ignore[return-value]

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def reset(self) -> None:
        with self._lock:
            for metric in self._metrics.values():
                metric.clear()

    def label_set_count(self) -> int:
        """Total live label sets (time series) across every registered
        metric — the number the cardinality guard keeps bounded."""
        with self._lock:
            metrics = list(self._metrics.values())
        total = 0
        for metric in metrics:
            with metric._lock:
                total += len(metric._children)
        return total

    def render(self, exemplars: bool = False) -> str:
        """Prometheus text exposition.  With ``exemplars=True`` bucket lines
        carry their exemplar in OpenMetrics syntax
        (``... # {trace_id="..."} value timestamp``)."""
        lines = []
        with self._lock:
            metrics = list(self._metrics.values())
        for metric in metrics:
            lines.append(f"# HELP {metric.name} {metric.help}")
            lines.append(f"# TYPE {metric.name} {metric.kind}")
            for name, labels, value, exemplar in metric.samples_with_exemplars():
                if labels:
                    rendered = ",".join(
                        f'{k}="{_escape_label_value(v)}"'
                        for k, v in sorted(labels.items())
                    )
                    line = f"{name}{{{rendered}}} {value}"
                else:
                    line = f"{name} {value}"
                if exemplars and exemplar is not None:
                    ex_labels, ex_value, ex_wall = exemplar
                    ex_rendered = ",".join(
                        f'{k}="{_escape_label_value(v)}"'
                        for k, v in sorted(ex_labels.items())
                    )
                    line += f" # {{{ex_rendered}}} {ex_value} {ex_wall:.3f}"
                lines.append(line)
        return "\n".join(lines) + "\n"


REGISTRY = Registry()

# Per-stage solve-pipeline histogram: one series per tracing span name
# (ingest/encode/dispatch/solve/decode/materialize plus the controller
# reconcile spans), observed at span close by tracing/trace.py with a
# trace_id exemplar — a latency outlier on a scrape links straight back to
# the trace that produced it (render(exemplars=True)).
SOLVE_STAGE_DURATION = Histogram(
    NAMESPACE + "_solve_stage_duration_seconds",
    "Duration of solve-pipeline stages, labeled by tracing span name.",
    ("stage",),
)
REGISTRY.register(SOLVE_STAGE_DURATION)

# Soak-subsystem SLO surface (soak/slo.py samples these every simulated
# tick): the live value of each time-series probe and a counter of SLO-rule
# violations, so a long-running soak is watchable on /metrics while the
# structured verdict report is still being accumulated (docs/SOAK.md).
SOAK_SLO_PROBE = Gauge(
    NAMESPACE + "_soak_slo_probe",
    "Latest sampled value of a soak SLO probe, by probe and scenario.",
    ("probe", "scenario"),
)
REGISTRY.register(SOAK_SLO_PROBE)
SOAK_SLO_VIOLATIONS = Counter(
    NAMESPACE + "_soak_slo_violations_total",
    "Soak SLO rules that failed evaluation, by probe and scenario.",
    ("probe", "scenario"),
)
REGISTRY.register(SOAK_SLO_VIOLATIONS)

# Policy-objective surface (docs/POLICY.md): the latest solve's selected
# fleet cost, raw offering prices ({view="price"}) and risk-weighted
# expectation ({view="expected"}), set by TPUSolver decode when the
# objective stage runs.
POLICY_FLEET_COST = Gauge(
    NAMESPACE + "_policy_fleet_cost",
    "Fleet cost of the latest policy-objective selection, by view "
    "(price = raw offering prices, expected = risk-weighted).",
    ("view",),
)
REGISTRY.register(POLICY_FLEET_COST)


class LabelCardinalityGuard:
    """Bounds the distinct values a high-cardinality label may take.

    The tenant id is the first unbounded-by-construction label value this
    registry carries (every other label is a small closed vocabulary).  The
    guard admits the first ``cap`` distinct values verbatim; every later
    value maps to the overflow bucket (``"_other"``), so a 10k-tenant churn
    holds /metrics to a bounded series count while the busiest (earliest)
    tenants keep per-tenant resolution.  Admission is for the process
    lifetime — releasing on session eviction would let churn re-admit
    forever, which is exactly the cardinality leak being prevented.
    """

    OVERFLOW = "_other"

    def __init__(self, cap: int) -> None:
        self._cap = max(int(cap), 1)
        self._lock = threading.Lock()
        self._seen: set = set()
        self.overflowed = 0

    def admit(self, value: str) -> str:
        value = str(value)
        with self._lock:
            if value in self._seen:
                return value
            if len(self._seen) < self._cap:
                self._seen.add(value)
                return value
            self.overflowed += 1
            return self.OVERFLOW

    @property
    def cap(self) -> int:
        return self._cap

    def seen(self) -> int:
        with self._lock:
            return len(self._seen)

    def reset(self, cap: Optional[int] = None) -> None:
        with self._lock:
            self._seen.clear()
            self.overflowed = 0
            if cap is not None:
                self._cap = max(int(cap), 1)


def _tenant_label_cap_from_env() -> int:
    try:
        return int(os.environ.get("KC_TENANT_LABEL_MAX", "64") or 64)
    except ValueError:
        return 64  # a tuning-knob typo must not take the operator down


TENANT_LABEL_GUARD = LabelCardinalityGuard(_tenant_label_cap_from_env())


def tenant_label(tenant_id: str) -> str:
    """The guarded spelling of a tenant id for metric labels: the id itself
    while the process-wide cap (``KC_TENANT_LABEL_MAX``) has room, the
    ``"_other"`` overflow bucket after.  Every ``{tenant=...}`` call site
    must route through this."""
    return TENANT_LABEL_GUARD.admit(tenant_id)


def measure(observer, clock=None):
    """Closure timer (constants.go:60-66): ``done = measure(hist.labels(...))``
    then ``done()`` observes the elapsed seconds."""
    start = time.perf_counter()

    def done() -> float:
        elapsed = time.perf_counter() - start
        observer.observe(elapsed)
        return elapsed

    return done
