from karpenter_core_tpu.metrics.registry import (
    Counter,
    Gauge,
    Histogram,
    LabelCardinalityGuard,
    Registry,
    Summary,
    REGISTRY,
    DURATION_BUCKETS,
    SOLVE_STAGE_DURATION,
    TENANT_LABEL_GUARD,
    measure,
    tenant_label,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "LabelCardinalityGuard",
    "Summary",
    "Registry",
    "REGISTRY",
    "DURATION_BUCKETS",
    "SOLVE_STAGE_DURATION",
    "TENANT_LABEL_GUARD",
    "measure",
    "tenant_label",
]
