from karpenter_core_tpu.metrics.registry import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    Summary,
    REGISTRY,
    DURATION_BUCKETS,
    SOLVE_STAGE_DURATION,
    measure,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Summary",
    "Registry",
    "REGISTRY",
    "DURATION_BUCKETS",
    "SOLVE_STAGE_DURATION",
    "measure",
]
