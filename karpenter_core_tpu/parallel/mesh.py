"""Device-mesh parallelism for the solver.

The reference has no collective layer (its "distributed backend" is the
kube-apiserver watch plane, SURVEY.md §2.11/§5.8); the TPU-native design adds
one where the problem is data-parallel:

  - **Monte-Carlo what-if** (BASELINE config 5): vmap the solve kernel over
    perturbed snapshot replicas (spot-interruption scenarios), sharded across
    the mesh's ``replica`` axis; cost statistics reduce over ICI with psum.
  - **Consolidation subset search** (BASELINE config 3): vmap the simulation
    over candidate node subsets, sharded the same way (ops.consolidate).

Multi-slice scaling note: the replica/subset axes are embarrassingly parallel,
so cross-slice traffic is one scalar reduction per solve — lay the mesh's
replica axis over DCN and everything else rides ICI.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from karpenter_core_tpu.models.snapshot import EncodedSnapshot
from karpenter_core_tpu.ops import solve as solve_ops


def default_mesh(n_devices: Optional[int] = None, axis: str = "replica") -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def perturb_spot_availability(
    snapshot: EncodedSnapshot, n_replicas: int, seed: int = 0, interruption_rate: float = 0.3
) -> jnp.ndarray:
    """bool[REP, I, Z, CT]: per-replica offering availability with spot
    offerings randomly interrupted — the scenario axis for the what-if sweep."""
    key = jax.random.PRNGKey(seed)
    avail = jnp.asarray(snapshot.it_avail)  # [I, Z, CT]
    is_spot = jnp.asarray(
        np.array([ct == "spot" for ct in snapshot.capacity_types], dtype=bool)
    )  # [CT]
    interrupted = (
        jax.random.uniform(key, (n_replicas,) + avail.shape) < interruption_rate
    ) & is_spot[None, None, None, :]
    return avail[None] & ~interrupted


def monte_carlo_solve(
    snapshot: EncodedSnapshot,
    n_replicas: int,
    mesh: Optional[Mesh] = None,
    seed: int = 0,
    interruption_rate: float = 0.3,
    n_slots: int = 0,
) -> dict:
    """Solve ``n_replicas`` perturbed snapshots in parallel across the mesh.

    Returns summary statistics (per-replica scheduled/failed/node counts and
    total cost, plus mean/min/max cost) — the cost-vs-disruption Pareto input.
    """
    if mesh is None:
        mesh = default_mesh()
    if n_slots <= 0:
        n_slots = solve_ops.estimate_slots(snapshot)

    cls, statics_arrays, key_has_bounds = solve_ops.prepare(snapshot)
    avail_r = perturb_spot_availability(snapshot, n_replicas, seed, interruption_rate)
    it_price = jnp.asarray(snapshot.it_price)

    # patch the availability plane by field name so a reordering of the
    # Statics tuple can't silently perturb the wrong tensor
    avail_idx = solve_ops.Statics._fields.index("it_avail")

    def one_replica(avail):
        arrays = list(statics_arrays)
        arrays[avail_idx] = avail
        out = solve_ops.solve_core(
            cls, tuple(arrays), n_slots, key_has_bounds,
            n_passes=snapshot.scan_passes,
        )
        scheduled = jnp.sum(out.assign)
        failed = jnp.sum(out.failed)
        nodes = jnp.sum((out.state.pod_count > 0).astype(jnp.int32))
        prices = solve_ops.node_prices(out.state, it_price)
        cost = jnp.sum(jnp.where(jnp.isfinite(prices), prices, 0.0))
        return scheduled, failed, nodes, cost

    replicated = NamedSharding(mesh, P())
    sharded = NamedSharding(mesh, P("replica"))
    fn = jax.jit(
        jax.vmap(one_replica),
        in_shardings=(sharded,),
        out_shardings=(sharded, sharded, sharded, sharded),
    )
    with mesh:
        scheduled, failed, nodes, cost = fn(avail_r)
        scheduled, failed, nodes, cost = jax.device_get(
            (scheduled, failed, nodes, cost)
        )
    return {
        "replicas": n_replicas,
        "scheduled": np.asarray(scheduled),
        "failed": np.asarray(failed),
        "nodes": np.asarray(nodes),
        "cost": np.asarray(cost),
        "cost_mean": float(np.mean(cost)),
        "cost_min": float(np.min(cost)),
        "cost_max": float(np.max(cost)),
        "failed_mean": float(np.mean(failed)),
    }
