"""Device-mesh parallelism for the solver.

The reference has no collective layer (its "distributed backend" is the
kube-apiserver watch plane, SURVEY.md §2.11/§5.8); the TPU-native design adds
one where the problem is data-parallel:

  - **Monte-Carlo what-if** (BASELINE config 5): vmap the solve kernel over
    perturbed snapshot replicas (spot-interruption scenarios), sharded across
    the mesh's ``replica`` axis; cost statistics reduce over ICI with psum.
  - **Consolidation subset search** (BASELINE config 3): vmap the simulation
    over candidate node subsets, sharded the same way (ops.consolidate).

Multi-slice scaling note: the replica/subset axes are embarrassingly parallel,
so cross-slice traffic is one scalar reduction per solve — lay the mesh's
replica axis over DCN and everything else rides ICI.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from karpenter_core_tpu.models.snapshot import EncodedSnapshot
from karpenter_core_tpu.ops import solve as solve_ops
from karpenter_core_tpu.utils import compilecache


def default_mesh(n_devices: Optional[int] = None, axis: str = "replica") -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def default_mesh_2d(
    shape: Optional[Tuple[int, int]] = None, axes: Tuple[str, str] = ("replica", "lane")
) -> Mesh:
    """(replica × lane) mesh for crossed studies: Monte-Carlo scenarios on one
    axis, consolidation prefix lanes on the other.  Both axes are
    embarrassingly parallel, so on multi-slice hardware lay ``replica`` over
    DCN and keep ``lane`` within a slice — the only cross-device traffic is
    the result gather."""
    devices = jax.devices()
    if shape is None:
        n = len(devices)
        lanes = 1
        for candidate in range(int(np.sqrt(n)), 0, -1):
            if n % candidate == 0:
                lanes = candidate
                break
        shape = (n // lanes, lanes)
    r, l = shape
    return Mesh(np.array(devices[: r * l]).reshape(r, l), axes)


def _pad_i_axis(arr, axis: int, target: int, value):
    pad = target - arr.shape[axis]
    if pad <= 0:
        return jnp.asarray(arr)
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, pad)
    return jnp.pad(jnp.asarray(arr), widths, constant_values=value)


def solve_catalog_sharded(
    snapshot: EncodedSnapshot,
    mesh: Optional[Mesh] = None,
    axis: str = "lane",
    n_slots: int = 0,
):
    """The PROVISIONING solve with the catalog (instance-type) axis sharded
    across the mesh (VERDICT r4 #7 / BASELINE config 4).

    Why the catalog axis: class dedup collapses the pod axis to ~a dozen
    classes regardless of pod count (models/snapshot.py docstring), and the
    class scan's carry is inherently sequential — but per class step the hot
    planes are [N slots, I instance types] with per-I independence
    (_it_intersects, _capacity, _offering_ok) and only max/any reductions
    over I.  Annotating the I-indexed inputs with a NamedSharding and letting
    GSPMD propagate yields per-device [N, I/D] compute with one small
    collective per reduction — the scaling-book recipe (mesh + annotations,
    XLA inserts collectives), no kernel changes.

    The catalog pads to a device multiple with inert instance types (no
    availability, zero allocatable, excluded from every template/class mask).
    Returns SolveOutputs identical to the single-device solve — decode sees
    the same planes (padded I tail is never viable).

    Bit-packed masks compose transparently: the shardings below annotate the
    HOST-layout bool planes, and solve_core packs them to uint32 words inside
    the jitted program — an elementwise transform over the trailing slot axis,
    so GSPMD keeps the I-axis partition for the packed catalog words and the
    word-wide AND reductions stay collective-free (__graft_entry__'s dry run
    asserts exact parity vs the single-device solve).
    """
    if mesh is None:
        mesh = default_mesh(axis=axis)
    if axis not in mesh.axis_names:
        axis = mesh.axis_names[-1]
    # pad to the SHARDING axis size, not the total device count — on a 2D
    # mesh P(axis) only splits the catalog that many ways
    axis_size = int(mesh.shape[axis])
    if n_slots <= 0:
        n_slots = solve_ops.estimate_slots(snapshot)

    cls, statics_arrays, key_has_bounds = solve_ops.prepare_host(snapshot)
    i0 = statics_arrays.it_alloc.shape[0]
    i_pad = -(-i0 // axis_size) * axis_size

    it = statics_arrays.it
    it_padded = type(it)(
        mask=_pad_i_axis(it.mask, 0, i_pad, False),
        defined=_pad_i_axis(it.defined, 0, i_pad, False),
        negative=_pad_i_axis(it.negative, 0, i_pad, False),
        gt=_pad_i_axis(it.gt, 0, i_pad, -np.inf),
        lt=_pad_i_axis(it.lt, 0, i_pad, np.inf),
    )
    statics_padded = statics_arrays._replace(
        it=it_padded,
        it_alloc=_pad_i_axis(statics_arrays.it_alloc, 0, i_pad, 0.0),
        it_avail=_pad_i_axis(statics_arrays.it_avail, 0, i_pad, False),
        tmpl_it=_pad_i_axis(statics_arrays.tmpl_it, 1, i_pad, False),
        it_capacity=_pad_i_axis(statics_arrays.it_capacity, 0, i_pad, 0.0),
    )
    cls_padded = cls._replace(it=_pad_i_axis(cls.it, 1, i_pad, False))

    shard_i = NamedSharding(mesh, P(axis))
    shard_i_ax1 = NamedSharding(mesh, P(None, axis))
    replicated = NamedSharding(mesh, P())

    # sharding pytrees mirroring the inputs: I-indexed leaves partitioned,
    # everything else replicated (GSPMD propagates through the scan)
    statics_shardings = jax.tree_util.tree_map(
        lambda _: replicated, statics_padded
    )._replace(
        it=type(it)(
            mask=shard_i, defined=shard_i, negative=shard_i, gt=shard_i, lt=shard_i
        ),
        it_alloc=shard_i,
        it_avail=shard_i,
        tmpl_it=shard_i_ax1,
        it_capacity=shard_i,
    )
    cls_shardings = jax.tree_util.tree_map(
        lambda _: replicated, cls_padded
    )._replace(it=shard_i_ax1)

    with mesh:
        cls_dev = jax.device_put(cls_padded, cls_shardings)
        statics_dev = jax.device_put(statics_padded, statics_shardings)
        fn = _catalog_solve_fn(
            key_has_bounds, n_slots, snapshot.scan_passes,
            compilecache.snap_features(solve_ops.snapshot_features(snapshot)),
            cls_shardings, statics_shardings,
        )
        out = fn(cls_dev, statics_dev)
        jax.block_until_ready(out)
    return out


@functools.lru_cache(maxsize=16)
def _catalog_solve_fn(key_has_bounds, n_slots: int, n_passes: int, features,
                      cls_shardings, statics_shardings):
    """Cached jitted catalog-sharded solve — a fresh ``jax.jit`` per call
    would defeat JAX's compile cache (keyed on callable identity) and retrace
    every solve (same pattern as ops.consolidate._sharded_sweep_fn; the
    sharding pytrees are NamedSharding namedtuples, hashable and
    mesh-identifying, so they key the cache instead of the mesh itself)."""
    return jax.jit(
        functools.partial(
            solve_ops.solve_core,
            n_slots=n_slots,
            key_has_bounds=key_has_bounds,
            n_passes=n_passes,
            features=features,
        ),
        in_shardings=(cls_shardings, statics_shardings),
    )


def perturb_spot_availability(
    snapshot: EncodedSnapshot, n_replicas: int, seed: int = 0, interruption_rate: float = 0.3
) -> jnp.ndarray:
    """bool[REP, I, Z, CT]: per-replica offering availability with spot
    offerings randomly interrupted — the scenario axis for the what-if sweep."""
    key = jax.random.PRNGKey(seed)
    avail = jnp.asarray(snapshot.it_avail)  # [I, Z, CT]
    is_spot = jnp.asarray(
        np.array([ct == "spot" for ct in snapshot.capacity_types], dtype=bool)
    )  # [CT]
    interrupted = (
        jax.random.uniform(key, (n_replicas,) + avail.shape) < interruption_rate
    ) & is_spot[None, None, None, :]
    return avail[None] & ~interrupted


def monte_carlo_solve(
    snapshot: EncodedSnapshot,
    n_replicas: int,
    mesh: Optional[Mesh] = None,
    seed: int = 0,
    interruption_rate: float = 0.3,
    n_slots: int = 0,
) -> dict:
    """Solve ``n_replicas`` perturbed snapshots in parallel across the mesh.

    Returns summary statistics (per-replica scheduled/failed/node counts and
    total cost, plus mean/min/max cost) — the cost-vs-disruption Pareto input.
    """
    if mesh is None:
        mesh = default_mesh()
    if n_slots <= 0:
        n_slots = solve_ops.estimate_slots(snapshot)

    cls, statics_arrays, key_has_bounds = solve_ops.prepare(snapshot)
    avail_r = perturb_spot_availability(snapshot, n_replicas, seed, interruption_rate)
    it_price = jnp.asarray(snapshot.it_price)

    # patch the availability plane by field name so a reordering of the
    # Statics tuple can't silently perturb the wrong tensor
    avail_idx = solve_ops.Statics._fields.index("it_avail")

    fn = _monte_carlo_fn(
        mesh, key_has_bounds, n_slots, snapshot.scan_passes, avail_idx,
        compilecache.snap_features(solve_ops.snapshot_features(snapshot)),
    )
    with mesh:
        scheduled, failed, nodes, cost = fn(
            avail_r, cls, statics_arrays, it_price
        )
        scheduled, failed, nodes, cost = jax.device_get(
            (scheduled, failed, nodes, cost)
        )
    return {
        "replicas": n_replicas,
        "scheduled": np.asarray(scheduled),
        "failed": np.asarray(failed),
        "nodes": np.asarray(nodes),
        "cost": np.asarray(cost),
        "cost_mean": float(np.mean(cost)),
        "cost_min": float(np.min(cost)),
        "cost_max": float(np.max(cost)),
        "failed_mean": float(np.mean(failed)),
    }


@functools.lru_cache(maxsize=16)
def _monte_carlo_fn(mesh, key_has_bounds, n_slots: int, n_passes: int,
                    avail_idx: int, features=None):
    """Cached jitted Monte-Carlo sweep.  The per-replica closure takes the
    snapshot tensors as ARGUMENTS (not captured values) so the cache key is
    the static config alone — a fresh closure per call would defeat JAX's
    compile cache and retrace every study."""

    def one_replica(avail, cls, statics_arrays, it_price):
        arrays = list(statics_arrays)
        arrays[avail_idx] = avail
        out = solve_ops.solve_core(
            cls, tuple(arrays), n_slots, key_has_bounds,
            n_passes=n_passes, features=features,
        )
        scheduled = jnp.sum(out.assign)
        failed = jnp.sum(out.failed)
        nodes = jnp.sum((out.state.pod_count > 0).astype(jnp.int32))
        prices = solve_ops.node_prices(out.state, it_price)
        cost = jnp.sum(jnp.where(jnp.isfinite(prices), prices, 0.0))
        return scheduled, failed, nodes, cost

    sharded = NamedSharding(mesh, P("replica"))
    return jax.jit(
        jax.vmap(one_replica, in_axes=(0, None, None, None)),
        in_shardings=(sharded, None, None, None),
        out_shardings=(sharded, sharded, sharded, sharded),
    )


def perturb_offering_availability(
    snapshot: EncodedSnapshot, risk, n_replicas: int, seed: int = 0
) -> jnp.ndarray:
    """bool[REP, I, Z, CT]: per-replica offering availability with every
    offering cell interrupted with ITS OWN prior probability — the policy
    risk planes (policy.planes) instead of ``perturb_spot_availability``'s
    one uniform spot rate.  Offerings with zero risk never drop, so a
    risk-free catalog reproduces the unperturbed solve in every replica."""
    key = jax.random.PRNGKey(seed)
    avail = jnp.asarray(snapshot.it_avail)  # [I, Z, CT]
    risk_arr = jnp.asarray(risk, dtype=jnp.float32)
    interrupted = (
        jax.random.uniform(key, (n_replicas,) + avail.shape) < risk_arr[None]
    )
    return avail[None] & ~interrupted


def policy_monte_carlo(
    snapshot: EncodedSnapshot,
    n_replicas: int,
    mesh: Optional[Mesh] = None,
    seed: int = 0,
    n_slots: int = 0,
) -> dict:
    """Risk-weighted policy variants over the Monte-Carlo replica machinery:
    sample one interruption OUTCOME per replica from the snapshot's
    per-offering risk priors (``pol_risk``), solve every outcome in parallel
    across the mesh, and pick the replica assignment minimizing risk-adjusted
    cost — fleet price plus an unschedulable-pod penalty that dominates any
    price difference, so a cheap fleet that strands pods under interruption
    never wins (docs/POLICY.md "Risk-weighted variants").

    Returns per-replica ``cost``/``failed``/``nodes`` arrays plus
    ``expected_cost`` (the mean risk-adjusted cost — the number a
    risk-averse objective reports for this fleet) and ``best_replica``."""
    if mesh is None:
        mesh = default_mesh()
    if n_slots <= 0:
        n_slots = solve_ops.estimate_slots(snapshot)
    risk = getattr(snapshot, "pol_risk", None)
    if risk is None:
        risk = np.zeros_like(np.asarray(snapshot.it_price))
    price = getattr(snapshot, "pol_price", None)
    if price is None:
        price = snapshot.it_price

    cls, statics_arrays, key_has_bounds = solve_ops.prepare(snapshot)
    avail_r = perturb_offering_availability(snapshot, risk, n_replicas, seed)
    it_price = jnp.asarray(price)
    avail_idx = solve_ops.Statics._fields.index("it_avail")

    fn = _monte_carlo_fn(
        mesh, key_has_bounds, n_slots, snapshot.scan_passes, avail_idx,
        compilecache.snap_features(solve_ops.snapshot_features(snapshot)),
    )
    with mesh:
        scheduled, failed, nodes, cost = jax.device_get(
            fn(avail_r, cls, statics_arrays, it_price)
        )
    cost = np.asarray(cost, dtype=np.float64)
    failed = np.asarray(failed, dtype=np.int64)
    # the penalty per unplaced pod dominates any achievable fleet price —
    # every open slot costs at most the max offering price, so max_price ×
    # n_slots bounds any replica's fleet cost and feasibility strictly
    # outranks price in the risk-adjusted ordering
    finite = np.asarray(price)[np.isfinite(price)]
    penalty = float(finite.max() if finite.size else 1.0) * max(n_slots, 1)
    adjusted = cost + failed * (penalty + 1.0)
    best = int(np.argmin(adjusted)) if len(adjusted) else 0
    return {
        "replicas": n_replicas,
        "scheduled": np.asarray(scheduled),
        "failed": failed,
        "nodes": np.asarray(nodes),
        "cost": cost,
        "adjusted_cost": adjusted,
        "expected_cost": float(np.mean(adjusted)) if len(adjusted) else 0.0,
        "cost_mean": float(np.mean(cost)) if len(cost) else 0.0,
        "cost_max": float(np.max(cost)) if len(cost) else 0.0,
        "best_replica": best,
        "best_cost": float(cost[best]) if len(cost) else 0.0,
        "feasible_replicas": int(np.sum(failed == 0)),
    }


@functools.lru_cache(maxsize=16)
def _crossed_grid_fn(mesh, key_has_bounds, n_slots: int, n_passes: int, avail_idx: int,
                     features=None):
    """Cached jitted crossed grid — a fresh closure per call would defeat
    JAX's compile cache (keyed on callable identity) and recompile the whole
    vmap-of-vmap solve every study (same pattern as
    ops.consolidate._sharded_sweep_fn)."""
    rep, lane = mesh.axis_names

    def one_cell(avail, k, cls, statics_arrays, ex_state, ex_static, rank, counts):
        arrays = list(statics_arrays)
        arrays[avail_idx] = avail
        subset = rank < k
        ex = ex_state._replace(open_=ex_state.open_ & ~subset)
        displaced = jnp.sum(counts * subset[None, :].astype(jnp.int32), axis=-1)
        cls_k = cls._replace(count=cls.count + displaced)
        out = solve_ops.solve_core(
            cls_k, tuple(arrays), n_slots, key_has_bounds, ex, ex_static,
            n_passes=n_passes, features=features,
        )
        return jnp.sum(out.failed), out.state.n_next

    batch_none = (None,) * 6
    grid = jax.vmap(
        jax.vmap(one_cell, in_axes=(None, 0) + batch_none),
        in_axes=(0, None) + batch_none,
    )
    return jax.jit(
        grid,
        in_shardings=(NamedSharding(mesh, P(rep)), NamedSharding(mesh, P(lane)))
        + (None,) * 6,
        out_shardings=(
            NamedSharding(mesh, P(rep, lane)),
            NamedSharding(mesh, P(rep, lane)),
        ),
    )


def crossed_consolidation_study(
    snapshot: EncodedSnapshot,
    ex_state,
    ex_static,
    candidate_rank: np.ndarray,  # i32[E] disruption order, big = not candidate
    ex_cls_count: np.ndarray,  # i32[C, E] candidate pods per class per node
    prefix_sizes: np.ndarray,  # i32[S]
    n_replicas: int,
    mesh: Optional[Mesh] = None,
    seed: int = 0,
    interruption_rate: float = 0.3,
    n_slots: int = 16,
) -> dict:
    """Risk-aware consolidation: every (spot-interruption scenario r,
    consolidation prefix k) pair is one simulation — close the first-k
    candidates AND apply replica r's perturbed offering availability, then
    re-schedule.  The [R, S] grid shards over a 2D (replica × lane) mesh
    (vmap∘vmap; XLA partitions both batch axes, no collectives until the
    result gather).

    Returns the failed/new-node grids plus ``safe_prefix``: per replica, the
    largest prefix whose simulation fully re-schedules — min over replicas is
    the consolidation depth that is safe under every sampled interruption
    scenario (the 1D sweep in ops.consolidate answers only the rate-0 row)."""
    if mesh is None:
        mesh = default_mesh_2d()
    n_rep_axis, n_lane_axis = (mesh.shape[name] for name in mesh.axis_names)

    cls, statics_arrays, key_has_bounds = solve_ops.prepare(snapshot)
    avail_r = perturb_spot_availability(snapshot, n_replicas, seed, interruption_rate)
    avail_idx = solve_ops.Statics._fields.index("it_avail")

    sizes = jnp.asarray(prefix_sizes, dtype=jnp.int32)
    pad_s = (-len(prefix_sizes)) % n_lane_axis
    if pad_s:
        sizes = jnp.concatenate([sizes, jnp.repeat(sizes[-1:], pad_s)])
    pad_r = (-n_replicas) % n_rep_axis
    if pad_r:
        avail_r = jnp.concatenate([avail_r, avail_r[-1:].repeat(pad_r, axis=0)])

    fn = _crossed_grid_fn(
        mesh, key_has_bounds, n_slots, snapshot.scan_passes, avail_idx,
        compilecache.snap_features(
            solve_ops.features_with_existing(snapshot, ex_static)
        ),
    )
    with mesh:
        failed, n_new = jax.device_get(
            fn(
                avail_r, sizes, cls, statics_arrays, ex_state, ex_static,
                jnp.asarray(candidate_rank), jnp.asarray(ex_cls_count),
            )
        )
    failed = np.asarray(failed)[:n_replicas, : len(prefix_sizes)]
    n_new = np.asarray(n_new)[:n_replicas, : len(prefix_sizes)]

    feasible = failed == 0  # [R, S]
    sizes_np = np.asarray(prefix_sizes)
    # rows with no feasible prefix reduce to 0 (sizes are >= 1)
    safe_prefix = np.max(np.where(feasible, sizes_np[None, :], 0), axis=1)
    return {
        "failed": failed,
        "n_new": n_new,
        "safe_prefix": safe_prefix,  # per replica
        "safe_prefix_all": int(safe_prefix.min()) if len(safe_prefix) else 0,
    }
