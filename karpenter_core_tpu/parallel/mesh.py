"""Device-mesh parallelism for the solver — the PRODUCTION dispatch layer.

The reference has no collective layer (its "distributed backend" is the
kube-apiserver watch plane, SURVEY.md §2.11/§5.8); the TPU-native design adds
one wherever the problem is data-parallel:

  - **Sharded production solve** (the default path on >1 device,
    ``KC_SOLVER_MESH``): every provisioning/repair solve runs as a
    ``shard_map`` over the device mesh with the CATALOG (instance-type) axis
    sharded and the pod/class planes replicated.  Per class step the hot
    planes are [N slots, I types] with per-I independence; the kernel's few
    I-axis reductions finish with exact pmax/psum collectives
    (ops.solve._imax/_isum), so the sharded solve is BIT-IDENTICAL to the
    single-device solve — and a 1-device mesh is the degenerate case running
    literally the same code.  ``partition_specs`` assigns specs to the solve
    pytrees by regex over leaf paths (the partition-rule pattern).
  - **Consolidation lane sweep**: the subset-prefix simulations
    (ops.consolidate.sweep) split across the mesh's second ``lane`` axis
    while each lane group shards the catalog — one 2D shard_map answers the
    whole largest-valid-prefix search.
  - **Monte-Carlo what-if** (BASELINE config 5): vmap the solve kernel over
    perturbed snapshot replicas (spot-interruption scenarios), sharded across
    the mesh's ``replica`` axis; cost statistics reduce over ICI with psum.

Multi-slice scaling note: the replica/lane axes are embarrassingly parallel,
so lay them over DCN; the catalog axis's per-step collectives are tiny
([N]-vector max/sum) and ride ICI.

Flags (docs/KERNEL_PERF.md "Layer 5"):

    KC_SOLVER_MESH=1|0       force the sharded path on/off; unset = auto
                             (on when the backend exposes >1 device)
    KC_SOLVER_MESH_DEVICES   cap the devices the solve mesh uses
    KC_SOLVER_MESH_SHAPE     "CxL" catalog×lane split for the sweep mesh
                             (default: lanes=2 when the count allows)
"""

from __future__ import annotations

import functools
import os
import re
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from karpenter_core_tpu.models.snapshot import EncodedSnapshot
from karpenter_core_tpu.ops import solve as solve_ops
from karpenter_core_tpu.utils import compilecache

CATALOG_AXIS = "catalog"
LANE_AXIS = "lane"
TENANT_AXIS = "tenant"

# partition rules: leaf-path regex -> the axis index the catalog shards.
# Applied to every solve pytree (ClassTensors/StaticArrays/NodeState/
# WarmCarry/SolveOutputs...) — unmatched leaves replicate.
# ``.it.<field>`` is the catalog ReqTensor ([I, K, ...]); bare ``.it`` is
# ClassTensors.it ([C, I]); ``.viable`` covers NodeState in carries and
# outputs alike, which is what lets the warm-start repair reuse the same
# rule set for its carry pytrees.  (The lane sweep's per-lane outputs add a
# leading lane axis and build their specs by hand —
# ops.consolidate._lane_sweep_fn.)
CATALOG_PARTITION_RULES: Tuple[Tuple[str, int], ...] = (
    (r"\.it\.(mask|defined|negative|gt|lt)$", 0),
    (r"\.(it_alloc|it_avail|it_capacity|it_price)$", 0),
    (r"\.tmpl_it$", 1),
    (r"\.it$", 1),
    (r"\.viable$", 1),
)

# tenant-batch partition rules (the multi-tenant coalesced solve,
# service/tenant.py): the coalescer stacks EVERY solve pytree leaf with a
# leading tenant axis, so one catch-all rule shards axis 0 of every leaf —
# each device holds T/D whole tenants and no collectives cross them (tenant
# solves are independent by construction).  The catch-all is what makes the
# rule set closed under new fused variants: the repair batch's extra
# positional pytrees (WarmCarry, RepairPlan, the synthesized ExistingStatic)
# and the ex-plane batch's ExistingState/ExistingStatic all stack with the
# same leading tenant axis and shard under this one rule, no per-variant
# additions needed (docs/SERVICE.md "Solve fusion").  Same rule-by-regex
# machinery as the catalog rules above, just a different axis and rule set.
TENANT_PARTITION_RULES: Tuple[Tuple[str, int], ...] = (
    (r".", 0),
)


def named_tree_map(fn, tree, path: str = ""):
    """tree_map with dotted field paths for namedtuple pytrees (the named
    partition-rule pattern): ``fn(path, leaf) -> leaf'``.  None subtrees pass
    through (optional ex/warm planes)."""
    if tree is None:
        return None
    if isinstance(tree, tuple) and hasattr(tree, "_fields"):
        return type(tree)(*(
            named_tree_map(fn, getattr(tree, f), f"{path}.{f}")
            for f in tree._fields
        ))
    if isinstance(tree, (list, tuple)):
        return type(tree)(
            named_tree_map(fn, v, f"{path}[{i}]") for i, v in enumerate(tree)
        )
    return fn(path, tree)


def _spec_for(path: str, axis_name: str, rules=CATALOG_PARTITION_RULES):
    for pattern, axis in rules:
        if re.search(pattern, path):
            return P(*([None] * axis), axis_name)
    return P()


def partition_specs(tree, axis_name: str = CATALOG_AXIS):
    """PartitionSpec pytree for a solve pytree: catalog-indexed leaves shard
    over ``axis_name`` (CATALOG_PARTITION_RULES), the rest replicate."""
    return named_tree_map(lambda p, _leaf: _spec_for(p, axis_name), tree)


def mesh_shardings(tree, mesh: Mesh, axis_name: str = CATALOG_AXIS):
    """NamedSharding pytree mirroring ``partition_specs`` — the device_put
    layout for uploading solve inputs onto the mesh."""
    return named_tree_map(
        lambda p, _leaf: NamedSharding(mesh, _spec_for(p, axis_name)), tree
    )


# -- production mesh configuration -------------------------------------------


def _env_tristate(name: str) -> Optional[bool]:
    raw = os.environ.get(name)
    # empty string = unset = AUTO (the chart's documented "" default rides
    # through as an env var set to ""), not forced-off
    if raw is None or raw == "":
        return None
    return raw not in ("0", "false", "False")


def _mesh_device_count() -> int:
    """Devices the solve mesh may use.  Reads ``jax.devices()`` — callers gate
    on the env kill switch first so KC_SOLVER_MESH=0 never initializes a
    backend."""
    n = len(jax.devices())
    cap = os.environ.get("KC_SOLVER_MESH_DEVICES")
    if cap:
        try:
            n = max(1, min(n, int(cap)))
        except ValueError:
            pass
    return n


def solve_mesh_axes() -> Optional[Tuple[Tuple[str, int], ...]]:
    """The production solve mesh topology, or None for the unsharded path.

    ``KC_SOLVER_MESH=0`` → None; ``=1`` → a catalog mesh over the available
    devices (1-device degenerate mesh included — same code, singleton
    collectives); unset → AUTO, on exactly when the backend exposes more
    than one device.  The returned hashable descriptor — not a Mesh — is
    what rides the compile-cache key (one warm executable per topology);
    ``mesh_for`` reconstructs the Mesh deterministically from it."""
    forced = _env_tristate("KC_SOLVER_MESH")
    if forced is False:
        return None
    n = _mesh_device_count()
    if forced is None and n <= 1:
        return None
    return ((CATALOG_AXIS, n),)


def lane_mesh_axes() -> Optional[Tuple[Tuple[str, int], ...]]:
    """The 2D (catalog × lane) sweep mesh topology, or None.  Enabled by the
    same switch as the solve mesh; ``KC_SOLVER_MESH_SHAPE=CxL`` pins the
    split, default peels a lane axis of 2 off an even device count ≥ 4 so
    consolidation prefixes evaluate in parallel WITH catalog sharding.

    A pinned shape's catalog axis must DIVIDE the solve mesh size: the
    encode pads the catalog to multiples of the solve mesh
    (``catalog_pad_multiple``), so any other split would fail the even-split
    check on every snapshot and silently degrade the sweep to lanes-only —
    reject it up front and fall back to the default split instead."""
    axes = solve_mesh_axes()
    if axes is None:
        return None
    n = axes[0][1]
    shape = os.environ.get("KC_SOLVER_MESH_SHAPE", "")
    if shape:
        try:
            c, lanes = (int(v) for v in shape.lower().split("x"))
            if c * lanes <= n and c >= 1 and lanes >= 1 and n % c == 0:
                return ((CATALOG_AXIS, c), (LANE_AXIS, lanes))
        except ValueError:
            pass
        import logging

        logging.getLogger(__name__).warning(
            "KC_SOLVER_MESH_SHAPE=%r rejected (needs CxL with C*L <= %d and "
            "C dividing %d); using the default split", shape, n, n,
        )
    lanes = 2 if n >= 4 and n % 2 == 0 else 1
    return ((CATALOG_AXIS, n // lanes), (LANE_AXIS, lanes))


def tenant_partition_specs(tree):
    """PartitionSpec pytree for a tenant-stacked solve pytree: every leaf
    shards its leading (tenant) axis (TENANT_PARTITION_RULES)."""
    return named_tree_map(
        lambda p, _leaf: _spec_for(p, TENANT_AXIS, TENANT_PARTITION_RULES), tree
    )


def tenant_mesh_shardings(tree, mesh: Mesh):
    """NamedSharding pytree for a tenant-stacked solve pytree — the
    device_put layout for a coalesced batch's inputs."""
    return named_tree_map(
        lambda p, _leaf: NamedSharding(
            mesh, _spec_for(p, TENANT_AXIS, TENANT_PARTITION_RULES)
        ),
        tree,
    )


def tenant_mesh_axes(n_tenants: int) -> Optional[Tuple[Tuple[str, int], ...]]:
    """Mesh topology for one coalesced tenant batch, or None for the
    vmap-only (single-device) path.  Gated by the same KC_SOLVER_MESH switch
    as the solve mesh; the device count must divide the batch size so every
    shard holds whole tenants — otherwise the batch runs unsharded (always
    correct, the batched executable is the same vmap body)."""
    forced = _env_tristate("KC_SOLVER_MESH")
    if forced is False:
        return None
    n = _mesh_device_count()
    if forced is None and n <= 1:
        return None
    if n < 1 or n_tenants % n != 0:
        return None
    return ((TENANT_AXIS, n),)


def tenant_solve_callable(mesh_axes, base_plain, structs):
    """jit(shard_map(vmap(solve))) for one coalesced tenant batch: the batch
    splits over the mesh's tenant axis, each device vmaps its local tenants
    through the plain (collective-free) solve body.  ``structs`` are the
    tenant-STACKED positional arg pytrees (ShapeDtypeStructs or arrays);
    the caller memoizes (utils.compilecache.batched_solve_callable)."""
    mesh = mesh_for(mesh_axes)
    vmapped = jax.vmap(base_plain)
    in_specs = tuple(tenant_partition_specs(s) for s in structs)
    out_specs = tenant_partition_specs(jax.eval_shape(vmapped, *structs))
    return jax.jit(shard_map(
        vmapped, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        # every output leaf is sharded over the tenant axis (no replicated
        # outputs to verify) and tenants never exchange data; the coalesced
        # parity tests (tests/test_tenant_service.py) pin bit-identity
        check_rep=False,
    ))


def catalog_pad_multiple() -> int:
    """The multiple the encode pads the instance-type axis to so every mesh
    topology in play divides it (models.snapshot.encode_snapshot).  The lane
    mesh's catalog axis divides the solve mesh's, so the solve mesh size is
    the binding constraint."""
    axes = solve_mesh_axes()
    return axes[0][1] if axes is not None else 1


@functools.lru_cache(maxsize=8)
def mesh_for(mesh_axes: Tuple[Tuple[str, int], ...]) -> Mesh:
    """Deterministic Mesh for a topology descriptor: the first prod(sizes)
    devices of ``jax.devices()`` reshaped to the axis sizes.  Cached so every
    consumer of one topology shares one Mesh object (and jit caches key
    consistently on it)."""
    names = tuple(name for name, _ in mesh_axes)
    sizes = tuple(size for _, size in mesh_axes)
    total = int(np.prod(sizes))
    devices = jax.devices()
    if total > len(devices):
        raise ValueError(
            f"mesh {mesh_axes} needs {total} devices, have {len(devices)}"
        )
    return Mesh(np.array(devices[:total]).reshape(sizes), names)


def sharded_solve_callable(mesh_axes, base_with_axis, base_plain, structs,
                           donate_argnums=()):
    """jit(shard_map(...)) over the solve pytrees for one mesh topology.

    ``base_with_axis`` is the solve_core partial with
    ``catalog_axis=CATALOG_AXIS`` (collectives traced); ``base_plain`` the
    axis-free twin used only to eval_shape the output structure (outside the
    mesh no axis name is bound).  ``structs`` are the positional arg pytrees
    (ShapeDtypeStructs or arrays).  Returns the jitted callable; the caller
    memoizes (utils.compilecache keys it by topology + leaf signatures).

    ``donate_argnums`` threads buffer donation through the sharded build —
    the pipelined loop's warm-carry variant (utils.pipeline) donates the
    carry argument so mesh-sharded churn repairs reuse the carry's sharded
    device buffers in place; the sharding layout is unchanged (the carry's
    partition specs cover inputs AND outputs, CATALOG_PARTITION_RULES)."""
    mesh = mesh_for(mesh_axes)
    in_specs = tuple(partition_specs(s) for s in structs)
    out_specs = partition_specs(jax.eval_shape(base_plain, *structs))
    return jax.jit(shard_map(
        base_with_axis, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        # replicated out_specs are guaranteed by construction (every
        # cross-shard reduction is an exact collective inside the body);
        # check_rep's rewrite machinery cannot see through the class scan,
        # so the static claim stands in for it — the mesh parity fuzz
        # (tests/test_mesh_dispatch.py) pins the guarantee at runtime
        check_rep=False,
    ), donate_argnums=tuple(donate_argnums))


def default_mesh(n_devices: Optional[int] = None, axis: str = "replica") -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def default_mesh_2d(
    shape: Optional[Tuple[int, int]] = None, axes: Tuple[str, str] = ("replica", "lane")
) -> Mesh:
    """(replica × lane) mesh for crossed studies: Monte-Carlo scenarios on one
    axis, consolidation prefix lanes on the other.  Both axes are
    embarrassingly parallel, so on multi-slice hardware lay ``replica`` over
    DCN and keep ``lane`` within a slice — the only cross-device traffic is
    the result gather."""
    devices = jax.devices()
    if shape is None:
        n = len(devices)
        lanes = 1
        for candidate in range(int(np.sqrt(n)), 0, -1):
            if n % candidate == 0:
                lanes = candidate
                break
        shape = (n // lanes, lanes)
    r, l = shape
    return Mesh(np.array(devices[: r * l]).reshape(r, l), axes)


def solve_catalog_sharded(
    snapshot: EncodedSnapshot,
    mesh: Optional[Mesh] = None,
    axis: str = "lane",
    n_slots: int = 0,
):
    """The PROVISIONING solve with the catalog (instance-type) axis sharded
    across the mesh (VERDICT r4 #7 / BASELINE config 4) — now a thin wrapper
    over the PRODUCTION shard_map dispatcher (utils.compilecache.run_solve
    with ``mesh_axes``), kept as the named dryrun entry __graft_entry__ and
    the parity suites call.

    Why the catalog axis: class dedup collapses the pod axis to ~a dozen
    classes regardless of pod count (models/snapshot.py docstring), and the
    class scan's carry is inherently sequential — but per class step the hot
    planes are [N slots, I instance types] with per-I independence
    (_it_intersects, _capacity, _offering_ok) and only max/any/or reductions
    over I.  The shard_map body computes per-device [N, I/D] planes and
    finishes each reduction with one exact collective
    (ops.solve._imax/_isum), so the result is BIT-IDENTICAL to the
    single-device solve; bit-packed masks compose transparently (packing is
    elementwise over the trailing slot axis, per catalog row).

    The catalog pads to a device multiple with inert instance types
    (ops.solve.pad_catalog) — the padded I tail is never viable, so decode
    sees the same placements."""
    if mesh is None:
        mesh = default_mesh(axis=axis)
    if axis not in mesh.axis_names:
        axis = mesh.axis_names[-1]
    # shard as many ways as the given mesh's sharding axis — on a 2D mesh
    # P(axis) only splits the catalog that many ways
    axis_size = int(mesh.shape[axis])
    if n_slots <= 0:
        n_slots = solve_ops.estimate_slots(snapshot)

    cls, statics_arrays, key_has_bounds = solve_ops.prepare_host(snapshot)
    cls, statics_arrays = solve_ops.pad_catalog(cls, statics_arrays, axis_size)
    out = compilecache.run_solve(
        cls, statics_arrays, n_slots, key_has_bounds,
        n_passes=snapshot.scan_passes,
        features=solve_ops.snapshot_features(snapshot),
        mesh_axes=((CATALOG_AXIS, axis_size),),
    )
    from karpenter_core_tpu.utils import watchdog

    watchdog.run("solve.sync", jax.block_until_ready, out)
    return out


def perturb_spot_availability(
    snapshot: EncodedSnapshot, n_replicas: int, seed: int = 0, interruption_rate: float = 0.3
) -> jnp.ndarray:
    """bool[REP, I, Z, CT]: per-replica offering availability with spot
    offerings randomly interrupted — the scenario axis for the what-if sweep."""
    key = jax.random.PRNGKey(seed)
    avail = jnp.asarray(snapshot.it_avail)  # [I, Z, CT]
    is_spot = jnp.asarray(
        np.array([ct == "spot" for ct in snapshot.capacity_types], dtype=bool)
    )  # [CT]
    interrupted = (
        jax.random.uniform(key, (n_replicas,) + avail.shape) < interruption_rate
    ) & is_spot[None, None, None, :]
    return avail[None] & ~interrupted


def monte_carlo_solve(
    snapshot: EncodedSnapshot,
    n_replicas: int,
    mesh: Optional[Mesh] = None,
    seed: int = 0,
    interruption_rate: float = 0.3,
    n_slots: int = 0,
) -> dict:
    """Solve ``n_replicas`` perturbed snapshots in parallel across the mesh.

    Returns summary statistics (per-replica scheduled/failed/node counts and
    total cost, plus mean/min/max cost) — the cost-vs-disruption Pareto input.
    """
    if mesh is None:
        mesh = default_mesh()
    if n_slots <= 0:
        n_slots = solve_ops.estimate_slots(snapshot)

    cls, statics_arrays, key_has_bounds = solve_ops.prepare(snapshot)
    avail_r = perturb_spot_availability(snapshot, n_replicas, seed, interruption_rate)
    it_price = jnp.asarray(snapshot.it_price)

    # patch the availability plane by field name so a reordering of the
    # Statics tuple can't silently perturb the wrong tensor
    avail_idx = solve_ops.Statics._fields.index("it_avail")

    fn = _monte_carlo_fn(
        mesh, key_has_bounds, n_slots, snapshot.scan_passes, avail_idx,
        compilecache.snap_features(solve_ops.snapshot_features(snapshot)),
    )
    from karpenter_core_tpu.utils import watchdog

    with mesh:
        scheduled, failed, nodes, cost = fn(
            avail_r, cls, statics_arrays, it_price
        )
        # monte-carlo replicas block on one batched fetch: deadline-bounded
        # like every device→host barrier (utils/watchdog.py)
        scheduled, failed, nodes, cost = watchdog.run(
            "mesh.monte_carlo", jax.device_get,
            (scheduled, failed, nodes, cost), key=n_replicas,
        )
    return {
        "replicas": n_replicas,
        "scheduled": np.asarray(scheduled),
        "failed": np.asarray(failed),
        "nodes": np.asarray(nodes),
        "cost": np.asarray(cost),
        "cost_mean": float(np.mean(cost)),
        "cost_min": float(np.min(cost)),
        "cost_max": float(np.max(cost)),
        "failed_mean": float(np.mean(failed)),
    }


@functools.lru_cache(maxsize=16)
def _monte_carlo_fn(mesh, key_has_bounds, n_slots: int, n_passes: int,
                    avail_idx: int, features=None):
    """Cached jitted Monte-Carlo sweep.  The per-replica closure takes the
    snapshot tensors as ARGUMENTS (not captured values) so the cache key is
    the static config alone — a fresh closure per call would defeat JAX's
    compile cache and retrace every study."""

    def one_replica(avail, cls, statics_arrays, it_price):
        arrays = list(statics_arrays)
        arrays[avail_idx] = avail
        out = solve_ops.solve_core(
            cls, tuple(arrays), n_slots, key_has_bounds,
            n_passes=n_passes, features=features,
        )
        scheduled = jnp.sum(out.assign)
        failed = jnp.sum(out.failed)
        nodes = jnp.sum((out.state.pod_count > 0).astype(jnp.int32))
        prices = solve_ops.node_prices(out.state, it_price)
        cost = jnp.sum(jnp.where(jnp.isfinite(prices), prices, 0.0))
        return scheduled, failed, nodes, cost

    sharded = NamedSharding(mesh, P("replica"))
    return jax.jit(
        jax.vmap(one_replica, in_axes=(0, None, None, None)),
        in_shardings=(sharded, None, None, None),
        out_shardings=(sharded, sharded, sharded, sharded),
    )


def perturb_offering_availability(
    snapshot: EncodedSnapshot, risk, n_replicas: int, seed: int = 0
) -> jnp.ndarray:
    """bool[REP, I, Z, CT]: per-replica offering availability with every
    offering cell interrupted with ITS OWN prior probability — the policy
    risk planes (policy.planes) instead of ``perturb_spot_availability``'s
    one uniform spot rate.  Offerings with zero risk never drop, so a
    risk-free catalog reproduces the unperturbed solve in every replica."""
    key = jax.random.PRNGKey(seed)
    avail = jnp.asarray(snapshot.it_avail)  # [I, Z, CT]
    risk_arr = jnp.asarray(risk, dtype=jnp.float32)
    interrupted = (
        jax.random.uniform(key, (n_replicas,) + avail.shape) < risk_arr[None]
    )
    return avail[None] & ~interrupted


def policy_monte_carlo(
    snapshot: EncodedSnapshot,
    n_replicas: int,
    mesh: Optional[Mesh] = None,
    seed: int = 0,
    n_slots: int = 0,
) -> dict:
    """Risk-weighted policy variants over the Monte-Carlo replica machinery:
    sample one interruption OUTCOME per replica from the snapshot's
    per-offering risk priors (``pol_risk``), solve every outcome in parallel
    across the mesh, and pick the replica assignment minimizing risk-adjusted
    cost — fleet price plus an unschedulable-pod penalty that dominates any
    price difference, so a cheap fleet that strands pods under interruption
    never wins (docs/POLICY.md "Risk-weighted variants").

    Returns per-replica ``cost``/``failed``/``nodes`` arrays plus
    ``expected_cost`` (the mean risk-adjusted cost — the number a
    risk-averse objective reports for this fleet) and ``best_replica``."""
    if mesh is None:
        mesh = default_mesh()
    if n_slots <= 0:
        n_slots = solve_ops.estimate_slots(snapshot)
    risk = getattr(snapshot, "pol_risk", None)
    if risk is None:
        risk = np.zeros_like(np.asarray(snapshot.it_price))
    price = getattr(snapshot, "pol_price", None)
    if price is None:
        price = snapshot.it_price

    cls, statics_arrays, key_has_bounds = solve_ops.prepare(snapshot)
    avail_r = perturb_offering_availability(snapshot, risk, n_replicas, seed)
    it_price = jnp.asarray(price)
    avail_idx = solve_ops.Statics._fields.index("it_avail")

    fn = _monte_carlo_fn(
        mesh, key_has_bounds, n_slots, snapshot.scan_passes, avail_idx,
        compilecache.snap_features(solve_ops.snapshot_features(snapshot)),
    )
    from karpenter_core_tpu.utils import watchdog

    with mesh:
        scheduled, failed, nodes, cost = watchdog.run(
            "mesh.monte_carlo", jax.device_get,
            fn(avail_r, cls, statics_arrays, it_price), key=n_replicas,
        )
    cost = np.asarray(cost, dtype=np.float64)
    failed = np.asarray(failed, dtype=np.int64)
    # the penalty per unplaced pod dominates any achievable fleet price —
    # every open slot costs at most the max offering price, so max_price ×
    # n_slots bounds any replica's fleet cost and feasibility strictly
    # outranks price in the risk-adjusted ordering
    finite = np.asarray(price)[np.isfinite(price)]
    penalty = float(finite.max() if finite.size else 1.0) * max(n_slots, 1)
    adjusted = cost + failed * (penalty + 1.0)
    best = int(np.argmin(adjusted)) if len(adjusted) else 0
    return {
        "replicas": n_replicas,
        "scheduled": np.asarray(scheduled),
        "failed": failed,
        "nodes": np.asarray(nodes),
        "cost": cost,
        "adjusted_cost": adjusted,
        "expected_cost": float(np.mean(adjusted)) if len(adjusted) else 0.0,
        "cost_mean": float(np.mean(cost)) if len(cost) else 0.0,
        "cost_max": float(np.max(cost)) if len(cost) else 0.0,
        "best_replica": best,
        "best_cost": float(cost[best]) if len(cost) else 0.0,
        "feasible_replicas": int(np.sum(failed == 0)),
    }


@functools.lru_cache(maxsize=16)
def _crossed_grid_fn(mesh, key_has_bounds, n_slots: int, n_passes: int, avail_idx: int,
                     features=None):
    """Cached jitted crossed grid — a fresh closure per call would defeat
    JAX's compile cache (keyed on callable identity) and recompile the whole
    vmap-of-vmap solve every study (same pattern as
    ops.consolidate._lane_sweep_fn)."""
    rep, lane = mesh.axis_names

    def one_cell(avail, k, cls, statics_arrays, ex_state, ex_static, rank, counts):
        arrays = list(statics_arrays)
        arrays[avail_idx] = avail
        subset = rank < k
        ex = ex_state._replace(open_=ex_state.open_ & ~subset)
        displaced = jnp.sum(counts * subset[None, :].astype(jnp.int32), axis=-1)
        cls_k = cls._replace(count=cls.count + displaced)
        out = solve_ops.solve_core(
            cls_k, tuple(arrays), n_slots, key_has_bounds, ex, ex_static,
            n_passes=n_passes, features=features,
        )
        return jnp.sum(out.failed), out.state.n_next

    batch_none = (None,) * 6
    grid = jax.vmap(
        jax.vmap(one_cell, in_axes=(None, 0) + batch_none),
        in_axes=(0, None) + batch_none,
    )
    return jax.jit(
        grid,
        in_shardings=(NamedSharding(mesh, P(rep)), NamedSharding(mesh, P(lane)))
        + (None,) * 6,
        out_shardings=(
            NamedSharding(mesh, P(rep, lane)),
            NamedSharding(mesh, P(rep, lane)),
        ),
    )


def crossed_consolidation_study(
    snapshot: EncodedSnapshot,
    ex_state,
    ex_static,
    candidate_rank: np.ndarray,  # i32[E] disruption order, big = not candidate
    ex_cls_count: np.ndarray,  # i32[C, E] candidate pods per class per node
    prefix_sizes: np.ndarray,  # i32[S]
    n_replicas: int,
    mesh: Optional[Mesh] = None,
    seed: int = 0,
    interruption_rate: float = 0.3,
    n_slots: int = 16,
) -> dict:
    """Risk-aware consolidation: every (spot-interruption scenario r,
    consolidation prefix k) pair is one simulation — close the first-k
    candidates AND apply replica r's perturbed offering availability, then
    re-schedule.  The [R, S] grid shards over a 2D (replica × lane) mesh
    (vmap∘vmap; XLA partitions both batch axes, no collectives until the
    result gather).

    Returns the failed/new-node grids plus ``safe_prefix``: per replica, the
    largest prefix whose simulation fully re-schedules — min over replicas is
    the consolidation depth that is safe under every sampled interruption
    scenario (the 1D sweep in ops.consolidate answers only the rate-0 row)."""
    if mesh is None:
        mesh = default_mesh_2d()
    n_rep_axis, n_lane_axis = (mesh.shape[name] for name in mesh.axis_names)

    cls, statics_arrays, key_has_bounds = solve_ops.prepare(snapshot)
    avail_r = perturb_spot_availability(snapshot, n_replicas, seed, interruption_rate)
    avail_idx = solve_ops.Statics._fields.index("it_avail")

    sizes = jnp.asarray(prefix_sizes, dtype=jnp.int32)
    pad_s = (-len(prefix_sizes)) % n_lane_axis
    if pad_s:
        sizes = jnp.concatenate([sizes, jnp.repeat(sizes[-1:], pad_s)])
    pad_r = (-n_replicas) % n_rep_axis
    if pad_r:
        avail_r = jnp.concatenate([avail_r, avail_r[-1:].repeat(pad_r, axis=0)])

    fn = _crossed_grid_fn(
        mesh, key_has_bounds, n_slots, snapshot.scan_passes, avail_idx,
        compilecache.snap_features(
            solve_ops.features_with_existing(snapshot, ex_static)
        ),
    )
    from karpenter_core_tpu.utils import watchdog

    with mesh:
        failed, n_new = watchdog.run(
            "mesh.monte_carlo", jax.device_get,
            fn(
                avail_r, sizes, cls, statics_arrays, ex_state, ex_static,
                jnp.asarray(candidate_rank), jnp.asarray(ex_cls_count),
            ),
            key="crossed",
        )
    failed = np.asarray(failed)[:n_replicas, : len(prefix_sizes)]
    n_new = np.asarray(n_new)[:n_replicas, : len(prefix_sizes)]

    feasible = failed == 0  # [R, S]
    sizes_np = np.asarray(prefix_sizes)
    # rows with no feasible prefix reduce to 0 (sizes are >= 1)
    safe_prefix = np.max(np.where(feasible, sizes_np[None, :], 0), axis=1)
    return {
        "failed": failed,
        "n_new": n_new,
        "safe_prefix": safe_prefix,  # per replica
        "safe_prefix_all": int(safe_prefix.min()) if len(safe_prefix) else 0,
    }
