"""State informers: pump watch events into the Cluster cache.

Mirror of /root/reference/pkg/controllers/state/informer/{pod,node,provisioner}.go:
three thin controllers translating object events into Cluster updates; the
provisioner informer records a consolidation change on spec-generation change.
"""

from __future__ import annotations

import threading
from typing import Optional

from karpenter_core_tpu.apis.objects import CSINode, Node, Pod
from karpenter_core_tpu.apis.v1alpha5 import Provisioner
from karpenter_core_tpu.state.cluster import Cluster


class NodeInformer:
    def __init__(self, cluster: Cluster, pod_informer: "Optional[PodInformer]" = None) -> None:
        self.cluster = cluster
        # node arrivals re-drive pods parked on unknown nodes (see PodInformer)
        self.pod_informer = pod_informer

    def start(self, kube_client) -> None:
        kube_client.watch(Node, self.on_event)

    def on_event(self, event_type: str, node: Node) -> None:
        if event_type == "DELETED":
            self.cluster.delete_node(node.name)
        else:
            self.cluster.update_node(node)
            if self.pod_informer is not None:
                self.pod_informer.retry_pending(node.name)


class PodInformer:
    """Pumps pod events into the Cluster, parking pods whose node the cluster
    has not ingested yet.  The in-memory KubeClient delivers events in global
    mutation order so the node always precedes its pods; the apiserver
    backend's per-kind reflector threads give no such cross-kind ordering, and
    a bound pod applied before its node would silently miss usage accounting
    until the next pod event.  Parked pods re-apply when the node lands."""

    def __init__(self, cluster: Cluster) -> None:
        self.cluster = cluster
        self._pending: dict = {}  # node name -> {pod key: Pod}
        self._lock = threading.Lock()

    def start(self, kube_client) -> None:
        kube_client.watch(Pod, self.on_event)

    def on_event(self, event_type: str, pod: Pod) -> None:
        key = (pod.namespace, pod.name)
        if event_type == "DELETED":
            with self._lock:
                for parked in self._pending.values():
                    parked.pop(key, None)
            self.cluster.delete_pod(key)
            return
        err = self.cluster.update_pod(pod)
        if err is not None and pod.spec.node_name:
            with self._lock:
                self._pending.setdefault(pod.spec.node_name, {})[key] = pod
            # close the check-then-park window: the node may have landed
            # between update_pod failing and the park (its retry_pending
            # would have popped an empty dict) — re-drive immediately
            self.retry_pending(pod.spec.node_name)

    def retry_pending(self, node_name: str) -> None:
        with self._lock:
            parked = self._pending.pop(node_name, None)
        if not parked:
            return
        for key, pod in parked.items():
            if self.cluster.update_pod(pod) is not None and pod.spec.node_name:
                # still unapplicable (node gone again mid-retry): re-park
                # rather than dropping the pod's usage accounting
                with self._lock:
                    self._pending.setdefault(pod.spec.node_name, {})[key] = pod


class ProvisionerInformer:
    """Records a consolidation change when a provisioner's spec changes
    (informer/provisioner.go:52-65 generation-change filter)."""

    def __init__(self, cluster: Cluster) -> None:
        self.cluster = cluster
        self._generations = {}

    def start(self, kube_client) -> None:
        kube_client.watch(Provisioner, self.on_event)

    def on_event(self, event_type: str, provisioner: Provisioner) -> None:
        if event_type == "DELETED":
            self._generations.pop(provisioner.name, None)
            self.cluster.record_consolidation_change()
            return
        gen = provisioner.metadata.generation
        if self._generations.get(provisioner.name) != gen:
            self._generations[provisioner.name] = gen
            self.cluster.record_consolidation_change()


class CSINodeInformer:
    """Re-hydrates a node's volume attach limits when its CSINode appears or
    changes — CSI driver registration always lands after node creation, so
    limits would otherwise stay stale until the next node event."""

    def __init__(self, cluster: Cluster) -> None:
        self.cluster = cluster
        self._kube = None

    def start(self, kube_client) -> None:
        self._kube = kube_client
        kube_client.watch(CSINode, self.on_event)

    def on_event(self, event_type: str, csi_node: CSINode) -> None:
        node = self._kube.get(Node, csi_node.metadata.name)
        if node is not None:
            self.cluster.update_node(node)


def start_informers(cluster: Cluster, kube_client) -> tuple:
    pod = PodInformer(cluster)
    node = NodeInformer(cluster, pod_informer=pod)
    provisioner = ProvisionerInformer(cluster)
    csi_node = CSINodeInformer(cluster)
    # nodes before pods: the registration replay (and the apiserver backend's
    # warm-start LIST) must ingest nodes before the pods bound to them
    node.start(kube_client)
    pod.start(kube_client)
    provisioner.start(kube_client)
    csi_node.start(kube_client)
    return node, pod, provisioner, csi_node
