"""State informers: pump watch events into the Cluster cache.

Mirror of /root/reference/pkg/controllers/state/informer/{pod,node,provisioner}.go:
three thin controllers translating object events into Cluster updates; the
provisioner informer records a consolidation change on spec-generation change.
"""

from __future__ import annotations

from karpenter_core_tpu.apis.objects import CSINode, Node, Pod
from karpenter_core_tpu.apis.v1alpha5 import Provisioner
from karpenter_core_tpu.state.cluster import Cluster


class NodeInformer:
    def __init__(self, cluster: Cluster) -> None:
        self.cluster = cluster

    def start(self, kube_client) -> None:
        kube_client.watch(Node, self.on_event)

    def on_event(self, event_type: str, node: Node) -> None:
        if event_type == "DELETED":
            self.cluster.delete_node(node.name)
        else:
            self.cluster.update_node(node)


class PodInformer:
    def __init__(self, cluster: Cluster) -> None:
        self.cluster = cluster

    def start(self, kube_client) -> None:
        kube_client.watch(Pod, self.on_event)

    def on_event(self, event_type: str, pod: Pod) -> None:
        if event_type == "DELETED":
            self.cluster.delete_pod((pod.namespace, pod.name))
        else:
            self.cluster.update_pod(pod)


class ProvisionerInformer:
    """Records a consolidation change when a provisioner's spec changes
    (informer/provisioner.go:52-65 generation-change filter)."""

    def __init__(self, cluster: Cluster) -> None:
        self.cluster = cluster
        self._generations = {}

    def start(self, kube_client) -> None:
        kube_client.watch(Provisioner, self.on_event)

    def on_event(self, event_type: str, provisioner: Provisioner) -> None:
        if event_type == "DELETED":
            self._generations.pop(provisioner.name, None)
            self.cluster.record_consolidation_change()
            return
        gen = provisioner.metadata.generation
        if self._generations.get(provisioner.name) != gen:
            self._generations[provisioner.name] = gen
            self.cluster.record_consolidation_change()


class CSINodeInformer:
    """Re-hydrates a node's volume attach limits when its CSINode appears or
    changes — CSI driver registration always lands after node creation, so
    limits would otherwise stay stale until the next node event."""

    def __init__(self, cluster: Cluster) -> None:
        self.cluster = cluster
        self._kube = None

    def start(self, kube_client) -> None:
        self._kube = kube_client
        kube_client.watch(CSINode, self.on_event)

    def on_event(self, event_type: str, csi_node: CSINode) -> None:
        node = self._kube.get(Node, csi_node.metadata.name)
        if node is not None:
            self.cluster.update_node(node)


def start_informers(cluster: Cluster, kube_client) -> tuple:
    node = NodeInformer(cluster)
    pod = PodInformer(cluster)
    provisioner = ProvisionerInformer(cluster)
    csi_node = CSINodeInformer(cluster)
    node.start(kube_client)
    pod.start(kube_client)
    provisioner.start(kube_client)
    csi_node.start(kube_client)
    return node, pod, provisioner, csi_node
