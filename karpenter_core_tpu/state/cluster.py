"""In-memory cluster state: the input snapshot for every solve.

Mirror of /root/reference/pkg/controllers/state/{cluster.go:42-407,
node.go:38-190}: nodes keyed by provider id, pod→node bindings, an
anti-affinity pod index, node nomination with a TTL window, mark-for-deletion,
and a consolidation-state timestamp that gates deprovisioning work.
"""

from __future__ import annotations

import copy
import threading
from typing import Callable, Dict, List, Optional, Tuple

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import Node, Pod, Taint
from karpenter_core_tpu.apis.v1alpha5 import Provisioner
from karpenter_core_tpu.scheduling import HostPortUsage, VolumeCount, VolumeUsage
from karpenter_core_tpu.utils import pod as pod_util
from karpenter_core_tpu.utils import resources as resources_util
from karpenter_core_tpu.utils.clock import Clock

TAINT_NODE_NOT_READY = "node.kubernetes.io/not-ready"
TAINT_NODE_UNREACHABLE = "node.kubernetes.io/unreachable"


class StateNode:
    """state.Node: a node with cached pod usage and inflight capacity."""

    def __init__(self, node: Node, kube_client=None) -> None:
        self.node = node
        self.inflight_allocatable: resources_util.ResourceList = {}
        self.inflight_capacity: resources_util.ResourceList = {}
        self.startup_taints: List[Taint] = []
        self.daemonset_requests: Dict[Tuple[str, str], resources_util.ResourceList] = {}
        self.daemonset_limits: Dict[Tuple[str, str], resources_util.ResourceList] = {}
        self.pod_requests: Dict[Tuple[str, str], resources_util.ResourceList] = {}
        self.pod_limits: Dict[Tuple[str, str], resources_util.ResourceList] = {}
        self._host_port_usage = HostPortUsage()
        self._volume_usage = VolumeUsage(kube_client)
        self._volume_limits = VolumeCount()
        self.marked_for_deletion = False
        self.nominated_until = 0.0

    # -- predicates ------------------------------------------------------------

    def initialized(self) -> bool:
        return self.node.metadata.labels.get(labels_api.LABEL_NODE_INITIALIZED) == "true"

    def owned(self) -> bool:
        return bool(self.node.metadata.labels.get(labels_api.PROVISIONER_NAME_LABEL_KEY))

    def marked(self) -> bool:
        return self.marked_for_deletion or self.node.metadata.deletion_timestamp is not None

    def nominated(self, clock: Clock) -> bool:
        return self.nominated_until > clock.now()

    # -- resources (node.go:80-145) ---------------------------------------------

    def taints(self) -> List[Taint]:
        """Node taints minus ephemeral/startup taints (node.go:61-78)."""
        ephemeral = [
            Taint(key=TAINT_NODE_NOT_READY, effect="NoSchedule"),
            Taint(key=TAINT_NODE_UNREACHABLE, effect="NoSchedule"),
        ]
        if not self.initialized() and self.owned():
            ephemeral.extend(self.startup_taints)
        return [
            t
            for t in self.node.spec.taints
            if not any(
                e.key == t.key and e.value == t.value and e.effect == t.effect
                for e in ephemeral
            )
        ]

    def capacity(self) -> resources_util.ResourceList:
        if not self.initialized() and self.owned():
            out = dict(self.node.status.capacity)
            for name, qty in self.inflight_capacity.items():
                if resources_util.is_zero(out.get(name, 0.0)):
                    out[name] = qty
            return out
        return dict(self.node.status.capacity)

    def allocatable(self) -> resources_util.ResourceList:
        if not self.initialized() and self.owned():
            out = dict(self.node.status.allocatable)
            for name, qty in self.inflight_allocatable.items():
                if resources_util.is_zero(out.get(name, 0.0)):
                    out[name] = qty
            return out
        return dict(self.node.status.allocatable)

    def available(self) -> resources_util.ResourceList:
        return resources_util.subtract(self.allocatable(), self.pod_requests_total())

    def pod_requests_total(self) -> resources_util.ResourceList:
        return resources_util.merge(*self.pod_requests.values())

    def pod_limits_total(self) -> resources_util.ResourceList:
        return resources_util.merge(*self.pod_limits.values())

    def daemon_set_requests(self) -> resources_util.ResourceList:
        return resources_util.merge(*self.daemonset_requests.values())

    def daemon_set_limits(self) -> resources_util.ResourceList:
        return resources_util.merge(*self.daemonset_limits.values())

    def host_port_usage(self) -> HostPortUsage:
        return self._host_port_usage

    def volume_usage(self) -> VolumeUsage:
        return self._volume_usage

    def volume_limits(self) -> VolumeCount:
        return self._volume_limits

    def pod_count(self) -> int:
        return len(self.pod_requests)

    # -- pod tracking (node.go:161-180) ------------------------------------------

    def update_for_pod(self, pod: Pod) -> None:
        key = (pod.namespace, pod.name)
        self.pod_requests[key] = resources_util.requests_for_pods(pod)
        self.pod_limits[key] = resources_util.limits_for_pods(pod)
        if pod_util.is_owned_by_daemon_set(pod):
            self.daemonset_requests[key] = resources_util.requests_for_pods(pod)
            self.daemonset_limits[key] = resources_util.limits_for_pods(pod)
        self._host_port_usage.add(pod)
        self._volume_usage.add(pod)

    def cleanup_for_pod(self, key: Tuple[str, str]) -> None:
        self._host_port_usage.delete_pod(key)
        self._volume_usage.delete_pod(key)
        self.pod_requests.pop(key, None)
        self.pod_limits.pop(key, None)
        self.daemonset_requests.pop(key, None)
        self.daemonset_limits.pop(key, None)

    def deep_copy(self) -> "StateNode":
        out = StateNode(copy.deepcopy(self.node), self._volume_usage.kube_client)
        out.inflight_allocatable = dict(self.inflight_allocatable)
        out.inflight_capacity = dict(self.inflight_capacity)
        out.startup_taints = list(self.startup_taints)
        out.daemonset_requests = copy.deepcopy(self.daemonset_requests)
        out.daemonset_limits = copy.deepcopy(self.daemonset_limits)
        out.pod_requests = copy.deepcopy(self.pod_requests)
        out.pod_limits = copy.deepcopy(self.pod_limits)
        out._host_port_usage = self._host_port_usage.deep_copy()
        out._volume_usage = self._volume_usage.deep_copy()
        out._volume_limits = VolumeCount(self._volume_limits)
        out.marked_for_deletion = self.marked_for_deletion
        out.nominated_until = self.nominated_until
        return out


def nomination_window(settings) -> float:
    """2× batch max duration, min 10s (node.go:184-190)."""
    period = 2.0 * settings.batch_max_duration
    return max(period, 10.0)


class Cluster:
    def __init__(self, clock: Clock, kube_client, cloud_provider, settings=None) -> None:
        self.clock = clock
        self.kube_client = kube_client
        self.cloud_provider = cloud_provider
        self.settings = settings
        self._mu = threading.RLock()
        self.nodes: Dict[str, StateNode] = {}  # provider id -> node
        self.bindings: Dict[Tuple[str, str], str] = {}  # pod key -> node name
        self.name_to_provider_id: Dict[str, str] = {}
        self.anti_affinity_pods: Dict[Tuple[str, str], Pod] = {}
        self._consolidation_state: float = 0.0

    # -- iteration ---------------------------------------------------------------

    def for_pods_with_anti_affinity(self, fn: Callable[[Pod, Node], bool]) -> None:
        with self._mu:
            items = list(self.anti_affinity_pods.items())
        for key, pod in items:
            with self._mu:
                node_name = self.bindings.get(key)
                if node_name is None:
                    continue
                state_node = self.nodes.get(self.name_to_provider_id.get(node_name, ""))
                if state_node is None:
                    continue
                node = state_node.node
            if not fn(pod, node):
                return

    def for_each_node(self, fn: Callable[[StateNode], bool]) -> None:
        with self._mu:
            nodes = list(self.nodes.values())
        for node in nodes:
            if not fn(node):
                return

    def snapshot_nodes(self) -> List[StateNode]:
        """Deep-copied state nodes (the scheduler mutates them)."""
        with self._mu:
            return [n.deep_copy() for n in self.nodes.values()]

    # -- nomination / deletion marks ----------------------------------------------

    def is_node_nominated(self, name: str) -> bool:
        with self._mu:
            node = self.nodes.get(self.name_to_provider_id.get(name, ""))
            return node.nominated(self.clock) if node else False

    def nominate_node_for_pod(self, name: str) -> None:
        window = nomination_window(self.settings) if self.settings else 10.0
        with self._mu:
            node = self.nodes.get(self.name_to_provider_id.get(name, ""))
            if node is not None:
                node.nominated_until = self.clock.now() + window

    def mark_for_deletion(self, *names: str) -> None:
        with self._mu:
            for name in names:
                node = self.nodes.get(self.name_to_provider_id.get(name, ""))
                if node is not None:
                    node.marked_for_deletion = True

    def unmark_for_deletion(self, *names: str) -> None:
        with self._mu:
            for name in names:
                node = self.nodes.get(self.name_to_provider_id.get(name, ""))
                if node is not None:
                    node.marked_for_deletion = False

    # -- consolidation state (cluster.go:195-215) -----------------------------------

    def record_consolidation_change(self) -> None:
        self._consolidation_state = self.clock.now()

    def cluster_consolidation_state(self) -> float:
        cs = self._consolidation_state
        # force a refresh at least every 5 minutes
        if self.clock.now() > cs + 300.0:
            self.record_consolidation_change()
            return self._consolidation_state
        return cs

    # -- ingestion (cluster.go:152-196, 227-343) --------------------------------------

    def update_node(self, node: Node) -> Optional[str]:
        with self._mu:
            if not node.spec.provider_id:
                node.spec.provider_id = node.name
            old = self.nodes.get(node.spec.provider_id)
            new, err = self._new_state_from_node(node, old)
            if err is not None:
                return err
            self.nodes[node.spec.provider_id] = new
            self.name_to_provider_id[node.name] = node.spec.provider_id
            return None

    def delete_node(self, node_name: str) -> None:
        with self._mu:
            provider_id = self.name_to_provider_id.get(node_name)
            if provider_id:
                self.nodes.pop(provider_id, None)
                del self.name_to_provider_id[node_name]
                self.record_consolidation_change()

    def update_pod(self, pod: Pod) -> Optional[str]:
        err = None
        if pod_util.is_terminal(pod):
            self._update_node_usage_from_pod_completion((pod.namespace, pod.name))
        else:
            err = self._update_node_usage_from_pod(pod)
        self._update_pod_anti_affinities(pod)
        return err

    def delete_pod(self, pod_key: Tuple[str, str]) -> None:
        with self._mu:
            self.anti_affinity_pods.pop(pod_key, None)
        self._update_node_usage_from_pod_completion(pod_key)
        self.record_consolidation_change()

    def reset(self) -> None:
        with self._mu:
            self.nodes = {}
            self.name_to_provider_id = {}
            self.bindings = {}
            self.anti_affinity_pods = {}

    # -- internals -------------------------------------------------------------------

    def _new_state_from_node(
        self, node: Node, old: Optional[StateNode]
    ) -> Tuple[Optional[StateNode], Optional[str]]:
        n = StateNode(node, self.kube_client)
        if old is not None:
            n.marked_for_deletion = old.marked_for_deletion
            n.nominated_until = old.nominated_until
        for populate in (
            self._populate_startup_taints,
            self._populate_inflight,
            self._populate_resource_requests,
            self._populate_volume_limits,
        ):
            err = populate(n)
            if err is not None:
                return None, err
        self._trigger_consolidation_on_change(old, n)
        return n, None

    def _get_provisioner(self, n: StateNode) -> Optional[Provisioner]:
        name = n.node.metadata.labels.get(labels_api.PROVISIONER_NAME_LABEL_KEY)
        if not name:
            return None
        return self.kube_client.get(Provisioner, name)

    def _populate_startup_taints(self, n: StateNode) -> Optional[str]:
        if not n.owned():
            return None
        provisioner = self._get_provisioner(n)
        if provisioner is not None:
            n.startup_taints = list(provisioner.spec.startup_taints)
        return None

    def _populate_inflight(self, n: StateNode) -> Optional[str]:
        if not n.owned():
            return None
        provisioner = self._get_provisioner(n)
        if provisioner is None:
            return None
        instance_types = self.cloud_provider.get_instance_types(provisioner)
        it_name = n.node.metadata.labels.get(labels_api.LABEL_INSTANCE_TYPE_STABLE)
        instance_type = next((it for it in instance_types if it.name == it_name), None)
        if instance_type is None:
            return f"instance type {it_name!r} not found"
        n.inflight_capacity = dict(instance_type.capacity)
        n.inflight_allocatable = instance_type.allocatable()
        return None

    def _populate_volume_limits(self, n: StateNode) -> Optional[str]:
        csi_node = self.kube_client.get_csi_node(n.node.name)
        if csi_node is not None:
            for driver in csi_node.drivers:
                if driver.allocatable_count is not None:
                    n._volume_limits[driver.name] = driver.allocatable_count
        return None

    def _populate_resource_requests(self, n: StateNode) -> Optional[str]:
        pods = self.kube_client.list_pods(
            selector=lambda p: p.spec.node_name == n.node.name
        )
        for pod in pods:
            if pod_util.is_terminal(pod):
                continue
            self._cleanup_old_bindings(pod)
            n.update_for_pod(pod)
            self.bindings[(pod.namespace, pod.name)] = pod.spec.node_name
        return None

    def _update_node_usage_from_pod(self, pod: Pod) -> Optional[str]:
        if not pod.spec.node_name:
            return None
        with self._mu:
            node = self.nodes.get(self.name_to_provider_id.get(pod.spec.node_name, ""))
            if node is None:
                return f"node {pod.spec.node_name} not found"
            self._cleanup_old_bindings(pod)
            node.update_for_pod(pod)
            self.bindings[(pod.namespace, pod.name)] = pod.spec.node_name
            return None

    def _update_node_usage_from_pod_completion(self, pod_key: Tuple[str, str]) -> None:
        with self._mu:
            node_name = self.bindings.pop(pod_key, None)
            if node_name is None:
                return
            node = self.nodes.get(self.name_to_provider_id.get(node_name, ""))
            if node is not None:
                node.cleanup_for_pod(pod_key)

    def _cleanup_old_bindings(self, pod: Pod) -> None:
        key = (pod.namespace, pod.name)
        old_node_name = self.bindings.get(key)
        if old_node_name is not None:
            if old_node_name == pod.spec.node_name:
                return
            old_node = self.nodes.get(self.name_to_provider_id.get(old_node_name, ""))
            if old_node is not None:
                old_node.cleanup_for_pod(key)
                del self.bindings[key]
        self.record_consolidation_change()

    def _update_pod_anti_affinities(self, pod: Pod) -> None:
        key = (pod.namespace, pod.name)
        with self._mu:
            if pod_util.has_pod_anti_affinity(pod):
                self.anti_affinity_pods[key] = pod
            else:
                self.anti_affinity_pods.pop(key, None)

    def _trigger_consolidation_on_change(
        self, old: Optional[StateNode], new: Optional[StateNode]
    ) -> None:
        if old is None or new is None:
            self.record_consolidation_change()
            return
        if old.initialized() != new.initialized():
            self.record_consolidation_change()
            return
        if old.marked() != new.marked():
            self.record_consolidation_change()
