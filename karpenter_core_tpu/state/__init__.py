from karpenter_core_tpu.state.cluster import Cluster, StateNode

__all__ = ["Cluster", "StateNode"]
