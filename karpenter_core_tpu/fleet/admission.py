"""Fleet-level admission: token buckets at the router.

Per-replica buckets alone over-admit a fleet: N replicas each granting a
tenant's full ``rate_per_s`` means N× the intended rate as soon as routing
spreads (and exactly that bug pre-dated the fleet: every process read the
same KC_TENANT_RATE).  The router is the single front door, so the
fleet-level buckets live HERE, shaped by the UNSCALED tenant config — and
the replicas scale their local buckets down by 1/N as a backstop
(TenantConfig.fleet_scaled), so a client dialing a replica directly still
cannot exceed its fair share by more than one replica's slice.

Shed responses carry the same machine-parseable ``retry-after-s=`` hint the
replica plane emits, computed from the bucket's actual next-token time —
clients cannot tell which layer shed them, and their pacing stays exact.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional, Tuple

from karpenter_core_tpu.service import tenant as tenant_mod
from karpenter_core_tpu.utils import retry
from karpenter_core_tpu.utils.clock import Clock

# the router never keeps per-tenant sessions, so its bucket map is bounded
# only by this LRU cap — far above any real tenant population, small enough
# that an id-spraying client cannot balloon router memory
MAX_TENANT_BUCKETS = 4096


class FleetAdmission:
    """Per-tenant RetryBudget buckets keyed by tenant id, LRU-bounded."""

    def __init__(self, config: Optional[tenant_mod.TenantConfig] = None, *,
                 clock: Optional[Clock] = None,
                 max_tenants: int = MAX_TENANT_BUCKETS) -> None:
        self.config = config or tenant_mod.TenantConfig.from_env()
        self.clock = clock or Clock()
        self.max_tenants = max(int(max_tenants), 1)
        self._buckets: "OrderedDict[str, retry.RetryBudget]" = OrderedDict()

    def _bucket(self, tenant_id: str, weight: Optional[float]) -> retry.RetryBudget:
        resolved = self.config.resolve_weight(tenant_id, weight)
        budget, window_s = self.config.bucket_shape(resolved)
        bucket = self._buckets.get(tenant_id)
        if bucket is None:
            bucket = retry.RetryBudget(
                self.clock, budget=budget, window_s=window_s,
                name=f"fleet:{tenant_id}",
            )
            self._buckets[tenant_id] = bucket
            while len(self._buckets) > self.max_tenants:
                self._buckets.popitem(last=False)
        else:
            self._buckets.move_to_end(tenant_id)
            bucket.reconfigure(budget, window_s)
        return bucket

    def admit(self, tenant_id: str, weight: Optional[float] = None
              ) -> Tuple[bool, float]:
        """(admitted, retry_after_s) — the hint is the bucket's exact
        next-token time, floored like the replica plane's rate shed."""
        bucket = self._bucket(tenant_id, weight)
        if bucket.allow():
            return True, 0.0
        return False, max(bucket.next_token_s(), 0.05)

    @staticmethod
    def shed_detail(retry_after_s: float) -> str:
        """The abort detail: same hint grammar as tenant-plane sheds
        (service/tenant.py parse_retry_after reads it back verbatim)."""
        return (
            f"fleet-shed reason=rate "
            f"{tenant_mod.RETRY_AFTER_PREFIX}{retry_after_s:.3f}"
        )
