"""Replicated solver fleet: router, session checkpoints, warm failover.

One replica of the snapshot-solver service holds each tenant's solve lineage
(session + warm carry + journal chain).  The fleet layer makes that lineage
SURVIVABLE and the replica set HORIZONTAL:

  fleet/ring.py        consistent-hash tenant→replica placement (bounded load)
  fleet/lease.py       replica liveness: lease heartbeats over the existing
                       LeaseGet/LeaseApply CAS plane
  fleet/checkpoint.py  tensor-level session checkpoints — one deserialize
                       restores a warm lineage instead of replaying N deltas
  fleet/admission.py   fleet-level token buckets at the router (the single
                       admission point; per-replica buckets stay as backstop)
  fleet/router.py      the thin forwarding router + failover + rebalancing
  fleet/replica_main.py subprocess entrypoint for the multi-process soak

This module holds only the shared config (``FleetLocal``) and the failover
outcome counter, so importing ``karpenter_core_tpu.fleet`` never drags in
grpc or the service.  ``KC_FLEET=0`` (or an empty ``KC_FLEET_MAP``) disables
everything: the service serves byte-identical responses to a fleetless build
(pinned by tests/test_fleet_router.py wire-regression).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

from karpenter_core_tpu.metrics import REGISTRY

from karpenter_core_tpu.fleet.ring import FleetMap, HashRing  # noqa: F401

FAILOVER_TOTAL = REGISTRY.counter(
    "karpenter_fleet_failover_total",
    "Tenant failover adoptions by outcome: warm (tensor checkpoint restored "
    "and digest-verified), replay (checkpoint missing/stale/corrupt — "
    "lineage rebuilt from a peer's journal chain), reanchor (no restorable "
    "artifact — the tenant re-anchors session-lost).",
    ("outcome",),
)


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_i(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


@dataclass
class FleetLocal:
    """One replica's (or the router's) view of the fleet configuration.

    ``None`` from :meth:`from_env` means "not in a fleet" — every caller
    treats that as the fleetless fast path.
    """

    directory: str
    replica_id: str = ""
    fleet_map: FleetMap = field(default_factory=FleetMap)
    ckpt_every: int = 8
    heartbeat_s: float = 2.0
    lease_ttl_s: float = 10.0
    router_address: str = ""

    @classmethod
    def from_env(cls) -> Optional["FleetLocal"]:
        if os.environ.get("KC_FLEET", "0") != "1":
            return None
        directory = os.environ.get("KC_FLEET_DIR", "")
        if not directory:
            return None
        return cls(
            directory=directory,
            replica_id=os.environ.get("KC_FLEET_REPLICA", ""),
            fleet_map=FleetMap.from_env(),
            ckpt_every=max(_env_i("KC_FLEET_CKPT_EVERY", 8), 1),
            heartbeat_s=max(_env_f("KC_FLEET_HEARTBEAT_S", 2.0), 0.05),
            lease_ttl_s=max(_env_f("KC_FLEET_LEASE_TTL_S", 10.0), 0.25),
            router_address=os.environ.get("KC_FLEET_ROUTER", ""),
        )

    @property
    def size(self) -> int:
        return self.fleet_map.size

    def checkpoint_dir(self) -> str:
        return os.path.join(self.directory, "checkpoints")

    def journal_root(self) -> str:
        return os.path.join(self.directory, "journals")

    def journal_dir(self, replica_id: Optional[str] = None) -> str:
        return os.path.join(self.journal_root(), replica_id or self.replica_id)

    def lease_path(self) -> str:
        return os.path.join(self.directory, "leases.json")
