"""Tensor-level session checkpoints: one deserialize restores a warm lineage.

The durable-session journal (service/journal.py) already makes a tenant's
solve lineage recoverable — by REPLAYING the anchor + every delta request,
one device solve each.  At fleet scale that rung is too slow for failover: a
peer adopting a 64-delta tenant would re-run 64 solves before answering.
This module serializes the lineage's *state* instead of its *history*:

  frame 1  header   — tenant identity, lineage ``lineage_state()`` (the
                      never-trust verification summary), synthetic-uid bases,
                      writer replica + clock time
  frame 2  anchor   — the RAW wire bytes of the last FULL-solve request
  frame 3  tensors  — the padded SolvePrep planes, the warm scan carry, the
                      cumulative assignment planes, and the host bookkeeping
                      (IncrementalSolveSession.export_lineage), msgpack-coded
                      through a small typed codec (ndarrays, NamedTuples)
  frame 4  trailer  — sha256 over frames 1-3's payload bytes
                      (models.store.content_digest)

Frames reuse the journal's exact crc32c framing (``read_frames(magic=...)``)
under a distinct file magic, so torn/corrupt tails are detected by the same
discipline; the whole-file trailer digest catches anything subtler.  A
checkpoint that fails ANY check is treated as missing — the restore ladder
(service/snapshot_channel.py ``_fleet_adopt``) falls to journal replay, then
to the session-lost re-anchor.  Never a stale answer.

Restore (``restore_session``) is never-trust end to end: the adopting
replica re-decodes the checkpointed anchor request with its OWN decoder,
re-encodes and re-commits the snapshot, and requires the fresh commit's
plane digests — and, after ``adopt_restored``, the full ``lineage_state()``
— to equal the checkpointed ones bit for bit before the lineage serves.
"""

from __future__ import annotations

import hashlib
import logging
import os
import re
import threading
from typing import Dict, List, NamedTuple, Optional, Tuple

import msgpack
import numpy as np

from karpenter_core_tpu.metrics import REGISTRY
from karpenter_core_tpu.models.store import content_digest
from karpenter_core_tpu.service import journal as journal_mod
from karpenter_core_tpu.utils.clock import Clock

log = logging.getLogger(__name__)

MAGIC = b"KCFC1\n"
FORMAT = 1

STATUS_OK = journal_mod.STATUS_OK
STATUS_MISSING = journal_mod.STATUS_MISSING
# the whole-file trailer digest did not match (or the frame set was
# structurally wrong): framing survived, content cannot be trusted
STATUS_DIGEST = "digest"

CHECKPOINT_TOTAL = REGISTRY.counter(
    "karpenter_fleet_checkpoint_total",
    "Fleet session-checkpoint writes by result: written (fsynced and "
    "atomically published), skipped (no warm lineage / no anchor bytes to "
    "stamp), error (export or I/O failed — the tenant keeps its previous "
    "checkpoint, the journal stays the fallback rung), gc (an older "
    "generation removed by the KC_FLEET_CHECKPOINT_KEEP retention sweep).",
    ("result",),
)
CHECKPOINT_BYTES = REGISTRY.histogram(
    "karpenter_fleet_checkpoint_bytes",
    "Published fleet session-checkpoint file sizes in bytes (header + "
    "anchor request + tensor planes + trailer).",
    (),
    buckets=[4096.0, 65536.0, 262144.0, 1048576.0, 4194304.0,
             16777216.0, 67108864.0],
)


class FleetRestoreError(Exception):
    """A checkpoint failed never-trust verification at restore — callers
    degrade to the journal-replay rung."""


# -- typed msgpack codec ------------------------------------------------------
#
# export_lineage's value tree holds numpy/jax ndarrays and the kernel's
# NamedTuples (SolvePrep and its members).  msgpack sees none of those, so
# each is wrapped in a ``{"__kc__": tag, ...}`` dict; everything else must
# already be a msgpack scalar/list/dict.  Decoding resolves NamedTuple class
# names through an explicit registry — never arbitrary import — so a crafted
# checkpoint cannot name a class into existence (and the registry doubles as
# the format's schema: an unknown name means an incompatible writer).


def _nt_registry() -> Dict[str, type]:
    from karpenter_core_tpu.ops import masks as mask_ops
    from karpenter_core_tpu.ops import solve as solve_ops
    from karpenter_core_tpu.policy.planes import ObjectivePlanes
    from karpenter_core_tpu.solver.tpu import SolvePrep

    classes = (
        SolvePrep,
        # SolvePrep.pol (the relax family's objective sheet) rides the
        # checkpointed prep when present
        ObjectivePlanes,
        mask_ops.ReqTensor,
        solve_ops.ClassTensors,
        solve_ops.StaticArrays,
        solve_ops.SnapshotFeatures,
        solve_ops.NodeState,
        solve_ops.ExistingState,
        solve_ops.ExistingStatic,
        solve_ops.TopoCounts,
        solve_ops.WarmCarry,
    )
    return {c.__name__: c for c in classes}


def _dtype_of(name: str):
    try:
        return np.dtype(name)
    except TypeError:
        # extension dtypes (bfloat16) register through ml_dtypes — jax
        # imports it, but resolve explicitly so decode order can't matter
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def enc(x):
    """Value tree → msgpack-able tree (see module docstring)."""
    if x is None or isinstance(x, (bool, int, float, str, bytes)):
        return x
    if isinstance(x, np.generic):
        arr = np.asarray(x)
        return {"__kc__": "np", "d": arr.dtype.name, "b": arr.tobytes()}
    if isinstance(x, tuple) and hasattr(x, "_fields"):
        return {"__kc__": "nt", "c": type(x).__name__,
                "f": [enc(v) for v in x]}
    if isinstance(x, tuple):
        return {"__kc__": "tu", "v": [enc(v) for v in x]}
    if isinstance(x, list):
        return [enc(v) for v in x]
    if isinstance(x, dict):
        return {"__kc__": "map", "v": [[enc(k), enc(v)] for k, v in x.items()]}
    if hasattr(x, "dtype") and hasattr(x, "shape"):
        # NOT ascontiguousarray: it promotes 0-d arrays to 1-d, and
        # tobytes() already emits C order for any layout
        arr = np.asarray(x)
        return {"__kc__": "nd", "d": arr.dtype.name,
                "s": list(arr.shape), "b": arr.tobytes()}
    raise TypeError(f"checkpoint codec cannot serialize {type(x).__name__}")


def dec(x, registry: Optional[Dict[str, type]] = None):
    """Inverse of :func:`enc`.  Raises on unknown tags/classes (never-trust:
    an unreadable tree fails the restore, it does not improvise)."""
    if registry is None:
        registry = _nt_registry()
    if isinstance(x, list):
        return [dec(v, registry) for v in x]
    if not isinstance(x, dict):
        return x
    tag = x.get("__kc__")
    if tag == "nd":
        dtype = _dtype_of(x["d"])
        arr = np.frombuffer(x["b"], dtype=dtype)
        return arr.reshape(tuple(x["s"]))
    if tag == "np":
        return np.frombuffer(x["b"], dtype=_dtype_of(x["d"]))[0]
    if tag == "nt":
        cls = registry.get(x["c"])
        if cls is None:
            raise FleetRestoreError(f"unknown checkpoint tuple {x['c']!r}")
        return cls(*(dec(v, registry) for v in x["f"]))
    if tag == "tu":
        return tuple(dec(v, registry) for v in x["v"])
    if tag == "map":
        return {dec(k, registry): dec(v, registry) for k, v in x["v"]}
    raise FleetRestoreError(f"unknown checkpoint codec tag {tag!r}")


# -- the file -----------------------------------------------------------------


class Checkpoint(NamedTuple):
    """A loaded, digest-verified checkpoint (still codec-encoded: the tensor
    frame decodes lazily at restore, where the NamedTuple registry lives)."""

    header: dict
    anchor: bytes
    tensors: dict
    path: str

    @property
    def version(self) -> int:
        return int(self.header.get("version", 0))

    @property
    def state(self) -> dict:
        return self.header.get("state") or {}


def _frame(payload: bytes) -> bytes:
    return journal_mod._FRAME_HEAD.pack(
        len(payload), journal_mod.crc32c(payload)
    ) + payload


def checkpoint_bytes(header: dict, anchor: bytes, tensors: dict) -> bytes:
    """Assemble the full checkpoint file image (used by write and by the
    round-trip tests): three payload frames + the digest trailer."""
    payloads = [
        msgpack.packb(header),
        msgpack.packb({"t": "anchor", "request": bytes(anchor)}),
        msgpack.packb(tensors),
    ]
    trailer = msgpack.packb({"t": "trailer", "sha256": content_digest(payloads)})
    return MAGIC + b"".join(_frame(p) for p in payloads + [trailer])


def load_checkpoint(path: str) -> Tuple[Optional[Checkpoint], str]:
    """Read + verify a checkpoint file.  NEVER raises: any torn frame, CRC
    failure, digest mismatch, or structural surprise returns (None, status)
    — the caller's ladder treats every non-OK status as "no checkpoint"."""
    try:
        records, status = journal_mod.read_frames(path, magic=MAGIC)
        if status != journal_mod.STATUS_OK:
            if status == journal_mod.STATUS_MISSING:
                return None, STATUS_MISSING
            return None, status
        if len(records) != 4:
            return None, STATUS_DIGEST
        header, anchor_rec, tensors, trailer = records
        if (
            header.get("t") != "header"
            or header.get("format") != FORMAT
            or anchor_rec.get("t") != "anchor"
            or tensors.get("t") != "tensors"
            or trailer.get("t") != "trailer"
        ):
            return None, STATUS_DIGEST
        # whole-file digest: re-pack the three payloads exactly as written
        # (msgpack round-trips our scalar/str/bytes/list/dict trees byte-
        # stably) and compare against the trailer
        digest = content_digest(
            msgpack.packb(rec) for rec in (header, anchor_rec, tensors)
        )
        if digest != trailer.get("sha256"):
            return None, STATUS_DIGEST
        anchor = anchor_rec.get("request")
        if not isinstance(anchor, (bytes, bytearray)):
            return None, STATUS_DIGEST
        return Checkpoint(header, bytes(anchor), tensors, path), STATUS_OK
    except Exception:  # noqa: BLE001 - a checkpoint is always optional
        log.exception("checkpoint load failed for %s", path)
        return None, STATUS_DIGEST


# -- the serving-side plane ---------------------------------------------------


def _safe_name(tenant_id: str) -> str:
    stem = re.sub(r"[^A-Za-z0-9_.-]", "_", tenant_id)[:64]
    suffix = hashlib.sha256(tenant_id.encode()).hexdigest()[:12]
    return f"{stem}-{suffix}.kcfc"


def _retention_keep() -> int:
    """Checkpoint generations retained per tenant (KC_FLEET_CHECKPOINT_KEEP,
    default 2, floor 1).  Keeping >1 lets the restore ladder fall back to the
    previous complete generation when the newest file fails verification —
    e.g. a disk-level corruption that lands AFTER publish — before degrading
    all the way to journal replay."""
    try:
        return max(int(os.environ.get("KC_FLEET_CHECKPOINT_KEEP", "2")), 1)
    except ValueError:
        return 2


class CheckpointPlane:
    """The serving replica's checkpoint writer + the adopting replica's
    reader, over one shared directory (FleetLocal.checkpoint_dir()).

    Writes are atomic (tmp + fsync + os.replace + directory fsync, the
    journal's compaction discipline) and KEYED BY TENANT AND GENERATION:
    each write publishes ``<stem>-<digest>.gNNNNNNNN.kcfc`` and then sweeps
    generations beyond the retention window (``keep``, default from
    KC_FLEET_CHECKPOINT_KEEP), so a reader always sees complete files and
    the newest failing verification still leaves the previous generation to
    restore from.  A pre-retention unsuffixed ``<stem>-<digest>.kcfc`` file
    is treated as generation 0.  ``after_solve`` is the cadence hook — every
    anchor solve, then every ``every``-th solve — and never raises:
    checkpointing is an optimization over the journal, losing one must never
    fail a solve that already answered."""

    def __init__(self, directory: str, *, clock: Optional[Clock] = None,
                 replica_id: str = "", every: int = 8,
                 keep: Optional[int] = None) -> None:
        self.directory = directory
        self.clock = clock or Clock()
        self.replica_id = replica_id
        self.every = max(int(every), 1)
        self.keep = _retention_keep() if keep is None else max(int(keep), 1)
        # serializes list-generations -> publish -> sweep: without it a
        # cadence write (serve path) racing the drain's write_all can compute
        # the SAME next generation number — one os.replace clobbers the
        # other — and sweep against a stale listing
        self._lock = threading.Lock()

    def _generations(self, tenant_id: str) -> List[Tuple[int, str]]:
        """Existing checkpoint generations for a tenant, newest first.  The
        legacy unsuffixed file participates as generation 0 so upgrades GC
        it once ``keep`` newer generations exist."""
        base = _safe_name(tenant_id)
        prefix = base[: -len(".kcfc")]
        pattern = re.compile(re.escape(prefix) + r"\.g(\d{8})\.kcfc\Z")
        found: List[Tuple[int, str]] = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for name in names:
            m = pattern.match(name)
            if m:
                found.append((int(m.group(1)),
                              os.path.join(self.directory, name)))
            elif name == base:
                found.append((0, os.path.join(self.directory, name)))
        found.sort(reverse=True)
        return found

    def path_for(self, tenant_id: str) -> str:
        """The current live checkpoint path: the newest published generation,
        or (before any generation exists) the legacy unsuffixed path."""
        gens = self._generations(tenant_id)
        if gens:
            return gens[0][1]
        return os.path.join(self.directory, _safe_name(tenant_id))

    def after_solve(self, tenant_id: str, entry, mode: str) -> None:
        # the cadence counter is entry state shared with the drain's
        # write_all: hold the entry lock (re-entrant — the serve path
        # already owns it) so the read-modify-write is never torn
        with entry.lock:
            entry.ckpt_ticks += 1
            if mode != "full" and entry.ckpt_ticks < self.every:
                return
            entry.ckpt_ticks = 0
        try:
            self.write(tenant_id, entry)
        except Exception:  # noqa: BLE001 - checkpointing must never fail a solve
            log.exception("fleet checkpoint write failed for tenant %s",
                          tenant_id)
            CHECKPOINT_TOTAL.labels("error").inc()

    def write(self, tenant_id: str, entry) -> Optional[str]:
        """Serialize the entry's lineage; returns the published path or None
        when there is nothing to checkpoint (no warm lineage, or the anchor
        request bytes were never captured)."""
        # snapshot the entry fields under its lock: write() also runs from
        # the drain's write_all, concurrently with a serve thread that is
        # mid-solve under this same lock on another tenant's thread
        with entry.lock:
            if not getattr(entry, "anchor_request", None):
                CHECKPOINT_TOTAL.labels("skipped").inc()
                return None
            export = entry.session.export_lineage()
            if export is None:
                CHECKPOINT_TOTAL.labels("skipped").inc()
                return None
            anchor_request = entry.anchor_request
            header = {
                "t": "header",
                "format": FORMAT,
                "tenant": tenant_id,
                "version": export["version"],
                "tseq": int(getattr(entry, "journal_tseq", 0)),
                "client_supply": getattr(entry, "supply_digest", None),
                "state": export["state"],
                "supply": export["supply"],
                "uid_bases": list(
                    getattr(entry, "anchor_uid_bases", ()) or ()
                ),
                "replica": self.replica_id,
                "written_at": float(self.clock.now()),
            }
        tensors = {
            "t": "tensors",
            "prep": enc(export["prep"]),
            "carry": enc(export["carry"]),
            "assign": enc(export["assign"]),
            "assign_ex": enc(export["assign_ex"]),
            "n_next": export["n_next"],
            "members_rows": export["members_rows"],
            "pod_loc": {u: list(v) for u, v in export["pod_loc"].items()},
            "failed_rows": dict(export["failed_rows"]),
            "delta_ticks": export["delta_ticks"],
            "initial_slots_used": export["initial_slots_used"],
            "materialized": list(export["materialized"]),
        }
        blob = checkpoint_bytes(header, anchor_request, tensors)
        os.makedirs(self.directory, exist_ok=True)
        # list -> publish -> sweep is one critical section: two concurrent
        # writers (cadence vs drain) listing the same generations would pick
        # the SAME next number — one publish silently clobbers the other —
        # and each would sweep against the other's stale listing
        with self._lock:
            gens = self._generations(tenant_id)
            gen = (gens[0][0] + 1) if gens else 1
            base = _safe_name(tenant_id)
            path = os.path.join(
                self.directory, f"{base[:-len('.kcfc')]}.g{gen:08d}.kcfc"
            )
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            dfd = os.open(self.directory, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
            CHECKPOINT_TOTAL.labels("written").inc()
            CHECKPOINT_BYTES.labels().observe(float(len(blob)))
            # retention sweep AFTER the publish fsync: the new generation is
            # durable before any older one disappears, so a crash mid-sweep
            # can only leave extras, never zero
            for _g, old in gens[self.keep - 1:]:
                try:
                    os.remove(old)
                    CHECKPOINT_TOTAL.labels("gc").inc()
                except OSError:
                    pass
        return path

    def write_all(self, entries: Dict[str, object]) -> int:
        """Drain hook: best-effort checkpoint of every resident lineage so
        the adopting replicas restore warm.  Returns the written count."""
        written = 0
        for tenant_id, entry in entries.items():
            try:
                if self.write(tenant_id, entry) is not None:
                    written += 1
            except Exception:  # noqa: BLE001 - drain keeps going
                log.exception("drain checkpoint failed for tenant %s",
                              tenant_id)
                CHECKPOINT_TOTAL.labels("error").inc()
        return written

    def load(self, tenant_id: str) -> Tuple[Optional[Checkpoint], str]:
        """Newest generation that VERIFIES wins: a corrupt newest file falls
        back to the previous retained generation before the caller's ladder
        degrades to journal replay."""
        gens = self._generations(tenant_id)
        if not gens:
            return load_checkpoint(
                os.path.join(self.directory, _safe_name(tenant_id))
            )
        status = STATUS_MISSING
        for i, (_gen, path) in enumerate(gens):
            ckpt, st = load_checkpoint(path)
            if ckpt is not None:
                return ckpt, st
            if i == 0:
                status = st  # report the newest generation's failure mode
        return None, status

    def drop(self, tenant_id: str) -> None:
        """A dropped tenant's checkpoints (every generation) must not
        resurrect it elsewhere."""
        for _gen, path in self._generations(tenant_id):
            try:
                os.remove(path)
            except OSError:
                pass
        try:
            os.remove(os.path.join(self.directory, _safe_name(tenant_id)))
        except OSError:
            pass


# -- restore ------------------------------------------------------------------


def restore_session(ckpt: Checkpoint, session, cloud_provider):
    """Rebuild a warm lineage from a checkpoint into a FRESH session.

    Never-trust, in order: (1) the anchor request re-decodes on THIS replica
    and its class-identity digests match the checkpointed uid bases; (2) the
    re-encoded snapshot commits at the checkpointed version with equal plane
    digests; (3) after ``adopt_restored``, the live ``lineage_state()``
    equals the checkpointed one exactly — aggregates, placement signature,
    supply, delta ticks.  Any failure raises :class:`FleetRestoreError`
    (or the underlying error) and the caller falls to journal replay.

    Returns the bound TPUSolver (callers reuse it for response bookkeeping).
    """
    from karpenter_core_tpu.policy import PolicyConfig
    from karpenter_core_tpu.service.snapshot_channel import (
        SnapshotSolverService,
    )
    from karpenter_core_tpu.solver.tpu import TPUSolver

    header = ckpt.header
    req = msgpack.unpackb(ckpt.anchor)
    (classes, uid_class, provisioners, daemonset_pods, state_nodes,
     bound, resolver) = SnapshotSolverService._decode_tenant_classes(req)
    want_bases = [str(b) for b in header.get("uid_bases", [])]
    if want_bases and list(uid_class) != want_bases:
        raise FleetRestoreError(
            "anchor class identities diverged from the checkpoint header"
        )
    solver = TPUSolver(
        cloud_provider, provisioners, daemonset_pods,
        kube_client=resolver,
        policy=PolicyConfig.from_wire(req.get("policy")),
    )
    session.reset()
    session.rebind(solver)
    version = int(header.get("version", 0))
    if version <= 0:
        raise FleetRestoreError("checkpoint carries no warm version")
    session.store.seed_version(version - 1)
    snapshot = solver.encode_classes(
        classes, state_nodes=state_nodes or None, bound_pods=bound
    )
    versioned = session.store.commit(snapshot, supply=str(header.get("supply")))
    state = ckpt.state
    if int(versioned.version) != version:
        raise FleetRestoreError(
            f"re-encoded anchor committed at version {versioned.version}, "
            f"checkpoint claims {version}"
        )
    if dict(versioned.digests) != dict(state.get("planes") or {}):
        raise FleetRestoreError(
            "re-encoded anchor plane digests diverged from the checkpoint"
        )
    registry = _nt_registry()
    t = ckpt.tensors
    prep = dec(t["prep"], registry)
    carry = dec(t["carry"], registry)
    members = {}
    for row, uids in t.get("members_rows", []):
        row = int(row)
        if row < 0 or row >= len(versioned.rows):
            raise FleetRestoreError(f"member row {row} outside the snapshot")
        members[versioned.rows[row].key] = tuple(str(u) for u in uids)
    session.adopt_restored(
        versioned, prep, carry,
        assign=dec(t["assign"], registry),
        assign_ex=dec(t["assign_ex"], registry),
        n_next=int(t["n_next"]),
        members=members,
        pod_loc={str(u): (int(r), str(k), int(i))
                 for u, (r, k, i) in t.get("pod_loc", {}).items()},
        failed_rows={str(u): int(r)
                     for u, r in t.get("failed_rows", {}).items()},
        supply=str(header.get("supply")),
        state_nodes=state_nodes,
        delta_ticks=int(t.get("delta_ticks", 0)),
        initial_slots_used=int(t.get("initial_slots_used", 0)),
        materialized=[str(u) for u in t.get("materialized", [])],
    )
    live = session.lineage_state()
    if live != state:
        session.reset()
        raise FleetRestoreError(
            f"restored lineage state diverged (have version "
            f"{live.get('version')}, checkpoint {state.get('version')})"
        )
    return solver


def scan_directory(directory: str) -> List[str]:
    """Checkpoint files currently published under ``directory`` (what the
    adopting replica and the soak's leak checks enumerate)."""
    try:
        return sorted(
            os.path.join(directory, name)
            for name in os.listdir(directory)
            if name.endswith(".kcfc")
        )
    except OSError:
        return []
