"""The fleet router: one thin front door over N solver replicas.

Clients dial the router exactly as they dial a single replica — same
service name, same msgpack wire shapes, same abort grammar.  The router:

  places    each tenant on one replica via the consistent-hash ring
            (fleet/ring.py), liveness-filtered by the lease directory
            (fleet/lease.py), and FORWARDS THE RAW REQUEST BYTES verbatim —
            the PR-12 tenant envelope and the PR-16 trace context cross
            untouched, so replica-side behavior (and the KC_FLEET=0
            byte-identity pin) is preserved by construction
  admits    at fleet level (fleet/admission.py) BEFORE forwarding: the
            router's token buckets are shaped by the unscaled tenant config,
            so N replicas can no longer over-admit N× the configured rate
  fails over on UNAVAILABLE / DEADLINE_EXCEEDED: the placement is dropped,
            the replica's breaker trips, and the request retries on the next
            alive replica of the tenant's arc — which restores the tenant
            WARM from its fleet checkpoint (fleet/checkpoint.py)
  rebalances on a cadence, moving at most ``KC_FLEET_REBALANCE_FRACTION`` of
            tenants per interval off the replica whose tenants burn their
            latency SLO hottest (service/tenant.py SloTracker)

Replica-originated aborts (tenant sheds, precondition failures) pass
through with code AND details intact — retry-after hints survive the hop.
The ``fleet.route`` chaos point injects error / timeout / partial faults on
the forwarding edge for the chaos matrix.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent import futures
from typing import Dict, List, Optional, Set, Tuple

import grpc
import msgpack

from karpenter_core_tpu import chaos, tracing
from karpenter_core_tpu.fleet import FleetLocal
from karpenter_core_tpu.fleet.admission import FleetAdmission
from karpenter_core_tpu.fleet.lease import LeaseDirectory, LeasePlane
from karpenter_core_tpu.fleet.ring import HashRing
from karpenter_core_tpu.metrics import REGISTRY, tenant_label
from karpenter_core_tpu.service import tenant as tenant_mod
from karpenter_core_tpu.utils import retry
from karpenter_core_tpu.utils.clock import Clock

log = logging.getLogger(__name__)

# must match service/snapshot_channel.py SERVICE — redeclared so a thin
# router process never imports the solver stack
SERVICE = "karpenter.v1.SnapshotSolver"

# the router→replica forwarding edge (docs/CHAOS.md): error (replica
# unreachable), timeout (forward deadline), partial (the replica answered
# but the client never saw it — the mid-stream eviction shape)
FLEET_ROUTE = chaos.point("fleet.route")

ROUTED_TOTAL = REGISTRY.counter(
    "karpenter_fleet_routed_total",
    "Router forwarding outcomes: ok, shed (fleet-level rate), failover "
    "(placement moved after an UNAVAILABLE/DEADLINE replica), exhausted "
    "(no alive replica accepted), upstream (replica abort passed through), "
    "chaos-error / chaos-timeout / chaos-partial (injected).",
    ("outcome",),
)
REPLICAS_ALIVE = REGISTRY.gauge(
    "karpenter_fleet_replicas_alive",
    "Fleet replicas currently alive by lease freshness (draining and "
    "lease-expired replicas excluded).",
)
REBALANCED_TOTAL = REGISTRY.counter(
    "karpenter_fleet_rebalanced_total",
    "Tenant placements moved by the router's load-aware rebalancer (at most "
    "KC_FLEET_REBALANCE_FRACTION of tenants per interval).",
)


class FleetRouter(grpc.GenericRpcHandler):
    """grpc generic handler + placement/liveness/rebalance state."""

    def __init__(self, fleet: FleetLocal, *,
                 clock: Optional[Clock] = None,
                 tenant_config: Optional[tenant_mod.TenantConfig] = None) -> None:
        self.fleet = fleet
        self.clock = clock or Clock()
        self.ring = HashRing(fleet.fleet_map)
        self.addresses = fleet.fleet_map.addresses()
        self.lease_plane = LeasePlane(fleet.lease_path())
        self.directory = LeaseDirectory(
            self.lease_plane, clock=self.clock, ttl_s=fleet.lease_ttl_s
        )
        self.admission = FleetAdmission(tenant_config, clock=self.clock)
        self.slo = tenant_mod.SloTracker()
        self.forward_timeout_s = tenant_mod._env_f(
            "KC_FLEET_FORWARD_TIMEOUT_S", 120.0
        )
        self.rebalance_interval_s = tenant_mod._env_f(
            "KC_FLEET_REBALANCE_INTERVAL_S", 30.0
        )
        self.rebalance_fraction = min(max(tenant_mod._env_f(
            "KC_FLEET_REBALANCE_FRACTION", 0.1
        ), 0.0), 1.0)
        self._lock = threading.Lock()
        self._placements: Dict[str, str] = {}
        # channel/stub construction has its own lock: concurrent handler
        # threads race the first use of a replica (duplicate channels, one
        # leaked unclosed), and _lock must stay free for placement scans
        self._stub_lock = threading.Lock()
        self._channels: Dict[str, grpc.Channel] = {}
        self._stubs: Dict[Tuple[str, str], object] = {}
        self._breakers: Dict[str, retry.CircuitBreaker] = {
            rid: retry.CircuitBreaker(
                self.clock, failure_threshold=2,
                reset_timeout_s=max(fleet.heartbeat_s * 2.0, 1.0),
                name=f"fleet-replica:{rid}",
            )
            for rid in fleet.fleet_map.ids()
        }
        self._last_rebalance = self.clock.now()

    # -- plumbing --------------------------------------------------------------

    def service(self, handler_call_details):
        method = handler_call_details.method
        if method == f"/{SERVICE}/SolveClasses":
            return grpc.unary_unary_rpc_method_handler(self._solve_classes)
        if method == f"/{SERVICE}/Health":
            return grpc.unary_unary_rpc_method_handler(self._health)
        if method == f"/{SERVICE}/LeaseGet":
            return grpc.unary_unary_rpc_method_handler(self._lease_get)
        if method == f"/{SERVICE}/LeaseApply":
            return grpc.unary_unary_rpc_method_handler(self._lease_apply)
        if method == f"/{SERVICE}/FleetState":
            return grpc.unary_unary_rpc_method_handler(self._fleet_state)
        return None

    def _lease_get(self, request: bytes, context) -> bytes:
        return self.lease_plane.get_wire(request)

    def _lease_apply(self, request: bytes, context) -> bytes:
        return self.lease_plane.apply_wire(request)

    def _stub(self, rid: str, method: str):
        key = (rid, method)
        with self._stub_lock:
            stub = self._stubs.get(key)
            if stub is None:
                channel = self._channels.get(rid)
                if channel is None:
                    channel = grpc.insecure_channel(self.addresses[rid])
                    self._channels[rid] = channel
                stub = channel.unary_unary(f"/{SERVICE}/{method}")
                self._stubs[key] = stub
        return stub

    # -- liveness + rebalance (lazy: piggybacked on routed requests) -----------

    def _maintain(self) -> Tuple[Set[str], Set[str]]:
        alive, draining = self.directory.view(self.fleet.fleet_map.ids())
        REPLICAS_ALIVE.labels().set(float(len(alive)))
        with self._lock:
            dead_placements = [
                t for t, rid in self._placements.items() if rid not in alive
            ]
            for t in dead_placements:
                del self._placements[t]
        now = self.clock.now()
        with self._lock:
            # claim the interval under the lock: two handler threads racing
            # the same deadline must not both run a rebalance round
            due = now - self._last_rebalance >= self.rebalance_interval_s
            if due:
                self._last_rebalance = now
        if due:
            self._rebalance(alive)
        return alive, draining

    def _rebalance(self, alive: Set[str]) -> None:
        """Move the hottest-burning replica's hottest tenants to the next
        alive replica on their arc — bounded to ``rebalance_fraction`` of
        ALL placed tenants per interval, so rebalancing converges instead of
        thrashing warm lineages around the fleet."""
        with self._lock:
            placements = dict(self._placements)
        if not placements or len(alive) < 2:
            return
        by_replica: Dict[str, List[Tuple[float, str]]] = {}
        for tenant, rid in placements.items():
            burn = self.slo.burn(tenant_label(tenant), "5m")
            by_replica.setdefault(rid, []).append((burn, tenant))
        hot_rid, hot_tenants = max(
            by_replica.items(),
            key=lambda kv: (max(b for b, _ in kv[1]), sum(b for b, _ in kv[1])),
        )
        hottest_burn = max(b for b, _ in hot_tenants)
        if hottest_burn <= 0.0:
            return  # every tenant inside budget: nothing to move
        budget = max(int(self.rebalance_fraction * len(placements)), 1)
        moved = 0
        for burn, tenant in sorted(hot_tenants, reverse=True):
            if moved >= budget or burn <= 0.0:
                break
            target = next(
                (rid for rid in self.ring.arc(tenant)
                 if rid in alive and rid != hot_rid), None,
            )
            if target is None:
                break
            with self._lock:
                if self._placements.get(tenant) == hot_rid:
                    self._placements[tenant] = target
                    moved += 1
                    REBALANCED_TOTAL.labels().inc()
        if moved:
            log.info("fleet rebalance: moved %d tenant(s) off %s", moved,
                     hot_rid)

    # -- the routed solve ------------------------------------------------------

    def _place(self, tenant: str, alive: Set[str]) -> Optional[str]:
        with self._lock:
            rid = self._placements.get(tenant)
            if rid in alive:
                return rid
            assigned: Dict[str, int] = {}
            for r in self._placements.values():
                assigned[r] = assigned.get(r, 0) + 1
            rid = self.ring.owner(tenant, alive, assigned)
            if rid is not None:
                self._placements[tenant] = rid
            return rid

    def _drop_placement(self, tenant: str, rid: str) -> None:
        with self._lock:
            if self._placements.get(tenant) == rid:
                del self._placements[tenant]

    def _solve_classes(self, request: bytes, context) -> bytes:
        try:
            req = msgpack.unpackb(request)
        except Exception:  # noqa: BLE001 - surface like the replica would
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          "malformed msgpack request")
        envelope = req.get("tenant") or {}
        tenant = str(envelope.get("id") or "")
        alive, _draining = self._maintain()
        if tenant:
            admitted, hint = self.admission.admit(
                tenant, envelope.get("weight")
            )
            if not admitted:
                ROUTED_TOTAL.labels("shed").inc()
                context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                              self.admission.shed_detail(hint))
        fault = FLEET_ROUTE.hit(
            kinds=("error", "timeout", "partial"), tenant=tenant,
        )
        if fault is not None and fault.kind == "error":
            ROUTED_TOTAL.labels("chaos-error").inc()
            context.abort(grpc.StatusCode.UNAVAILABLE, fault.describe())
        if fault is not None and fault.kind == "timeout":
            ROUTED_TOTAL.labels("chaos-timeout").inc()
            context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, fault.describe())
        place_key = tenant or "solo"
        with tracing.span_remote("fleet.route", envelope.get("trace"),
                                 tenant=tenant) as sp:
            first = self._place(place_key, alive)
            tried: List[str] = []
            candidates = [first] if first is not None else []
            candidates += [
                rid for rid in self.ring.arc(place_key)
                if rid in alive and rid != first
            ]
            for rid in candidates:
                breaker = self._breakers[rid]
                if not breaker.allow():
                    continue
                tried.append(rid)
                t0 = time.perf_counter()
                try:
                    response = self._stub(rid, "SolveClasses")(
                        request, timeout=self.forward_timeout_s
                    )
                except grpc.RpcError as e:
                    code = e.code()
                    if code in (grpc.StatusCode.UNAVAILABLE,
                                grpc.StatusCode.DEADLINE_EXCEEDED):
                        # the replica is gone (or wedged): trip its breaker,
                        # drop the placement, walk the arc — the adopting
                        # replica restores the tenant from its checkpoint
                        breaker.record_failure()
                        self._drop_placement(place_key, rid)
                        ROUTED_TOTAL.labels("failover").inc()
                        sp.set(**{"fleet.failover": rid})
                        continue
                    # a replica VERDICT (shed, precondition, bad request):
                    # pass code + details through verbatim — retry-after
                    # hints and eject reasons must survive the hop
                    breaker.record_success()
                    ROUTED_TOTAL.labels("upstream").inc()
                    context.abort(code, e.details() or "")
                breaker.record_success()
                with self._lock:
                    self._placements[place_key] = rid
                if tenant:
                    self.slo.observe(
                        tenant_label(tenant), time.perf_counter() - t0
                    )
                if fault is not None and fault.kind == "partial":
                    # the replica computed and journaled the answer, the
                    # client never receives it — the mid-stream eviction leg
                    ROUTED_TOTAL.labels("chaos-partial").inc()
                    context.abort(grpc.StatusCode.UNAVAILABLE,
                                  fault.describe())
                ROUTED_TOTAL.labels("ok").inc()
                sp.set(**{"fleet.replica": rid})
                return response
            ROUTED_TOTAL.labels("exhausted").inc()
            context.abort(
                grpc.StatusCode.UNAVAILABLE,
                f"fleet-no-replica tried={','.join(tried) or 'none'} "
                f"alive={len(alive)}",
            )

    # -- health + debug --------------------------------------------------------

    def _health(self, request: bytes, context) -> bytes:
        alive, draining = self._maintain()
        replicas: Dict[str, Dict] = {}
        for rid in sorted(self.fleet.fleet_map.ids()):
            if rid not in alive:
                replicas[rid] = {
                    "status": "draining" if rid in draining else "dead"
                }
                continue
            try:
                raw = self._stub(rid, "Health")(request, timeout=2.0)
                replicas[rid] = msgpack.unpackb(raw)
            except grpc.RpcError as e:
                replicas[rid] = {"status": "unreachable",
                                 "code": str(e.code())}
        ok = any(r.get("status") == "ok" for r in replicas.values())
        return msgpack.packb({
            "status": "ok" if ok else "degraded",
            "fleet": {"router": True, "alive": sorted(alive),
                      "replicas": replicas},
        })

    def _fleet_state(self, request: bytes, context) -> bytes:
        alive, draining = self._maintain()
        with self._lock:
            placements = dict(self._placements)
        return msgpack.packb({
            "alive": sorted(alive),
            "draining": sorted(draining),
            "replicas": dict(self.addresses),
            "placements": placements,
        })

    def close(self) -> None:
        with self._stub_lock:
            for channel in self._channels.values():
                channel.close()
            self._channels.clear()
            self._stubs.clear()


def serve_router(fleet: FleetLocal, address: str = "127.0.0.1:0", *,
                 clock: Optional[Clock] = None,
                 tenant_config: Optional[tenant_mod.TenantConfig] = None,
                 max_workers: int = 8):
    """Start the router; returns (server, bound_port).  ``server.kc_router``
    carries the FleetRouter for tests and the soak harness."""
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        maximum_concurrent_rpcs=max_workers * 4,
    )
    router = FleetRouter(fleet, clock=clock, tenant_config=tenant_config)
    server.add_generic_rpc_handlers((router,))
    port = server.add_insecure_port(address)
    server.start()
    server.kc_router = router
    log.info("fleet router listening on port %d over %d replica(s)",
             port, fleet.fleet_map.size)
    return server, port
